# ringpop_tpu build/test entry points (model: reference Makefile:1-75 —
# test / test-race / lint / integration split, adapted to the Python+JAX
# toolchain; native hash core built via g++ like the reference's vendored
# deps were via glide).

PY ?= python

.PHONY: all test test-fast test-slow test-integration test-accel bench simbench native lint lint-json clean profile-mesh telemetry-smoke chaos-smoke aot-smoke mc-smoke serve-smoke serve-fanin-smoke multihost-smoke dcn-smoke topo-smoke fleet-smoke live-smoke trace-smoke transport-smoke gameday-smoke race-smoke bench-trend bench-trend-report

all: native test

# full unit+functional suite (CPU, virtual 8-device mesh via tests/conftest.py;
# XLA compiles hit the persistent .jax_cache — cold first run pays compile
# once, warm runs are compile-free.  --durations prints the tier timings.)
# profile-mesh runs first so CI exercises the sharded compile + collective
# budget ratchet without the slow 1M program; telemetry-smoke gates the
# telemetry plane (journal produced + telemetry-on digest-equal to off);
# tests/test_mesh_budget.py re-asserts the while-body budgets from inside
# pytest.  lint runs the two-plane jaxlint suite (AST hazards + traced-
# program invariants; ANALYSIS.md) — the static gate in front of the
# dynamic certificates, mirroring the reference Makefile's test/lint
# split.
test: profile-mesh telemetry-smoke chaos-smoke topo-smoke mc-smoke fleet-smoke aot-smoke serve-smoke serve-fanin-smoke multihost-smoke dcn-smoke live-smoke trace-smoke transport-smoke gameday-smoke race-smoke bench-trend-report lint
	$(PY) -m pytest tests/ -q --durations=15

# live-operations-plane gate (r20, obs/): a P=2 in-process fleet sweep
# serving its live endpoint mid-run — /progress shows BOTH ranks'
# ticks_done, /metrics aggregated counters equal the ranks' journal
# sums exactly, live-plane-on digests == plane-off (bit-transparency),
# and killing one rank mid-sweep leaves a flight-recorder dump whose
# last block record matches that rank's journal tail.
live-smoke:
	$(PY) scripts/live_smoke.py

# span-tracing gate (r20, obs/trace.py): a forwarded key's span chain
# (frontend route -> per-owner forward RPC -> receive-side handle ->
# quorum-read wave) reconstructs from the JSONL journal alone with hop
# counts equal to the ringpop-hops header values, span ids are
# rerun-deterministic (sampled by key hash), and the P=2 serve mesh's
# digests are bit-identical tracing-on vs off.
trace-smoke:
	$(PY) scripts/trace_smoke.py

# closed-observability-loop gate (r22, obs/rules.py + obs/controller.py):
# one in-process game day — zone cut into a live P=2 fleet with the rule
# engine + OpsController attached; the controller must drain the cut
# zone's ring block strictly EARLIER than the no-controller twin's
# organic SWIM declaration, controller-on == controller-off == bare
# no-obs digests bit for bit, the drain's effect probe reads 0, and
# obs.chain() reconstructs alert -> action -> effect from the journal.
gameday-smoke:
	$(PY) scripts/gameday_smoke.py

# perf-trajectory tripwire (r22): a fresh quick measurement (transport
# RTT best-of-N p50, no jax) against the newest committed BENCH_*.json
# value per tracked row, direction-aware; exit 1 on a >15% regression.
# bench-trend-report is the make-test wiring — same comparison, always
# exit 0 (the 2-core CI container reports trends, it does not gate on
# them; gate deliberately via make bench-trend).
bench-trend:
	$(PY) scripts/bench_trend.py

bench-trend-report:
	$(PY) scripts/bench_trend.py --report-only

# one-transport-plane gate (r21): serve lookups (shm zero-copy + folded
# TCP), a gossip window exchange, an obs-class snapshot and a mesh-style
# forward all through the unified transport — owner digests equal the
# host-bisect oracle, every merged-ledger class row reconciles with the
# transport's legacy counters, and copy_bytes reads 0.
transport-smoke:
	$(PY) scripts/transport_smoke.py

# the race gate (analysis plane 3, dynamic half — the rebuild's
# test-race): transport/serve/dcn/gameday smokes rerun under
# racecheck's instrumented locks + seeded schedule perturbation (3
# seeds), failing on smoke breakage or a dynamic lock-order cycle;
# plus the non-vacuity pair — the r22 count-after-respond mutant is
# deliberately reintroduced and MUST be caught (exit 3 if missed).
race-smoke:
	$(PY) scripts/race_harness.py

# tiny-config telemetry gate: lifecycle run with telemetry on must emit a
# parseable JSONL journal AND end digest-equal to a telemetry-off run;
# the delta journal hook must be bit-transparent too.
telemetry-smoke:
	$(PY) scripts/telemetry_smoke.py

# tiny churn+flap chaos scenario (sim/chaos.py): scorer output shape +
# telemetry-on/off bit-identity under a time-varying FaultPlan + the
# scored JSONL journal round-trip.
chaos-smoke:
	$(PY) scripts/chaos_smoke.py

# topology-plane gate (sim/topology.py): tiny 2-rack/2-zone tree —
# compile (blocked ids, monotone drop table, penalty-free tree emits NO
# legs), scored-fleet round-trip with per-tier telemetry (journal tier
# keys + per-tier ttd/false-positive split on every score; zone loss
# must NOT read as independent crashes), sharded==unsharded digest twin
# on the 4x2 virtual mesh, and the constant-tree jaxpr identity with
# the flat fault-plan step.
topo-smoke:
	$(PY) scripts/topo_smoke.py

# batched chaos-fleet gate (sim/scenarios.py, r12): tiny churn x loss
# grid through the stacked-FaultPlan Monte-Carlo fleet — B=1 member must
# be bit-identical (state digest + telemetry blocks) to the solo chaos
# path, the scored per-scenario journal (scenario_id on blocks + scores)
# must round-trip, and the response surface must match a solo probe.
mc-smoke:
	$(PY) scripts/mc_smoke.py

# scenario-fleet gate (r19): tiny grid through cli/fleet_bench — P=1
# unbroken == P=2 with a MID-SWEEP orbax fleet checkpoint (each rank
# writes only its shards, run continues) == P=1 restore of the P=2
# checkpoint (a DIFFERENT process count), per-scenario digests + score
# records bit-exact; plus the adaptive cliff driver finding the dense
# 1-dose grid's cliff coordinate at strictly fewer scenario-evals.
fleet-smoke:
	$(PY) scripts/fleet_smoke.py

# serve-the-ring gate (serve/): tiny 2-frontend shared-memory A/B —
# owner digests serve==bisect per (worker, rep), generation-pinned
# answers, live-update re-certification, B=1 oracle match, serve-journal
# telemetry schema, DGRO movement gate.  Correctness only: throughput
# ratios are priced by the committed SIMBENCH serve_ring artifact, not
# asserted here (2-core CI container).
serve-smoke:
	$(PY) scripts/serve_smoke.py

# production-fan-in serve-plane gate (r17): forward-then-answer round
# trip (per-owner coalesced batch -> fused LookupN answer == host walk,
# ONE RPC per owner), quorum reads under an owner-killing FaultPlan
# (acks >= ceil((R+1)/2) every wave, recovery scored by chaos.score_blocks),
# and the P=2 serve mesh digest-equal to the single-process oracle.
# Correctness only — the throughput curve is the committed SIMBENCH
# serve_fanin artifact, never asserted on the 2-core container.
serve-fanin-smoke:
	$(PY) scripts/serve_fanin_smoke.py

# multi-host DCN-fabric gate (r14): 2 coordinated OS processes through the
# real jax.distributed bring-up — 1-proc vs 2-proc twin digests must equal
# the in-process engine's, and a 2-proc block-sharded orbax save must
# restore at 1 process and continue digest-equal to an unbroken run.
multihost-smoke:
	$(PY) scripts/multihost_smoke.py

# DCN wire-codec + exchange-schedule gate (r15/r16): tiny codec A/B over
# the fabric — codec-on digests == codec-off == engine, wire bytes
# strictly lower on every dissemination tick, the measured RAW fallback
# exercised, exchange-leg device→host transfer pinned under the pre-r15
# full-plane-per-leg floor (pieces-only) — plus the r16 grid: every
# (swing|cyclic) x (overlap on|off) combination at P=2 and the P=4 swing
# relay leg land the SAME engine digest, the drain/overlap journal keys
# are present, P=2 swing wire bytes == cyclic exactly (the schedule
# degenerates) and the P=4 relay overhead is visible in raw accounting.
dcn-smoke:
	$(PY) scripts/dcn_smoke.py

# AOT warm-start gate (util/aot.py): serialize the sharded (pipelined)
# tick block, reload it through the front door in a fresh subprocess —
# must report cache_hit with compile_s < 2 s and a bit-identical block
# digest vs the in-process compile.
aot-smoke:
	$(PY) scripts/aot_smoke.py

# compile the sharded programs at CI scale (8k, hierarchical select forced
# on, the sharded-caller defaults rng=counter + shard-local exchange) and
# diff the collective census against the committed budget capture — non-zero
# exit if any collective class regressed beyond tolerance.  --phase-budget
# additionally ratchets the exchange/peer-choice phase rows (r8), so a
# regression there can't hide inside an unchanged global total.  --chaos
# drives the profiled step with the canonical churn+flap+loss FaultPlan —
# the chaos plane's zero-added-collectives claim is ratcheted against the
# UNCHANGED static budget (verified identical at re-introduction: 147
# collectives / 0.29 MB, collective-for-collective equal).
# Re-baseline (after an INTENDED budget change, with PERF.md updated):
#   $(PY) scripts/profile_mesh.py --step-n 8192 --step-k 64 --detect-n 8192 \
#     --force-sparse --out captures/mesh_profile_small_budget.json
# --overlap (r11): the pipelined exchange's compiled schedule must show
# response-leg crossing sends issued off PARTIAL request-leg receives,
# interleaved with the merge (exit 5 if the fused leg loop regressed to
# a strictly sequential dependency graph).
# --fail-unattributed (r20): every censused collective must carry a
# named-scope phase — '(unattributed)' was a printed warning the doc
# already called a coverage bug; CI now fails on it (exit 6).
profile-mesh:
	$(PY) scripts/profile_mesh.py --step-n 8192 --step-k 64 --detect-n 8192 \
	  --force-sparse --chaos --overlap --fail-unattributed \
	  --compare captures/mesh_profile_small_budget.json \
	  --phase-budget --out /tmp/mesh_profile_small.json

# skip the scale spot-checks
test-fast:
	$(PY) -m pytest tests/ -q -m "not slow" --durations=15

# only the scale spot-checks (20k-node sim, 10-process cluster)
test-slow:
	$(PY) -m pytest tests/ -q -m slow --durations=15

# tier-3 multi-process clusters only (reference: make test-integration)
test-integration:
	$(PY) -m pytest tests/test_integration_processes.py -q

# real-hardware smoke suite (own process: tests/ pins CPU at conftest import;
# auto-skips when the axon tunnel is down)
test-accel:
	$(PY) -m pytest tests_accel/ -q

# headline benchmark — one JSON line (1M-node convergence on an accelerator)
bench:
	$(PY) bench.py

# detach the TPU tunnel watcher: probes the axon tunnel all round and runs
# bench.py + scripts/tpu_ksweep.py the moment the chip answers, committing
# timestamped captures under captures/ (see scripts/tpu_watch.sh)
tpu-watch:
	chmod +x scripts/tpu_watch.sh
	setsid nohup scripts/tpu_watch.sh >> /tmp/tpu_watch.log 2>&1 < /dev/null &
	@echo "watcher detached; log: /tmp/tpu_watch.log"

# all BASELINE scenario configs + paired A/Bs (forward_ab, mc_churn, ...)
simbench:
	$(PY) -m ringpop_tpu.cli.simbench

# judge the newest watcher ksweep capture against PERF.md's cost model
# (prints CERTIFIES/REFUTES per measurement; rc=2 on refutation)
certify:
	$(PY) scripts/certify_cost_model.py

# native FarmHash core (rebuilds the .so the hashing layer loads via ctypes)
native:
	$(PY) -c "from ringpop_tpu import native; assert native._build(), 'g++ build failed'; print('native hash core built')"

# two-plane static analysis (scripts/jaxlint.py; rule catalog ANALYSIS.md):
# plane 1 AST-lints the package for codebase-specific hazards (raw threefry
# draws, traced rolls, host syncs in jit, x64 promotion, missing phase
# scopes); plane 2 traces the public jitted entry points dense + on the
# 8-way virtual mesh and statically asserts no f64, no host callbacks,
# donation aliased, collectives confined to their r8 phases (peer-choice =
# zero), and sharded == unsharded trace structure modulo sharding ops.
# Waivers: ringpop_tpu/analysis/waivers.toml (justification mandatory).
lint:
	$(PY) -m compileall -q ringpop_tpu tests tests_accel bench.py __graft_entry__.py
	$(PY) scripts/jaxlint.py

# machine-readable rule-outcome listing (every finding incl. waived +
# unused waivers) — diff this across budget re-baselines
lint-json:
	$(PY) scripts/jaxlint.py --format=json

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
	rm -f ringpop_tpu/native/*.so
