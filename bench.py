"""Benchmark: million-node SWIM dissemination on one chip.

North star (BASELINE.json): simulate 1M-node SWIM convergence < 60 s.  This
bench runs the delta engine — 1M nodes, 128 concurrent rumors — until every
rumor reaches every node, and reports wall-clock seconds with
``vs_baseline = 60 / measured`` (>1 beats the target).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import json
import os
import sys
import time


def _accelerator_alive(timeout_s: float = 120.0) -> bool:
    """Probe device init in a subprocess: a wedged TPU tunnel can HANG
    jax.devices() indefinitely rather than raise, which would otherwise
    leave the bench silent.  A dead probe → CPU fallback."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    import jax

    if not _accelerator_alive():
        jax.config.update("jax_platforms", "cpu")

    from ringpop_tpu.sim.delta import DeltaParams, DeltaSim, init_state, run_until_converged

    try:
        platform = jax.devices()[0].platform
    except Exception:  # accelerator backend down — still produce a result
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
    # full scale on an accelerator; CPU fallback keeps CI fast
    if platform in ("tpu", "axon") or os.environ.get("BENCH_FULL"):
        n, k = 1_000_000, 128
    else:
        n, k = 50_000, 64

    sim = DeltaSim(n=n, k=k, seed=0)

    # compile + warm up one step so the measurement is steady-state
    t_compile = time.perf_counter()
    sim.tick()
    jax.block_until_ready(sim.state.learned)
    compile_s = time.perf_counter() - t_compile

    # fresh state, timed convergence run (BENCH_PROFILE=dir captures a
    # jax.profiler trace for kernel-level analysis on real hardware)
    sim.state = init_state(sim.params, seed=1)
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    state, ticks, ok = run_until_converged(sim.params, sim.state, max_ticks=4096)
    jax.block_until_ready(state.learned)
    elapsed = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()

    # secondary BASELINE metric: batched ring lookup qps (1M-vnode ring on
    # the accelerator; cheap relative to the convergence run)
    import numpy as np

    from ringpop_tpu.ops.ring_ops import build_ring_tokens, ring_lookup

    n_servers = 4096 if n >= 1_000_000 else 512
    servers = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(n_servers)]
    tokens, owners = build_ring_tokens(servers, 256)
    rng = np.random.default_rng(0)
    batch = 1_000_000 if n >= 1_000_000 else 100_000
    hashes = jax.numpy.asarray(rng.integers(0, 2**32, size=batch, dtype=np.uint32))
    jax.block_until_ready(ring_lookup(tokens, owners, hashes))  # compile
    t_r = time.perf_counter()
    for _ in range(10):
        out = ring_lookup(tokens, owners, hashes)
    jax.block_until_ready(out)
    ring_qps = batch * 10 / (time.perf_counter() - t_r)

    baseline_s = 60.0  # BASELINE.json north star
    result = {
        "metric": f"swim_sim_convergence_n{n}",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(baseline_s / elapsed, 2) if elapsed > 0 else 0.0,
        "converged": ok,
        "ticks": ticks,
        "ticks_per_s": round(ticks / elapsed, 1) if elapsed > 0 else 0.0,
        "n_nodes": n,
        "n_rumors": k,
        "compile_s": round(compile_s, 2),
        "ring_lookup_qps": round(ring_qps, 0),
        "platform": platform,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
