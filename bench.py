"""Benchmark: million-node SWIM failure detection + dissemination.

North star (BASELINE.json): simulate 1M-node SWIM convergence < 60 s.

Headline metric — the *product* (failure detection, reference call stack
``swim/node.go:470-513``): crash 0.1% of a 1M-node cluster and measure
wall-clock until every live observer believes every victim faulty
(probe → suspect → timer → faulty → full dissemination), on the lifecycle
engine.  Secondary metrics: delta-engine rumor convergence at 1M (the pure
dissemination axis) and batched ring lookup qps.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N, ...extras}

The whole measurement runs in a CHILD subprocess so a dying accelerator
cannot take the artifact with it: the parent probes the accelerator (a
wedged axon tunnel HANGS jax device init rather than raising), launches the
child on the live platform, and — if the child dies or stalls mid-run (the
axon remote-compile service has been observed to drop AFTER a successful
probe) — relaunches it pinned to CPU at the FULL 1M configs, recording the
probe outcome and fallback reason in the JSON.  The driver always gets one
JSON line, even if both attempts fail.  ``BENCH_FAST=1`` shrinks scales for
CI smoke runs; ``BENCH_PROFILE=dir`` captures a jax.profiler trace of the
timed sections.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _watcher_capture() -> dict | None:
    """A TPU bench result captured earlier by the round's tunnel watcher.

    The axon tunnel is alive only in windows; a watcher loop probes all
    round and runs the full bench the moment the chip revives, saving the
    JSON (with a capture timestamp) to ``.tpu_bench_result.json``.  When
    the driver's own run lands in a dead window and falls back to CPU,
    that capture rides along under this clearly-labelled key — auxiliary
    evidence of on-chip behavior, never a substitute for the ``platform``
    field of the current run."""
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(repo_dir, ".tpu_bench_result.json")
    if not os.path.exists(path):
        # fall back to the newest committed capture: the watcher writes
        # both, and the captures/ copy survives clean-ups of the working
        # file (timestamped names sort chronologically)
        import glob

        committed = sorted(glob.glob(os.path.join(repo_dir, "captures", "tpu_bench_2*.json")))
        if committed:
            path = committed[-1]
    try:
        with open(path) as f:
            cap = json.load(f)
    except (OSError, ValueError):
        # ValueError covers JSONDecodeError AND UnicodeDecodeError from a
        # torn concurrent write by the watcher — never crash the artifact
        return None
    if not (isinstance(cap, dict) and "result" in cap):
        return None
    # staleness guards: a capture from an older round (different code, or
    # simply old) must not read as evidence for the current tree
    try:
        cap["age_hours"] = round((time.time() - os.path.getmtime(path)) / 3600.0, 1)
    except OSError:
        cap["age_hours"] = None
    # a committed captures/ file's mtime is CHECKOUT time, not capture
    # time — prefer the capture's own timestamp when it parses, so a
    # months-old capture cannot ride a fresh clone as fresh evidence
    try:
        import calendar

        t_cap = calendar.timegm(
            time.strptime(cap["captured_at"], "%Y-%m-%dT%H:%M:%SZ")
        )
        cap["age_hours"] = round((time.time() - t_cap) / 3600.0, 1)
    except (KeyError, TypeError, ValueError):
        pass  # keep the mtime-based estimate
    # NOT dirname(path): the committed-capture fallback's path lives in
    # captures/, and `git -C captures/ diff -- ringpop_tpu/sim ...` resolves
    # the pathspecs against captures/ — matching nothing, exit 0 — which
    # would silently mark an old-engine capture engine_unchanged
    repo = repo_dir

    def _git(*args):
        try:
            r = subprocess.run(
                ["git", "-C", repo, *args], capture_output=True, text=True, timeout=10
            )
        except (OSError, subprocess.SubprocessError):
            return None
        return r.stdout.strip() if r.returncode == 0 else None

    head = _git("rev-parse", "HEAD")
    cap["git_head_now"] = head
    cap["same_code"] = (
        bool(head) and cap.get("git_head") == head if cap.get("git_head") else None
    )
    # a capture is only invalidated by changes that touch what it MEASURED:
    # doc/test/host-plane commits after a window must not mark the round's
    # on-chip evidence stale.  The diff runs capture-commit vs the WORKING
    # TREE whenever both heads are known — even at the same head, dirty
    # engine edits invalidate.  Unknown diff (bad head, git failure) stays
    # conservative (treated as engine-changed).  swim/ is included because
    # the sim engines import their measured semantics (member precedence /
    # override rules) from it.
    engine_changed = None
    if cap.get("git_head"):
        # needs only the capture's commit — a failed rev-parse HEAD must
        # not skip the check (diff failure falls back to engine-changed)
        diff = _git(
            "diff", "--name-only", cap["git_head"], "--",
            "ringpop_tpu/sim", "ringpop_tpu/ops", "ringpop_tpu/hashing",
            "ringpop_tpu/parallel", "ringpop_tpu/swim", "bench.py",
            "scripts/tpu_ksweep.py",
        )
        engine_changed = True if diff is None else bool(diff)
    cap["engine_paths_changed_since"] = engine_changed
    cap["stale"] = bool(cap["age_hours"] is not None and cap["age_hours"] > 20.0) or (
        engine_changed is True
    )
    return cap


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        run_bench()
        return

    from ringpop_tpu.util.accel import probe_accelerator

    if os.environ.get("BENCH_FORCE_CPU"):
        # deterministic CPU-only run (tests, smoke): skip the probe and the
        # accelerator attempt entirely instead of relying on a short probe
        # timeout losing the race against a live tunnel
        probe = {"alive": False, "platform": None, "probe_s": 0.0,
                 "reason": "BENCH_FORCE_CPU=1"}
    else:
        # one quick + one patient attempt (a cold tunnel can be slow-but-
        # alive).  Continuous probing is the round watcher's job (see
        # _watcher_capture); burning 330s here, as the round-2 artifact
        # did, buys nothing.
        probe_timeouts = tuple(
            float(t)
            for t in os.environ.get("BENCH_PROBE_TIMEOUTS_S", "75,150").split(",")
        )
        probe = probe_accelerator(timeouts_s=probe_timeouts)
    fallback_reason = None if probe["alive"] else probe["reason"]

    attempt_plan = []
    if probe["alive"]:
        # inherit the environment's platform (axon/tpu); generous-but-bounded
        # timeout so a mid-run wedge still leaves time for the CPU rerun
        attempt_plan.append((None, float(os.environ.get("BENCH_ACCEL_TIMEOUT_S", "1500"))))
    attempt_plan.append(("cpu", float(os.environ.get("BENCH_CPU_TIMEOUT_S", "2700"))))

    # XLA:CPU AOT loader warning: a persistent-cache entry whose embedded
    # target-machine features don't match this machine's.  The fingerprinted
    # cache dir (util/accel.py) should make this unreachable; if it still
    # fires (unknown future environment skew), the entries are evidence of a
    # real mismatch — purge that cache dir and rerun the attempt once so the
    # artifact records a cleanly-compiled run, not 100kB of loader warnings.
    aot_mismatch_texts = (
        "doesn't match the machine type",
        "could lead to execution errors such as SIGILL",
    )
    aot_purged = False

    failures = []
    attempts = list(attempt_plan)
    while attempts:
        platform_pin, timeout_s = attempts.pop(0)
        env = dict(os.environ, BENCH_CHILD="1")
        if platform_pin:
            # BENCH_PIN makes the child call jax.config.update("jax_platforms")
            # — the env var alone is NOT enough: this environment's axon site
            # hook can init the axon client regardless of JAX_PLATFORMS, and
            # hangs doing so when the TPU tunnel is down
            env["JAX_PLATFORMS"] = platform_pin
            env["BENCH_PIN"] = platform_pin
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            failures.append(f"{platform_pin or 'accel'}: timeout after {timeout_s:.0f}s")
            continue
        line = next(
            (ln for ln in reversed(r.stdout.strip().splitlines()) if ln.startswith("{")),
            None,
        )
        if r.returncode == 0 and line:
            try:
                result = json.loads(line)
            except json.JSONDecodeError as e:
                sys.stderr.write(r.stderr or "")
                failures.append(f"{platform_pin or 'accel'}: bad child output: {e}")
                continue
            stale = r.stderr and any(t in r.stderr for t in aot_mismatch_texts)
            if stale and not aot_purged and result.get("compile_cache_dir"):
                import shutil

                shutil.rmtree(result["compile_cache_dir"], ignore_errors=True)
                aot_purged = True
                sys.stderr.write(
                    "bench: AOT target-feature mismatch warning in child stderr; "
                    f"purged stale cache {result['compile_cache_dir']} and rerunning\n"
                )
                attempts.insert(0, (platform_pin, timeout_s))
                continue
            # only the kept attempt's stderr reaches the artifact tail — a
            # discarded (purged) attempt leaves the one-line note above
            if r.stderr:
                sys.stderr.write(r.stderr)
            result["probe"] = probe
            result["aot_cache_purged"] = aot_purged
            result["fallback_reason"] = (
                fallback_reason
                if result.get("platform") == "cpu" and probe["alive"] is False
                else (failures[-1] if failures else fallback_reason)
            )
            if result.get("platform") == "cpu":
                result["tpu_watcher_capture"] = _watcher_capture()
            print(json.dumps(result))
            return
        if r.stderr:
            sys.stderr.write(r.stderr)
        tail = (r.stderr or "").strip().splitlines()[-3:]
        failures.append(
            f"{platform_pin or 'accel'}: rc={r.returncode} {' | '.join(tail)[-300:]}"
        )
        # a stale AOT entry can also CRASH the child (the SIGILL the warning
        # text is about) — no JSON to read a cache dir from, so purge the
        # whole cache base and retry the attempt once
        if (
            r.stderr
            and any(t in r.stderr for t in aot_mismatch_texts)
            and not aot_purged
        ):
            import shutil

            # same default base as configure_compile_cache (util/accel.py)
            base = os.environ.get("BENCH_COMPILE_CACHE") or os.environ.get(
                "RINGPOP_TPU_COMPILE_CACHE"
            ) or os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
            shutil.rmtree(base, ignore_errors=True)
            aot_purged = True
            sys.stderr.write(
                "bench: AOT target-feature mismatch in failed child stderr; "
                f"purged cache base {base} and rerunning\n"
            )
            attempts.insert(0, (platform_pin, timeout_s))

    # both attempts failed — still emit one diagnostic JSON line.
    # vs_baseline is null (not 0.0): null means "no comparable number",
    # and this is the one path where a watcher capture may be the only
    # on-chip evidence, so it rides along here too.
    print(
        json.dumps(
            {
                "metric": "swim_lifecycle_detect",
                "value": None,
                "unit": "s",
                "vs_baseline": None,
                "ok": False,
                "probe": probe,
                "failures": failures,
                "tpu_watcher_capture": _watcher_capture(),
            }
        )
    )


def _trimmed_batch_median(samples: list, batches: int = 8) -> float:
    """Trimmed median-of-batches: split ``samples`` (in arrival order)
    into ``batches`` contiguous batches, take each batch's median, drop
    the highest and lowest batch medians, mean the rest.

    Why: a single p50 over N mixed samples is hostage to WHICH scheduler
    regime the run landed in on a busy 2-core container — 200 fast-mode
    reps vs 1000 full-mode reps disagreed by far more than the effect
    being gated.  Batch medians kill per-sample outliers; trimming kills
    whole displaced batches (a noisy-neighbor burst); the mean of the
    surviving medians is stable enough that fast and full mode agree
    within noise (pinned by test_bench_probe)."""
    n = len(samples)
    if n == 0:
        raise ValueError("no samples")
    batches = max(1, min(batches, n))
    size = n / batches
    meds = []
    for b in range(batches):
        chunk = sorted(samples[int(b * size): int((b + 1) * size)])
        if chunk:
            meds.append(chunk[len(chunk) // 2])
    meds.sort()
    if len(meds) > 2:
        meds = meds[1:-1]  # drop the one high + one low batch
    return sum(meds) / len(meds)


def _transport_rtt_us(reps: int, codec: str = "msgpack") -> dict:
    """Small-RPC echo round-trip over the folded TCP channel — r23
    latency-tiered path: sync handler dispatched on the link's reader
    thread, ``call_sync`` inline completion (zero event-loop hops end to
    end).  In-process server, one link, spin-then-park readers.

    Returns ``{"p50_us", "p99_us"}``; p50 is a trimmed median-of-batches
    (fast-mode undersampling fix — see ``_trimmed_batch_median``)."""
    from ringpop_tpu.net import TCPChannel

    server = TCPChannel(app="bench", codec=codec)

    def echo(body: dict, headers: dict) -> dict:
        return body

    server.register("bench", "/echo", echo)
    client = TCPChannel(app="bench-cli", codec=codec)
    try:
        addr = server.listen_sync("127.0.0.1", 0)
        payload = {"x": 7, "k": "bench"}
        for _ in range(20):  # warm the link + demux path
            client.call_sync(addr, "bench", "/echo", payload, timeout=10)
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            client.call_sync(addr, "bench", "/echo", payload, timeout=10)
            samples.append(time.perf_counter() - t0)
    finally:
        client.close_sync()
        server.close_sync()
    p50 = _trimmed_batch_median(samples) * 1e6
    ordered = sorted(samples)
    p99 = ordered[min(len(ordered) - 1, (len(ordered) * 99) // 100)] * 1e6
    return {"p50_us": p50, "p99_us": p99}


def _transport_bulk_mbps(reps: int = 12, size: int = 256 * 1024) -> float:
    """Bulk-body throughput (MB/s, msgpack) over the same channel path —
    one ``size``-byte binary blob echoed per call; measures the vectored
    send + pooled receive arena path, not the small-frame tiers."""
    from ringpop_tpu.net import TCPChannel

    server = TCPChannel(app="bench", codec="msgpack")

    def echo(body: dict, headers: dict) -> dict:
        return body

    server.register("bench", "/echo", echo)
    client = TCPChannel(app="bench-cli", codec="msgpack")
    try:
        addr = server.listen_sync("127.0.0.1", 0)
        payload = {"blob": b"\xa5" * size}
        for _ in range(3):
            client.call_sync(addr, "bench", "/echo", payload, timeout=30)
        t0 = time.perf_counter()
        for _ in range(reps):
            client.call_sync(addr, "bench", "/echo", payload, timeout=30)
        dt = time.perf_counter() - t0
    finally:
        client.close_sync()
        server.close_sync()
    # bytes cross the wire twice per call (request + echoed response)
    return (2 * reps * size) / dt / 1e6


def run_bench() -> None:
    import jax

    pin = os.environ.get("BENCH_PIN")
    if pin:
        try:
            jax.config.update("jax_platforms", pin)
        except RuntimeError:
            pass  # backend already initialized

    import numpy as np

    # persistent XLA compilation cache: the 1M-node lifecycle step is a big
    # program (minutes of single-threaded XLA CPU compile); warming the cache
    # once makes every later bench run on the same machine compile-free.
    # The cache lives in a per-platform-fingerprint SUBDIR (compile_cache_dir):
    # a cached XLA:CPU kernel compiled for another container's CPU features
    # can SIGILL here, so heterogeneous containers must never share entries.
    from ringpop_tpu.util.accel import configure_compile_cache

    # BENCH_COMPILE_CACHE overrides; otherwise the shared default base
    cache_dir = configure_compile_cache(os.environ.get("BENCH_COMPILE_CACHE"))

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    fast = bool(os.environ.get("BENCH_FAST"))

    # -- scales -------------------------------------------------------------
    # both delta convergence AND the lifecycle headline run the full 1M
    # configs on every platform: the bit-packed engine (sim/packbits.py)
    # made the 1M lifecycle tick single-core-affordable, so the CPU
    # fallback measures the same dynamics at the same scale as the accel
    # path — vs_baseline is honest everywhere.
    if fast:
        n_delta, k_delta = 50_000, 64
        n_life, k_life, victims_frac = 20_000, 64, 0.00025
        life_scale_reason = "BENCH_FAST=1 smoke scales"
    elif on_accel:
        n_delta, k_delta = 1_000_000, 128
        # k=256 rumor slots: with 1000 concurrent victims the K-slot table
        # saturates and detection ticks scale ~1/K (measured 448/224/128
        # ticks at k=64/128/256, 100k nodes, same victim fraction); the
        # reference's piggyback buffer is an unbounded map, so more capacity
        # is *closer* to its semantics, at [N,K] memory the chip easily holds
        n_life, k_life, victims_frac = 1_000_000, 256, 0.001
        life_scale_reason = None
    else:
        n_delta, k_delta = 1_000_000, 128
        # FULL headline scale on the CPU fallback too (round-3): the
        # bit-packed engine runs the 1M x 256 tick in ~2.5-3 s single-core
        # (was ~31 s), so the same config the accel path measures — 1000
        # victims, k=256 — detects in ~130 ticks ≈ 310-400 s wall, well
        # inside the bench budget.  No more scale-reduced fallback metric.
        n_life, k_life, victims_frac = 1_000_000, 256, 0.001
        life_scale_reason = None

    # -- headline: lifecycle failure detection ------------------------------
    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults, DeltaSim, init_state

    rng = np.random.default_rng(0)
    n_victims = max(1, int(n_life * victims_frac))
    victims = np.sort(rng.choice(n_life, size=n_victims, replace=False))
    up = np.ones(n_life, bool)
    up[victims] = False
    faults = DeltaFaults(up=jax.numpy.asarray(up))

    check_every = 32
    t_c0 = time.perf_counter()
    life = lifecycle.LifecycleSim(n=n_life, k=k_life, seed=0)
    # warm exactly the program the timed section runs — the on-device
    # while_loop (blocks + detection check in ONE dispatch; round-1 traces
    # showed the host-side detection walk was ~90% of wall-clock at 1M) —
    # then restart from a fresh state
    # max_ticks=0 dispatches each device loop once with 0 blocks: the full
    # program (blocks + predicate + early exit) compiles and the predicate
    # executes, without paying a 32-tick block (~80 s of warmup at 1M on
    # the CPU fallback) just to warm it
    life.run_until_detected(
        victims, faults, max_ticks=0, check_every=check_every
    )
    life.run_until_converged(faults, max_ticks=0, check_every=check_every)
    jax.block_until_ready(life.state.learned)
    life_warmup_s = time.perf_counter() - t_c0

    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        # a narrow kernel-level window: one already-warmed steady-state
        # dispatch (same static shape as the warmup, so no compile lands
        # inside the trace)
        life.state = lifecycle.init_state(life.params, seed=0)
        jax.profiler.start_trace(profile_dir)
        life.run_until_detected(
            victims, faults, max_ticks=check_every, check_every=check_every
        )
        jax.block_until_ready(life.state.learned)
        jax.profiler.stop_trace()
    life.state = lifecycle.init_state(life.params, seed=0)

    t0 = time.perf_counter()
    life_ticks, life_ok = life.run_until_detected(
        victims,
        faults,
        max_ticks=4096,
        check_every=check_every,
        time_budget_s=float(os.environ.get("BENCH_TIME_BUDGET_S", "900")),
        blocks_per_dispatch=8,
    )
    jax.block_until_ready(life.state.learned)
    life_s = time.perf_counter() - t0

    # -- headline companion: literal convergence (BASELINE.md north-star
    # wording) — continue from the detected state until NO changes remain in
    # flight and every live view checksum agrees (the reference's
    # waitForConvergence criterion, swim/test_utils.go:164-199)
    t_cv = time.perf_counter()
    cv_ticks, cv_ok = life.run_until_converged(
        faults,
        max_ticks=4096,
        check_every=check_every,
        blocks_per_dispatch=8,
        time_budget_s=float(os.environ.get("BENCH_CONVERGE_BUDGET_S", "900")),
    )
    jax.block_until_ready(life.state.learned)
    converge_s = time.perf_counter() - t_cv

    # -- secondary: order-invariant view checksum at headline scale ---------
    # (SURVEY §7 hard-part #5: the sim-plane checksum is a sum of mixed
    # member hashes — no sort, O(N·K), one jit)
    cs = lifecycle.view_checksums(life.state, faults)
    jax.block_until_ready(cs)  # compile
    t_cs = time.perf_counter()
    jax.block_until_ready(lifecycle.view_checksums(life.state, faults))
    checksum_s = time.perf_counter() - t_cs

    # -- secondary: delta rumor convergence ---------------------------------
    # the device loop goes through the AOT warm-start front door
    # (util/aot.py): first-ever run on this toolchain exports + serializes
    # the compiled loop, every later bench deserializes it — no retrace,
    # no relowering — and delta_cache_hit below is a measured fact, not a
    # timing inference.  Any front-door failure falls back to the plain
    # jit path (delta_aot_error says why).
    import functools

    from jax import numpy as jnp

    from ringpop_tpu.sim import delta as _delta
    from ringpop_tpu.util import aot

    sim = DeltaSim(n=n_delta, k=k_delta, seed=0)
    check_every_delta = 8
    dfaults = DeltaFaults()
    t_c1 = time.perf_counter()
    delta_run, delta_aot = aot.load_or_compile(
        functools.partial(_delta._run_until_converged_device, sim.params),
        sim.state,
        dfaults,
        dyn_kw={"max_blocks": jnp.int32(0)},
        tag=f"bench-delta-n{n_delta}k{k_delta}",
        static_kw={"block_ticks": check_every_delta},
        statics=(repr(sim.params),),
    )
    # warm the exact device-loop program the timed run uses (0 blocks:
    # one entry-predicate eval, no block stepping — same trick as the
    # lifecycle warmup above)
    jax.block_until_ready(delta_run(sim.state, dfaults, max_blocks=jnp.int32(0)))
    delta_compile_s = time.perf_counter() - t_c1

    t1 = time.perf_counter()
    dstate, d_blocks, d_ok = delta_run(
        init_state(sim.params, seed=1), dfaults,
        max_blocks=jnp.int32(-(-4096 // check_every_delta)),
    )
    jax.block_until_ready(dstate.learned)
    delta_s = time.perf_counter() - t1
    d_ticks, d_ok = int(d_blocks) * check_every_delta, bool(d_ok)

    # -- secondary: batched ring lookup qps ---------------------------------
    from ringpop_tpu.ops.ring_ops import build_ring_tokens, ring_lookup

    n_servers = 4096 if not fast else 512
    servers = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(n_servers)]
    tokens, owners = build_ring_tokens(servers, 256)
    batch = 1_000_000 if not fast else 100_000
    hashes = jax.numpy.asarray(
        np.random.default_rng(0).integers(0, 2**32, size=batch, dtype=np.uint32)
    )
    # 10 distinct batches inside ONE jitted loop: measures sustained lookup
    # throughput, not per-dispatch latency (which, through the axon network
    # tunnel, would dominate and measure the tunnel instead of the ring op);
    # the sum forces every row of every gather to materialize
    @jax.jit
    def _qps_loop(tokens, owners, hashes):
        def body(i, acc):
            out = ring_lookup(tokens, owners, hashes + i.astype(hashes.dtype))
            # uint32 accumulation END TO END: the sum only defeats dead-code
            # elimination, and 1M owner indices (mean ~2048) overflow int32
            # inside the reduction itself, so cast before summing
            return acc + out.astype(jax.numpy.uint32).sum()
        return jax.lax.fori_loop(0, 10, body, jax.numpy.uint32(0))

    jax.block_until_ready(_qps_loop(tokens, owners, hashes))  # compile
    t_r = time.perf_counter()
    jax.block_until_ready(_qps_loop(tokens, owners, hashes))
    ring_qps = batch * 10 / (time.perf_counter() - t_r)

    # -- the serve tier's resident program (r13, PERF.md "serve the ring"):
    # the same ring at FIXED capacity with traced live count + generation —
    # the fused dispatch the shared serving collector amortizes across
    # frontend processes.  Measured with the same jitted-loop methodology
    # so the headline record prices the padding + generation fusion the
    # serving path actually pays.
    from ringpop_tpu.serve.state import device_ring, serve_lookup_fused

    sring = device_ring(
        np.asarray(tokens), np.asarray(owners), 2 * int(tokens.shape[0])
    )

    @jax.jit
    def _serve_loop(ring, hashes):
        def body(i, acc):
            out = serve_lookup_fused(ring, hashes + i.astype(hashes.dtype))
            return acc + out.astype(jax.numpy.uint32).sum()

        return jax.lax.fori_loop(0, 10, body, jax.numpy.uint32(0))

    jax.block_until_ready(_serve_loop(sring, hashes))  # compile
    t_r = time.perf_counter()
    jax.block_until_ready(_serve_loop(sring, hashes))
    serve_qps = batch * 10 / (time.perf_counter() - t_r)

    # -- secondary: transport RTT (r21 fold, r23 latency tiers) -------------
    # the channel's small-RPC p50/p99 vs the retired asyncio channel's
    # captured baselines (same probe methodology, same container class —
    # PERF.md r21/r23).  r23 measures the tiered path (reader-thread
    # dispatch + inline completion) for BOTH codecs; the acceptance bar
    # is p50 at or below the pre-fold asyncio numbers.
    transport_rtt_baseline = 82.1  # pre-fold asyncio channel, msgpack p50 µs
    transport_rtt_json_baseline = 104.0  # pre-fold asyncio channel, json p50 µs
    transport_bulk_baseline = 981.0  # r21 bulk msgpack MB/s (PERF.md r21)
    rtt_reps = 200 if fast else 1000
    try:
        _rtt_mp = _transport_rtt_us(rtt_reps, codec="msgpack")
        _rtt_js = _transport_rtt_us(rtt_reps, codec="json")
        transport_rtt = round(_rtt_mp["p50_us"], 1)
        transport_rtt_p99 = round(_rtt_mp["p99_us"], 1)
        transport_rtt_json = round(_rtt_js["p50_us"], 1)
        transport_rtt_json_p99 = round(_rtt_js["p99_us"], 1)
        transport_bulk = round(_transport_bulk_mbps(6 if fast else 12), 1)
        transport_rtt_err = None
    except Exception as e:  # never let the side probe kill the headline
        transport_rtt = transport_rtt_p99 = None
        transport_rtt_json = transport_rtt_json_p99 = transport_bulk = None
        transport_rtt_err = f"{type(e).__name__}: {e}"

    baseline_s = 60.0  # BASELINE.json north star
    baseline_n = 1_000_000
    # vs_baseline is only honest when the metric's scale matches the
    # baseline's (1M nodes): a 100k detection time divided into the 1M
    # target would *shrink* at true scale.  At mismatched scale the ratio
    # moves to vs_baseline_at_reduced_scale and vs_baseline is null.
    at_scale = n_life == baseline_n
    ratio = round(baseline_s / life_s, 2) if life_s > 0 else 0.0
    result = {
        "metric": f"swim_lifecycle_detect_n{n_life}",
        "value": round(life_s, 4),
        "unit": "s",
        "vs_baseline": ratio if at_scale else None,
        "vs_baseline_at_reduced_scale": None if at_scale else ratio,
        "detected": life_ok,
        "ticks": life_ticks,
        # the BASELINE rebuild metric names "simulated SWIM ticks/sec"
        # explicitly — protocol ticks advanced per wall second
        "ticks_per_s": round(life_ticks / life_s, 3) if life_s > 0 else None,
        "sim_time_s": round(life_ticks * 0.2, 1),  # 200ms protocol periods
        "n_nodes": n_life,
        "n_rumor_slots": k_life,
        "n_victims": n_victims,
        "warmup_s": round(life_warmup_s, 2),  # detect+converge compiles + entry checks
        "lifecycle_scale_reason": life_scale_reason,
        # literal north-star convergence, continued from the detected state:
        # wall seconds and extra ticks until quiescence + checksum agreement
        "converge_s": round(converge_s, 4),
        "converge_extra_ticks": cv_ticks,
        "converge_total_ticks": life_ticks + cv_ticks,
        "converged": cv_ok,
        "converge_total_s": round(life_s + converge_s, 4),
        "delta_converge_s": round(delta_s, 4),
        "delta_n_nodes": n_delta,
        "delta_n_rumors": k_delta,
        "delta_ticks": d_ticks,
        "delta_converged": d_ok,
        # same scale-honesty rule as the headline: a ratio against the 1M
        # baseline only when delta actually ran at 1M
        "delta_vs_baseline": (
            (round(baseline_s / delta_s, 2) if delta_s > 0 else 0.0)
            if n_delta == baseline_n
            else None
        ),
        "delta_compile_s": round(delta_compile_s, 2),
        # the AOT front door's measured facts (util/aot.py): was the
        # serialized executable reloaded (warm) or compiled fresh (cold),
        # and how long the load-or-compile step itself took
        "delta_cache_hit": delta_aot["cache_hit"],
        "delta_aot_compile_s": delta_aot["compile_s"],
        "delta_aot_error": delta_aot["error"],
        "ring_lookup_qps": round(ring_qps, 0),
        "serve_lookup_qps": round(serve_qps, 0),
        "transport_rtt_us": transport_rtt,
        "transport_rtt_p99_us": transport_rtt_p99,
        "transport_rtt_baseline_us": transport_rtt_baseline,
        "transport_rtt_json_us": transport_rtt_json,
        "transport_rtt_json_p99_us": transport_rtt_json_p99,
        "transport_rtt_json_baseline_us": transport_rtt_json_baseline,
        "transport_bulk_mbps": transport_bulk,
        "transport_bulk_baseline_mbps": transport_bulk_baseline,
        "transport_rtt_error": transport_rtt_err,
        "view_checksum_s": round(checksum_s, 4),
        "platform": platform,
        # lets the parent purge exactly this dir if the XLA:CPU AOT loader
        # reported a target-feature mismatch while loading cached entries
        "compile_cache_dir": cache_dir,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
