"""Example: one full failure-detection study on the sim plane.

The lifecycle engine's workflow end-to-end, CPU-sized (runs in seconds):

1. crash 1% of a 4096-node simulated cluster;
2. run until every live observer believes every victim faulty — the
   detection loop and its test run on-device (one dispatch per few blocks);
3. keep running until quiescence: no rumors in flight and every live
   node's order-invariant view checksum agrees (the reference's
   waitForConvergence criterion, ``swim/test_utils.go:164-199``);
4. snapshot the converged cluster and prove the restore is bit-exact —
   a capability the soft-state reference cannot offer.

    python examples/failure_study.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if not os.environ.get("KEEP_PLATFORM"):
    # this example is CPU-sized; pin before backend init (see PERF.md)
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from ringpop_tpu.sim import lifecycle
from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.sim.snapshot import load_state, save_state


def main():
    n = 4096
    sim = lifecycle.LifecycleSim(n=n, k=64, seed=0, suspect_ticks=25)

    rng = np.random.default_rng(0)
    victims = np.sort(rng.choice(n, size=n // 100, replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    faults = DeltaFaults(up=jax.numpy.asarray(up), drop_rate=0.02)
    print(f"crashing {len(victims)} of {n} nodes (2% packet loss)...")

    ticks, ok = sim.run_until_detected(victims, faults, max_ticks=2000, check_every=16)
    sim_s = ticks * sim.params.tick_ms / 1000
    print(f"  detected by every live observer: {ok} after {ticks} ticks "
          f"({sim_s:.1f}s of simulated protocol time)")

    q_ticks, q_ok = sim.run_until_converged(faults, max_ticks=2000, check_every=16)
    print(f"  quiescent (rumors drained, all live view checksums agree): "
          f"{q_ok} after {q_ticks} more ticks")

    if q_ok:
        cs = np.asarray(lifecycle.view_checksums(sim.state, faults))
        print(f"  shared live-view checksum: 0x{cs[up][0]:08x}")
    else:
        print("  (no shared checksum — convergence budget exhausted)")

    path = "/tmp/failure_study_snapshot.npz"
    save_state(path, sim.state)
    resumed = load_state(path, lifecycle.LifecycleState)
    same = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(resumed, sim.state)
    )
    print(f"  snapshot -> restore bit-exact: {same} ({path})")


if __name__ == "__main__":
    main()
