"""Example: the keyed ServiceAdapter (codegen-free adapter, parity with the
reference's generated thrift adapters, ``examples/ping-thrift-gen/main.go:48-96``).

A sharded in-memory counter service: each user's counter lives on the ring
owner for that user; requests landing anywhere are routed exactly once.

    python examples/keyed_service.py
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ringpop_tpu.adapter import ServiceAdapter
from ringpop_tpu.net import TCPChannel
from ringpop_tpu.options import Options
from ringpop_tpu.ringpop import Ringpop
from ringpop_tpu.swim.node import BootstrapOptions

APP = "counter-app"


async def main():
    channels, rps, adapters, stores = [], [], [], []
    for _ in range(3):
        ch = TCPChannel(app=APP)
        await ch.listen()
        channels.append(ch)
        rps.append(Ringpop(APP, ch, Options()))
    hosts = [ch.hostport for ch in channels]

    for rp in rps:
        store = {}
        stores.append(store)

        async def incr(body, store=store, rp=rp):
            user = body["user"]
            store[user] = store.get(user, 0) + body.get("by", 1)
            return {"user": user, "value": store[user], "stored_on": rp.who_am_i()}

        adapters.append(
            ServiceAdapter(
                rp, rp.channel, APP, endpoints={"/counter/incr": (lambda b: b["user"], incr)}
            )
        )

    await asyncio.gather(
        *(rp.bootstrap(BootstrapOptions(discover_provider=hosts)) for rp in rps)
    )

    client = TCPChannel(app=APP)
    for i, user in enumerate(["ada", "grace", "alan", "ada", "ada", "grace"]):
        entry = hosts[i % 3]  # spray requests across entry points
        res = await client.call(entry, APP, "/counter/incr", {"user": user}, timeout=5.0)
        print(f"incr {user!r:8} via {entry} -> value={res['value']} on {res['stored_on']}")

    # each user's counter lives on exactly one node
    for user in ("ada", "grace", "alan"):
        holders = [i for i, s in enumerate(stores) if user in s]
        owner = rps[0].lookup(user)
        print(f"{user}: held by node(s) {holders}, ring owner {owner}")

    for rp in rps:
        rp.destroy()
    for ch in channels + [client]:
        await ch.close()


if __name__ == "__main__":
    asyncio.run(main())
