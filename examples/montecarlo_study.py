"""Example: a Monte-Carlo protocol study on the sim plane.

Question a ringpop operator actually asks: "if two nodes crash in a
1024-node cluster, how long until every live member knows?"  The reference
answers by running process clusters repeatedly; here B seeded replicas of
the whole cluster run as ONE compiled program (`[B, N, K]` arrays,
``ringpop_tpu/sim/montecarlo.py``), so the distribution comes from a single
sweep — and the same code scales the study to accelerator-sized clusters.

    python examples/montecarlo_study.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if not os.environ.get("KEEP_PLATFORM"):
    # this example is CPU-sized; pin before backend init (see PERF.md)
    jax.config.update("jax_platforms", "cpu")

from ringpop_tpu.sim import detection_latency_distribution
from ringpop_tpu.sim.montecarlo import detection_latency_under_churn


def main():
    n, crashes, replicas = 1024, 2, 16
    victims = [7, 613]
    print(f"crashing {crashes} of {n} nodes across {replicas} seeded replicas...")
    out = detection_latency_distribution(
        n=n,
        seeds=range(replicas),
        victims=victims,
        k=32,
        max_ticks=1024,
    )
    print(f"replicas detected: {out['detected']}/{out['n_replicas']}")
    if out["ticks_median"] is None:
        print("no replica reached full detection within the tick budget")
        return
    print(
        f"detection latency: median {out['ticks_median']:.0f} ticks "
        f"({out['sim_s_median']:.1f}s of simulated time at 200ms periods), "
        f"p90 {out['ticks_p90']:.0f}, max {out['ticks_max']:.0f}"
    )

    # follow-up question: how does that latency degrade while the cluster
    # is ALSO digesting unrelated churn?  Replica b crashes ~b/B of
    # churn_max extra background nodes (a [B, N] fault-mask batch — the
    # fault pytree vmaps alongside the state), detection still judged on
    # the same two victims.  The dose-response curve is the answer.
    churn_max = n // 16
    print(f"\nsame study under background churn (0..{churn_max} extra crashes):")
    out = detection_latency_under_churn(
        n=n,
        seeds=range(replicas),
        victims=victims,
        churn_max=churn_max,
        k=32,
        max_ticks=2048,
    )
    print(f"replicas detected: {out['detected']}/{out['n_replicas']}")
    detected_ticks = [t for _, t in out["churn_ticks"] if t is not None]
    scale = max(detected_ticks) if detected_ticks else 1
    for churn, ticks in out["churn_ticks"]:
        # normalize to the slowest replica so the chart fits a terminal
        bar = "" if ticks is None else "#" * max(1, round(ticks / scale * 50))
        label = "never" if ticks is None else f"{ticks:4d} ticks"
        print(f"  churn {churn:4d}: {label} {bar}")


if __name__ == "__main__":
    main()
