"""Example: manual keyed routing with handle_or_forward
(parity: reference ``examples/ping-json/main.go:75-100``).

Starts a 3-node cluster in one process over real TCP, registers a /ping
endpoint on each node, and routes keyed requests to their owners.

    python examples/ping_json.py
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ringpop_tpu.net import TCPChannel
from ringpop_tpu.options import Options
from ringpop_tpu.ringpop import Ringpop
from ringpop_tpu.swim.node import BootstrapOptions

APP = "ping-app"


async def main():
    # start three nodes
    channels = []
    rps = []
    for _ in range(3):
        ch = TCPChannel(app=APP)
        await ch.listen()
        channels.append(ch)
        rps.append(Ringpop(APP, ch, Options()))
    hosts = [ch.hostport for ch in channels]

    # each node's /ping handler: handle locally or forward to the owner
    for rp in rps:
        async def ping(body, headers, rp=rp):
            key = body.get("key", "")
            handled, res = await rp.handle_or_forward(
                key, body, APP, "/ping", headers=headers
            )
            if handled:
                return {"from": rp.who_am_i(), "key": key, "pheader": headers.get("p")}
            return res

        rp.channel.register(APP, "/ping", ping)

    await asyncio.gather(
        *(rp.bootstrap(BootstrapOptions(discover_provider=hosts)) for rp in rps)
    )
    print("cluster up:", hosts)

    # send keyed requests to an arbitrary node; they land on the owner
    client = TCPChannel(app=APP)
    for key in ("alpha", "beta", "gamma", "delta", "epsilon"):
        res = await client.call(
            hosts[0], APP, "/ping", {"key": key}, headers={"p": "v"}, timeout=5.0
        )
        owner = rps[0].lookup(key)
        print(f"key={key!r:10} owner={owner}  served-by={res['from']}  ok={res['from'] == owner}")

    for rp in rps:
        rp.destroy()
    for ch in channels + [client]:
        await ch.close()


if __name__ == "__main__":
    asyncio.run(main())
