"""ringpop_tpu — a TPU-native application-layer sharding framework.

A ground-up rebuild of the capabilities of ringpop-go (reference:
/root/reference) designed TPU-first:

* **Host plane** — a real coordination library: SWIM gossip membership,
  consistent hash ring, request forwarding, routing and replication over an
  asyncio JSON-over-TCP transport.  Mirrors the reference public API surface
  (``ringpop.Interface``, reference ``ringpop.go:48-63``).

* **Sim plane** — the entire simulated cluster as one pytree of dense JAX
  arrays; a single jitted/vmapped ``protocol_step`` advances every node at
  once, sharded across a TPU mesh with ``shard_map``.  This replaces the
  reference's goroutine-per-node concurrency (reference ``swim/gossip.go:151``)
  with data-parallel SPMD over the node axis.

Both planes share one semantics core (``ringpop_tpu.swim.member``): the SWIM
override/precedence rules are written once as pure functions operating on
scalars *or* arrays, which is how host and sim stay bit-identical.
"""

from ringpop_tpu.version import __version__

_FACADE_EXPORTS = {
    "Ringpop": "ringpop_tpu.ringpop",
    "Interface": "ringpop_tpu.ringpop",
    "Options": "ringpop_tpu.options",
    "RingpopError": "ringpop_tpu.errors",
    "NotBootstrappedError": "ringpop_tpu.errors",
    "EphemeralIdentityError": "ringpop_tpu.errors",
    "InvalidStateError": "ringpop_tpu.errors",
}


def __getattr__(name):
    # lazy so substrate submodules import without pulling the full facade
    mod = _FACADE_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod), name)

__all__ = [
    "__version__",
    "Ringpop",
    "Interface",
    "Options",
    "RingpopError",
    "NotBootstrappedError",
    "EphemeralIdentityError",
    "InvalidStateError",
]
