"""Keyed service adapter — the codegen replacement.

Parity: the reference ships a thrift-gen template (``ringpop.thrift-gen``)
that generates a per-service adapter routing each endpoint by a user-supplied
``Key(ctx, req)`` closure, handling locally when the node owns the key and
forwarding otherwise, with the forwarded-header loop guard (generated
example: ``examples/ping-thrift-gen/gen-go/ping/ringpop-ping.go:98-118``).

Python needs no codegen: :class:`ServiceAdapter` wraps any service at
runtime.  Register ``endpoint -> (key_fn, handler)`` pairs; calls landing on
a non-owner are transparently proxied to the owner, exactly once.
"""

from __future__ import annotations

from typing import Awaitable, Callable, Optional

from ringpop_tpu.forward import Options as ForwardOptions, has_forwarded_header

KeyFn = Callable[[dict], str]
HandlerFn = Callable[[dict], Awaitable[dict]]


class EndpointConfig:
    """(parity: the generated ``<Svc>Configuration`` Key closures)"""

    def __init__(self, key_fn: KeyFn, handler: HandlerFn):
        self.key_fn = key_fn
        self.handler = handler


class ServiceAdapter:
    def __init__(
        self,
        ringpop,
        channel,
        service: str,
        endpoints: Optional[dict[str, tuple[KeyFn, HandlerFn]]] = None,
        forward_options: Optional[ForwardOptions] = None,
    ):
        self.ringpop = ringpop
        self.channel = channel
        self.service = service
        self.forward_options = forward_options
        self._endpoints: dict[str, EndpointConfig] = {}
        for ep, (key_fn, handler) in (endpoints or {}).items():
            self.register(ep, key_fn, handler)

    def register(self, endpoint: str, key_fn: KeyFn, handler: HandlerFn) -> None:
        cfg = EndpointConfig(key_fn, handler)
        self._endpoints[endpoint] = cfg

        async def wire_handler(body, headers, _cfg=cfg, _ep=endpoint):
            # loop guard: a request forwarded to us is always handled locally
            # (generated adapter behavior, ringpop-ping.go:100)
            if has_forwarded_header(headers):
                return await _cfg.handler(body)
            key = _cfg.key_fn(body)
            handled, res = await self.ringpop.handle_or_forward(
                key, body, self.service, _ep, options=self.forward_options, headers=headers
            )
            if handled:
                return await _cfg.handler(body)
            return res

        self.channel.register(self.service, endpoint, wire_handler)

    async def call(self, endpoint: str, body: dict, timeout: float = 3.0) -> dict:
        """Client-side convenience: route a request to the key's owner
        directly (local fast path, remote call otherwise)."""
        cfg = self._endpoints[endpoint]
        key = cfg.key_fn(body)
        dest = self.ringpop.lookup(key)
        if dest == self.ringpop.who_am_i():
            return await cfg.handler(body)
        return await self.channel.call(dest, self.service, endpoint, body, timeout=timeout)


def keyed(service_adapter: ServiceAdapter, endpoint: str, key: KeyFn):
    """Decorator sugar:

    >>> @keyed(adapter, "/ping", key=lambda body: body["user"])
    ... async def ping(body): return {"pong": True}
    """

    def deco(handler: HandlerFn) -> HandlerFn:
        service_adapter.register(endpoint, key, handler)
        return handler

    return deco
