"""jaxlint — the repo's two-plane static-analysis suite.

Every hard-won invariant of the r6–r8 rounds — bit-identical sharded vs.
unsharded execution, zero-collective peer choice, partition-invariant
counter RNG, no host sync inside jitted bodies, phase-attributable
collectives — is a fact of the *traced program*, checkable before a
single tick runs.  Until this package, each was enforced only
dynamically (paired runs, budget ratchets), so a regression surfaced
ticks after it was introduced.  The suite checks them at lint time:

* **Plane 1 — Python AST** (``astlint``): codebase-specific source
  hazards — raw threefry draws bypassing ``sim/prng.py``'s counter RNG
  in sharded-capable paths, traced-shift rolls outside
  ``parallel/shift.shard_roll``, host-sync constructs inside jitted
  bodies, 64-bit dtype promotion, missing protocol-phase
  ``jax.named_scope`` coverage.
* **Plane 2 — jaxpr/HLO** (``trace_checks``): traces the public jitted
  entry points (lifecycle step, delta step, detect walk, shard_roll
  exchange, telemetry fetch) dense AND under the 8-way virtual mesh and
  statically asserts no f64, no host callbacks, donation actually
  aliased, collectives confined to the phases the r8 budget allows
  (peer-choice = zero), and structural equality of the sharded vs.
  unsharded traces modulo sharding ops — the static shadow of the
  bit-identity certificates.

Rules are individually waivable via the checked-in
``analysis/waivers.toml`` (mandatory justification strings; see
``waivers``).  ``scripts/jaxlint.py`` drives both planes; ``make lint``
runs it and joins ``make test``.  Rule catalog: ``ANALYSIS.md``.
"""

from ringpop_tpu.analysis.findings import Finding  # noqa: F401
