"""Plane 1 of jaxlint: Python-AST lint rules for codebase-specific hazards.

Each rule guards an invariant a previous PR bought with measurements and
paired-run certificates (rule catalog with the full story: ANALYSIS.md):

* **RPA101 raw-threefry** — a raw ``jax.random.*`` draw in a
  sharded-capable module with no counter-RNG dispatch in the enclosing
  function.  Threefry is not partitionable: under GSPMD the draw either
  materializes replicated (the pre-r8 ~12 MB/chip/tick peer-choice
  all-reduce) or silently generates DIFFERENT lanes sharded vs unsharded
  (the r7 telemetry finding).  Engines must route draws through
  ``sim/prng.py``'s partition-invariant counter RNG or gate the threefry
  family behind the ``rng`` param dispatch.
* **RPA102 traced-roll** — ``jnp.roll`` (or ``np.roll`` on device
  values) outside ``parallel/shift.py``.  A traced-shift roll lowers to
  a slice-select chain XLA:CPU re-derives per consuming element, and the
  SPMD partitioner can only serve it with a plane-sized all-gather; the
  blessed lowerings are materialized-index gathers and
  ``parallel/shift.shard_roll``.
* **RPA103 host-sync-in-jit** — ``.item()``/``.tolist()``,
  ``jax.device_get``, host-numpy coercions (``np.asarray`` & friends),
  or ``int()``/``float()``/``bool()`` casts of non-literals inside
  functions reachable from a ``jax.jit`` root.  Each is a concretization
  fence: at best a trace-time error on an untested branch, at worst a
  silent device→host sync serializing the dispatch pipeline.
* **RPA104 x64-promotion** — 64-bit jnp dtypes, ``dtype="float64"``
  strings, or ``jax_enable_x64`` anywhere in device code.  The sim runs
  x64-disabled, so ``jnp.int64`` silently produces int32 (a real
  overflow hazard this rule's first repo run caught in
  ``ops/ring_ops.py``), and enabling x64 would double the packed planes'
  HBM traffic.
* **RPA105 phase-scope** — ``jax.named_scope`` strings must come from
  the canonical phase vocabulary (``analysis/phases.PHASES``), and the
  protocol-phase functions the r7 telemetry attribution depends on must
  carry a scope at all; a scope-less collective censuses as
  "(unattributed)", defeating the phase budget.
* **RPA106 int32-flat-index** — a ``row * K (+ col)`` flat-index product
  in jit-reachable code whose operands are an arange-derived index
  vector and an array-extent (``.shape`` unpack / ``params.n``-style),
  or an ``arange`` iota SIZED by a product of two extents, with no
  explicit dtype widening.  Under disabled x64 the product lands in
  int32 and silently wraps once N·K ≥ 2³¹ — 16M × 256 ≈ 4.1e9 is inside
  the multi-host target (the r14 audit's hazard class).  Blessed forms:
  keep (row, col) pairs, or route mod-2³² lanes through
  ``packbits.flat_index_u32`` (explicit wrapping uint32).

The linter is file-local by design: alias-aware name resolution plus a
per-module call-graph closure from ``jax.jit`` roots.  Cross-module
closure is deliberately out of scope — the jaxpr plane
(``trace_checks``) catches what source locality cannot.

Fixture corpus convention: a file under
``tests/analysis_fixtures/<slug>/`` is linted by exactly the rule whose
slug matches its directory — trip/clean snippets stay minimal without
accidentally tripping neighbouring rules.
"""

from __future__ import annotations

import ast
import os

from ringpop_tpu.analysis.findings import Finding
from ringpop_tpu.analysis.phases import PHASES

FIXTURE_DIR = "analysis_fixtures"

RULES = {
    "RPA101": "raw-threefry",
    "RPA102": "traced-roll",
    "RPA103": "host-sync-in-jit",
    "RPA104": "x64-promotion",
    "RPA105": "phase-scope",
    "RPA106": "int32-flat-index",
}

# modules whose programs run (or may run) under a device mesh — the
# RPA101 scope.  sim/fullview.py matches the pattern but never shards
# (the O(N²) oracle engine, threefry pinned by the conformance harness):
# its draw sites are waived in analysis/waivers.toml with that
# justification rather than carved out here, so the exception stays
# visible and reasoned.
SHARDED_CAPABLE = (
    "ringpop_tpu/sim/",
    "ringpop_tpu/parallel/",
)

# jax.random functions that CONSUME randomness (draws / key evolution).
# PRNGKey construction is init-time host work and stays legal.
_RANDOM_DRAWS_EXEMPT = {"PRNGKey", "key", "wrap_key_data"}

# protocol-phase functions that must contain a jax.named_scope block —
# the census attributes collectives by these scopes, so a missing scope
# regresses every budget table to "(unattributed)" (RPA105).
REQUIRED_SCOPED = {
    "ringpop_tpu/sim/lifecycle.py": (
        "step",
        "detection_complete",
        "_walk_subject_slots",
        "view_checksums",
    ),
    "ringpop_tpu/sim/delta.py": ("step",),
    "ringpop_tpu/sim/chaos.py": ("faults_at",),
    "ringpop_tpu/parallel/shift.py": ("shard_roll",),
    "ringpop_tpu/sim/packbits.py": ("_tree_reduce_rows", "set_bit", "set_bit_per_row"),
}
# in the rule's fixture dir, the function named "step" plays the role of
# a protocol-phase function
_FIXTURE_REQUIRED_SCOPED = ("step",)

_BAD_64 = ("int64", "uint64", "float64", "complex128")

# host-numpy calls that force materialization of their argument — on a
# tracer, a concretization error (or worse, a silent sync)
_NP_COERCIONS = {
    "asarray", "array", "flatnonzero", "nonzero", "unique", "copy",
    "frombuffer", "save", "load", "concatenate", "stack",
}
# numpy helpers legal inside traced code because the engines only ever
# apply them to STATIC config scalars (trace-time constants): dtype
# constructors, dtype metadata, and host math on param-derived Python
# numbers (e.g. resolve_max_p's ceil/log10)
_NP_STATIC_OK = {
    "bool_", "int8", "int16", "int32", "int64", "uint8", "uint16",
    "uint32", "uint64", "float16", "float32", "float64", "dtype",
    "iinfo", "finfo", "shape", "ndim", "ceil", "floor", "log", "log2",
    "log10", "sqrt", "prod", "arange",
}


def _fixture_slug(relpath: str) -> str | None:
    """The rule slug a fixture path belongs to, or None outside the
    corpus (``tests/analysis_fixtures/<slug>/x.py`` → ``<slug>``)."""
    parts = relpath.replace(os.sep, "/").split("/")
    if FIXTURE_DIR in parts:
        i = parts.index(FIXTURE_DIR)
        if len(parts) > i + 2:  # .../analysis_fixtures/<slug>/file.py
            return parts[i + 1]
    return None


# fixture directories that exercise an existing rule under a scenario-
# specific name (the <alias> dir is linted by the rule whose slug it maps
# to): chaos-host-sync pins RPA103 catching a host-synced faults_at — the
# chaos plane's one banned implementation shape (a concretized tick
# turns the device-resident timeline into a per-tick host round-trip).
FIXTURE_SLUG_ALIASES = {
    "chaos-host-sync": "host-sync-in-jit",
    # the topology plane's shape of the same hazard: a host-synced tier
    # lookup inside the jitted step (sim/topology.py compiles host-side
    # ONCE; evaluation must stay device-pure)
    "topo-host-sync": "host-sync-in-jit",
}


def _rule_applies(rule: str, relpath: str) -> bool:
    slug = _fixture_slug(relpath)
    if slug is not None:
        return RULES[rule] == FIXTURE_SLUG_ALIASES.get(slug, slug)
    if rule == "RPA101":
        return relpath.startswith(SHARDED_CAPABLE)
    if rule == "RPA102":
        return relpath != "ringpop_tpu/parallel/shift.py"
    if rule == "RPA104":
        return relpath.startswith(("ringpop_tpu/", "scripts/", "examples/"))
    if rule == "RPA105":
        return relpath.startswith("ringpop_tpu/")
    if rule == "RPA106":
        return relpath.startswith("ringpop_tpu/")
    return True  # RPA103: anywhere a jit root lives


class _Module:
    """One parsed file: alias map, function table, jit-root closure."""

    def __init__(self, tree: ast.Module, relpath: str):
        self.tree = tree
        self.relpath = relpath
        self.aliases: dict[str, str] = {}
        # function simple name -> list of (node, qualname) (defs can be
        # nested or duplicated; simple name is what call sites use)
        self.functions: dict[str, list[tuple[ast.AST, str]]] = {}
        self.qualname_of: dict[ast.AST, str] = {}
        self._collect()
        self.jit_marked = self._mark_jit_reachable()

    # -- collection ---------------------------------------------------------

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}.{child.name}" if prefix else child.name
                    self.functions.setdefault(child.name, []).append((child, qn))
                    self.qualname_of[child] = qn
                    visit(child, qn)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}.{child.name}" if prefix else child.name)
                else:
                    visit(child, prefix)

        visit(self.tree, "")

    def resolve(self, node) -> str | None:
        """Dotted name of an expression through the import-alias map:
        ``jnp.roll`` → ``jax.numpy.roll`` — or None for non-name trees."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    # -- jit-root closure ---------------------------------------------------

    def _jit_target_names(self, call: ast.Call) -> list[str]:
        """Function simple names a ``jax.jit(...)`` call traces: a bare
        name, or the first argument of a ``functools.partial`` wrapper."""
        out = []
        for arg in call.args[:1]:
            if isinstance(arg, ast.Name):
                out.append(arg.id)
            elif isinstance(arg, ast.Call):
                fn = self.resolve(arg.func)
                if fn in ("functools.partial", "partial") and arg.args:
                    if isinstance(arg.args[0], ast.Name):
                        out.append(arg.args[0].id)
        return out

    def _mark_jit_reachable(self) -> set[str]:
        """Simple names of module functions reachable from a jit root:
        decorator roots (``@jax.jit``, ``@functools.partial(jax.jit,
        ...)``) plus every function handed to a ``jax.jit(...)`` call,
        closed transitively over same-module references."""
        roots: set[str] = set()
        for name, defs in self.functions.items():
            for node, _ in defs:
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    r = self.resolve(target)
                    if r == "jax.jit":
                        roots.add(name)
                    elif r in ("functools.partial", "partial") and isinstance(
                        dec, ast.Call
                    ):
                        if dec.args and self.resolve(dec.args[0]) == "jax.jit":
                            roots.add(name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and self.resolve(node.func) == "jax.jit":
                roots.update(self._jit_target_names(node))

        refs: dict[str, set[str]] = {}
        for name, defs in self.functions.items():
            names: set[str] = set()
            for node, _ in defs:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            refs[name] = names

        marked = set(n for n in roots if n in self.functions)
        frontier = list(marked)
        while frontier:
            fn = frontier.pop()
            for ref in refs.get(fn, ()):
                if ref in self.functions and ref not in marked:
                    marked.add(ref)
                    frontier.append(ref)
        return marked

    def enclosing(self, lineno: int) -> str:
        """Qualname of the innermost function containing ``lineno``
        (``<module>`` at top level)."""
        best, best_span = "<module>", None
        for defs in self.functions.values():
            for node, qn in defs:
                end = getattr(node, "end_lineno", node.lineno)
                if node.lineno <= lineno <= end:
                    span = end - node.lineno
                    if best_span is None or span < best_span:
                        best, best_span = qn, span
        return best

    def in_jit(self, lineno: int) -> bool:
        for name, defs in self.functions.items():
            if name not in self.jit_marked:
                continue
            for node, _ in defs:
                if node.lineno <= lineno <= getattr(node, "end_lineno", node.lineno):
                    return True
        return False


def _is_static_cast_arg(node) -> bool:
    """True when an int()/float()/bool() argument is a trace-time
    constant: literals, unary ops on them, len()/min()/max() of anything
    (shape-land), or attribute chains ending in shape/size/ndim."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_static_cast_arg(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_static_cast_arg(node.left) and _is_static_cast_arg(node.right)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("len", "min", "max", "round"):
            return True
    if isinstance(node, (ast.Attribute, ast.Subscript)):
        root = node
        while isinstance(root, (ast.Attribute, ast.Subscript)):
            if isinstance(root, ast.Attribute) and root.attr in (
                "shape", "size", "ndim", "dtype",
            ):
                return True
            root = root.value
    return False


# attribute leaves that read as array extents when assigned to a local
# name or used directly in an index product (params.n, params.k, x.shape)
_EXTENT_ATTRS = ("n", "k", "shape")

# dtype-widening / wrapping constructors that mark an index product as
# DELIBERATE (the flat_index_u32 helper's own spelling, host-numpy 64-bit
# math, float accumulators) — RPA106 passes these through
_WIDENING_CALLS = {
    "uint32", "uint64", "int64", "float32", "float64", "asarray",
}


def _rpa106_sets(fn_node) -> tuple[set, set]:
    """Per-function (extent_names, arange_names) for RPA106: names bound
    from ``.shape`` unpacks / ``.shape[i]`` / ``params.n``-style attrs,
    and names bound from ``jnp.arange(...)`` calls."""
    extents: set[str] = set()
    aranges: set[str] = set()
    for sub in ast.walk(fn_node):
        if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
            continue
        tgt, val = sub.targets[0], sub.value

        def names_of(t):
            if isinstance(t, ast.Name):
                return [t.id]
            if isinstance(t, ast.Tuple):
                return [e.id for e in t.elts if isinstance(e, ast.Name)]
            return []

        def is_extent_value(v):
            if isinstance(v, ast.Attribute) and v.attr in _EXTENT_ATTRS:
                return True
            if (
                isinstance(v, ast.Subscript)
                and isinstance(v.value, ast.Attribute)
                and v.value.attr == "shape"
            ):
                return True
            return False

        if isinstance(val, ast.Tuple) and isinstance(tgt, ast.Tuple):
            for t_el, v_el in zip(tgt.elts, val.elts):
                if isinstance(t_el, ast.Name) and is_extent_value(v_el):
                    extents.add(t_el.id)
        elif is_extent_value(val):
            extents.update(names_of(tgt))
        elif isinstance(val, ast.Call):
            fn = val.func
            if isinstance(fn, ast.Attribute) and fn.attr == "arange":
                aranges.update(names_of(tgt))
    return extents, aranges


def _rpa106_is_widened(node) -> bool:
    """True when an operand explicitly names its width: ``.astype(...)``,
    a dtype-constructor call (jnp.uint32(...), np.int64(...)), or an
    arange with an explicit ``dtype=``."""
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "astype":
                return True
            if node.func.attr in _WIDENING_CALLS:
                return True
            if node.func.attr == "arange" and any(
                kw.arg == "dtype" for kw in node.keywords
            ):
                return True
        if isinstance(node.func, ast.Name) and node.func.id in _WIDENING_CALLS:
            return True
    if isinstance(node, (ast.Subscript, ast.Attribute)):
        return _rpa106_is_widened(node.value)
    return False


def lint_source(src: str, relpath: str) -> list[Finding]:
    """Lint one file's source; ``relpath`` is repo-relative (it decides
    rule scoping and appears in findings/waivers)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [
            Finding("RPA000", relpath, e.lineno or 0, "<module>",
                    f"syntax error: {e.msg}")
        ]
    mod = _Module(tree, relpath)
    findings: list[Finding] = []

    def add(rule, node, msg):
        findings.append(
            Finding(rule, relpath, node.lineno, mod.enclosing(node.lineno), msg)
        )

    # per-top-level-function counter-RNG dispatch detection for RPA101: a
    # draw is "guarded" when its enclosing function also references the
    # counter stream (the sim/prng module or the use_counter dispatch
    # flag) — i.e. the threefry call is one branch of the rng-family
    # dispatch, not a bypass.
    def counter_guarded(lineno: int) -> bool:
        for defs in mod.functions.values():
            for node, _ in defs:
                if not (node.lineno <= lineno <= getattr(node, "end_lineno", node.lineno)):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and (
                        mod.aliases.get(sub.id, "").endswith("sim.prng")
                        or sub.id == "use_counter"
                    ):
                        return True
                    if isinstance(sub, ast.ImportFrom) and sub.module and (
                        sub.module.endswith("sim") or sub.module.endswith("prng")
                    ):
                        for a in sub.names:
                            if a.name == "prng":
                                return True
        return False

    named_scope_spans: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.With):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call) and mod.resolve(ce.func) == "jax.named_scope":
                    named_scope_spans.append(
                        (node.lineno, getattr(node, "end_lineno", node.lineno))
                    )

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            target = mod.resolve(node.func)

            # RPA101 -----------------------------------------------------
            if (
                _rule_applies("RPA101", relpath)
                and target
                and target.startswith("jax.random.")
                and target.split(".")[-1] not in _RANDOM_DRAWS_EXEMPT
                and not counter_guarded(node.lineno)
            ):
                add(
                    "RPA101", node,
                    f"raw threefry draw {target} in a sharded-capable path "
                    "with no counter-RNG dispatch in the enclosing function "
                    "— route through sim/prng.py (partition-invariant, "
                    "zero-collective) or gate behind the rng-family param",
                )

            # RPA102 -----------------------------------------------------
            if (
                _rule_applies("RPA102", relpath)
                and target in ("jax.numpy.roll", "numpy.roll")
            ):
                add(
                    "RPA102", node,
                    f"{target} outside parallel/shift.py: a traced-shift "
                    "roll re-derives its slice-select chain per consuming "
                    "element on CPU and all-gathers the plane under GSPMD — "
                    "use a materialized-index gather, or shard_roll for "
                    "sharded exchange legs",
                )

            # RPA103 -----------------------------------------------------
            if _rule_applies("RPA103", relpath) and mod.in_jit(node.lineno):
                if isinstance(node.func, ast.Attribute) and node.func.attr in (
                    "item", "tolist", "block_until_ready",
                ):
                    add(
                        "RPA103", node,
                        f".{node.func.attr}() inside a jit-traced function "
                        "— a device→host sync (trace-time error on a "
                        "tracer); hoist to the host caller",
                    )
                elif target and target.startswith("numpy."):
                    leaf = target.split(".")[-1]
                    if leaf in _NP_COERCIONS:
                        add(
                            "RPA103", node,
                            f"np.{leaf} inside a jit-traced function "
                            "materializes its operand on host — use the "
                            "jnp equivalent or hoist to the caller",
                        )
                elif target in ("jax.device_get", "jax.device_put"):
                    add(
                        "RPA103", node,
                        f"{target} inside a jit-traced function — host "
                        "transfer constructs belong outside the trace",
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")
                    and node.args
                    and not _is_static_cast_arg(node.args[0])
                ):
                    add(
                        "RPA103", node,
                        f"{node.func.id}(...) on a non-literal inside a "
                        "jit-traced function — concretizes a tracer; keep "
                        "values as jnp scalars or compute on static config",
                    )

            # RPA104: dtype= string form + x64 flag ----------------------
            if _rule_applies("RPA104", relpath):
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in _BAD_64
                    ):
                        add(
                            "RPA104", node,
                            f'dtype="{kw.value.value}" in device code: the '
                            "sim runs x64-disabled, so this silently "
                            "becomes 32-bit (overflow hazard) — use an "
                            "explicit 32-bit dtype or restructure",
                        )
                if (
                    target in ("jax.config.update",)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "jax_enable_x64"
                ):
                    add(
                        "RPA104", node,
                        "jax_enable_x64: x64 promotion doubles the packed "
                        "planes' HBM traffic and breaks the uint32 "
                        "bit-packing contracts — forbidden in device code",
                    )

            # RPA105 (a): canonical scope names --------------------------
            if (
                _rule_applies("RPA105", relpath)
                and target == "jax.named_scope"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value not in PHASES
            ):
                add(
                    "RPA105", node,
                    f'named_scope "{node.args[0].value}" is not in the '
                    "canonical phase vocabulary (analysis/phases.PHASES) — "
                    "collectives under it census as unattributable; add "
                    "the phase to the vocabulary or reuse an existing one",
                )

        # RPA104: bare 64-bit dtype attribute ----------------------------
        elif isinstance(node, ast.Attribute) and _rule_applies("RPA104", relpath):
            target = mod.resolve(node)
            if target and target.startswith("jax.numpy.") and target.split(".")[-1] in _BAD_64:
                add(
                    "RPA104", node,
                    f"{target.replace('jax.numpy', 'jnp')}: with x64 "
                    "disabled this silently produces a 32-bit value "
                    "(overflow hazard, as in the ring_ops composite-sort "
                    "bug this rule first caught) — restructure to stay in "
                    "32-bit, e.g. a stable argsort instead of a packed "
                    "composite key",
                )
            elif (
                target
                and target.startswith("numpy.")
                and target.split(".")[-1] in _BAD_64
                and mod.in_jit(node.lineno)
            ):
                add(
                    "RPA104", node,
                    f"np.{target.split('.')[-1]} inside a jit-traced "
                    "function — 64-bit host dtypes do not exist on the "
                    "x64-disabled device; use 32-bit",
                )

    # RPA106: int32 flat-index products in jit-reachable code ------------
    if _rule_applies("RPA106", relpath):
        seen_rpa106: set[int] = set()
        for fname, defs in mod.functions.items():
            for fn_node, _qn in defs:
                extents, aranges = _rpa106_sets(fn_node)

                def extentish(e):
                    if isinstance(e, ast.Name):
                        return e.id in extents
                    if isinstance(e, ast.Attribute):
                        return e.attr in ("n", "k")
                    return False

                def arangeish(e):
                    if isinstance(e, ast.Name):
                        return e.id in aranges
                    if isinstance(e, (ast.Subscript,)) and isinstance(e.value, ast.Name):
                        return e.value.id in aranges
                    if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute):
                        return e.func.attr == "arange" and not _rpa106_is_widened(e)
                    return False

                for sub in ast.walk(fn_node):
                    lineno = getattr(sub, "lineno", None)
                    if lineno is None or lineno in seen_rpa106 or not mod.in_jit(lineno):
                        continue
                    if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mult):
                        l, r = sub.left, sub.right
                        pair = (
                            (arangeish(l) and extentish(r))
                            or (arangeish(r) and extentish(l))
                        )
                        if pair and not (_rpa106_is_widened(l) or _rpa106_is_widened(r)):
                            seen_rpa106.add(sub.lineno)
                            add(
                                "RPA106", sub,
                                "int32 flat-index product of a traced index "
                                "vector and an array extent — wraps silently "
                                "once the plane reaches N*K >= 2**31 (16M x "
                                "256 is inside the multi-host target); keep "
                                "(row, col) pairs, or use packbits."
                                "flat_index_u32 for mod-2**32 digest lanes",
                            )
                    elif (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "arange"
                        and sub.args
                        and isinstance(sub.args[0], ast.BinOp)
                        and isinstance(sub.args[0].op, ast.Mult)
                        and extentish(sub.args[0].left)
                        and extentish(sub.args[0].right)
                        and not any(kw.arg == "dtype" for kw in sub.keywords)
                    ):
                        seen_rpa106.add(sub.lineno)
                        add(
                            "RPA106", sub,
                            "arange sized by a product of two traced extents "
                            "builds an int32 iota that wraps past 2**31 — "
                            "iterate (row, col) instead of a flat index, or "
                            "state the wrapping intent with an explicit dtype",
                        )

    # RPA105 (b): required protocol-phase functions carry a scope --------
    if _rule_applies("RPA105", relpath):
        required = REQUIRED_SCOPED.get(relpath, ())
        if _fixture_slug(relpath) == RULES["RPA105"]:
            required = _FIXTURE_REQUIRED_SCOPED
        for fname in required:
            for node, qn in mod.functions.get(fname, ()):
                end = getattr(node, "end_lineno", node.lineno)
                if not any(a >= node.lineno and b <= end for a, b in named_scope_spans):
                    findings.append(
                        Finding(
                            "RPA105", relpath, node.lineno, qn,
                            f"protocol-phase function {qn} carries no "
                            "jax.named_scope — its collectives census as "
                            "(unattributed), breaking the r7 phase "
                            "attribution and the r8 phase budget",
                        )
                    )
            if not mod.functions.get(fname) and relpath in REQUIRED_SCOPED:
                findings.append(
                    Finding(
                        "RPA105", relpath, 1, "<module>",
                        f"required protocol-phase function {fname!r} not "
                        "found — update analysis/astlint.REQUIRED_SCOPED "
                        "if it moved",
                    )
                )

    return findings


def lint_paths(paths, repo_root: str) -> list[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    findings: list[Finding] = []
    files: list[str] = []
    for p in paths:
        ap = os.path.join(repo_root, p) if not os.path.isabs(p) else p
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f) for f in sorted(filenames) if f.endswith(".py")
                )
        elif ap.endswith(".py"):
            files.append(ap)
    for f in sorted(set(files)):
        rel = os.path.relpath(f, repo_root).replace(os.sep, "/")
        try:
            src = open(f).read()
        except OSError as e:
            findings.append(Finding("RPA000", rel, 0, "<module>", f"unreadable: {e}"))
            continue
        findings.extend(lint_source(src, rel))
    return findings
