"""Finding record + rendering shared by both jaxlint planes."""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field


@dataclass
class Finding:
    """One rule violation.

    ``path`` is repo-relative for AST findings and the ``<trace:entry>``
    pseudo-path for jaxpr/HLO-plane findings (there is no single source
    line for a traced-program property).  ``scope`` is the enclosing
    function qualname (``<module>`` at file top level) or the trace entry
    point name — it is what waivers key on, so a waiver survives the line
    churn of ordinary edits."""

    rule: str
    path: str
    line: int
    scope: str
    message: str
    waived: bool = field(default=False)
    justification: str = field(default="")

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def render(self) -> str:
        tag = " [waived]" if self.waived else ""
        out = f"{self.location()}: {self.rule} ({self.scope}): {self.message}{tag}"
        if self.waived and self.justification:
            out += f"\n    waiver: {self.justification}"
        return out


def to_json(findings, unused_waivers=(), extra=None) -> str:
    """The ``--format=json`` listing mode: every finding (waived ones
    included, flagged) plus unused waivers — a stable machine-readable
    surface so future budget re-baselines can diff rule outcomes."""
    doc = {
        "findings": [asdict(f) for f in findings],
        "unwaived_count": sum(1 for f in findings if not f.waived),
        "waived_count": sum(1 for f in findings if f.waived),
        "unused_waivers": [dict(w) for w in unused_waivers],
    }
    if extra:
        doc.update(extra)
    return json.dumps(doc, indent=1, sort_keys=True)
