"""Optimized-HLO collective census with protocol-phase attribution.

Moved out of ``scripts/profile_mesh.py`` (which still re-exports every
name here for its callers) so the jaxlint HLO plane
(``analysis/trace_checks.check_hlo_confinement``) and the pytest budget
guards (``tests/test_mesh_budget.py``) share ONE parser: the r6 lesson —
an HLO text-format rotation silently reporting an empty census as a
passing budget — must only ever need fixing in one place.

Census semantics (r8): collectives inside sibling branches of one
``conditional`` (``lax.switch``/``lax.cond``) are mutually exclusive per
execution — the shift exchange's shard-local lowering switches over the
traced shard offset, and the sparse candidate select conds between the
hierarchical path and its full-sort fallback — so every summary charges
only the most expensive branch of each conditional (worst case actually
executable per tick), not the sum of all branches in the program text.
"""

from __future__ import annotations

import glob
import os
import re

from ringpop_tpu.analysis.phases import PHASES

COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
    "reduce-scatter",
)

_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_SRC_RE = re.compile(r'source_file="([^"]+)" source_line=(\d+)')
_PHASE_SPAN_CACHE: dict = {}


def _source_spans(path: str):
    """(named-scope spans, function starts) of one source file — the
    fallback attributor for collectives whose op_name lost its scope (the
    SPMD partitioner re-homes resharding ops onto loop boundaries, whose
    metadata names only the enclosing while)."""
    if path not in _PHASE_SPAN_CACHE:
        spans, funcs = [], []
        try:
            src = open(path).read().split("\n")
        except OSError:
            src = []
        for i, ln in enumerate(src):
            m = re.match(r'(\s*)with jax\.named_scope\("([^"]+)"\):', ln)
            if m:
                indent = len(m.group(1))
                j = i + 1
                while j < len(src) and (
                    not src[j].strip()
                    or len(src[j]) - len(src[j].lstrip()) > indent
                ):
                    j += 1
                spans.append((i + 1, j, m.group(2)))
            d = re.match(r"def (\w+)\(", ln)
            if d:
                funcs.append((i + 1, d.group(1)))
        _PHASE_SPAN_CACHE[path] = (spans, funcs)
    return _PHASE_SPAN_CACHE[path]


def _phase_of(line: str) -> str:
    """Protocol phase of one HLO instruction line: the named-scope path
    XLA keeps in metadata op_name when present (fusions inherit a
    representative instruction's metadata), else the scope lexically
    enclosing the op's source line, else ``loop:<function>`` for ops the
    partitioner re-homed onto a loop boundary (e.g. the detect walk's
    learned-plane replication hoisted to the tick loop)."""
    m = _OPNAME_RE.search(line)
    if m:
        for part in m.group(1).split("/"):
            if part in PHASES:
                return part
    s = _SRC_RE.search(line)
    if s:
        spans, funcs = _source_spans(s.group(1))
        ln = int(s.group(2))
        for a, b, name in spans:
            if a <= ln <= b:
                return name
        owner = None
        for a, name in funcs:
            if a <= ln:
                owner = name
            else:
                break
        if owner:
            return f"loop:{owner}"
    return "(unattributed)"


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of every array in an HLO result type string (handles
    tuples; layout annotations ignored)."""
    total = 0
    for dtype, dims in re.findall(r"(pred|[suf]\d+|bf16)\[([\d,]*)\]", shape_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def parse_collectives(hlo_path: str) -> dict:
    """Per-computation collective census of one optimized HLO module.

    Returns {computation_name: [{op, kind, bytes}...]} plus, for loop
    attribution, each computation's while-loop depth (a collective inside
    a while BODY executes once per iteration, so depth distinguishes the
    one-shot entry collectives from the per-tick / per-walk-step ones),
    the ``conditional`` branch groups (lists of sibling branch
    computations, of which exactly ONE executes per evaluation), and the
    ``executed`` computation set: everything reachable from the module
    roots taking only the most expensive branch of each conditional —
    the worst case one execution can actually pay.  Summaries charge the
    executed set only; ``by_computation`` keeps the full text census.

    ``total_computations`` counts EVERY computation header parsed
    (collective-bearing or not): zero on a non-empty file means the dump
    format rotated out from under the parser — callers must treat that
    as an error, not an empty budget (see ``profile_mesh`` and
    jaxlint's ``check_hlo_confinement``)."""
    comps: dict = {}
    bodies: dict = {}  # while-body computation -> owning computation
    calls: dict = {}  # computation -> calling computations (reverse edges)
    fwd: dict = {}  # computation -> called computations (forward edges)
    cond_groups: list = []  # [{caller, branches: [comp, ...]}, ...]
    total_computations = 0
    cur = None
    # instruction/computation names carry a "%" sigil in older XLA text
    # dumps and none in current ones — accept both, or a format rotation
    # silently reports an empty census (bit us once: the r6 'before'
    # capture came out all-zero against a 297-collective program)
    for line in open(hlo_path):
        stripped = line.rstrip()
        if stripped.endswith("{") and not line.lstrip().startswith("ROOT"):
            cur = stripped.split()[0].lstrip("%")
            comps.setdefault(cur, [])
            total_computations += 1
        elif cur is not None and line.strip() == "}":
            cur = None
        elif cur is not None:
            m = re.search(
                r"%?([\w.\-]+) = (.+?) (" + "|".join(COLLECTIVES) + r")(?:-start)?\(",
                line,
            )
            if m and "-done" not in line.split("=", 1)[1][:60]:
                comps[cur].append(
                    {
                        "op": m.group(1),
                        "kind": m.group(3),
                        "bytes": _shape_bytes(m.group(2)),
                        "phase": _phase_of(line),
                    }
                )
            b = re.search(r"body=%?([\w.\-]+)", line)
            if b:
                bodies[b.group(1)] = cur
            # conditional branches: N-ary (lax.switch) and binary forms
            branches = []
            bm = re.search(r"branch_computations=\{([^}]*)\}", line)
            if bm:
                branches = [c.strip().lstrip("%") for c in bm.group(1).split(",") if c.strip()]
            else:
                tm = re.search(r"true_computation=%?([\w.\-]+)", line)
                fm = re.search(r"false_computation=%?([\w.\-]+)", line)
                if tm and fm:
                    branches = [tm.group(1), fm.group(1)]
            if branches:
                cond_groups.append({"caller": cur, "branches": branches})
            for callee in re.findall(
                r"(?:calls|to_apply|condition|body|true_computation|"
                r"false_computation)=%?([\w.\-]+)",
                line,
            ) + branches:
                calls.setdefault(callee, set()).add(cur)
                fwd.setdefault(cur, set()).add(callee)

    def loop_depth(name: str, seen=()) -> int:
        if name in seen:
            return 0
        best = 0
        if name in bodies:
            best = 1 + loop_depth(bodies[name], seen + (name,))
        for owner in calls.get(name, ()):
            best = max(best, loop_depth(owner, seen + (name,)))
        return best

    # -- worst-case-executed computation set: at every conditional take the
    # branch whose subtree carries the most collective bytes (count as
    # tie-break); sibling branches are mutually exclusive per execution
    branch_edges = {
        (g["caller"], b) for g in cond_groups for b in g["branches"]
    }
    groups_of = {}
    for g in cond_groups:
        groups_of.setdefault(g["caller"], []).append(g["branches"])

    def subtree_cost(name, seen=()):
        if name in seen:
            return (0, 0)
        seen = seen + (name,)
        by, ct = 0, 0
        for r in comps.get(name, ()):
            by += r["bytes"]
            ct += 1
        for branches in groups_of.get(name, []):
            bb, bc = max((subtree_cost(b, seen) for b in branches), default=(0, 0))
            by += bb
            ct += bc
        for callee in fwd.get(name, ()):
            if (name, callee) in branch_edges:
                continue
            cb, cc = subtree_cost(callee, seen)
            by += cb
            ct += cc
        return (by, ct)

    executed: set = set()

    def walk(name):
        if name in executed:
            return
        executed.add(name)
        for branches in groups_of.get(name, []):
            walk(max(branches, key=lambda b: subtree_cost(b)))
        for callee in fwd.get(name, ()):
            if (name, callee) not in branch_edges:
                walk(callee)

    all_names = set(comps) | set(fwd) | {c for cs in fwd.values() for c in cs}
    roots = all_names - {c for cs in fwd.values() for c in cs}
    for r in sorted(roots):
        walk(r)
    if not roots:  # degenerate single-computation module
        executed = all_names

    return {
        "computations": {k: v for k, v in comps.items() if v},
        "loop_depth": {k: loop_depth(k) for k, v in comps.items() if v},
        "cond_groups": cond_groups,
        "executed": sorted(executed),
        "total_computations": total_computations,
    }


def newest_module(dump: str, marker: str) -> str | None:
    """Largest after-optimizations text dump in ``dump`` whose file name
    contains ``marker`` (buffer/memory sidecar dumps excluded)."""
    mods = [
        p
        for p in glob.glob(os.path.join(dump, "*after_optimizations.txt"))
        if marker in os.path.basename(p) and "buffer" not in p and "memory" not in p
    ]
    return max(mods, key=os.path.getsize) if mods else None


def executed_rows(census: dict):
    """Iterate (computation, row) over the worst-case EXECUTED collective
    set: sibling conditional branches contribute only their most expensive
    member (see parse_collectives) — the census tests and both summaries
    share this one definition of "per-tick cost"."""
    executed = set(census.get("executed") or census["computations"])
    for comp, rows in census["computations"].items():
        if comp in executed:
            for r in rows:
                yield comp, r


def summarize(census: dict) -> dict:
    """{kind: {count, bytes}} over the executed collective set."""
    by_kind: dict = {}
    for _, r in executed_rows(census):
        e = by_kind.setdefault(r["kind"], {"count": 0, "bytes": 0})
        e["count"] += 1
        e["bytes"] += r["bytes"]
    return by_kind


def summarize_phases(census: dict) -> dict:
    """{phase: {kind: {count, bytes}}} — the protocol-phase attribution of
    the collective census (the table PERF.md's budget discussion reads)."""
    by_phase: dict = {}
    for _, r in executed_rows(census):
        kinds = by_phase.setdefault(r.get("phase", "(unattributed)"), {})
        e = kinds.setdefault(r["kind"], {"count": 0, "bytes": 0})
        e["count"] += 1
        e["bytes"] += r["bytes"]
    return by_phase
