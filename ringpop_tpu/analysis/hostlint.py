"""Plane 3 of jaxlint: host-concurrency AST rules (the racelint plane).

Since r13 the host layer has grown to ~8.3k LoC of lock-and-thread code
(persistent sender/reader threads, sticky link failure, inline-completion
futures, shm seq-word rings) with zero static coverage — the repo paid
for that gap twice (the r22 ``TCPChannel._handle`` count-after-respond
flake, the r21 honest-cost rework).  This plane is the rebuild's analog
of the reference's ``make test-race`` (ringpop-go runs its whole suite
under Go's race detector): source-level hazards caught before a single
thread runs, cross-checked dynamically by ``analysis/racecheck.py``
(``make race-smoke``).

Rules (catalog with the full story: ANALYSIS.md):

* **RPH301 lock-order-inversion** — the per-module lock-acquisition
  graph (``with self._lock`` nesting + blocking ``.acquire()`` spans,
  closed over same-module calls) contains a cycle.  Two threads taking
  the same two locks in opposite orders is the canonical deadlock; the
  graph makes the order a checkable invariant instead of a convention.
* **RPH302 blocking-under-lock** — a blocking call (socket
  ``recv``/``sendmsg``/``connect``, ``Condition.wait``, ``Event.wait``,
  ``future.result()``, ``Thread.join``, ``time.sleep``, jax dispatch)
  while a lock is held.  A blocked holder extends its critical section
  by an unbounded wait — every other thread needing the lock stalls
  behind a peer's socket.  ``Condition.wait`` on the condition whose
  OWN lock is held is the one legal shape (wait releases it) and is
  allowlisted.  Deliberate designs (e.g. a lock whose purpose IS to
  serialize a wire write) are waivable with justification.
* **RPH303 thread-leak** — a non-daemon ``threading.Thread`` whose
  creating scope never joins anything.  A leaked non-daemon thread
  keeps the process alive past main-exit; the blessed shapes are
  ``daemon=True`` (+ bounded join on the shutdown path) or an explicit
  join in the creating scope.
* **RPH304 unlocked-shared-attr** — an attribute written from ≥ 2
  distinct thread roots (``threading.Thread(target=...)``,
  ``submit(...)``, loop-callback registrations) where at least one
  write site is outside any lock region.  Heuristic by design —
  single-writer hand-offs and seq-word protocols are legal — so
  findings are waivable via waivers.toml with mandatory justification.
* **RPH305 journal-schema** — a ``{"kind": "<k>", ...}`` record emit
  site whose literal keys are not documented in OBSERVABILITY.md's
  "Journal record schema index" table (or whose kind is absent from it
  entirely).  The r22 flake class: docs and emitters drifting silently.

Thread-root closure: the same per-module machinery as RPA103's jit-root
closure (``astlint._Module``), but rooted at thread-spawn sites instead
of ``jax.jit`` — a function is "on a thread root" when it is the target
of ``threading.Thread(target=...)`` / ``executor.submit(...)`` /
``loop.call_soon*``/``run_in_executor``/``add_reader`` or reachable
from one through same-module calls (``self.m()`` resolves through the
enclosing class, bare names through the module function table).

File-local by design, like plane 1: cross-module lock graphs are the
dynamic harness's job (``racecheck`` records the real process-wide
order).  Fixture corpus convention matches plane 1: a file under
``tests/analysis_fixtures/<slug>/`` is linted by exactly the rule whose
slug names its directory.
"""

from __future__ import annotations

import ast
import os
import re

from ringpop_tpu.analysis.findings import Finding

FIXTURE_DIR = "analysis_fixtures"

RULES = {
    "RPH301": "lock-order-inversion",
    "RPH302": "blocking-under-lock",
    "RPH303": "thread-leak",
    "RPH304": "unlocked-shared-attr",
    "RPH305": "journal-schema",
}

# lock-constructing callables (resolved through the import-alias map)
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
}

# thread-spawn / callback-registration attribute names whose callable
# argument becomes a thread root (RPH304's closure roots)
_ROOT_REGISTRARS = {
    "submit", "call_soon", "call_soon_threadsafe", "call_later",
    "call_at", "run_in_executor", "add_reader", "add_writer",
    "add_done_callback",
}
# registrars whose callbacks all run serialized on ONE event-loop
# thread: they share a single root label (two loop callbacks never
# preempt each other, so they are not "distinct threads" for RPH304)
_LOOP_SERIALIZED = {
    "call_soon", "call_soon_threadsafe", "call_later", "call_at",
    "add_reader", "add_writer", "add_done_callback",
}

# method names that block the calling thread (RPH302).  Socket family +
# synchronization waits + future/thread joins.  ``acquire`` is handled
# separately (it IS the lock-order edge, RPH301's subject).
_BLOCKING_METHODS = {
    "recv", "recv_into", "recvmsg", "recvmsg_into", "recvfrom",
    "sendall", "sendmsg", "connect", "accept",
    "wait", "wait_for", "result", "block_until_ready",
}
# ``.join()`` blocks only on thread-like receivers — ``", ".join(parts)``
# is the most common method call in Python; gate on the receiver's name
_THREADISH = re.compile(r"(thread|sender|reader|writer|worker|proc)", re.I)
# dotted-name calls that block (through the alias map)
_BLOCKING_DOTTED = {
    "time.sleep", "jax.device_get", "jax.device_put",
    "jax.block_until_ready", "select.select",
}
# receivers whose ``.send`` is a socket write.  Bare ``.send`` is too
# generic to flag (generators, queues); the repo's sockets live on
# attributes matching this pattern.
_SOCKISH_ATTRS = re.compile(r"(^|_)(sock|socket|conn)\b")

_SCHEMA_HEADING = "journal record schema index"


def _fixture_slug(relpath: str) -> str | None:
    parts = relpath.replace(os.sep, "/").split("/")
    if FIXTURE_DIR in parts:
        i = parts.index(FIXTURE_DIR)
        if len(parts) > i + 2:
            return parts[i + 1]
    return None


def _rule_applies(rule: str, relpath: str) -> bool:
    slug = _fixture_slug(relpath)
    if slug is not None:
        return RULES[rule] == slug
    if rule == "RPH305":
        # journal records are emitted by the package only; scripts print
        return relpath.startswith("ringpop_tpu/")
    return relpath.startswith(("ringpop_tpu/", "scripts/"))


# -- OBSERVABILITY.md schema index (RPH305) ----------------------------------


def load_schema_index(md_path: str) -> dict[str, set[str]] | None:
    """Parse the "Journal record schema index" table out of
    OBSERVABILITY.md: ``| `kind` | `key`, `key`, ... |`` rows.  Returns
    {kind: allowed key set} or None when the doc/section is missing
    (RPH305 then reports nothing — explicit paths outside the repo)."""
    try:
        text = open(md_path).read()
    except OSError:
        return None
    lines = text.splitlines()
    idx: dict[str, set[str]] = {}
    in_section = False
    for line in lines:
        if line.startswith("#"):
            in_section = _SCHEMA_HEADING in line.lower()
            continue
        if not in_section or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 2 or set(cells[0]) <= {"-", " ", ":"}:
            continue
        kind = cells[0].strip("`")
        if kind == "kind":  # the header row
            continue
        keys = {k for k in re.findall(r"`([^`]+)`", cells[1])}
        idx[kind] = keys | {"kind"}
    return idx or None


# -- the per-module model -----------------------------------------------------


class _HostModule:
    """One parsed file: alias map, class/function tables, the lock
    attribute table, and the thread-root closure."""

    def __init__(self, tree: ast.Module, relpath: str):
        self.tree = tree
        self.relpath = relpath
        self.aliases: dict[str, str] = {}
        # (class_name or None, simple name) -> function node
        self.functions: dict[tuple[str | None, str], ast.AST] = {}
        self.qualname_of: dict[ast.AST, str] = {}
        self.class_of: dict[ast.AST, str | None] = {}
        # class -> {attr: lineno} for self.attr = threading.Lock()/...
        self.class_locks: dict[str, dict[str, int]] = {}
        # module-level lock names
        self.module_locks: dict[str, int] = {}
        self._collect()
        # lock attr name -> owning classes (for self.<obj>.<attr> guesses)
        self.lock_attr_owners: dict[str, list[str]] = {}
        for cls, attrs in self.class_locks.items():
            for a in attrs:
                self.lock_attr_owners.setdefault(a, []).append(cls)
        self.thread_roots = self._thread_roots()
        self.root_reach = self._close_roots(self.thread_roots)

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

        def visit(node, prefix, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}.{child.name}" if prefix else child.name
                    self.functions[(cls, child.name)] = child
                    self.qualname_of[child] = qn
                    self.class_of[child] = cls
                    visit(child, qn, cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}.{child.name}" if prefix else child.name,
                          child.name)
                else:
                    visit(child, prefix, cls)

        visit(self.tree, "", None)

        # lock construction sites
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            ctor = self.resolve(node.value.func)
            if ctor not in _LOCK_CTORS:
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    cls = self._enclosing_class(node.lineno)
                    if cls is not None:
                        self.class_locks.setdefault(cls, {})[tgt.attr] = node.lineno
                elif isinstance(tgt, ast.Name):
                    self.module_locks[tgt.id] = node.lineno

    def _enclosing_class(self, lineno: int) -> str | None:
        best, best_span = None, None
        for (cls, _), node in self.functions.items():
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = cls, span
        return best

    def resolve(self, node) -> str | None:
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def enclosing(self, lineno: int) -> str:
        best, best_span = "<module>", None
        for node, qn in ((n, self.qualname_of[n]) for n in self.qualname_of):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = qn, span
        return best

    # -- lock expression resolution ------------------------------------------

    def lock_node(self, expr, cls: str | None) -> str | None:
        """The graph-node name of a lock expression, or None when the
        expression is not a known lock.  ``self._x`` resolves through the
        enclosing class's lock table; a deeper receiver (``self.ep._x``)
        resolves when exactly one class in the module declares a lock
        named ``_x`` (else an anonymous per-attr node that still counts
        as held for RPH302 but never aggregates into RPH301 edges)."""
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return f"<module>.{expr.id}"
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id == "self" and cls is not None:
            if attr in self.class_locks.get(cls, {}):
                return f"{cls}.{attr}"
            # self._x in a subclass-ish shape: unique owner in the module
            owners = self.lock_attr_owners.get(attr, [])
            if len(owners) == 1:
                return f"{owners[0]}.{attr}"
            return None
        owners = self.lock_attr_owners.get(attr, [])
        if len(owners) == 1:
            return f"{owners[0]}.{attr}"
        if owners:
            # ambiguous owner: held (RPH302) but edge-inert (RPH301)
            return f"?anon:{attr}:{getattr(expr, 'lineno', 0)}"
        return None

    # -- thread roots and their closure --------------------------------------

    def _callable_key(self, expr) -> tuple[str | None, str] | None:
        """(class, simple-name) key of a callable expression when it
        names a same-module function: bare name, ``self.m``, or a
        ``functools.partial(f, ...)`` wrapper."""
        if isinstance(expr, ast.Name):
            for (cls, name) in self.functions:
                if name == expr.id and cls is None:
                    return (None, expr.id)
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            cls = self._enclosing_class(expr.lineno)
            if cls is not None and (cls, expr.attr) in self.functions:
                return (cls, expr.attr)
            return None
        if isinstance(expr, ast.Call):
            fn = self.resolve(expr.func)
            if fn in ("functools.partial", "partial") and expr.args:
                return self._callable_key(expr.args[0])
        return None

    def _thread_roots(self) -> dict[tuple[str | None, str], set[str]]:
        """{function key: root labels} for every thread-spawn /
        callback-registration site in the module.  Loop-serialized
        registrations (``call_soon``/``add_reader``/...) all share ONE
        label — their callbacks run serialized on the event-loop thread,
        so they are never concurrent with each other."""
        roots: dict[tuple[str | None, str], set[str]] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self.resolve(node.func)
            cand, serialized = None, False
            if target == "threading.Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        cand = kw.value
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _ROOT_REGISTRARS
            ):
                # submit(f, ...) / call_soon(f, ...) / add_reader(fd, f)
                # / run_in_executor(executor_or_None, f, ...)
                serialized = node.func.attr in _LOOP_SERIALIZED
                args = list(node.args)
                if node.func.attr in ("add_reader", "add_writer"):
                    args = args[1:]
                elif node.func.attr == "run_in_executor":
                    args = args[1:]
                if args:
                    cand = args[0]
            if cand is None:
                continue
            key = self._callable_key(cand)
            if key is not None:
                if serialized:
                    label = "event-loop"
                else:
                    name = f"{key[0]}.{key[1]}" if key[0] else key[1]
                    label = f"thread:{name}@{node.lineno}"
                roots.setdefault(key, set()).add(label)
        return roots

    def _call_keys(self, fn_node, cls: str | None, include_refs: bool = True):
        """Same-module function keys this function's body calls.  With
        ``include_refs`` (the thread-root closure), bare references to
        module functions count too — a callback handed onward still runs
        on the root's thread; the acquire/blocking fixpoints use actual
        calls only."""
        out = set()
        for sub in ast.walk(fn_node):
            if not isinstance(sub, ast.Call):
                continue
            key = None
            if isinstance(sub.func, ast.Name):
                if (None, sub.func.id) in self.functions:
                    key = (None, sub.func.id)
            elif (
                isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == "self"
                and cls is not None
                and (cls, sub.func.attr) in self.functions
            ):
                key = (cls, sub.func.attr)
            if key is not None:
                out.add(key)
        if include_refs:
            # bare references (callbacks handed onward) count as reachable
            for sub in ast.walk(fn_node):
                if isinstance(sub, ast.Name) and (None, sub.id) in self.functions:
                    out.add((None, sub.id))
        return out

    def _close_roots(self, roots) -> dict[tuple[str | None, str], set[str]]:
        """{function key: set of root labels reaching it} — the
        thread-root analog of astlint's jit closure."""
        reach: dict[tuple[str | None, str], set[str]] = {}
        calls: dict[tuple[str | None, str], set] = {}
        for key, node in self.functions.items():
            calls[key] = self._call_keys(node, key[0])
        for key, labels in roots.items():
            frontier = [key]
            seen = set()
            while frontier:
                k = frontier.pop()
                if k in seen:
                    continue
                seen.add(k)
                reach.setdefault(k, set()).update(labels)
                frontier.extend(calls.get(k, ()))
        return reach


# -- the lock-region walker ---------------------------------------------------


class _RegionWalker:
    """Walks one function's statements tracking held locks; feeds the
    acquisition graph (RPH301), blocking-call findings (RPH302), and the
    per-write lock context (RPH304)."""

    def __init__(self, mod: _HostModule, cls: str | None):
        self.mod = mod
        self.cls = cls
        # (held_node, acquired_node) -> first site lineno
        self.edges: dict[tuple[str, str], int] = {}
        # lock nodes this function acquires anywhere (for closure edges)
        self.acquired: set[str] = set()
        # (lineno, call_repr, held_nodes, receiver_node) blocking sites
        self.blocking: list[tuple[int, str, tuple[str, ...], str | None]] = []
        # (attr_target_repr, lineno, under_lock)
        self.writes: list[tuple[str, int, bool]] = []
        # same-module callee keys invoked while holding locks:
        # (callee_key, held_nodes, lineno)
        self.held_calls: list[tuple[tuple, tuple[str, ...], int]] = []

    # -- helpers -------------------------------------------------------------

    def _acquire(self, node_name: str, held: list[str], lineno: int) -> None:
        for h in held:
            if h != node_name and not h.startswith("?anon:") \
                    and not node_name.startswith("?anon:"):
                self.edges.setdefault((h, node_name), lineno)
        self.acquired.add(node_name)

    def _is_nonblocking_acquire(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
                return kw.value.value is False
        if call.args and isinstance(call.args[0], ast.Constant):
            return call.args[0].value is False
        return False

    def _lock_of_call_recv(self, call: ast.Call) -> str | None:
        if isinstance(call.func, ast.Attribute):
            return self.mod.lock_node(call.func.value, self.cls)
        return None

    # -- expression scan (calls + writes inside one statement) ---------------

    def _scan_expr(self, expr, held: list[str]) -> None:
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            # blocking acquire of another lock mid-expression
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"
                and not self._is_nonblocking_acquire(sub)
            ):
                ln = self._lock_of_call_recv(sub)
                if ln is not None and held:
                    self._acquire(ln, held, sub.lineno)
                continue
            target = self.mod.resolve(sub.func)
            blocked, recv_node = None, None
            if target in _BLOCKING_DOTTED:
                blocked = target
            elif isinstance(sub.func, ast.Attribute):
                attr = sub.func.attr
                if attr in _BLOCKING_METHODS:
                    blocked = f".{attr}()"
                    recv_node = self.mod.lock_node(sub.func.value, self.cls)
                elif attr == "join":
                    recv_txt = ast.unparse(sub.func.value) if hasattr(
                        ast, "unparse") else ""
                    if _THREADISH.search(recv_txt.split(".")[-1]):
                        blocked = ".join()"
                elif attr in ("send", "sendto"):
                    recv_txt = ast.unparse(sub.func.value) if hasattr(
                        ast, "unparse") else ""
                    if _SOCKISH_ATTRS.search(recv_txt.split(".")[-1]):
                        blocked = f".{attr}()"
            if blocked is not None:
                self.blocking.append(
                    (sub.lineno, blocked, tuple(held), recv_node)
                )

    def _scan_writes(self, stmt, held: list[str]) -> None:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for tgt in targets:
            tgts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for t in tgts:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    self.writes.append((t.attr, t.lineno, bool(held)))

    def _scan_calls_out(self, stmt, held: list[str]) -> None:
        if not held:
            return
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            key = None
            if isinstance(sub.func, ast.Name):
                if (None, sub.func.id) in self.mod.functions:
                    key = (None, sub.func.id)
            elif (
                isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == "self"
                and self.cls is not None
                and (self.cls, sub.func.attr) in self.mod.functions
            ):
                key = (self.cls, sub.func.attr)
            if key is not None:
                self.held_calls.append((key, tuple(held), sub.lineno))

    # -- statement walk ------------------------------------------------------

    def walk(self, stmts, held: list[str]) -> None:
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested defs are their own functions
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered = []
                for item in stmt.items:
                    ln = None
                    ce = item.context_expr
                    ln = self.mod.lock_node(ce, self.cls)
                    if ln is None and isinstance(ce, ast.Call):
                        # with lock.acquire_timeout()-style helpers: skip
                        ln = None
                    if ln is not None:
                        self._acquire(ln, held, stmt.lineno)
                        entered.append(ln)
                        held.append(ln)
                    elif item.context_expr is not None:
                        self._scan_expr(item.context_expr, held)
                self.walk(stmt.body, held)
                for ln in entered:
                    held.remove(ln)
                continue
            if isinstance(stmt, ast.If):
                # `if lock.acquire(blocking=False):` / `if X and
                # lock.acquire(False):` — the body runs lock-held
                acq = self._cond_acquires(stmt.test)
                self._scan_expr(stmt.test, held)
                self._scan_writes(stmt, held)
                self.walk(stmt.body, held + acq)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter, held)
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, held)
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.Try):
                self.walk(stmt.body, held)
                for h in stmt.handlers:
                    self.walk(h.body, held)
                self.walk(stmt.orelse, held)
                self.walk(stmt.finalbody, held)
                continue
            # bare acquire/release statements (the try/finally idiom)
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute):
                    recv = self._lock_of_call_recv(call)
                    if call.func.attr == "acquire" and recv is not None:
                        if not self._is_nonblocking_acquire(call):
                            self._acquire(recv, held, call.lineno)
                        held.append(recv)
                        continue
                    if call.func.attr == "release" and recv is not None:
                        if recv in held:
                            held.remove(recv)
                        continue
            self._scan_expr(stmt, held)
            self._scan_writes(stmt, held)
            self._scan_calls_out(stmt, held)

    def _cond_acquires(self, test) -> list[str]:
        out = []
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"
            ):
                ln = self._lock_of_call_recv(sub)
                if ln is not None:
                    out.append(ln)
        return out


# -- graph utilities ----------------------------------------------------------


def _find_cycles(edges: dict[tuple[str, str], int]) -> list[list[str]]:
    """Elementary cycles in the lock graph (DFS; the graphs are tiny).
    Each cycle is reported once, rotated to its lexicographic minimum."""
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen: set[tuple[str, ...]] = set()
    cycles: list[list[str]] = []

    def dfs(start, node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                i = cyc.index(min(cyc))
                key = tuple(cyc[i:] + cyc[:i])
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(key))
            elif nxt not in on_path and nxt > start:
                # only explore nodes >= start: each cycle found from its
                # smallest node exactly once
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return cycles


# -- the linter ---------------------------------------------------------------


def lint_source(
    src: str,
    relpath: str,
    schema_index: dict[str, set[str]] | None = None,
) -> list[Finding]:
    """Lint one file's source with every applicable RPH rule.
    ``schema_index`` is the OBSERVABILITY.md kind→keys table for RPH305
    (None disables that rule — e.g. linting outside the repo)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [
            Finding("RPH000", relpath, e.lineno or 0, "<module>",
                    f"syntax error: {e.msg}")
        ]
    mod = _HostModule(tree, relpath)
    findings: list[Finding] = []

    def add(rule, lineno, msg):
        findings.append(Finding(rule, relpath, lineno, mod.enclosing(lineno), msg))

    # one walker per function; module-level statements get their own
    walkers: dict[tuple[str | None, str], _RegionWalker] = {}
    for key, node in mod.functions.items():
        w = _RegionWalker(mod, key[0])
        w.walk(node.body, [])
        walkers[key] = w
    top = _RegionWalker(mod, None)
    top.walk(
        [s for s in tree.body
         if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))],
        [],
    )
    walkers[(None, "<module>")] = top

    # -- RPH301: per-module lock graph + same-module call closure ------------
    if _rule_applies("RPH301", relpath) or _rule_applies("RPH302", relpath):
        # transitive acquire-sets per function (fixpoint over held_calls
        # and plain calls: callee acquisitions happen under the caller's
        # held set)
        acq: dict[tuple, set[str]] = {
            k: set(w.acquired) for k, w in walkers.items()
        }
        calls_of: dict[tuple, set[tuple]] = {}
        for key, node in mod.functions.items():
            calls_of[key] = {
                k for k in mod._call_keys(node, key[0], include_refs=False)
                if k in walkers
            }
        changed = True
        while changed:
            changed = False
            for key, callees in calls_of.items():
                for c in callees:
                    new = acq[c] - acq[key]
                    if new:
                        acq[key] |= new
                        changed = True

        edges: dict[tuple[str, str], int] = {}
        for w in walkers.values():
            for e, ln in w.edges.items():
                edges.setdefault(e, ln)
            # closure edges: calling f() while holding H implies H ->
            # every lock f (transitively) acquires
            for callee, held, ln in w.held_calls:
                for h in held:
                    if h.startswith("?anon:"):
                        continue
                    for a in acq.get(callee, ()):
                        if a != h and not a.startswith("?anon:"):
                            edges.setdefault((h, a), ln)

        if _rule_applies("RPH301", relpath):
            for cyc in _find_cycles(edges):
                lns = sorted(
                    edges[(cyc[i], cyc[(i + 1) % len(cyc)])]
                    for i in range(len(cyc))
                    if (cyc[i], cyc[(i + 1) % len(cyc)]) in edges
                )
                add(
                    "RPH301", lns[0] if lns else 1,
                    "lock-order inversion: acquisition cycle "
                    + " -> ".join(cyc + [cyc[0]])
                    + f" (edge sites: {', '.join(map(str, lns))}) — two "
                    "threads walking this cycle from different entries "
                    "deadlock; impose one global order (document it at "
                    "the lock's construction site) or collapse the locks",
                )

    # -- RPH302: blocking call while a lock is held --------------------------
    if _rule_applies("RPH302", relpath):
        for key, w in walkers.items():
            for lineno, what, held, recv_node in w.blocking:
                if not held:
                    continue
                if what == ".wait()" or what == ".wait_for()":
                    # Condition.wait on its own (held) lock releases it —
                    # the one legal blocking shape under a lock
                    if recv_node is not None and recv_node in held:
                        others = [h for h in held if h != recv_node]
                        if not others:
                            continue
                        held = tuple(others)
                if what == ".join()" and not any(
                    not h.startswith("?anon:") for h in held
                ):
                    continue
                add(
                    "RPH302", lineno,
                    f"blocking call {what} while holding "
                    f"{', '.join(sorted(set(held)))} — the critical "
                    "section now spans an unbounded wait; move the "
                    "blocking call outside the lock (snapshot state "
                    "under the lock, act after releasing), or waive "
                    "with the design justification",
                )

        # interprocedural half: a same-module call made under a lock
        # whose callee (transitively) blocks is the same hazard one
        # frame removed — fabric's ``with self._send_lock:
        # self._write_batch(...)`` shape, where the sendmsg lives in the
        # callee.  One representative blocking chain per callee.
        blocker_of: dict[tuple, str] = {}
        for key in sorted(walkers, key=str):
            w = walkers[key]
            descs = set()
            for _, what, held, recv_node in w.blocking:
                if what in (".wait()", ".wait_for()") and recv_node is not None \
                        and recv_node in held:
                    # releases its own lock, but a CALLER's lock stays
                    # held across the wait — still blocking one frame up
                    descs.add(f"{what} [own-lock wait]")
                else:
                    descs.add(what)
            if descs:
                blocker_of[key] = sorted(descs)[0]
        changed = True
        while changed:
            changed = False
            for key in sorted(calls_of, key=str):
                if key in blocker_of:
                    continue
                for c in sorted(calls_of[key], key=str):
                    if c in blocker_of:
                        blocker_of[key] = f"{c[1]}() -> {blocker_of[c]}"
                        changed = True
                        break
        for key, w in walkers.items():
            for callee, held, lineno in w.held_calls:
                if callee not in blocker_of:
                    continue
                add(
                    "RPH302", lineno,
                    f"call to {callee[1]}() while holding "
                    f"{', '.join(sorted(set(held)))} blocks "
                    f"({blocker_of[callee]}) — the critical section "
                    "spans the callee's unbounded wait; hoist the call "
                    "out of the lock or waive with the design "
                    "justification",
                )

    # -- RPH303: non-daemon thread with no join in scope ---------------------
    if _rule_applies("RPH303", relpath):
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and mod.resolve(node.func) == "threading.Thread"
            ):
                continue
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                    daemon = kw.value.value
            if daemon is True:
                continue
            # find the enclosing function; a `.join(` anywhere in it (or
            # in its class when the thread lands on self.<attr>) clears
            scope_node = None
            for fn_node in mod.qualname_of:
                end = getattr(fn_node, "end_lineno", fn_node.lineno)
                if fn_node.lineno <= node.lineno <= end:
                    if scope_node is None or (
                        end - fn_node.lineno
                        < getattr(scope_node, "end_lineno", 0) - scope_node.lineno
                    ):
                        scope_node = fn_node
            search_nodes = [scope_node] if scope_node is not None else [tree]
            cls = mod._enclosing_class(node.lineno)
            if cls is not None:
                search_nodes += [
                    f for (c, _), f in mod.functions.items() if c == cls
                ]
            joined = False
            for sn in search_nodes:
                for sub in ast.walk(sn):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "join"
                    ):
                        joined = True
                        break
                if joined:
                    break
            if not joined:
                add(
                    "RPH303", node.lineno,
                    "non-daemon Thread never joined in its creating scope "
                    "— leaks past main-exit and holds the process open; "
                    "pass daemon=True (with a bounded join on the "
                    "shutdown path) or join it where it was spawned",
                )

    # -- RPH304: attr written from >=2 thread roots, >=1 site unlocked -------
    if _rule_applies("RPH304", relpath):
        # attr -> {root labels} and the write sites
        by_attr: dict[tuple[str | None, str], dict] = {}
        for key, w in walkers.items():
            roots = mod.root_reach.get(key, set())
            if not roots:
                continue
            cls = key[0]
            for attr, lineno, locked in w.writes:
                ent = by_attr.setdefault((cls, attr), {"roots": set(), "sites": []})
                ent["roots"] |= roots
                ent["sites"].append((lineno, locked))
        for (cls, attr), ent in sorted(by_attr.items(), key=lambda e: str(e[0])):
            if len(ent["roots"]) < 2:
                continue
            unlocked = [ln for ln, locked in ent["sites"] if not locked]
            if not unlocked:
                continue
            add(
                "RPH304", min(unlocked),
                f"attribute self.{attr} written from "
                f"{len(ent['roots'])} distinct thread roots with an "
                "unlocked write site — torn/stale reads under free-"
                "running threads; guard every write with one lock, or "
                "waive with the hand-off protocol that makes it safe",
            )

    # -- RPH305: journal record emit sites vs the schema index ---------------
    if _rule_applies("RPH305", relpath) and schema_index:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Dict):
                continue
            kind = None
            literal_keys: list[str] = []
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    literal_keys.append(k.value)
                    if k.value == "kind" and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        kind = v.value
            if kind is None:
                continue
            if kind not in schema_index:
                add(
                    "RPH305", node.lineno,
                    f'journal record kind "{kind}" is not documented in '
                    "OBSERVABILITY.md's journal record schema index — "
                    "add its row (kind + key set) so readers and "
                    "emitters cannot drift",
                )
                continue
            allowed = schema_index[kind]
            extra = [k for k in literal_keys if k not in allowed]
            if extra:
                add(
                    "RPH305", node.lineno,
                    f'journal record kind "{kind}" emits undocumented '
                    f"key(s) {sorted(extra)} — OBSERVABILITY.md's schema "
                    "index doesn't list them (the r22 drift class); "
                    "document the keys or drop them",
                )

    return findings


def lint_paths(paths, repo_root: str) -> list[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)
    with the plane-3 rules; the RPH305 schema index loads once from
    ``<repo_root>/OBSERVABILITY.md``."""
    schema = load_schema_index(os.path.join(repo_root, "OBSERVABILITY.md"))
    findings: list[Finding] = []
    files: list[str] = []
    for p in paths:
        ap = os.path.join(repo_root, p) if not os.path.isabs(p) else p
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        elif ap.endswith(".py"):
            files.append(ap)
    for f in sorted(set(files)):
        rel = os.path.relpath(f, repo_root).replace(os.sep, "/")
        try:
            src = open(f).read()
        except OSError as e:
            findings.append(Finding("RPH000", rel, 0, "<module>", f"unreadable: {e}"))
            continue
        findings.extend(lint_source(src, rel, schema))
    return findings
