"""Compiled-schedule overlap analysis for the pipelined exchange (r11).

The r8 exchange ran its two roll legs as two sequential shard_map
regions: every response-leg ppermute was data-dependent on the FULL
request-leg stitch, so the compiled schedule had to finish the merge
before the first crossing send of leg 2 could issue.  The r11 fused
region (``parallel/shift.shard_roll_pipelined``) issues each leg-2 send
off only the two leg-1 pieces its window needs — the dependency graph
leaves the scheduler free to overlap crossing sends with merge compute.

This module makes that claim CHECKABLE from the optimized HLO text
(``scripts/profile_mesh.py --overlap``): it parses instruction-level
def-use inside every computation, finds the exchange-phase
collective-permutes, and asks two questions:

* **dependent sends** — does any collective-permute transitively depend
  on another permute's result THROUGH at least one non-trivial compute
  op?  That is the signature of the fused leg loop: leg-2's send
  operand is built (stitch + merge elementwise) from leg-1 receives
  inside one region.  The sequential program can never show it — its
  legs live in separate conditionals, and cross-computation inputs are
  opaque parameters.
* **interleaving** — inside such a region, does merge compute that
  consumes permute results sit BETWEEN permutes in the schedule order
  (i.e. the crossing sends no longer strictly precede the merge)?

The analysis is deliberately topology-free: it never needs to know
which send belongs to which leg — only the dependency shape that
permits overlap.  On backends with async collectives the
``collective-permute-start`` is the send issue point; the plain
``collective-permute`` spelling (XLA:CPU) is handled identically.
"""

from __future__ import annotations

import re

# ops that neither compute nor move data meaningfully: a permute→permute
# path through only these is forwarding, not merge work
_TRIVIAL_OPS = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "copy", "copy-start", "copy-done", "after-all", "iota",
}

_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(?:[^=]*?\s)?([\w\-]+)\(")
_NAME_RE = re.compile(r"%?([\w.\-]+)")


def _op_of(line: str) -> str | None:
    m = _DEF_RE.match(line)
    return m.group(2) if m else None


def parse_computations(hlo_path: str) -> dict:
    """{computation: [instr...]} with per-instruction
    ``{name, op, operands, pos, phase}`` — operands resolved against the
    names already defined in the same computation (HLO is in SSA order;
    cross-computation references enter as parameters and carry no dep
    info, which is exactly the blindness the dependent-send test
    exploits)."""
    from ringpop_tpu.analysis.hlo_census import _phase_of

    comps: dict = {}
    cur = None
    defined: dict = {}
    for line in open(hlo_path):
        stripped = line.rstrip()
        if stripped.endswith("{") and not line.lstrip().startswith("ROOT"):
            cur = stripped.split()[0].lstrip("%")
            comps[cur] = []
            defined = {}
            continue
        if cur is None:
            continue
        if line.strip() == "}":
            cur = None
            continue
        m = _DEF_RE.match(line)
        if not m:
            continue
        name, op = m.group(1), m.group(2)
        rhs = line.split("=", 1)[1]
        # strip metadata/attrs so operand-name scanning doesn't pick up
        # computation references (to_apply=..., branch_computations=...)
        rhs = re.split(r",\s*(?:metadata|backend_config|sharding)=", rhs)[0]
        rhs = re.sub(r"\w+=\{[^}]*\}", " ", rhs)
        rhs = re.sub(r"(?:to_apply|calls|body|condition|true_computation|"
                     r"false_computation)=%?[\w.\-]+", " ", rhs)
        operands = [
            t for t in _NAME_RE.findall(rhs)
            if t in defined and t != name
        ]
        instr = {
            "name": name,
            "op": op,
            "operands": operands,
            "pos": len(comps[cur]),
            "phase": _phase_of(line),
        }
        comps[cur].append(instr)
        defined[name] = instr
    return comps


def _is_permute(op: str) -> bool:
    return op in ("collective-permute", "collective-permute-start")


def analyze(hlo_path: str, phases=("rumor-exchange", "shard-roll")) -> dict:
    """Per-region overlap report over every computation holding >= 2
    exchange-phase collective-permutes.  See module docstring for the
    two properties reported."""
    comps = parse_computations(hlo_path)
    regions = []
    for cname, instrs in comps.items():
        perms = [i for i in instrs
                 if _is_permute(i["op"]) and i["phase"] in phases]
        if len(perms) < 2:
            continue
        by_name = {i["name"]: i for i in instrs}
        # forward DP in SSA order: pd = depends (transitively) on a
        # permute (or is one); pvc = some permute→here path crosses a
        # non-trivial compute op strictly between
        pd: dict = {}
        pvc: dict = {}
        for i in instrs:
            d = _is_permute(i["op"])
            v = False
            for o in i["operands"]:
                oi = by_name[o]
                d = d or pd.get(o, False)
                via = pvc.get(o, False) or (
                    pd.get(o, False)
                    and oi["op"] not in _TRIVIAL_OPS
                    and not _is_permute(oi["op"])
                )
                v = v or via
            pd[i["name"]], pvc[i["name"]] = d, v
        dependent_sends = [
            p["name"] for p in perms
            if any(
                pvc.get(o, False)
                or (pd.get(o, False)
                    and by_name[o]["op"] not in _TRIVIAL_OPS
                    and not _is_permute(by_name[o]["op"]))
                for o in p["operands"]
            )
        ]
        # schedule view: merge ops = non-trivial compute consuming permute
        # results; interleaved iff one sits before the last crossing send
        perm_pos = [p["pos"] for p in perms]
        merge_pos = [
            i["pos"] for i in instrs
            if not _is_permute(i["op"]) and i["op"] not in _TRIVIAL_OPS
            and any(pd.get(o, False) for o in i["operands"])
        ]
        interleaved = bool(merge_pos) and min(merge_pos) < max(perm_pos)
        regions.append({
            "computation": cname,
            "sends": len(perms),
            "send_positions": perm_pos,
            "merge_positions": merge_pos[:16],
            "dependent_sends": dependent_sends,
            "interleaved": interleaved,
        })
    overlapped = [r for r in regions if r["dependent_sends"] and r["interleaved"]]
    return {
        "regions": regions,
        "overlap": bool(overlapped),
        "overlapped_regions": [r["computation"] for r in overlapped],
    }


def print_report(report: dict) -> None:
    regs = report["regions"]
    print(f"\n== exchange overlap report ({len(regs)} "
          f"permute-bearing region(s)) ==")
    for r in regs:
        dep = len(r["dependent_sends"])
        print(f"  {r['computation'][:56]:56s} sends={r['sends']:2d} "
              f"dependent={dep} interleaved={r['interleaved']}")
        if dep:
            first_merge = min(r["merge_positions"]) if r["merge_positions"] else None
            print(f"    send schedule positions {r['send_positions']}, "
                  f"first permute-consuming merge op at {first_merge} — "
                  "crossing sends do NOT strictly precede the merge")
    if report["overlap"]:
        print("  verdict: PIPELINED — response-leg sends issue off partial "
              "request-leg receives while the merge computes "
              f"({', '.join(report['overlapped_regions'][:4])})")
    else:
        print("  verdict: SEQUENTIAL — every crossing send strictly precedes "
              "the merge that consumes its leg (no overlap window)")
