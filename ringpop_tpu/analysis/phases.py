"""Canonical protocol-phase vocabulary — ONE source of truth.

The r7 telemetry plane attributes every censused collective to the
``jax.named_scope`` protocol phase that emitted it; the r8 phase budget
ratchets the exchange/peer-choice rows; and the jaxlint planes (both the
AST scope-coverage rule and the jaxpr/HLO confinement checks) decide
"is this scope name meaningful" and "may this phase carry collectives"
from the same vocabulary.  Scattered copies of these tuples silently
drifting apart is exactly the class of bug a linter exists to prevent,
so ``scripts/profile_mesh.py``, ``analysis/astlint.py`` and
``analysis/trace_checks.py`` all import from here.
"""

from __future__ import annotations

# protocol-phase named scopes (jax.named_scope in sim/lifecycle.py,
# sim/delta.py, sim/packbits.py, parallel/shift.py) — XLA carries them
# through to each instruction's metadata op_name, which is how a censused
# collective gets attributed to the protocol phase that emitted it.
# Outermost-first: a collective under "rumor-exchange/row-reduce" belongs
# to the exchange phase.
PHASES = (
    "fault-plan",
    "tick-prologue",
    "ping-target",
    "rumor-exchange",
    "heal",
    "piggyback-counters",
    "timers-fold",
    "peer-choice",
    "candidate-select",
    "alloc-seed",
    "commit",
    "telemetry",
    "detect-walk",
    "view-checksum",
    "row-reduce",
    "set-bit",
    "shard-roll",
)

# the phases profile_mesh --phase-budget ratchets (r8): the exchange legs
# must stay ppermute-only and the peer-choice draws collective-free — a
# regression in either can hide inside a roughly-unchanged global total,
# which is exactly what the per-phase ratchet exists to catch
PHASE_BUDGET_PHASES = ("rumor-exchange", "ping-target", "peer-choice", "shard-roll")

# phases that must carry ZERO cross-chip collectives in any compiled
# sharded program (jaxlint RPJ203/RPJ206 "forbid by construction" — the
# static extension of the r8 ratchet).  peer-choice: under rng="counter"
# the [N, P] draw is elementwise in (node, column), so a collective here
# means the partition-invariant RNG stopped being shard-local (the
# ~12 MB/chip/tick threefry all-reduce coming back).  fault-plan: the
# chaos plane's ``faults_at`` timeline evaluation is elementwise in the
# node lane by construction (sim/chaos.py) — a collective here means
# fault evaluation stopped being shard-local.  "(unattributed)" is
# forbidden too: a collective with no phase scope defeats the whole
# attribution plane — extend the named_scope coverage instead.
FORBIDDEN_COLLECTIVE_PHASES = ("peer-choice", "fault-plan", "(unattributed)")


def collective_phase_allowed(phase: str) -> bool:
    """May an HLO/jaxpr collective be attributed to ``phase``?  Canonical
    phases other than the forbidden set, plus the ``loop:<function>``
    bucket the census uses for ops the SPMD partitioner re-homed onto a
    loop boundary (e.g. the detect walk's learned-plane replication
    hoisted to the tick loop)."""
    if phase in FORBIDDEN_COLLECTIVE_PHASES:
        return False
    return phase in PHASES or phase.startswith("loop:")
