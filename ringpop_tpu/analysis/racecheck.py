"""racecheck — the dynamic half of analysis plane 3 (``make race-smoke``).

hostlint (RPH301/302) reasons about lock order and blocking-under-lock
*statically*, one module at a time.  This module checks the same two
invariants against REAL executions: it monkeypatches ``threading.Lock``
/ ``RLock`` / ``Condition`` (and ``time.sleep``) with thin instrumented
wrappers that record, process-wide:

* the **dynamic lock-order graph** — an edge A→B whenever a thread
  acquires the lock allocated at site B while holding the one allocated
  at site A.  A cycle in this graph is a deadlock schedule some pair of
  threads can realize — the dynamic cross-check of RPH301, and it sees
  across modules where hostlint deliberately stops at file boundaries.
* **held-while-blocking events** — ``Condition.wait`` or ``time.sleep``
  entered while OTHER instrumented locks are held (the wait's own
  condition lock is excluded: wait releases it) — RPH302's cross-check.

Plus a **schedule-perturbation mode**: seeded, bounded random preemption
(a sub-millisecond sleep) injected at instrumentation points — before
lock acquisition and before condition waits — so the smokes rerun under
adversarial interleavings instead of the cooperative schedules a lightly
loaded box produces.  This is the rebuild's stand-in for Go's race
detector runs in the reference repo (``make test-race``): same suite,
hostile scheduler.  The decision stream is drawn from one seeded
``random.Random`` under the recorder's own (uninstrumented) lock, so a
seed names a reproducible perturbation sequence.

``scripts/race_harness.py`` installs this around the transport / serve /
dcn / gameday smokes and fails on dynamic cycles; its non-vacuity leg
reintroduces the r22 count-after-respond mutant and MUST see it caught.

Everything here is stdlib-only and jax-free.  Locks created BEFORE
``install()`` are untouched (module-import-time locks in third-party
code keep their exact stdlib behavior); wrappers orphaned by
``uninstall()`` keep working — they own a private real lock.
"""

from __future__ import annotations

import atexit
import json
import os
import random
import sys
import threading
import time
import _thread

from ringpop_tpu.analysis.hostlint import _find_cycles

_ORIG_LOCK_ALLOC = _thread.allocate_lock
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition
_ORIG_SLEEP = time.sleep

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SELF = os.path.abspath(__file__)


def _call_site() -> str:
    """`path:lineno` of the first frame outside racecheck + threading —
    the lock's allocation site, which names its node in the graph (all
    instances born at one site share a node, matching hostlint's
    per-attribute granularity)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _SELF and not fn.endswith(("threading.py", "_threading_local.py")):
            path = fn
            if path.startswith(_REPO + os.sep):
                path = os.path.relpath(path, _REPO).replace(os.sep, "/")
            return f"{path}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class Recorder:
    """Process-wide event sink.  Internals use REAL locks (allocated
    before patching) — the recorder must never route through its own
    instrumentation."""

    def __init__(self, seed=None, perturb=False, p=0.02,
                 sleep_range_us=(300, 3000)):
        self.seed = seed
        self.perturb = perturb
        self.p = p
        self.sleep_range_us = sleep_range_us
        self._rng = random.Random(seed)
        self._mx = _ORIG_LOCK_ALLOC()
        self._tls = threading.local()  # .held: list[(site, lock_id)]
        self.edges: dict[tuple[str, str], int] = {}
        self.block_events: list[dict] = []
        self.sites: dict[str, int] = {}  # site -> locks allocated there
        self.perturb_count = 0
        self.acquire_count = 0

    # -- held-stack bookkeeping (called from the wrappers) -------------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def on_alloc(self, site: str) -> None:
        with self._mx:
            self.sites[site] = self.sites.get(site, 0) + 1

    def maybe_perturb(self) -> None:
        """One seeded preemption decision.  Drawn under the recorder
        lock so the decision STREAM is a pure function of the seed; the
        draw is cheap (two rng calls) and the sleep happens outside."""
        if not self.perturb:
            return
        lo, hi = self.sleep_range_us
        with self._mx:
            hit = self._rng.random() < self.p
            dt = self._rng.uniform(lo, hi) * 1e-6 if hit else 0.0
            if hit:
                self.perturb_count += 1
        if hit:
            _ORIG_SLEEP(dt)

    def on_acquired(self, site: str, lock_id: int) -> None:
        held = self._held()
        with self._mx:
            self.acquire_count += 1
            for h_site, h_id in held:
                if h_site != site:  # same-site edges are lock reentry
                    # across instances, not an order: excluded like
                    # hostlint's self-edges
                    self.edges.setdefault((h_site, site), 0)
                    self.edges[(h_site, site)] += 1
        held.append((site, lock_id))

    def on_released(self, site: str, lock_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == lock_id:
                del held[i]
                return

    def on_blocking(self, op: str, own_lock_id=None) -> None:
        held = [
            (s, i) for (s, i) in self._held() if i != own_lock_id
        ]
        if not held:
            return
        site = _call_site()
        with self._mx:
            self.block_events.append({
                "op": op, "site": site,
                "held": sorted({s for s, _ in held}),
                "thread": threading.current_thread().name,
            })

    # -- results -------------------------------------------------------------

    def cycles(self) -> list:
        with self._mx:
            edges = dict(self.edges)
        return _find_cycles(edges)

    def report(self) -> dict:
        with self._mx:
            edges = sorted(self.edges.items())
            blocks = list(self.block_events)
            sites = dict(self.sites)
            nper, nacq = self.perturb_count, self.acquire_count
        return {
            "seed": self.seed,
            "perturb": self.perturb,
            "p": self.p,
            "lock_sites": sites,
            "edges": [[a, b, n] for (a, b), n in edges],
            "cycles": self.cycles(),
            "block_events": blocks,
            "perturb_count": nper,
            "acquire_count": nacq,
        }

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.report(), fh, indent=1, sort_keys=True)
            fh.write("\n")


# -- the instrumented primitives ----------------------------------------------


class _InstrumentedLock:
    """Drop-in ``threading.Lock`` riding a private real lock.  Survives
    every stdlib use (Condition's acquire/release protocol included) and
    keeps working after uninstall."""

    def __init__(self, recorder: Recorder, site: str):
        self._rec = recorder
        self._inner = _ORIG_LOCK_ALLOC()
        self._site = site
        recorder.on_alloc(site)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._rec.maybe_perturb()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._rec.on_acquired(self._site, id(self))
        return got

    def release(self) -> None:
        self._rec.on_released(self._site, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib os.register_at_fork consumers (concurrent.futures,
        # threading internals) call this on forked children
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<racecheck.Lock @{self._site} {self._inner!r}>"


class _InstrumentedRLock:
    """Drop-in ``threading.RLock``: forwards the private Condition
    protocol (``_is_owned``/``_release_save``/``_acquire_restore``) to
    the real RLock so ``Condition(RLock())`` keeps its exact stdlib
    semantics, with held-stack bookkeeping on each transition."""

    def __init__(self, recorder: Recorder, site: str):
        self._rec = recorder
        self._inner = _ORIG_RLOCK()
        self._site = site
        self._depth = 0  # owner-side only; guarded by holding _inner
        recorder.on_alloc(site)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._rec.maybe_perturb()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._depth += 1
            if self._depth == 1:
                self._rec.on_acquired(self._site, id(self))
        return got

    __enter__ = acquire

    def release(self) -> None:
        if self._depth == 1:
            self._rec.on_released(self._site, id(self))
        self._depth -= 1
        self._inner.release()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol ------------------------------------------------
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        self._rec.on_released(self._site, id(self))
        depth, self._depth = self._depth, 0
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        self._depth = depth
        self._rec.on_acquired(self._site, id(self))

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()
        self._depth = 0

    def __repr__(self) -> str:
        return f"<racecheck.RLock @{self._site} {self._inner!r}>"


def _make_condition_class(recorder: Recorder):
    class _InstrumentedCondition(_ORIG_CONDITION):
        # the default lock comes from threading's *global* ``RLock`` name,
        # which install() has already patched — a bare Condition() is
        # instrumented end to end with no code here

        def wait(self, timeout=None):
            recorder.on_blocking(
                "Condition.wait", own_lock_id=id(self._lock))
            recorder.maybe_perturb()
            return super().wait(timeout)

    return _InstrumentedCondition


_STATE: dict = {"recorder": None}


def current() -> Recorder | None:
    """The installed recorder, or None."""
    return _STATE["recorder"]


def install(seed=None, perturb: bool = False, p: float = 0.02,
            sleep_range_us=(300, 3000)) -> Recorder:
    """Patch ``threading.Lock``/``RLock``/``Condition`` and
    ``time.sleep``; every lock allocated from here on is recorded.
    ``Event``/``Barrier``/``Semaphore``/``queue.Queue`` pick the patched
    primitives up automatically — their constructors resolve
    ``Lock``/``Condition`` through threading's module globals at call
    time.  Idempotent per process: a second install raises."""
    if _STATE["recorder"] is not None:
        raise RuntimeError("racecheck already installed")
    rec = Recorder(seed=seed, perturb=perturb, p=p,
                   sleep_range_us=sleep_range_us)
    _STATE["recorder"] = rec

    def make_lock():
        return _InstrumentedLock(rec, _call_site())

    def make_rlock():
        return _InstrumentedRLock(rec, _call_site())

    def patched_sleep(secs):
        rec.on_blocking("time.sleep")
        _ORIG_SLEEP(secs)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = _make_condition_class(rec)
    time.sleep = patched_sleep

    report_path = os.environ.get("RINGPOP_RACE_REPORT")
    if report_path:
        atexit.register(lambda: rec.dump(report_path))
    return rec


def uninstall() -> Recorder | None:
    """Restore the stdlib primitives.  Wrappers already handed out keep
    functioning (each owns a private real lock); they just stop feeding
    new edges once their recorder is detached here."""
    rec = _STATE["recorder"]
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    threading.Condition = _ORIG_CONDITION
    time.sleep = _ORIG_SLEEP
    _STATE["recorder"] = None
    return rec
