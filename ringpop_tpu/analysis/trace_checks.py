"""Plane 2 of jaxlint: jaxpr/HLO invariant checks on the public jitted
entry points.

Plane 1 reads source; this plane reads the TRACED PROGRAM — the artifact
the r6–r8 invariants are actually facts about.  Ten entry points
(lifecycle step, delta step, the chaos-enabled variants of both — the
same engines driven by a time-varying ``chaos.FaultPlan`` with every
scenario leg populated — the r12 BATCHED chaos-MC step (a heterogeneous
stacked plan vmapped over (plan, state), the Monte-Carlo fleet's
program), detect walk, shard_roll exchange, telemetry fetch, and the r11
sequential-exchange variants of both steps, sharded only) are traced
dense AND under the 8-way virtual mesh (4×2 node × rumor — the
``profile_mesh`` topology), then checked:

* **RPJ201 f64-in-trace** — no 64-bit aval anywhere (the engines are
  built on uint32 bit-packing and int32 keys; a stray f64/i64 doubles
  HBM traffic or — x64 being disabled — silently truncates).
* **RPJ202 host-callback-in-trace** — no callback/infeed primitives: a
  host round-trip inside a jitted body serializes the dispatch pipeline
  (the round-1 lesson that moved the detect walk on-device).
* **RPJ203 collective-confinement (jaxpr)** — every *explicit*
  collective primitive sits under an allowed protocol phase scope, and
  the forbidden phases (peer-choice — the r8 zero-collective
  certificate) carry none.
* **RPJ204 donation-aliased** — lowering the tick block with the state
  donated must actually alias every state leaf to an output
  (``tf.aliasing_output``); a silent copy doubles peak memory at the 1M
  headline.
* **RPJ205 sharded-trace-equivalence** — the sharded
  (``exchange_mesh``) and unsharded traces of the SAME engine must be
  structurally equal modulo sharding ops and the exchange region (the
  one deliberately different lowering, excised by its ``rumor-exchange``
  scope on both sides).  This is the static shadow of the r8
  bit-identity certificates: any OTHER structural divergence between the
  two programs is a partition-dependence bug by construction.
* **RPJ206 collective-confinement (HLO)** — the compiled sharded tick's
  full collective census (``analysis/hlo_census``, the profile_mesh
  parser) re-checked against the phase whitelist: this is where
  partitioner-INTRODUCED collectives (resharding all-gathers etc.)
  appear, extending the jaxpr-level check from "no explicit collective
  escaped its phase" to "no collective at all, however it arose, lands
  in a forbidden phase".

Fixture corpus: ``tests/analysis_fixtures/<slug>/{trip,clean}.py`` for
the jaxpr-plane rules define ``build()`` (returning ``(fn, args)``) plus
``JAXLINT_TRACE_RULE = "<rule id>"``; ``scripts/jaxlint.py`` dispatches
them to :func:`check_fixture`.
"""

from __future__ import annotations

import contextlib
import functools
import os
import tempfile

from ringpop_tpu.analysis import hlo_census
from ringpop_tpu.analysis.findings import Finding
from ringpop_tpu.analysis.phases import (
    PHASES,
    collective_phase_allowed,
)

TRACE_RULES = {
    "RPJ201": "f64-in-trace",
    "RPJ202": "host-callback-in-trace",
    "RPJ203": "collective-confinement",
    "RPJ204": "donation-aliased",
    "RPJ205": "sharded-trace-equivalence",
    "RPJ206": "hlo-collective-confinement",
}

# explicit cross-device collective primitives at jaxpr level
COLLECTIVE_PRIMS = {
    "ppermute", "pshuffle", "psum", "pmax", "pmin", "pmean", "all_gather",
    "all_to_all", "pgather", "pbroadcast", "psum_scatter", "reduce_scatter",
    "psum_invariant",
}
# host round-trip primitives
CALLBACK_PRIMS = {"infeed", "outfeed", "outside_call"}

# primitives that exist only to express placement/partitioning — the
# "modulo sharding ops" of the RPJ205 equivalence statement
SHARDING_PRIMS = {
    "shard_map", "sharding_constraint", "with_sharding_constraint",
    "device_put",
} | COLLECTIVE_PRIMS

# scopes excised from the RPJ205 skeletons: the exchange region is the
# one place the sharded program intentionally lowers differently
# (shard_roll's switch/ppermute/stitch vs the materialized-index
# gathers); everything outside it must match exactly
EXCISED_SCOPES = ("rumor-exchange", "shard-roll")

_BAD_AVAL_DTYPES = ("float64", "int64", "uint64", "complex128")


# -- jaxpr walking -----------------------------------------------------------


def _sub_jaxprs(eqn):
    """Inner (Closed)Jaxprs of one eqn, wherever its params keep them."""
    import jax

    out = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if isinstance(item, jax.core.ClosedJaxpr):
                out.append(item.jaxpr)
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                out.append(item)
    return out


def iter_eqns(closed):
    """Yield ``(eqn, scope)`` over a ClosedJaxpr recursively; ``scope`` is
    the '/'-joined named-scope path with enclosing eqns' stacks prefixed
    (inner jaxpr eqns carry stacks relative to their trace point)."""
    def rec(jaxpr, prefix):
        for eqn in jaxpr.eqns:
            stack = str(eqn.source_info.name_stack)
            scope = "/".join(p for p in (prefix, stack) if p)
            yield eqn, scope
            for sub in _sub_jaxprs(eqn):
                yield from rec(sub, scope)

    yield from rec(closed.jaxpr, "")


def _phase_of_scope(scope: str) -> str:
    """Outermost canonical phase in a scope path, mirroring the HLO
    census's op_name attribution."""
    for part in scope.split("/"):
        if part in PHASES:
            return part
    return "(unattributed)"


# -- the jaxpr-plane checks --------------------------------------------------


def check_no_64bit(entry: str, closed) -> list[Finding]:
    findings = []
    seen = set()
    for eqn, scope in iter_eqns(closed):
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in _BAD_AVAL_DTYPES and (eqn.primitive.name, dt) not in seen:
                seen.add((eqn.primitive.name, dt))
                findings.append(
                    Finding(
                        "RPJ201", f"<trace:{entry}>", 0, entry,
                        f"{dt} aval on primitive {eqn.primitive.name!r} "
                        f"(scope {scope or '-'}): the engines contract to "
                        "32-bit device types — a 64-bit value doubles HBM "
                        "traffic and breaks the packed-plane layout",
                    )
                )
    return findings


def check_no_callbacks(entry: str, closed) -> list[Finding]:
    findings = []
    for eqn, scope in iter_eqns(closed):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMS or "callback" in name:
            findings.append(
                Finding(
                    "RPJ202", f"<trace:{entry}>", 0, entry,
                    f"host callback primitive {name!r} (scope "
                    f"{scope or '-'}) inside a jitted entry point — a "
                    "device→host round-trip per execution serializes the "
                    "dispatch pipeline",
                )
            )
    return findings


def check_collective_confinement(entry: str, closed) -> list[Finding]:
    """Jaxpr-level RPJ203: explicit collectives only under allowed phase
    scopes; the forbidden phases carry none."""
    findings = []
    for eqn, scope in iter_eqns(closed):
        if eqn.primitive.name not in COLLECTIVE_PRIMS:
            continue
        phase = _phase_of_scope(scope)
        if not collective_phase_allowed(phase):
            findings.append(
                Finding(
                    "RPJ203", f"<trace:{entry}>", 0, entry,
                    f"collective {eqn.primitive.name!r} attributed to "
                    f"phase {phase!r} (scope {scope or '-'}): the r8 "
                    "budget allows this phase ZERO collectives — the "
                    "partition-invariant construction regressed",
                )
            )
    return findings


# -- RPJ205: structural equivalence modulo sharding --------------------------


def trace_skeleton(closed, excised_scopes=EXCISED_SCOPES) -> list[tuple]:
    """Canonical structural skeleton of a trace: the recursive sequence of
    (primitive, out-shapes/dtypes), with sharding primitives and the
    excised scopes removed (sub-jaxprs of excised/sharding eqns are not
    descended — a shard_map region vanishes whole)."""
    skel: list[tuple] = []

    def rec(jaxpr, prefix):
        for eqn in jaxpr.eqns:
            stack = str(eqn.source_info.name_stack)
            scope = "/".join(p for p in (prefix, stack) if p)
            parts = scope.split("/")
            if any(s in parts for s in excised_scopes):
                continue
            if eqn.primitive.name in SHARDING_PRIMS:
                continue
            outs = tuple(
                (str(v.aval.dtype), tuple(v.aval.shape))
                for v in eqn.outvars
                if hasattr(v, "aval") and hasattr(v.aval, "dtype")
            )
            subs = _sub_jaxprs(eqn)
            if subs:
                skel.append(("enter", eqn.primitive.name, outs))
                for sub in subs:
                    rec(sub, scope)
                skel.append(("exit", eqn.primitive.name))
            else:
                skel.append((eqn.primitive.name, outs))

    rec(closed.jaxpr, "")
    return skel


def check_structural_equivalence(entry: str, dense, sharded) -> list[Finding]:
    """RPJ205: the two skeletons must be identical.  On mismatch, report
    the first divergence with local context — enough to name the op that
    exists in one program and not the other."""
    a, b = trace_skeleton(dense), trace_skeleton(sharded)
    if a == b:
        return []
    i = 0
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            break
    else:
        i = min(len(a), len(b))
    ctx = (
        f"first divergence at op {i}/{max(len(a), len(b))}: "
        f"dense={a[i] if i < len(a) else '<end>'} vs "
        f"sharded={b[i] if i < len(b) else '<end>'}"
    )
    return [
        Finding(
            "RPJ205", f"<trace:{entry}>", 0, entry,
            "sharded and unsharded traces differ structurally OUTSIDE the "
            f"exchange region ({ctx}) — a partition-dependent computation "
            "crept in; the bit-identity certificates no longer have a "
            "static shadow",
        )
    ]


# -- RPJ204: donation aliasing ----------------------------------------------


def check_donation(entry: str, lowered_text: str, n_leaves: int) -> list[Finding]:
    """The lowered module must alias every donated state leaf to an
    output (``tf.aliasing_output`` arg attributes)."""
    aliased = lowered_text.count("tf.aliasing_output")
    if aliased >= n_leaves:
        return []
    return [
        Finding(
            "RPJ204", f"<trace:{entry}>", 0, entry,
            f"only {aliased} of {n_leaves} donated state leaves alias an "
            "output (tf.aliasing_output) — a donated buffer is being "
            "silently copied, doubling peak memory at the 1M headline "
            "(shape/dtype drift between a carried leaf and its update?)",
        )
    ]


# -- RPJ206: compiled-HLO confinement ----------------------------------------


@contextlib.contextmanager
def _no_compile_cache():
    """Disable the persistent compilation cache around a censused compile.

    The cache keys executables on the metadata-STRIPPED program, so two
    programs differing only in named_scope/op_name alias to one cached
    text — a confinement check could then read another program's phase
    attribution (observed: a clean fixture served its trip twin's
    peer-choice metadata once a prior test dropped the cache's
    min-compile-time threshold to zero).  Phase attribution is only
    trustworthy on a fresh compile.

    The enable flag alone is NOT sufficient on this jax version once a
    cache dir has been configured and USED in the process: the cache
    singleton binds its directory at first use (the same trap
    ``accel._xla_target_bits`` documents) and a dir-backed entry written
    by an earlier session can still serve its alien phase metadata —
    observed as phantom peer-choice collectives in the fleet census with
    a warm repo cache.  So the dir is unset AND the singleton reset for
    the censused compile, then restored (and reset again) on exit."""
    import jax
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_enable_compilation_cache
    old_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_enable_compilation_cache", False)
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()  # unbind the first-use-bound directory
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", old)
        jax.config.update("jax_compilation_cache_dir", old_dir)
        _cc.reset_cache()  # rebind lazily on the next ordinary compile


def census_of_text(hlo_text: str) -> dict:
    """``hlo_census.parse_collectives`` over an in-memory compiled module
    (``compiled.as_text()``)."""
    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as f:
        f.write(hlo_text)
        path = f.name
    try:
        return hlo_census.parse_collectives(path)
    finally:
        os.unlink(path)


def _census_parse_guard(entry: str, census: dict) -> list[Finding]:
    """RPJ000 when the census parsed nothing — a zero-computation parse
    means dump/text format drift, and NO census verdict can be trusted
    until ``analysis/hlo_census.parse_collectives`` is fixed."""
    if census.get("total_computations", 0) != 0:
        return []
    return [
        Finding(
            "RPJ000", f"<trace:{entry}>", 0, entry,
            "compiled-HLO census parsed ZERO computations from a "
            "non-trivial module — dump/text format drift; fix "
            "analysis/hlo_census.parse_collectives before trusting "
            "any confinement result",
        )
    ]


def check_hlo_confinement(entry: str, hlo_text: str) -> list[Finding]:
    census = census_of_text(hlo_text)
    findings = []
    guard = _census_parse_guard(entry, census)
    if guard:
        return guard
    rows = list(hlo_census.executed_rows(census))
    if not rows:
        return [
            Finding(
                "RPJ000", f"<trace:{entry}>", 0, entry,
                "compiled sharded program censused ZERO collectives — "
                "either the parser drifted (r6 failure mode) or the mesh "
                "stopped partitioning; both need a human",
            )
        ]
    flagged = set()
    for comp, r in rows:
        phase = r.get("phase", "(unattributed)")
        if not collective_phase_allowed(phase) and (phase, r["kind"]) not in flagged:
            flagged.add((phase, r["kind"]))
            findings.append(
                Finding(
                    "RPJ206", f"<trace:{entry}>", 0, entry,
                    f"compiled {r['kind']} ({r['bytes']} B, computation "
                    f"{comp}) attributed to phase {phase!r}: the r8 "
                    "budget allows this phase ZERO collectives — the "
                    "partitioner found a way back in (run "
                    "scripts/profile_mesh.py for the full table)",
                )
            )
    return findings


def check_hlo_collective_free(entry: str, hlo_text: str) -> list[Finding]:
    """RPJ206 (collective-FREE flavor, r13): the serve-tier lookup
    programs are dense elementwise/searchsorted code — their compiled
    census must contain ZERO collectives.  Any collective here means the
    serving dispatch grew a cross-device dependency that would serialize
    every frontend's lookup behind it."""
    census = census_of_text(hlo_text)
    guard = _census_parse_guard(entry, census)
    if guard:
        return guard
    findings = []
    for comp, r in hlo_census.executed_rows(census):
        findings.append(
            Finding(
                "RPJ206", f"<trace:{entry}>", 0, entry,
                f"compiled {r['kind']} ({r['bytes']} B, computation "
                f"{comp}) in a serve-tier lookup program that is "
                "collective-free BY CONSTRUCTION — a cross-device "
                "dependency crept into the serving dispatch",
            )
        )
    return findings


# -- entry-point registry ----------------------------------------------------

# small but structurally faithful configs: big enough for every code path
# the 1M program runs (hierarchical select forced separately), divisible
# by the 4-way node axis, k a multiple of 32 * rumor-shards
_N, _K = 256, 64
_HLO_N = 2048


def _mesh8():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices("cpu")
    if len(devs) < 8:
        raise RuntimeError(
            f"jaxlint plane 2 needs the 8-way virtual mesh but only "
            f"{len(devs)} devices exist — set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "jax initializes (scripts/jaxlint.py does this)"
        )
    return Mesh(np.asarray(devs[:8]).reshape(4, 2), ("node", "rumor"))


def _faults(n):
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults

    up = np.ones(n, bool)
    up[:: max(n // 16, 1)] = False
    return DeltaFaults(up=jnp.asarray(up), drop_rate=0.05)


def _chaos_plan(n):
    """A FaultPlan exercising EVERY leg of the chaos vocabulary (churn +
    flap + scalar drop from the canonical smoke plan, plus a directed
    partition window and per-node loss) — the traced program whose
    fault evaluation RPJ203/RPJ206 pin collective-free and whose
    sharded/unsharded skeletons RPJ205 pins equal."""
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim import chaos

    group = np.zeros(n, np.int32)
    group[: n // 3] = 1
    dn = np.zeros(n, np.float32)
    dn[:: max(n // 16, 1)] = 0.2
    return chaos._merge_plans(
        chaos.scenario_plan("smoke", n, seed=0, horizon=64),
        chaos.FaultPlan(
            group=jnp.asarray(group),
            part_from=jnp.asarray(np.int32(4)),
            part_until=jnp.asarray(np.int32(32)),
            reach=jnp.asarray(np.asarray([[True, False], [True, True]])),
            drop_node=jnp.asarray(dn),
        ),
    )


def _stacked_plan(n):
    """A heterogeneous STACKED plan (r12, ``chaos.stack_plans``): the
    every-leg chaos plan plus a churn-only member, so the stacked program
    carries both populated legs and materialized defaults — the shape the
    Monte-Carlo fleet actually runs."""
    from ringpop_tpu.sim import chaos

    return chaos.stack_plans(
        [_chaos_plan(n), chaos.scenario_plan("churn", n, seed=1, horizon=64)]
    )


def _topo_plan(n):
    """The topology-enabled chaos plan (sim/topology.py): the every-leg
    chaos plan PLUS the compiled rack/zone/region tier legs (penalized,
    so they genuinely trace) and the traced suspicion-timeout override —
    the program whose fault-plan phase RPJ203/RPJ206 must census
    collective-free with the blocked one-hot tier expansion inside it."""
    import jax.numpy as jnp

    from ringpop_tpu.sim import chaos, topology

    topo = topology.default_topology(n)
    assert topo.has_penalties(), "the lint plan must trace the tier legs"
    return chaos._merge_plans(
        _chaos_plan(n),
        topo.plan_legs(),
        chaos.FaultPlan(suspect_ticks=jnp.asarray(7, jnp.int32)),
    )


def _serve_ring(capacity=256, t=180, b=64):
    """A deterministic capacity-padded DeviceRing + key batch for the
    serve-tier entry points (duplicate tokens included via the modulo)."""
    import numpy as np

    from ringpop_tpu.serve import state as serve_state

    toks = np.sort(
        ((np.arange(t, dtype=np.uint64) * np.uint64(2654435761)) % (1 << 32))
        .astype(np.uint32)
    )
    owners = (np.arange(t) % 12).astype(np.int32)
    ring = serve_state.device_ring(toks, owners, capacity, gen=3)
    hashes = ((np.arange(b, dtype=np.uint64) * np.uint64(40503)) % (1 << 32)).astype(
        np.uint32
    )
    return ring, hashes


def build_entrypoints(mesh=None) -> dict:
    """{name: ClosedJaxpr} for the ten public jitted entry points, traced
    dense (``mesh=None``) or with the shard-local exchange lowering
    (``mesh`` = the 4×2 virtual mesh; the shard_roll region and the
    sequential-exchange step variants exist sharded only).
    rng="counter" — the sharded-caller default whose zero-collective
    peer choice the confinement rules pin."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ringpop_tpu.parallel.shift import shard_roll
    from ringpop_tpu.sim import delta, lifecycle, telemetry

    out = {}
    lparams = lifecycle.LifecycleParams(
        n=_N, k=_K, suspect_ticks=5, rng="counter", exchange_mesh=mesh
    )
    lstate = lifecycle.init_state(lparams, seed=0)
    lfaults = _faults(_N)
    out["lifecycle_step"] = jax.make_jaxpr(
        lambda s, f: lifecycle.step(lparams, s, f)
    )(lstate, lfaults)

    dparams = delta.DeltaParams(n=_N, k=_K, rng="counter", exchange_mesh=mesh)
    dstate = delta.init_state(dparams, seed=0)
    out["delta_step"] = jax.make_jaxpr(
        lambda s, f: delta.step(dparams, s, f)
    )(dstate, lfaults)

    subjects = jnp.asarray(np.flatnonzero(~np.asarray(lfaults.up))[:8], jnp.int32)
    learned_sharding = (
        NamedSharding(mesh, P("node", None)) if mesh is not None else None
    )
    out["detect_walk"] = jax.make_jaxpr(
        lambda s, f: lifecycle.detection_complete(
            s, subjects, f, lifecycle.FAULTY, learned_sharding=learned_sharding
        )
    )(lstate, lfaults)

    tel = telemetry.zeros(lparams)
    out["telemetry_fetch"] = jax.make_jaxpr(
        lambda t, s, f: telemetry.fetch(t, s, f)
    )(tel, lstate, lfaults)

    # the serve-tier lookup programs (r13): capacity-padded shared-ring
    # dispatch (fused owners+generation transfer) and the windowed
    # N-owner scan — dense elementwise/searchsorted programs that must
    # stay 32-bit, callback-free and collective-free (RPJ201/202/203
    # here; the compiled census lives in run_hlo_checks)
    from ringpop_tpu.ops import ring_ops
    from ringpop_tpu.serve import state as serve_state

    sring, shashes = _serve_ring()
    out["serve_lookup"] = jax.make_jaxpr(
        lambda r, h: serve_state.serve_lookup_fused(r, h)
    )(sring, jnp.asarray(shashes))
    out["serve_lookup_n"] = jax.make_jaxpr(
        lambda t, o, c, h: ring_ops._lookup_n_window_padded(t, o, c, h, 3, 16)
    )(sring.tokens, sring.owners, sring.count[0], jnp.asarray(shashes))
    # the r17 fused LookupN serve dispatch: the windowed scan with the
    # generation concatenated into the flattened owner matrix — the
    # program the collector's n>1 flushes and the serve mesh actually
    # run; 32-bit, callback-free, collective-free (census in
    # run_hlo_checks) like its n=1 sibling
    out["serve_lookup_n_fused"] = jax.make_jaxpr(
        lambda r, ns, h: serve_state._serve_lookup_n_window_fused(
            r, ns, h, 3, 16
        )
    )(sring, jnp.int32(12), jnp.asarray(shashes))

    # the r15 multihost device-side window programs: the P=1 full-window
    # gather and the per-leg nonzero-row summary + compaction
    # (sim/delta_multihost._k_window_all / _k_plane_summary).  They run
    # PER PROCESS, outside any mesh — dense-only entry points, and the
    # compiled census must show ZERO collectives (run_hlo_checks pins the
    # collective-free RPJ206 flavor); RPJ201/202/203 here keep them
    # 32-bit, callback-free, phase-scoped.
    if mesh is None:
        from ringpop_tpu.sim import delta_multihost
        from ringpop_tpu.sim.packbits import n_words as _n_words

        mh_plane = jnp.zeros((_N, _n_words(_K)), jnp.uint32)
        out["mh_window_slice"] = jax.make_jaxpr(
            lambda pl, s: delta_multihost._k_window_all(pl, s)
        )(mh_plane, jnp.int32(7))
        out["mh_window_summary"] = jax.make_jaxpr(
            lambda pl: delta_multihost._k_plane_nzbits(pl)
        )(mh_plane)
        out["mh_rows_gather"] = jax.make_jaxpr(
            lambda pl, ix: delta_multihost._k_rows_gather(pl, ix)
        )(mh_plane, jnp.arange(16, dtype=jnp.int32))

        # the r16 addition: the engine's shard-local kernel quartet A–D
        # (the programs the cross-tick overlap runs UNDER the draining
        # wire) traced with the full supported fault surface (victims +
        # loss).  Per-process, outside any mesh — 32-bit, callback-free,
        # and censused collective-free in run_hlo_checks like the window
        # programs above.
        mh_params = delta.DeltaParams(n=_N, k=_K, rng="counter")
        mh_key = jnp.zeros((2,), jnp.uint32)
        mh_up = jnp.ones((_N,), bool)
        mh_bool = jnp.ones((_N,), bool)
        mh_pcount = jnp.zeros((_N, _K), jnp.int8)
        mh_words = jnp.zeros((_n_words(_K),), jnp.uint32)
        out["mh_kernel_sent"] = jax.make_jaxpr(
            lambda L, R, key, t, lo, up, dr: delta_multihost._k_sent(
                mh_params, L, R, key, t, lo, up, dr,
                has_up=True, has_drop=True,
            )
        )(mh_plane, mh_plane, mh_key, jnp.int32(3), jnp.int32(0), mh_up,
          jnp.float32(0.1))
        out["mh_kernel_merge"] = jax.make_jaxpr(
            lambda L, R, I, key, t, lo, s, up, dr: delta_multihost._k_merge(
                mh_params, L, R, I, key, t, lo, s, up, dr,
                has_up=True, has_drop=True,
            )
        )(mh_plane, mh_plane, mh_plane, mh_key, jnp.int32(3), jnp.int32(0),
          jnp.int32(5), mh_up, jnp.float32(0.1))
        out["mh_kernel_counters"] = jax.make_jaxpr(
            lambda L, L1, Rs, c, gp, ri, pc, up: delta_multihost._k_counters(
                mh_params, L, L1, Rs, c, gp, ri, pc, up, has_up=True
            )
        )(mh_plane, mh_plane, mh_plane, mh_bool, mh_bool, mh_plane,
          mh_pcount, mh_up)
        out["mh_kernel_finish"] = jax.make_jaxpr(
            lambda L2, pm, mr, fw, rw: delta_multihost._k_finish(
                mh_params, L2, pm, mr, fw, rw
            )
        )(mh_plane, mh_pcount, mh_plane, mh_words, mh_words)

    # the chaos-enabled steps: the same engines driven by a time-varying
    # FaultPlan with every leg populated — fault evaluation (the
    # fault-plan phase) must stay collective-free (RPJ203/RPJ206) and the
    # sharded/unsharded chaos traces structurally equal (RPJ205)
    plan = _chaos_plan(_N)
    out["lifecycle_step_chaos"] = jax.make_jaxpr(
        lambda s, p: lifecycle.step(lparams, s, p)
    )(lstate, plan)
    out["delta_step_chaos"] = jax.make_jaxpr(
        lambda s, p: delta.step(dparams, s, p)
    )(dstate, plan)

    # the topology-enabled chaos step (the tentpole of the topology
    # round): the same engine driven by a plan that additionally carries
    # the compiled rack/zone/region tier legs + the traced
    # suspect_ticks override.  The tier-table expansion runs under the
    # fault-plan scope, which must stay collective-free (RPJ203 here,
    # compiled-census RPJ206 in run_hlo_checks); 32-bit and
    # callback-free like its flat sibling.
    tplan = _topo_plan(_N)
    out["lifecycle_step_topo"] = jax.make_jaxpr(
        lambda s, p: lifecycle.step(lparams, s, p)
    )(lstate, tplan)

    # the batched chaos-MC step (r12): B heterogeneous stacked FaultPlans
    # vmapped over (plan, state) — the Monte-Carlo fleet's program.  Every
    # invariant must hold UNDER the batching transform: fault-plan phase
    # zero-collective (RPJ203/RPJ206), no f64/callbacks, and the
    # sharded/unsharded skeletons equal modulo the excised exchange
    # region (RPJ205) — vmap must not introduce partition-dependence.
    from ringpop_tpu.sim import chaos, montecarlo

    stacked = _stacked_plan(_N)
    axes = chaos.plan_axes(stacked)
    mc_states = montecarlo.init_replicas(lparams, [1, 2])
    out["mc_chaos_step"] = jax.make_jaxpr(
        lambda s, p: jax.vmap(
            lambda s1, p1: lifecycle.step(lparams, s1, p1), in_axes=(0, axes)
        )(s, p)
    )(mc_states, stacked)

    if mesh is not None:
        plane = jnp.zeros((_N, lifecycle.n_words(_K)), jnp.uint32)
        out["shard_roll"] = jax.make_jaxpr(
            lambda x, sh: shard_roll(
                (x,), sh, mesh, "node", (P("node", None),)
            )
        )(plane, jnp.int32(3))
        # the sequential-leg sharded step (exchange_pipelined=False): the
        # r8 lowering the tpu_ksweep pipelined_exchange A/B still runs —
        # traced so RPJ201/202/203 cover it, and so run_trace_checks can
        # pin the pipelined step skeleton-equal to it modulo the excised
        # exchange region (the r11 RPJ205 extension)
        import dataclasses as _dc

        sparams = _dc.replace(lparams, exchange_pipelined=False)
        out["lifecycle_step_seq_exchange"] = jax.make_jaxpr(
            lambda s, f: lifecycle.step(sparams, s, f)
        )(lstate, lfaults)
        sdparams = _dc.replace(dparams, exchange_pipelined=False)
        out["delta_step_seq_exchange"] = jax.make_jaxpr(
            lambda s, f: delta.step(sdparams, s, f)
        )(dstate, lfaults)

        # r14: the PROCESS-SPANNING construction path, single-process
        # traced — the same delta step bound to a mesh built by
        # make_multihost_mesh (the DCN granule layout) with shardings
        # from the canonical partition table.  At lint time one process
        # owns all 8 virtual devices, so the traced program is the exact
        # program every rank of a real multi-host job traces; RPJ201/202/
        # 203 pin it 32-bit, callback-free and phase-confined.
        from ringpop_tpu.parallel.mesh import with_exchange_mesh
        from ringpop_tpu.parallel.multihost import make_multihost_mesh

        mh_mesh = make_multihost_mesh()
        mh_params = with_exchange_mesh(
            delta.DeltaParams(n=_N, k=_K, rng="counter"), mh_mesh
        )
        out["multihost_step"] = jax.make_jaxpr(
            lambda s, f: delta.step(mh_params, s, f)
        )(dstate, lfaults)
    return out


def run_trace_checks() -> list[Finding]:
    """The full plane-2 jaxpr suite: every entry point, dense + sharded."""
    mesh = _mesh8()
    dense = build_entrypoints(mesh=None)
    sharded = build_entrypoints(mesh=mesh)
    findings: list[Finding] = []
    for variant, entries in (("dense", dense), ("sharded", sharded)):
        for name, closed in entries.items():
            tag = f"{name}[{variant}]"
            findings += check_no_64bit(tag, closed)
            findings += check_no_callbacks(tag, closed)
            findings += check_collective_confinement(tag, closed)
    # mc_chaos_step is deliberately NOT in the RPJ205 list: vmap's
    # batching rules legally materialize/reorder broadcasts around the
    # exchange region depending on how that region lowers (shard_map vs
    # gathers), so the batched dense/sharded skeletons differ in ops that
    # are NOT partition-dependence — the fleet's equivalence is certified
    # dynamically instead (mc-smoke B=1 identity + the ksweep mc_chaos
    # bit_equal flag); its confinement/f64/callback/donation checks all
    # still run.
    for name in (
        "lifecycle_step",
        "delta_step",
        "detect_walk",
        "lifecycle_step_chaos",
        "delta_step_chaos",
        "lifecycle_step_topo",
    ):
        findings += check_structural_equivalence(name, dense[name], sharded[name])
    # r11: the pipelined sharded step must be skeleton-equal to the
    # sequential-leg sharded step modulo the excised exchange region —
    # the fused leg loop may only ever differ INSIDE the shard-roll
    # scope, everything else is a scheduling bug leaking out
    for a, b in (
        ("lifecycle_step", "lifecycle_step_seq_exchange"),
        ("delta_step", "delta_step_seq_exchange"),
    ):
        findings += check_structural_equivalence(
            f"{a}[pipelined-vs-sequential]", sharded[a], sharded[b]
        )
    findings += _donation_checks()
    return findings


def _donation_checks() -> list[Finding]:
    import jax

    from ringpop_tpu.sim import delta, lifecycle

    findings: list[Finding] = []
    lparams = lifecycle.LifecycleParams(n=_N, k=_K, suspect_ticks=5, rng="counter")
    lstate = lifecycle.init_state(lparams, seed=0)
    blk = jax.jit(
        functools.partial(lifecycle._run_block, lparams),
        static_argnames="ticks",
        donate_argnums=(0,),
    )
    findings += check_donation(
        "lifecycle_block",
        blk.lower(lstate, _faults(_N), ticks=1).as_text(),
        len(jax.tree.leaves(lstate)),
    )
    dparams = delta.DeltaParams(n=_N, k=_K, rng="counter")
    dstate = delta.init_state(dparams, seed=0)

    def dblk(s, f):
        return jax.lax.fori_loop(0, 2, lambda _, st: delta.step(dparams, st, f), s)

    jblk = jax.jit(dblk, donate_argnums=(0,))
    findings += check_donation(
        "delta_block",
        jblk.lower(dstate, _faults(_N)).as_text(),
        len(jax.tree.leaves(dstate)),
    )
    # the batched fleet carry (r12): donating the [B, ...] replica batch
    # into the vmapped tick block must alias every leaf too — a silent
    # copy on the fleet path multiplies peak memory by B
    from ringpop_tpu.sim import montecarlo

    mc_states = montecarlo.init_replicas(lparams, [1, 2])
    mblk = jax.jit(
        functools.partial(montecarlo._mc_block, lparams),
        static_argnames="ticks",
        donate_argnums=(0,),
    )
    findings += check_donation(
        "mc_chaos_block",
        mblk.lower(mc_states, _stacked_plan(_N), ticks=1).as_text(),
        len(jax.tree.leaves(mc_states)),
    )
    # the serve tier's generation swap (r13): ring_commit donates the
    # retiring DeviceRing — every leaf must alias an output, else a
    # membership change holds TWO rings live at peak
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.serve import state as serve_state

    sring, _ = _serve_ring()
    findings += check_donation(
        "ring_commit",
        serve_state.ring_commit.lower(
            sring,
            jnp.asarray(np.zeros(256, np.uint32)),
            jnp.asarray(np.zeros(256, np.int32)),
            jnp.asarray([7], jnp.int32),
            jnp.asarray([4], jnp.uint32),
        ).as_text(),
        len(jax.tree.leaves(sring)),
    )
    return findings


def run_hlo_checks() -> list[Finding]:
    """RPJ206: compile the sharded lifecycle tick (hierarchical select
    forced, the sharded-caller defaults) on the virtual mesh — once with
    the static fault model and once chaos-enabled (the full FaultPlan) —
    and confine each program's full collective census.  The chaos compile
    is where a partitioner-introduced collective inside the fault-plan
    phase would surface."""
    import jax

    from ringpop_tpu.sim import lifecycle

    mesh = _mesh8()
    params = lifecycle.LifecycleParams(
        n=_HLO_N, k=_K, suspect_ticks=5, rng="counter", exchange_mesh=mesh
    )
    state = jax.tree.map(
        jax.device_put,
        lifecycle.init_state(params, seed=0),
        lifecycle.state_shardings(mesh, k=_K),
    )
    old_min_n = lifecycle._SPARSE_TOPK_MIN_N
    lifecycle._SPARSE_TOPK_MIN_N = 0
    findings: list[Finding] = []
    try:
        blk = jax.jit(
            functools.partial(lifecycle._run_block, params), static_argnames="ticks"
        )
        with _no_compile_cache():
            text = blk.lower(state, _faults(_HLO_N), ticks=1).compile().as_text()
            chaos_text = (
                blk.lower(state, _chaos_plan(_HLO_N), ticks=1).compile().as_text()
            )
            # the topology-enabled compile: the blocked one-hot tier
            # expansion runs under the fault-plan scope — a
            # partitioner-introduced collective there (e.g. the tier
            # table replicating mid-phase) is exactly what this census
            # exists to catch
            topo_text = (
                blk.lower(state, _topo_plan(_HLO_N), ticks=1).compile().as_text()
            )
    finally:
        lifecycle._SPARSE_TOPK_MIN_N = old_min_n
    findings += check_hlo_confinement("lifecycle_step[hlo,sharded]", text)
    findings += check_hlo_confinement("lifecycle_step_chaos[hlo,sharded]", chaos_text)
    findings += check_hlo_confinement("lifecycle_step_topo[hlo,sharded]", topo_text)

    # r12: the BATCHED chaos-MC block compiled over the same mesh (batch
    # axis replicated, node/rumor sharded as canonical — the fleet ksweep
    # layout).  This is where a partitioner-introduced collective inside
    # the vmapped fault-plan phase would surface.
    from ringpop_tpu.sim import montecarlo

    stacked = _stacked_plan(_HLO_N)
    mc_states = jax.tree.map(
        jax.device_put,
        montecarlo.init_replicas(params, [1, 2]),
        montecarlo.fleet_state_shardings(mesh, k=_K),
    )
    mblk = jax.jit(
        functools.partial(montecarlo._mc_block, params), static_argnames="ticks"
    )
    with _no_compile_cache():
        fleet_text = mblk.lower(mc_states, stacked, ticks=1).compile().as_text()
    findings += check_hlo_confinement("mc_chaos_block[hlo,sharded]", fleet_text)

    # r13: the serve-tier lookup program compiled DENSE — censused
    # collective-free (the serving dispatch is one device's searchsorted;
    # a collective here would serialize every frontend behind ICI)
    from ringpop_tpu.serve import state as serve_state

    sring, shashes = _serve_ring()
    import jax.numpy as jnp

    with _no_compile_cache():
        serve_text = (
            serve_state.serve_lookup_fused.lower(sring, jnp.asarray(shashes))
            .compile()
            .as_text()
        )
    findings += check_hlo_collective_free("serve_lookup[hlo,dense]", serve_text)

    # r17: the fused LookupN dispatch compiled dense — same collective-
    # free bar (a collective in the preference-list program would
    # serialize every mesh rank's answer path behind ICI)
    with _no_compile_cache():
        fanin_text = (
            serve_state._serve_lookup_n_window_fused.lower(
                sring, jnp.int32(12), jnp.asarray(shashes), n=3, w=16
            )
            .compile()
            .as_text()
        )
    findings += check_hlo_collective_free(
        "serve_lookup_n_fused[hlo,dense]", fanin_text
    )

    # r15: the multihost device-side window programs compiled dense —
    # they run per-process OUTSIDE the mesh, so their census must show
    # zero collectives of any kind (same flavor as the serve lookup)
    from ringpop_tpu.sim import delta_multihost
    from ringpop_tpu.sim.packbits import n_words as _n_words

    mh_plane = jnp.zeros((_HLO_N, _n_words(_K)), jnp.uint32)
    with _no_compile_cache():
        slice_text = (
            delta_multihost._k_window_all.lower(mh_plane, jnp.int32(7))
            .compile()
            .as_text()
        )
        summary_text = (
            delta_multihost._k_plane_nzbits.lower(mh_plane).compile().as_text()
        )
        gather_text = (
            delta_multihost._k_rows_gather.lower(
                mh_plane, jnp.arange(64, dtype=jnp.int32)
            )
            .compile()
            .as_text()
        )
    findings += check_hlo_collective_free("mh_window_slice[hlo,dense]", slice_text)
    findings += check_hlo_collective_free("mh_window_summary[hlo,dense]", summary_text)
    findings += check_hlo_collective_free("mh_rows_gather[hlo,dense]", gather_text)

    # r16: the engine's shard-local kernel quartet A–D compiled dense —
    # the programs the cross-tick overlap runs while the wire drains.
    # They execute per-process OUTSIDE the mesh (the fabric carries the
    # only cross-process data), so a collective in any of them would be
    # a layering bug: censused zero like the window programs.
    from ringpop_tpu.sim import delta as _delta

    mh_params = _delta.DeltaParams(n=_HLO_N, k=_K, rng="counter")
    mh_key = jnp.zeros((2,), jnp.uint32)
    mh_up = jnp.ones((_HLO_N,), bool)
    mh_bool = jnp.ones((_HLO_N,), bool)
    mh_pcount = jnp.zeros((_HLO_N, _K), jnp.int8)
    mh_words = jnp.zeros((_n_words(_K),), jnp.uint32)
    with _no_compile_cache():
        kernel_texts = {
            "mh_kernel_sent": delta_multihost._k_sent.lower(
                mh_params, mh_plane, mh_plane, mh_key, jnp.int32(3),
                jnp.int32(0), mh_up, jnp.float32(0.1),
                has_up=True, has_drop=True,
            ).compile().as_text(),
            "mh_kernel_merge": delta_multihost._k_merge.lower(
                mh_params, mh_plane, mh_plane, mh_plane, mh_key,
                jnp.int32(3), jnp.int32(0), jnp.int32(5), mh_up,
                jnp.float32(0.1), has_up=True, has_drop=True,
            ).compile().as_text(),
            "mh_kernel_counters": delta_multihost._k_counters.lower(
                mh_params, mh_plane, mh_plane, mh_plane, mh_bool, mh_bool,
                mh_plane, mh_pcount, mh_up, has_up=True,
            ).compile().as_text(),
            "mh_kernel_finish": delta_multihost._k_finish.lower(
                mh_params, mh_plane, mh_pcount, mh_plane, mh_words,
                mh_words,
            ).compile().as_text(),
        }
    for kname, ktext in kernel_texts.items():
        findings += check_hlo_collective_free(f"{kname}[hlo,dense]", ktext)
    return findings


# -- fixture dispatch --------------------------------------------------------


def check_fixture(rule: str, fn, args) -> list[Finding]:
    """Run one plane-2 rule against a fixture's ``build()`` output.  For
    RPJ205 ``build()`` returns ``(fn_a, fn_b, args)`` — two programs to
    compare; for RPJ204, ``(fn, args)`` with arg 0 donated; for RPJ206,
    ``(fn, args)`` compiled and censused; else ``(fn, args)`` traced."""
    import jax

    entry = f"fixture:{rule}"
    if rule == "RPJ205":
        fn_a, fn_b = fn
        a = jax.make_jaxpr(fn_a)(*args)
        b = jax.make_jaxpr(fn_b)(*args)
        return check_structural_equivalence(entry, a, b)
    if rule == "RPJ204":
        low = jax.jit(fn, donate_argnums=(0,)).lower(*args)
        return check_donation(entry, low.as_text(), len(jax.tree.leaves(args[0])))
    if rule == "RPJ206":
        with _no_compile_cache():
            text = jax.jit(fn).lower(*args).compile().as_text()
        return check_hlo_confinement(entry, text)
    closed = jax.make_jaxpr(fn)(*args)
    if rule == "RPJ201":
        return check_no_64bit(entry, closed)
    if rule == "RPJ202":
        return check_no_callbacks(entry, closed)
    if rule == "RPJ203":
        return check_collective_confinement(entry, closed)
    raise ValueError(f"unknown trace rule {rule!r}")
