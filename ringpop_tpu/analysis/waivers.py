"""Checked-in rule waivers with mandatory justifications.

``analysis/waivers.toml`` is an array of ``[[waiver]]`` tables:

.. code-block:: toml

    [[waiver]]
    rule = "RPA101"                      # rule id, or "*"
    path = "ringpop_tpu/sim/fullview.py" # repo-relative file, or "*"
    scope = "step"                       # enclosing-function qualname
                                         # (prefix match on dotted parts),
                                         # or "*"
    justification = "why this violation is deliberate"

A waiver with an empty/missing ``justification`` is a CONFIGURATION
ERROR (jaxlint exits 2): the file exists to record *reasoned* exceptions,
not to silence rules.  Unused waivers are reported so stale entries rot
visibly instead of silently.

Python 3.10 has no ``tomllib``, and the repo adds no dependencies, so
``load_waivers`` parses the TOML subset the file needs: ``[[waiver]]``
array-of-table headers, ``key = "string"`` pairs, comments, blank lines.
Anything else in the file is rejected loudly (better than a waiver
half-parsing into a rule silencer it never promised to be).
"""

from __future__ import annotations

import re


class WaiverError(ValueError):
    """Malformed waivers file — a config error, not a lint finding."""


_KV_RE = re.compile(r'^([A-Za-z_][\w\-]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$')

REQUIRED_KEYS = ("rule", "path", "scope", "justification")


def load_waivers(path: str) -> list[dict]:
    """Parse the waiver file into a list of dicts, validating that every
    entry carries the required keys and a non-empty justification."""
    waivers: list[dict] = []
    cur: dict | None = None
    try:
        lines = open(path).read().split("\n")
    except OSError:
        return []
    for ln, raw in enumerate(lines, 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            cur = {"_line": ln}
            waivers.append(cur)
            continue
        m = _KV_RE.match(line)
        if m and cur is not None:
            cur[m.group(1)] = m.group(2).replace('\\"', '"')
            continue
        raise WaiverError(
            f"{path}:{ln}: unparseable waiver line {line!r} — the file "
            "accepts only [[waiver]] headers and key = \"string\" pairs"
        )
    for w in waivers:
        for key in REQUIRED_KEYS:
            if not str(w.get(key, "")).strip():
                raise WaiverError(
                    f"{path}:{w['_line']}: waiver missing required "
                    f"non-empty {key!r} (every waiver must say what it "
                    "waives and WHY)"
                )
    return waivers


def _scope_matches(pattern: str, scope: str) -> bool:
    return (
        pattern == "*"
        or scope == pattern
        or scope.startswith(pattern + ".")
        or scope.startswith(pattern + ".<locals>")
    )


def apply_waivers(findings, waivers) -> list[dict]:
    """Mark matching findings waived (in place) and return the UNUSED
    waiver entries.  A waiver matches on (rule, path, scope); ``*``
    wildcards each field; scope matches the enclosing qualname or any of
    its nested functions."""
    used = [False] * len(waivers)
    for f in findings:
        for i, w in enumerate(waivers):
            if w["rule"] not in ("*", f.rule):
                continue
            if w["path"] not in ("*", f.path):
                continue
            if not _scope_matches(w["scope"], f.scope):
                continue
            f.waived = True
            f.justification = w["justification"]
            used[i] = True
            break
    return [w for i, w in enumerate(waivers) if not used[i]]
