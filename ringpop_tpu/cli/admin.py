"""ringpop-admin — operate a live node over its admin endpoints.

The reference ecosystem drives nodes through the same wire surface
(`swim/handlers.go:63-82` admin endpoint table, facade `handlers.go:33-43`);
this CLI is the operator client for it.  Every command is one RPC to one
node; cluster-wide views come from asking any member (membership is
gossip-replicated).

Usage::

    python -m ringpop_tpu.cli.admin status   HOST:PORT
    python -m ringpop_tpu.cli.admin members  HOST:PORT
    python -m ringpop_tpu.cli.admin lookup   HOST:PORT KEY
    python -m ringpop_tpu.cli.admin health   HOST:PORT
    python -m ringpop_tpu.cli.admin gossip   HOST:PORT {start|stop|tick}
    python -m ringpop_tpu.cli.admin member   HOST:PORT {join|leave}
    python -m ringpop_tpu.cli.admin reap     HOST:PORT
    python -m ringpop_tpu.cli.admin heal     HOST:PORT
    python -m ringpop_tpu.cli.admin debug    HOST:PORT {set|clear}

Output is JSON (one object per line) so it pipes into jq; ``--wire
msgpack`` talks the binary codec to msgpack-pinned clusters (auto-detected
by receivers either way).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


async def _call(target: str, endpoint: str, body: dict, wire: str | None, timeout: float):
    from ringpop_tpu.net import TCPChannel

    ch = TCPChannel(app="ringpop-admin", codec=wire)
    try:
        return await ch.call(target, "ringpop", endpoint, body, timeout=timeout)
    finally:
        await ch.close()


def _emit(obj) -> None:
    print(json.dumps(obj, indent=None, sort_keys=True))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ringpop-admin", description=__doc__)
    p.add_argument("--wire", choices=["json", "msgpack"], default=None)
    p.add_argument("--timeout", type=float, default=5.0)
    sub = p.add_subparsers(dest="cmd", required=True)

    for name in ("status", "members", "health", "reap", "heal"):
        sp = sub.add_parser(name)
        sp.add_argument("target", help="HOST:PORT of any cluster member")

    sp = sub.add_parser("lookup")
    sp.add_argument("target")
    sp.add_argument("key")

    sp = sub.add_parser("gossip")
    sp.add_argument("target")
    sp.add_argument("action", choices=["start", "stop", "tick"])

    sp = sub.add_parser("member")
    sp.add_argument("target")
    sp.add_argument("action", choices=["join", "leave"])

    sp = sub.add_parser("debug")
    sp.add_argument("target")
    sp.add_argument("action", choices=["set", "clear"])

    args = p.parse_args(argv)

    endpoint, body = {
        "status": ("/admin/stats", {}),
        "members": ("/admin/stats", {}),
        "health": ("/health", {}),
        "lookup": ("/admin/lookup", {"key": getattr(args, "key", "")}),
        "reap": ("/admin/reap", {}),
        "heal": ("/admin/healpartition/disco", {}),
        "gossip": (f"/admin/gossip/{getattr(args, 'action', '')}", {}),
        "member": (f"/admin/member/{getattr(args, 'action', '')}", {}),
        "debug": (
            "/admin/debugSet" if getattr(args, "action", "") == "set" else "/admin/debugClear",
            {},
        ),
    }[args.cmd]
    if args.cmd == "gossip" and args.action == "tick":
        endpoint = "/admin/tick"

    try:
        res = asyncio.run(_call(args.target, endpoint, body, args.wire, args.timeout))
    except Exception as e:
        _emit({"ok": False, "target": args.target, "error": f"{type(e).__name__}: {e}"})
        return 1

    if args.cmd == "members":
        # distill the stats payload into one row per member
        for m in (res.get("membership") or {}).get("members", []):
            _emit(m)
        _emit(
            {
                "checksum": (res.get("membership") or {}).get("checksum"),
                "ring_checksum": (res.get("ring") or {}).get("checksum"),
                "state": res.get("state"),
            }
        )
    else:
        _emit(res)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
