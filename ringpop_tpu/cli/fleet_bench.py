"""One rank of a scenario-fleet certification run (r19).

Spawned by ``scripts/multihost_launch.py`` (simbench ``fleet_scale``,
``make fleet-smoke``, the test suite): reads the standard
``jax.distributed`` env contract, slices the deterministic scenario grid
by ``partition.process_block`` over its batch axis, runs its slice as a
``scenarios.FleetSweep``, and emits JSONL records to ``MULTIHOST_JSONL``.

Legs::

    sweep          — scored sweep over this rank's batch slice to the
                     horizon; emits per-scenario state digests + score
                     verdicts + peak RSS.  ``--save-at T --path D``
                     additionally checkpoints the whole fleet carry at
                     tick T (each process writing only its shards) and
                     CONTINUES — certifying that a mid-sweep save does
                     not perturb the run.
    sweep-restore  — restore the checkpoint AT THIS PROCESS COUNT (need
                     not match the saver's), continue to the horizon,
                     emit the same digests/scores record — the
                     kill-and-restore certificate.

The grid is a pure function of (n, k, doses, losses, seed), so every
process count constructs the identical B scenarios and any slicing of
them is bit-exact per scenario (``chaos.slice_plan``).  Works
single-process too (no coordinator env → plain local run), which is what
makes the P=1 unbroken run the SAME code path as P=2/4.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time


def _emit(rec: dict) -> None:
    path = os.environ.get("MULTIHOST_JSONL")
    line = json.dumps(rec)
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")
        # stdout gets a SUMMARY only: the full record is ~0.5 MB at fleet
        # scale (2048 digests + score records), and the launcher reads
        # records from the JSONL file anyway — an un-drained 64 KB stdout
        # pipe must never be able to block a rank's exit
        line = json.dumps({
            k: rec.get(k)
            for k in ("kind", "b", "b_local", "lo", "hi", "ticks_done",
                      "wall_s", "peak_rss_mb", "process_id", "saved_at")
            if k in rec
        })
    print(line, flush=True)


def _peak_rss_mb() -> float:
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)


def build_grid(args):
    """The deterministic grid every rank (and every process count)
    reconstructs identically: victims drawn like the mc_chaos scenario,
    the shared churn-dose ladder, loss rows, ``grid_seeds`` pairing."""
    import numpy as np

    from ringpop_tpu.sim import scenarios

    rng = np.random.default_rng(args.seed)
    victims = sorted(rng.choice(args.n, size=4, replace=False).tolist())
    doses = scenarios.mc_churn_doses(args.b_doses, args.churn_max or args.n // 32)
    losses = tuple(float(x) for x in args.losses.split(","))
    plan, meta = scenarios.scenario_grid(
        args.n, victims=victims, doses=doses, losses=losses,
        churn_seed=args.seed + 777,
    )
    seeds = scenarios.grid_seeds(meta, args.seed)
    return victims, plan, meta, seeds


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fleet_bench", description=__doc__)
    p.add_argument("leg", choices=["sweep", "sweep-restore"])
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--k", type=int, default=64)
    p.add_argument("--b-doses", type=int, default=32)
    p.add_argument("--losses", default="0.0,0.1")
    p.add_argument("--churn-max", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--suspect-ticks", type=int, default=10)
    p.add_argument("--horizon", type=int, default=32)
    p.add_argument("--journal-every", type=int, default=16)
    p.add_argument("--save-at", type=int, default=0,
                   help="sweep leg: checkpoint the carry at this tick "
                   "(a journal block boundary), then continue")
    p.add_argument("--path", default=None, help="fleet checkpoint dir")
    p.add_argument("--live-port", type=int,
                   default=int(os.environ.get("RINGPOP_OBS_PORT", "0") or 0),
                   help="serve the live operations plane (/metrics "
                   "/healthz /progress) on this port (0 = off; the "
                   "launcher exports RINGPOP_OBS_PORT = base + rank)")
    args = p.parse_args(argv)

    import jax

    from ringpop_tpu.parallel.multihost import init_distributed

    # distributed bring-up FIRST: the compile-cache probe runs a jax
    # computation, which jax.distributed.initialize refuses to follow
    distributed = init_distributed()
    from ringpop_tpu.util.accel import configure_compile_cache

    configure_compile_cache()
    nprocs = jax.process_count() if distributed else 1
    rank = jax.process_index() if distributed else 0

    from ringpop_tpu.parallel.partition import process_block
    from ringpop_tpu.sim import chaos, scenarios
    from ringpop_tpu.sim.lifecycle import LifecycleParams

    params = LifecycleParams(
        n=args.n, k=args.k, suspect_ticks=args.suspect_ticks, rng="counter"
    )
    victims, plan, meta, seeds = build_grid(args)
    b = len(meta)
    lo, hi = process_block(b, rank, nprocs) if nprocs > 1 else (0, b)
    plan_s = chaos.slice_plan(plan, lo, hi)
    meta_s, seeds_s = meta[lo:hi], seeds[lo:hi]

    # live operations plane (r20, opt-in): a per-rank pull endpoint with
    # rank-0 cross-rank aggregation over its OWN obs fabric, plus a
    # flight recorder armed on fabric failures and uncaught exceptions —
    # a rank that dies mid-sweep leaves its last blocks behind.
    ops = None
    live_addr = None
    if args.live_port:
        # the ops plane must never take the rank down: a failed HTTP
        # bind (port collision) keeps the collector (other ranks still
        # aggregate this one), and a failed LiveOps bring-up runs the
        # sweep dark — both reported, neither fatal
        try:
            from ringpop_tpu.obs.endpoint import LiveOps
            from ringpop_tpu.obs.flight import FlightRecorder

            kv = None
            if distributed and nprocs > 1:
                from ringpop_tpu.parallel.fabric import DistributedKV

                kv = DistributedKV()
            recorder = FlightRecorder(rank=rank).install()
            ops = LiveOps(rank, nprocs, recorder=recorder, kv=kv)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"kind": "live", "rank": rank,
                              "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
        if ops is not None:
            try:
                live_addr = ops.serve(port=args.live_port)
            except OSError as e:
                print(json.dumps({"kind": "live", "rank": rank,
                                  "error": f"bind: {e}"}), flush=True)
            else:
                print(json.dumps({"kind": "live", "rank": rank,
                                  "addr": live_addr}), flush=True)

    t0 = time.perf_counter()
    if args.leg == "sweep":
        sweep = scenarios.FleetSweep(
            params, plan_s, meta_s, seeds_s, horizon=args.horizon,
            journal_every=args.journal_every, scenario="fleet_scale",
            global_b=b, obs=ops,
        )
        save_s = None
        if args.save_at:
            sweep.run(until_tick=args.save_at)
            ts = time.perf_counter()
            sweep.save(args.path)
            save_s = round(time.perf_counter() - ts, 3)
        sweep.run()
    else:
        sweep = scenarios.FleetSweep.restore(
            args.path, params, plan_s, meta_s, seeds_s,
            scenario="fleet_scale", global_b=b, obs=ops,
        )
        sweep.run()
    rec = {
        "kind": args.leg,
        "n": args.n,
        "k": args.k,
        "b": b,
        "b_local": len(meta_s),
        "lo": lo,
        "hi": hi,
        "horizon": args.horizon,
        "ticks_done": sweep.ticks_done,
        "victims": victims,
        "digests": {str(k_): v for k_, v in sweep.digests().items()},
        "scores": sweep.scores(),
        "wall_s": round(time.perf_counter() - t0, 3),
        "peak_rss_mb": _peak_rss_mb(),
        "process_count": nprocs,
        "process_id": rank,
        **sweep.header_params(),
    }
    if args.leg == "sweep" and args.save_at:
        rec["saved_at"] = args.save_at
        rec["save_s"] = save_s
    if live_addr is not None:
        rec["live_addr"] = live_addr
    _emit(rec)
    if ops is not None:
        ops.close()
    if distributed and nprocs > 1:
        # explicit exit barrier through the coordination-service client
        # (plain gRPC, the same channel _orbax_mp_options routes orbax's
        # barriers through): rank slices can finish far apart (per-rank
        # host-side scoring on shared cores), and jax.distributed's own
        # shutdown barrier is short — an early rank would SIGABRT at
        # exit AFTER all its work succeeded
        from jax._src import distributed as _dist

        client = _dist.global_state.client
        if client is not None:
            client.wait_at_barrier("fleet_bench_exit", 3600 * 1000)
    return 0


if __name__ == "__main__":
    sys.exit(main())
