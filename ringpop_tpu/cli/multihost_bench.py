"""One rank of a multi-process delta certification run.

Spawned by ``scripts/multihost_launch.py`` (simbench ``multihost16m``,
``make multihost-smoke``, the test suite): reads the standard
``jax.distributed`` env contract, brings up the runtime
(``init_distributed``), builds the host-bridged DCN fabric, and runs one
of the certification legs, emitting JSONL records to ``MULTIHOST_JSONL``.

Legs::

    twin              — step a seeded scenario T ticks; emit the global
                        state digest (the 1/2/4-process bit-identity twin)
    converge          — run delta convergence through the fabric with a
                        per-block journal; emit ticks/digest/peak-RSS/
                        fabric-bytes
    snapshot-save     — step T ticks, write the block-sharded orbax
                        checkpoint, emit the digest at save
    snapshot-restore  — restore the checkpoint AT THIS PROCESS COUNT
                        (need not match the saver's), continue E ticks,
                        emit the digest (the cross-process-count
                        continuation certificate)

Works single-process too (no coordinator env → plain local run), which is
what makes the P=1 twin the SAME code path as P=2/4.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time


def _emit(rec: dict) -> None:
    path = os.environ.get("MULTIHOST_JSONL")
    line = json.dumps(rec)
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")
    print(line, flush=True)


def _peak_rss_mb() -> float:
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="multihost_bench", description=__doc__)
    p.add_argument("leg", choices=["twin", "converge", "snapshot-save", "snapshot-restore"])
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--k", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ticks", type=int, default=24)
    p.add_argument("--extra-ticks", type=int, default=8)
    p.add_argument("--max-ticks", type=int, default=4096)
    p.add_argument("--journal-every", type=int, default=64)
    p.add_argument("--victims", type=int, default=0)
    p.add_argument("--drop", type=float, default=0.0)
    p.add_argument("--path", default=None, help="orbax checkpoint dir (snapshot legs)")
    p.add_argument(
        "--journal-light", action="store_true",
        help="periodic converge-leg journal records skip the state digest "
        "(the per-tick wire-wave mode — at 16M a per-tick digest costs "
        "more than the tick); the exit record is always full",
    )
    p.add_argument(
        "--codec", choices=["on", "off"], default="on",
        help="r15 wire codec (zero-row/run suppression + XOR-delta); "
        "'off' ships raw frames — the A/B baseline the dcn_wire scenario "
        "certifies against",
    )
    p.add_argument(
        "--schedule", choices=["cyclic", "swing"], default="cyclic",
        help="r16 window-exchange schedule: 'cyclic' direct sends (r14) "
        "or 'swing' distance-halving relay rounds (power-of-two P; the "
        "relay bytes are priced in the fabric accounting)",
    )
    p.add_argument(
        "--overlap", choices=["on", "off"], default="off",
        help="r16 cross-tick pipelining: sends drain on persistent fabric "
        "threads while the next tick's shard-local kernels run; 'off' is "
        "the blocking r15 semantics — the A/B baseline",
    )
    args = p.parse_args(argv)

    import jax

    from ringpop_tpu.parallel.fabric import DistributedKV, Fabric, LocalKV
    from ringpop_tpu.parallel.multihost import init_distributed

    distributed = init_distributed()
    nprocs = jax.process_count() if distributed else 1
    rank = jax.process_index() if distributed else 0
    kv = DistributedKV() if distributed else LocalKV()
    fabric = Fabric(
        rank, nprocs, kv, namespace=f"mhb-{args.leg}", codec=args.codec == "on"
    )

    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=args.n, k=args.k, rng="counter")
    faults = None
    if args.victims or args.drop:
        kw = {}
        if args.victims:
            rng = np.random.default_rng(args.seed + 999)
            up = np.ones(args.n, bool)
            up[rng.choice(args.n, size=args.victims, replace=False)] = False
            kw["up"] = jnp.asarray(up)
        if args.drop:
            kw["drop_rate"] = jnp.float32(args.drop)
        faults = DeltaFaults(**kw)

    engine_kw = dict(
        seed=args.seed, faults=faults,
        schedule=args.schedule, overlap=args.overlap == "on",
    )
    t0 = time.perf_counter()
    if args.leg == "twin":
        mh = MultihostDelta(params, fabric, **engine_kw)
        for _ in range(args.ticks):
            mh.step()
        _emit(
            {
                "kind": "twin",
                **mh.journal_record(),
                "wall_s": round(time.perf_counter() - t0, 3),
                "peak_rss_mb": _peak_rss_mb(),
            }
        )
    elif args.leg == "converge":
        mh = MultihostDelta(params, fabric, **engine_kw)
        sink = (lambda rec: _emit({"kind": "block", **rec}))
        ticks, ok = mh.run_until_converged(
            max_ticks=args.max_ticks, sink=sink, journal_every=args.journal_every,
            journal_light=args.journal_light,
        )
        wall = time.perf_counter() - t0
        ws = fabric.wire_stats()
        _emit(
            {
                "kind": "result",
                "ticks": ticks,
                "converged": ok,
                "digest": mh.state_digest(),
                "wall_s": round(wall, 3),
                "ms_per_tick": round(1000.0 * wall / max(ticks, 1), 3),
                "peak_rss_mb": _peak_rss_mb(),
                "fabric_bytes_sent": ws["bytes_sent"],
                "fabric_bytes_recv": ws["bytes_recv"],
                "fabric_raw_sent": ws["raw_bytes_sent"],
                "fabric_mb_per_tick": round(
                    ws["bytes_sent"] / max(ticks, 1) / 1e6, 3
                ),
                "fabric_raw_mb_per_tick": round(
                    ws["raw_bytes_sent"] / max(ticks, 1) / 1e6, 3
                ),
                "fabric_codec_ratio": round(
                    ws["raw_bytes_sent"] / ws["bytes_sent"], 4
                ) if ws["bytes_sent"] else 1.0,
                "fabric_codec_counts": ws["codec_counts"],
                "d2h_bytes": mh.d2h_bytes,
                "codec": args.codec,
                "schedule": args.schedule,
                "overlap": args.overlap == "on",
                # cumulative blocked-per-leg + hidden-drain wall (r16
                # observability; per-interval deltas ride the journal)
                **mh.leg_timing(),
                "process_count": nprocs,
                "process_id": rank,
                "n": args.n,
                "k": args.k,
            }
        )
    elif args.leg == "snapshot-save":
        mh = MultihostDelta(params, fabric, **engine_kw)
        for _ in range(args.ticks):
            mh.step()
        mh.save_snapshot(args.path)
        _emit(
            {
                "kind": "saved",
                "tick": mh.tick,
                "digest": mh.state_digest(),
                "process_count": nprocs,
                "peak_rss_mb": _peak_rss_mb(),
            }
        )
    elif args.leg == "snapshot-restore":
        mh = MultihostDelta.restore_snapshot(
            args.path, params, fabric, faults=faults,
            schedule=args.schedule, overlap=args.overlap == "on",
        )
        restored_digest = mh.state_digest()
        for _ in range(args.extra_ticks):
            mh.step()
        _emit(
            {
                "kind": "restored",
                "tick": mh.tick,
                "digest_at_restore": restored_digest,
                "digest": mh.state_digest(),
                "process_count": nprocs,
                "peak_rss_mb": _peak_rss_mb(),
            }
        )
    fabric.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
