"""simbench — the BASELINE.json benchmark suite.

Runs the five scenario configs from ``BASELINE.json`` and prints one JSON
line per scenario:

1. ``host10``      — 10-node in-process host-plane cluster (real asyncio
                     TCP gossip): time to bootstrap + converge to one
                     checksum (reference tier: ``scripts/testpop`` cluster).
2. ``loss1k``      — 1k-node lifecycle sim, 5% packet loss: crash 1% of
                     nodes, wall-clock + ticks until every live node
                     believes every victim faulty.
3. ``sweep100k``   — 100k-node lifecycle sim, 3 indirect probes: suspicion
                     timeout sweep; detection latency per suspect period.
4. ``partition1m`` — 1M-node delta sim: 30% partition, run, heal, run;
                     wall-clock until post-heal full dissemination.
5. ``ring1m``      — 1M-vnode ring: batched device Lookup qps and a 1%
                     churn rebalance (reference analog:
                     ``hashring_test.go:332`` micro-bench, scaled up).

Beyond the five BASELINE configs:

- ``montecarlo``   — B seeded replicas in one vmapped program; exact
                     per-replica first-detection ticks (1-tick resolution).
- ``forward`` / ``forward_comparator`` — keyed forwarding qps through a
                     live 3-node cluster, and the minimal asyncio-proxy
                     ceiling it is compared against.
- ``sharded100k``  — the 100k-node lifecycle step AND the full detect
                     path (blocks + on-device predicate + early exit)
                     jitted over a 4x2
                     virtual device mesh, asserted bit-equal to the
                     unsharded step.

Chaos-plane scenarios (``sim/chaos.py`` FaultPlans evaluated inside the
jitted step; each emits a SCORED journal — the per-block telemetry
records plus one ``kind: "score"`` verdict — and certifies
sharded == unsharded state digests for its plan on the 4x2 virtual
mesh):

- ``churn100k``    — staggered crash/restart churn waves (a few nodes
                     permanently down): time-to-detect per crash wave,
                     rumor half-life, re-join convergence after the last
                     restart.
- ``flap1k``       — 1k nodes with ~1% flapping members under 2% loss:
                     false-positive suspicion/refutation churn, scored.
- ``asym_partition`` — a DIRECTED partition window (majority→minority
                     blocked, minority→majority delivering): false
                     accusations pile up and refute through the open
                     direction, then the window heals.

Scale auto-shrinks on CPU hosts (full sizes on an accelerator or with
``--full``).  Usage::

    python -m ringpop_tpu.cli.simbench [--only NAME] [--full] [--seed N]
"""

from __future__ import annotations

import argparse
import functools as _functools
import json
import time
from typing import Optional


def _emit(result: dict) -> None:
    print(json.dumps(result), flush=True)


# --telemetry PATH: the run-journal sink (sim/telemetry.py).  Scenario
# benches that drive a sim engine consult _telemetry_sink(); each opens
# the shared JSONL in append mode and writes its own header record, so
# one file carries the whole run.  None (default) leaves every measured
# path exactly as it was — the telemetry leg compiles out.
_TELEMETRY_PATH = None


def _telemetry_sink(scenario: str, engine: str, params: dict):
    """A TelemetrySink journaling to the --telemetry file, or None."""
    if _TELEMETRY_PATH is None:
        return None
    from ringpop_tpu.sim.telemetry import TelemetryJournal, TelemetrySink

    journal = TelemetryJournal(_TELEMETRY_PATH, append=True)
    journal.header(engine, scenario, params)
    return TelemetrySink(journal=journal)


def _close_sink(sink) -> None:
    if sink is not None and sink.journal is not None:
        sink.journal.close()


def _platform():
    # A wedged axon tunnel HANGS jax.devices() rather than raising, so ask
    # via the shared subprocess probe before touching jax in this process.
    from ringpop_tpu.util.accel import ensure_live_backend

    ensure_live_backend()
    import jax

    return jax.devices()[0].platform


def bench_host10(seed: int, full: bool) -> dict:
    """10 real nodes over asyncio TCP: bootstrap → converged checksums →
    kill one → survivors converge on faulty."""
    import asyncio

    from ringpop_tpu.net import TCPChannel
    from ringpop_tpu.swim.node import BootstrapOptions, Node, NodeOptions
    from ringpop_tpu.swim.state_transitions import StateTimeouts

    n = 10

    async def run():
        chans = [TCPChannel(app="simbench") for _ in range(n)]
        for ch in chans:
            await ch.listen()
        nodes = [
            Node(
                "simbench",
                ch.hostport,
                ch,
                NodeOptions(
                    min_protocol_period=0.02,
                    ping_timeout=0.2,
                    ping_request_timeout=0.4,
                    state_timeouts=StateTimeouts(suspect=0.8),
                ),
            )
            for ch in chans
        ]
        hosts = [nd.address for nd in nodes]
        t0 = time.perf_counter()
        await asyncio.gather(
            *(
                nd.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=1.0))
                for nd in nodes
            )
        )
        # converge: all checksums equal
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            if len({nd.memberlist.checksum() for nd in nodes}) == 1:
                break
            await asyncio.sleep(0.05)
        t_converge = time.perf_counter() - t0
        converged = len({nd.memberlist.checksum() for nd in nodes}) == 1

        # kill one, detect
        t1 = time.perf_counter()
        victim = nodes[-1]
        victim.destroy()  # silent death: timers torn down, no Leave announced
        await chans[-1].close()
        detected = False
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            ok = all(
                any(
                    m.address == victim.address and m.status >= 2
                    for m in nd.memberlist.get_members()
                )
                for nd in nodes[:-1]
            )
            if ok:
                detected = True
                break
            await asyncio.sleep(0.05)
        t_detect = time.perf_counter() - t1

        for nd in nodes[:-1]:
            nd.destroy()
        for ch in chans[:-1]:
            await ch.close()
        return t_converge, converged, t_detect, detected

    t_converge, converged, t_detect, detected = asyncio.run(run())
    return {
        "metric": "host_cluster_10node",
        "value": round(t_converge, 3),
        "unit": "s_to_converge",
        "converged": converged,
        "failure_detect_s": round(t_detect, 3),
        "detected": detected,
    }


def bench_loss1k(seed: int, full: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults
    from ringpop_tpu.sim.lifecycle import LifecycleSim

    n = 1000
    sink = _telemetry_sink("loss1k", "lifecycle", {"n": n, "k": 128, "seed": seed})
    sim = LifecycleSim(n=n, k=128, seed=seed, suspect_ticks=25, rng="counter", telemetry=sink)
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=10, replace=False).tolist())
    up = np.ones(n, bool)
    up[victims] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=0.05)

    try:
        sim.tick(faults)  # compile
        jax.block_until_ready(sim.state.learned)
        t0 = time.perf_counter()
        ticks, ok = sim.run_until_detected(victims, faults, max_ticks=4000)
        elapsed = time.perf_counter() - t0
        # continue to full quiescence: rumors drained + every live view
        # checksum agrees (the reference's waitForConvergence criterion) —
        # only meaningful when detection actually completed
        conv_ticks, conv_ok = (
            sim.run_until_converged(faults, max_ticks=4000) if ok else (None, False)
        )
    finally:
        _close_sink(sink)  # a dying bench must still flush its journal tail
    return {
        "metric": "lifecycle_1k_5pct_loss_detection",
        "value": round(elapsed, 3),
        "unit": "s",
        "ticks": ticks,
        "sim_seconds": round(ticks * sim.params.tick_ms / 1000, 1),
        "detected": ok,
        "n_victims": len(victims),
        "quiescence_ticks_after_detect": conv_ticks,
        "quiesced": conv_ok,
    }


def bench_montecarlo(seed: int, full: bool) -> dict:
    """Detection-latency DISTRIBUTION in one compiled program: B seeded
    cluster replicas vmapped over a replica axis (``sim/montecarlo.py``) —
    the study the reference's integration suite would need B process-cluster
    runs for."""
    import numpy as np

    from ringpop_tpu.sim.montecarlo import detection_latency_distribution

    n = 4096 if full else 512
    b = 32 if full else 8
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    out = detection_latency_distribution(
        n=n, seeds=range(seed, seed + b), victims=victims, k=32, max_ticks=1024
    )
    return {
        "metric": f"mc_detection_distribution_n{n}_x{b}",
        # -1 sentinel keeps the value numeric when no replica detected
        "value": -1.0 if out["ticks_median"] is None else out["ticks_median"],
        "unit": "ticks_median",
        "ticks_p90": out["ticks_p90"],
        "ticks_max": out["ticks_max"],
        "sim_s_median": out["sim_s_median"],
        "replicas": out["n_replicas"],
        "all_detected": out["detected"] == out["n_replicas"],
        # exact per-replica detection ticks (1-tick resolution): the
        # distribution is the deliverable, so ship it whole
        "ticks_all": out["ticks_all"],
    }


def bench_delta16m(seed: int, full: bool) -> dict:
    """Stretch scale: rumor convergence at 16 MILLION nodes — 16x the
    north-star scale — on whatever backend is live.  The packed planes
    (uint32 words + int8 counters at [N, 64]) fit this in ~1.3 GB, and the
    round-2 TPU window measured the same config at 0.24 s wall; the CPU
    number exists to show the scale axis has headroom, not a cliff, on
    the fallback path too."""
    import functools

    import jax

    from ringpop_tpu.sim.delta import DeltaParams, init_state, run_until_converged

    n = 16_000_000 if full else 2_000_000
    params = DeltaParams(n=n, k=64, rng="counter")
    # jitted init: eager pack_bool would materialize a multi-GB [N, W, 32]
    # intermediate at this scale; under jit only the packed output exists
    jinit = jax.jit(functools.partial(init_state, params), static_argnames="seed")
    state = jinit(seed=seed)
    run_until_converged(params, state, max_ticks=8)  # compile + warm
    state = jinit(seed=seed + 1)
    t0 = time.perf_counter()
    dstate, ticks, ok = run_until_converged(params, state, max_ticks=4096)
    jax.block_until_ready(dstate.learned)
    wall = time.perf_counter() - t0
    return {
        "metric": f"delta_{n // 1_000_000}m_convergence",
        "value": round(wall, 2),
        "unit": "s",
        "n_nodes": n,
        "n_rumors": 64,
        "ticks": ticks,
        "converged": ok,
    }


def bench_sharded100k(seed: int, full: bool) -> dict:
    """Sharded lifecycle step AT SCALE on the virtual 8-device CPU mesh
    (VERDICT round-2 item 7; SURVEY §7 hard-part 6): run the full
    100k-node protocol tick jitted over a ("node" x "rumor") mesh with
    real shardings, and assert every state leaf is BIT-EQUAL to the
    unsharded step at the same seed — the partitioned program computes
    exactly the single-device program.

    Runs in a child process because the 8-device virtual mesh needs
    ``xla_force_host_platform_device_count`` set before backend init."""
    import os
    import subprocess
    import sys

    del full  # scale IS the point of this scenario — always 100k
    n = 100_000
    ticks = 6
    code = f"""
import os, json, time
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from ringpop_tpu.util.accel import configure_compile_cache
configure_compile_cache()
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh
from ringpop_tpu.sim import lifecycle
from ringpop_tpu.sim.delta import DeltaFaults

n, k, ticks, seed = {n}, 256, {ticks}, {seed}
rng = np.random.default_rng(seed)
victims = np.sort(rng.choice(n, size=100, replace=False))
up = np.ones(n, bool); up[victims] = False
faults = DeltaFaults(up=jnp.asarray(up))
params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, rng="counter")

state = lifecycle.init_state(params, seed=seed)
import functools
blk = jax.jit(functools.partial(lifecycle._run_block, params), static_argnames="ticks")
t0 = time.perf_counter()
ref = blk(state, faults, ticks=ticks)
jax.block_until_ready(ref.learned)
unsharded_s = time.perf_counter() - t0

devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
mesh = Mesh(devs, ("node", "rumor"))
# the sharded twin runs the r8 sharded-caller defaults: same counter RNG
# (partition-invariant, so the bit-equality below is exact) plus the
# shard-local exchange legs (bit-identical data motion) — bound via the
# one shared helper so its guards can't drift between sharded callers
from ringpop_tpu.parallel.mesh import with_exchange_mesh
sm_params = with_exchange_mesh(params, mesh)
sm_blk = jax.jit(functools.partial(lifecycle._run_block, sm_params), static_argnames="ticks")
shardings = lifecycle.state_shardings(mesh, k=params.k)
sstate = jax.tree.map(jax.device_put, lifecycle.init_state(params, seed=seed),
                      shardings)
t0 = time.perf_counter()
sout = sm_blk(sstate, faults, ticks=ticks)
jax.block_until_ready(sout.learned)
sharded_s = time.perf_counter() - t0

equal = all(bool((np.asarray(a) == np.asarray(b)).all())
            for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(sout)))

# -- the FULL headline detect path, sharded (VERDICT r3 item 4): blocks +
# on-device detection predicate + early exit in one dispatch, over the
# 8-device mesh at 100k — must take the same number of blocks, reach the
# same verdict, and land bit-equal state vs the unsharded run
subjects = jnp.asarray(victims, jnp.int32)
detect_kw = dict(min_status=lifecycle.FAULTY, block_ticks=32, max_blocks=jnp.int32(16))
detect_block_ticks = detect_kw["block_ticks"]
# first call = compile (unless the persistent cache covers it) + execute;
# second call on the SAME inputs = execute only.  Round-4's single-call
# timings swung 6x with cache state and read as perf evidence they were
# not (VERDICT r4 weak #2) — exec_s is the comparable number.
t0 = time.perf_counter()
dref, ref_blocks, ref_done = lifecycle._run_until_detected_device(
    params, lifecycle.init_state(params, seed=seed), faults, subjects, **detect_kw)
jax.block_until_ready(dref.learned)
detect_unsharded_s = time.perf_counter() - t0
t0 = time.perf_counter()
dref2, _, _ = lifecycle._run_until_detected_device(
    params, lifecycle.init_state(params, seed=seed), faults, subjects, **detect_kw)
jax.block_until_ready(dref2.learned)
detect_unsharded_exec_s = time.perf_counter() - t0

# the sharded detect passes the rumor-axis replication hint so each
# check's slot walk pays ONE learned-plane gather instead of collectives
# every fori iteration (r6 tentpole); the hint is a layout constraint
# only — the bit-equality assertion below is what certifies that
from jax.sharding import NamedSharding, PartitionSpec as P
sh_detect_kw = dict(detect_kw, learned_sharding=NamedSharding(mesh, P("node", None)))
t0 = time.perf_counter()
dsh, sh_blocks, sh_done = lifecycle._run_until_detected_device(
    sm_params,
    jax.tree.map(jax.device_put, lifecycle.init_state(params, seed=seed), shardings),
    faults, subjects, **sh_detect_kw)
jax.block_until_ready(dsh.learned)
detect_sharded_s = time.perf_counter() - t0
t0 = time.perf_counter()
dsh2, _, _ = lifecycle._run_until_detected_device(
    sm_params,
    jax.tree.map(jax.device_put, lifecycle.init_state(params, seed=seed), shardings),
    faults, subjects, **sh_detect_kw)
jax.block_until_ready(dsh2.learned)
detect_sharded_exec_s = time.perf_counter() - t0

detect_equal = all(bool((np.asarray(a) == np.asarray(b)).all())
                   for a, b in zip(jax.tree.leaves(dref), jax.tree.leaves(dsh)))
detect = dict(detected=bool(ref_done), ticks=int(ref_blocks) * detect_block_ticks,
              blocks_equal=int(ref_blocks) == int(sh_blocks),
              verdict_equal=bool(ref_done) == bool(sh_done),
              state_equal=detect_equal,
              unsharded_s=round(detect_unsharded_s, 2),
              sharded_s=round(detect_sharded_s, 2),
              unsharded_exec_s=round(detect_unsharded_exec_s, 2),
              sharded_exec_s=round(detect_sharded_exec_s, 2))

# print the certificate BEFORE attempting the 1M step: a non-Python
# death there (OOM SIGKILL) must not destroy the already-computed 100k
# results — the parent takes the LAST parseable line it finds
print(json.dumps(dict(tick_equal=equal, n_devices=len(jax.devices("cpu")),
                      unsharded_s=round(unsharded_s, 2), sharded_s=round(sharded_s, 2),
                      ticks=ticks, detect=detect,
                      step1m=dict(ok=False, error="not attempted (died before the 1M step?)"))),
      flush=True)

# -- one sharded step at FULL headline scale (1M x 256) on the same mesh:
# proves the mesh path compiles + executes at the shape the framework is
# built for (memory-permitting; failure is reported, not fatal)
try:
    p1m = lifecycle.LifecycleParams(n=1_000_000, k=256, suspect_ticks=10,
                                    rng="counter", exchange_mesh=mesh)
    up1 = np.ones(p1m.n, bool); up1[::1000] = False
    f1m = DeltaFaults(up=jnp.asarray(up1))
    s1m = jax.tree.map(jax.device_put, lifecycle.init_state(p1m, seed=seed),
                       lifecycle.state_shardings(mesh, k=p1m.k))
    blk1m = jax.jit(functools.partial(lifecycle._run_block, p1m), static_argnames="ticks")
    # AOT warm-start front door (util/aot.py): a cache hit deserializes
    # the exported executable — no retrace, no relowering, sub-second XLA
    # load — and compile_s/cache_hit below are MEASURED facts, not the
    # first_s - execute_s guess of r4-r10 (which swung 9.08 s -> 362.98 s
    # purely on invisible persistent-cache state).
    from ringpop_tpu.util import aot
    call1m, aot_info = aot.load_or_compile(
        blk1m, s1m, f1m, tag="step1m", static_kw=dict(ticks=1),
        statics=(repr(p1m),))
    t0 = time.perf_counter()
    o1m = call1m(s1m, f1m)
    jax.block_until_ready(o1m.learned)
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    o1m2 = call1m(s1m, f1m)
    jax.block_until_ready(o1m2.learned)
    execute_s = time.perf_counter() - t0
    step1m = dict(ok=True, first_call_s=round(first_s, 2),
                  compile_s=aot_info["compile_s"],
                  execute_s=round(execute_s, 2),
                  cache_hit=aot_info["cache_hit"],
                  aot_error=aot_info["error"],
                  cache_dir=aot_info.get("cache_dir"),
                  tick=int(o1m.tick))
except Exception as e:
    step1m = dict(ok=False, error=(type(e).__name__ + ": " + str(e))[:300])

print(json.dumps(dict(tick_equal=equal, n_devices=len(jax.devices("cpu")),
                      unsharded_s=round(unsharded_s, 2), sharded_s=round(sharded_s, 2),
                      ticks=ticks, detect=detect, step1m=step1m)))
"""
    env = dict(os.environ)
    env.pop("BENCH_PIN", None)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True,
                       timeout=2700, env=env)
    # take the LAST parseable JSON line even on a nonzero exit: the child
    # prints its 100k certificate before attempting the (optional) 1M
    # step, so an OOM kill there must not erase the certificate
    child = None
    for ln in reversed(r.stdout.strip().splitlines()):
        if ln.startswith("{"):
            try:
                child = json.loads(ln)
                break
            except json.JSONDecodeError:
                continue
    if child is None:
        return {
            "metric": f"sharded_lifecycle_step_n{n}",
            "value": None,
            "unit": "s",
            "sharded": True,
            "error": f"child rc={r.returncode}: " + (r.stderr or "")[-400:],
        }
    if r.returncode != 0:
        child.setdefault("step1m", {})
        child["step1m"] = dict(child["step1m"], ok=False,
                               child_rc=r.returncode,
                               stderr_tail=(r.stderr or "")[-200:])
    detect = child["detect"]
    detect_equal = (
        detect["blocks_equal"] and detect["verdict_equal"] and detect["state_equal"]
    )
    result = {
        "metric": f"sharded_lifecycle_step_n{n}",
        "value": child["sharded_s"],
        "unit": "s",
        "sharded": True,
        "n_nodes": n,
        "n_rumor_slots": 256,
        "mesh": "4x2 (node x rumor), virtual CPU devices",
        "ticks": child["ticks"],
        "tick_equal_to_unsharded": child["tick_equal"],
        "unsharded_s": child["unsharded_s"],
        # the full headline path — blocks + on-device predicate + early
        # exit — sharded over the mesh at 100k (VERDICT r3 item 4)
        "detect_path": True,
        "detect_detected": detect["detected"],
        "detect_ticks": detect["ticks"],
        "detect_equal": detect_equal,
        "detect_sharded_s": detect["sharded_s"],
        "detect_unsharded_s": detect["unsharded_s"],
        # execute-only (second call, same inputs): the comparable pair —
        # the *_s fields above include compile on a cold persistent cache
        "detect_sharded_exec_s": detect.get("sharded_exec_s"),
        "detect_unsharded_exec_s": detect.get("unsharded_exec_s"),
        # one sharded 1M x 256 step on the same mesh (headline scale)
        "step1m": child["step1m"],
        "equal": child["tick_equal"] and detect_equal,
    }
    if not result["equal"]:
        # the certificate IS the scenario — a mismatch must read as failure
        # in the artifact, not as a normal row with one odd field
        result["ok"] = False
        result["error"] = "sharded run diverged from unsharded run"
    return result


# -- shared forwarding-bench plumbing (used by forward, forward_comparator
# and the paired forward_ab; one copy so the A/B sides cannot drift) ---------


class _MinimalProxy:
    """The comparator fixture: a MINIMAL asyncio TCP proxy — 4-byte-length
    JSON frames, client → proxy → echo upstream → back, zero protocol
    logic.  This is the bare asyncio+socket+json ceiling of the container;
    the ringpop forwarding number over it states the protocol's real
    overhead instead of an unfalsifiable "Go-class" adjective (the
    reference's forwarding path for comparison:
    ``forward/request_sender.go:148-204``)."""

    def __init__(self):
        self.conns = []
        self._servers = []

    async def start(self, wave: int):
        import asyncio
        import json as _json
        import struct

        async def _serve_echo(reader, writer):
            try:
                while True:
                    (ln,) = struct.unpack(">I", await reader.readexactly(4))
                    body = _json.loads(await reader.readexactly(ln))
                    out = _json.dumps({"ok": True, "i": body["i"]}).encode()
                    writer.write(struct.pack(">I", len(out)) + out)
                    await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionResetError):
                pass

        echo_srv = await asyncio.start_server(_serve_echo, "127.0.0.1", 0)
        echo_port = echo_srv.sockets[0].getsockname()[1]

        async def _serve_proxy(reader, writer):
            up_r, up_w = await asyncio.open_connection("127.0.0.1", echo_port)
            try:
                while True:
                    hdr = await reader.readexactly(4)
                    payload = await reader.readexactly(struct.unpack(">I", hdr)[0])
                    up_w.write(hdr + payload)
                    await up_w.drain()
                    rhdr = await up_r.readexactly(4)
                    rbody = await up_r.readexactly(struct.unpack(">I", rhdr)[0])
                    writer.write(rhdr + rbody)
                    await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionResetError):
                pass
            finally:
                up_w.close()

        proxy_srv = await asyncio.start_server(_serve_proxy, "127.0.0.1", 0)
        proxy_port = proxy_srv.sockets[0].getsockname()[1]
        self._servers = [proxy_srv, echo_srv]
        self.conns = [
            await asyncio.open_connection("127.0.0.1", proxy_port)
            for _ in range(wave)
        ]
        return self

    async def _drive(self, conn, base, count):
        import json as _json
        import struct

        reader, writer = conn
        for i in range(count):
            out = _json.dumps({"i": base + i}).encode()
            writer.write(struct.pack(">I", len(out)) + out)
            await writer.drain()
            (ln,) = struct.unpack(">I", await reader.readexactly(4))
            await reader.readexactly(ln)

    async def rep(self, rep_idx: int, per_conn: int) -> float:
        """One timed rep: every connection drives per_conn requests
        concurrently; returns req/s."""
        import asyncio

        t0 = time.perf_counter()
        await asyncio.gather(
            *(
                self._drive(c, (rep_idx * len(self.conns) + j) * per_conn, per_conn)
                for j, c in enumerate(self.conns)
            )
        )
        return len(self.conns) * per_conn / (time.perf_counter() - t0)

    def close(self):
        for _, w in self.conns:
            w.close()
        for srv in self._servers:
            srv.close()


class _FwdCluster:
    """The full-path fixture: a live 3-node TCP ringpop cluster with a
    keyed /op handler; requests enter at node 0 via handle_or_forward, so
    ~2/3 proxy to the key's owner over the wire and ~1/3 handle locally
    (SURVEY §3.4 hot loop)."""

    def __init__(self):
        self.rps = []
        self.chans = []

    async def start(self):
        import asyncio

        from ringpop_tpu.net import TCPChannel
        from ringpop_tpu.ringpop import Ringpop

        self.chans = [TCPChannel(app="fwd") for _ in range(3)]
        for ch in self.chans:
            await ch.listen()
            ch.register("fwd", "/op", lambda body, headers: {"ok": True})
        self.rps = [Ringpop("fwd", ch) for ch in self.chans]
        hosts = [ch.hostport for ch in self.chans]
        await asyncio.gather(*(rp.bootstrap(discover_provider=hosts) for rp in self.rps))
        return self

    async def one(self, i: int) -> bool:
        handled, _ = await self.rps[0].handle_or_forward(
            f"key-{i}", {"i": i}, "fwd", "/op"
        )
        return handled

    async def rep(self, rep_idx: int, waves: int, wave: int):
        """One timed rep of ``waves`` sequential waves of ``wave``
        concurrent requests; returns (req/s, handled_locally)."""
        import asyncio

        t0 = time.perf_counter()
        done = local = 0
        for w in range(waves):
            base = (rep_idx * waves + w) * wave
            results = await asyncio.gather(
                *(self.one(base + i) for i in range(wave))
            )
            done += len(results)
            local += sum(1 for h in results if h)
        return done / (time.perf_counter() - t0), local

    async def close(self):
        for rp in self.rps:
            rp.destroy()
        for ch in self.chans:
            await ch.close()


def bench_forward_comparator(seed: int, full: bool) -> dict:
    """Comparator for forward_keyed_qps_3node (VERDICT round-2 item 9): the
    minimal-proxy fixture (see ``_MinimalProxy``) measured with the same
    wave/rep methodology on the same container.  Kept as a standalone
    scenario for history; the PAIRED measurement that survives container
    drift is ``forward_ab``."""
    import asyncio

    n_req = 5000 if full else 500
    wave = 100  # concurrent client connections, each strictly RTT-bound
    per_conn = max(1, n_req // wave)

    async def run():
        proxy = await _MinimalProxy().start(wave)
        reps, warm_reps = (5, 2) if full else (3, 1)
        qps = []
        for rep in range(warm_reps + reps):
            q = await proxy.rep(rep, per_conn)
            if rep >= warm_reps:
                qps.append(q)
        proxy.close()
        return sorted(qps)

    qps = asyncio.run(run())
    return {
        "metric": "forward_comparator_qps_minimal_proxy",
        "value": round(qps[len(qps) // 2], 0),
        "unit": "req_per_s",
        "qps_reps": [round(q) for q in qps],
        # the count actually driven (wave * per_conn), not the requested
        # n_req — they differ whenever n_req is not a multiple of wave
        "n_requests_per_rep": wave * per_conn,
    }


def bench_sweep100k(seed: int, full: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults
    from ringpop_tpu.sim.lifecycle import LifecycleSim

    n = 100_000 if full else 20_000
    sweep = {}
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=20, replace=False).tolist())
    up = np.ones(n, bool)
    up[victims] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    t0 = time.perf_counter()
    for suspect_ticks in (5, 25, 50):
        sim = LifecycleSim(n=n, k=256, seed=seed, suspect_ticks=suspect_ticks, rng="counter")
        ticks, ok = sim.run_until_detected(victims, faults, max_ticks=4000)
        sweep[str(suspect_ticks)] = {"ticks": ticks, "detected": ok}
    elapsed = time.perf_counter() - t0
    return {
        "metric": f"lifecycle_{n//1000}k_suspicion_sweep",
        "value": round(elapsed, 3),
        "unit": "s_total",
        "n_nodes": n,
        "sweep": sweep,
    }


def bench_partition1m(seed: int, full: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaSim

    n = 1_000_000 if full else 50_000
    k = 128 if full else 64
    group = np.zeros(n, np.int32)
    group[: int(0.3 * n)] = 1
    part = DeltaFaults(up=jnp.ones(n, bool), group=jnp.asarray(group))
    heal = DeltaFaults(up=jnp.ones(n, bool))

    # the sink (--telemetry) journals one coverage/digest record per
    # 64-tick block; with no sink DeltaSim dispatches exactly the old
    # single-call path
    sink = _telemetry_sink("partition1m", "delta", {"n": n, "k": k, "seed": seed})
    sim = DeltaSim(n=n, k=k, seed=seed, rng="counter", telemetry_sink=sink)
    try:
        t0 = time.perf_counter()
        # partition phase: dissemination proceeds within each side only
        t_part, _ = sim.run_until_converged(part, max_ticks=256)
        # heal phase: cross-side exchange completes global convergence
        t_heal, ok = sim.run_until_converged(heal, max_ticks=4096)
        elapsed = time.perf_counter() - t0
    finally:
        _close_sink(sink)  # a dying bench must still flush its journal tail
    return {
        "metric": f"delta_{n//1000}k_30pct_partition_heal",
        "value": round(elapsed, 3),
        "unit": "s",
        "partition_ticks": t_part,
        "heal_ticks": t_heal,
        "converged": ok,
        "n_nodes": n,
    }


def bench_partition_lifecycle(seed: int, full: bool) -> dict:
    """Detection and convergence SEPARATED, at scale (VERDICT r4 item 6):
    the headline bench always reports ``converge_extra_ticks: 0`` because
    at that config quiescence coincides with detection — this row makes
    the two criteria discriminate.

    Crash 0.1% of the cluster and run the headline detection to
    completion; then, before the views are left to quiesce, a 30%
    partition blips for ``blip_ticks`` and heals.  During the blip every
    cross-partition probe fails, so the cluster admits (budget-bounded)
    FALSE suspicions about live nodes.  Detection of the true victims is
    already done — but literal convergence (the reference's
    waitForConvergence criterion, ``swim/test_utils.go:164-199``: NO
    rumors in flight and every live view checksum equal) must now wait
    for every falsely-accused node to learn of its accusation, refute by
    reincarnation, and for the refutations to disseminate and quiesce:
    ``converge_extra_ticks > 0``, measured at 4-tick granularity.

    Why the blip comes AFTER detection: a partition held across the
    whole detection episode wedges the bounded global rumor table —
    cross-partition rumors can never reach full coverage, the full-sync
    re-seeder keeps them alive, admission stalls, and the true victims'
    accusations queue behind ~0.3·N false candidates at 64 admissions/
    tick (measured: 20k-node smoke never detected within 1024 partition
    ticks).  That wedge is a real property of bounded-slot dissemination
    under partition (the reference's per-node piggyback maps are
    unbounded, ``swim/disseminator.go``), and the committed row's fields
    record the post-heal reconciliation instead of fighting it.

    Reference analog: partition tests build partitions by fiat then heal
    (``swim/heal_partition_test.go:15-53``); refutation-by-reincarnation
    is ``swim/memberlist.go:337-354``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults

    n = 1_000_000 if full else 20_000
    k = 256 if full else 64
    blip_ticks = 24  # < suspect_ticks (25): accusations stay refutable suspects
    rng = np.random.default_rng(seed)
    victims = np.sort(rng.choice(n, size=max(4, n // 1000), replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    group = np.zeros(n, np.int32)
    group[: int(0.3 * n)] = 1
    plain = DeltaFaults(up=jnp.asarray(up))
    blip = DeltaFaults(up=jnp.asarray(up), group=jnp.asarray(group))

    sink = _telemetry_sink(
        "partition_lc", "lifecycle", {"n": n, "k": k, "seed": seed}
    )
    sim = lifecycle.LifecycleSim(n=n, k=k, seed=seed, rng="counter", telemetry=sink)
    try:
        # phase 1: headline failure detection, no partition
        t0 = time.perf_counter()
        detect_ticks, detected = sim.run_until_detected(
            victims, plain, max_ticks=4096, check_every=16, blocks_per_dispatch=8,
            time_budget_s=2400.0,
        )
        jax.block_until_ready(sim.state.learned)
        detect_s = time.perf_counter() - t0

        # phase 2: the 30% partition blips and heals late
        t0 = time.perf_counter()
        sim.run(blip_ticks, blip)
        jax.block_until_ready(sim.state.learned)
        blip_s = time.perf_counter() - t0

        # the blip left the cluster detected-but-not-converged: false
        # accusations are in flight and views diverge across nodes
        cs = np.asarray(lifecycle.view_checksums(sim.state, plain))
        views_agree_after_blip = bool(len(np.unique(cs[np.asarray(plain.up)])) == 1)

        # phase 3 (healed): literal convergence — refutations must disseminate
        # and quiesce; 4-tick checks so a short tail still resolves as > 0
        t0 = time.perf_counter()
        extra_ticks, converged = sim.run_until_converged(
            plain, max_ticks=4096, check_every=4, blocks_per_dispatch=8,
            time_budget_s=2400.0,
        )
        jax.block_until_ready(sim.state.learned)
        converge_s = time.perf_counter() - t0
    finally:
        _close_sink(sink)  # a dying bench must still flush its journal tail

    return {
        "metric": f"lifecycle_{n // 1000}k_30pct_partition_blip_heal",
        "value": round(detect_s + blip_s + converge_s, 3),
        "unit": "s",
        "n_nodes": n,
        "n_rumor_slots": k,
        "n_victims": int(len(victims)),
        "detect_ticks": detect_ticks,
        "detected": detected,
        "detect_s": round(detect_s, 3),
        "blip_ticks": blip_ticks,
        "blip_s": round(blip_s, 3),
        # detection is NOT convergence here: views differ after the blip
        "views_agree_after_blip": views_agree_after_blip,
        # the deliverable: convergence lands strictly AFTER detection
        "converge_extra_ticks": extra_ticks,
        "converged": converged,
        "converge_s": round(converge_s, 3),
    }


def bench_ring1m(seed: int, full: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.ops.ring_ops import build_ring_tokens, ring_lookup

    # 256 vnodes/server per the BASELINE config line
    n_servers = 4096 if full else 512
    replicas = 256
    batch = 1_000_000 if full else 100_000
    servers = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(n_servers)]
    t0 = time.perf_counter()
    tokens, owners = build_ring_tokens(servers, replicas)
    build_s = time.perf_counter() - t0

    rng = np.random.default_rng(seed)
    hashes = jnp.asarray(rng.integers(0, 2**32, size=batch, dtype=np.uint32))
    out = ring_lookup(tokens, owners, hashes)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    iters = 10
    for _ in range(iters):
        out = ring_lookup(tokens, owners, hashes)
    jax.block_until_ready(out)
    qps = batch * iters / (time.perf_counter() - t0)

    # fused keyed path: hash raw keys on-device then look up owners
    from ringpop_tpu.hashing.farm import pack_strings
    from ringpop_tpu.ops.hash_ops import keyed_owner_lookup

    n_keys = 100_000 if full else 20_000
    keys = [f"user:{i}:{i * 37}" for i in range(n_keys)]
    mat, lens = pack_strings([s.encode() for s in keys])
    mat, lens = jnp.asarray(mat), jnp.asarray(lens)
    out2 = keyed_owner_lookup(tokens, owners, mat, lens)
    jax.block_until_ready(out2)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out2 = keyed_owner_lookup(tokens, owners, mat, lens)
    jax.block_until_ready(out2)
    keyed_qps = n_keys * iters / (time.perf_counter() - t0)

    # 1% churn: remove + add servers, rebuild the token arrays
    n_churn = max(1, n_servers // 100)
    t0 = time.perf_counter()
    survivors = servers[n_churn:] + [f"10.9.{i // 256}.{i % 256}:3000" for i in range(n_churn)]
    tokens2, owners2 = build_ring_tokens(survivors, replicas)
    jax.block_until_ready(ring_lookup(tokens2, owners2, hashes[:1024]))
    rebalance_s = time.perf_counter() - t0

    return {
        "metric": f"ring_lookup_{n_servers * replicas // 1000}k_vnodes",
        "value": round(qps, 0),
        "unit": "lookups_per_s",
        "build_s": round(build_s, 3),
        "keyed_hash_lookup_qps": round(keyed_qps, 0),
        "churn_rebalance_s": round(rebalance_s, 3),
        "n_servers": n_servers,
        "replica_points": replicas,
        "batch": batch,
    }


def bench_forward_qps(seed: int, full: bool) -> dict:
    """App data path (SURVEY §3.4 hot loop): keyed requests through
    handle_or_forward on a live 3-node TCP cluster (``_FwdCluster``) —
    ~2/3 of requests proxy to the owner over the wire, 1/3 handle
    locally.  Kept as a standalone scenario for history; the PAIRED
    protocol-overhead measurement is ``forward_ab``."""
    import asyncio

    n_req = 5000 if full else 500  # per rep; short reps are noise-dominated

    # Measurement shape matters on one core: a single gather of all n_req
    # tasks queues thousands of concurrent callbacks at once and measured
    # anywhere from 9k to 22k req/s run to run.  Instead: sequential waves
    # of 500 in-flight requests; discard several full warm reps (warmup is
    # long and variable — interpreter specialization + allocator state can
    # keep reps climbing past 20k requests); report the median of the
    # measured reps WITH the sorted rep list so consumers see the spread,
    # not one lucky number.  Smoke mode shrinks so `--only forward` stays
    # fast.
    wave = 500
    waves = max(1, n_req // wave)

    async def run():
        cluster = await _FwdCluster().start()
        reps, warm_reps = (5, 4) if full else (3, 1)
        qps, local, total = [], 0, 0
        for rep in range(warm_reps + reps):
            q, l = await cluster.rep(rep, waves, wave)
            if rep >= warm_reps:
                qps.append(q)
                local += l
                total += waves * wave
        await cluster.close()
        return sorted(qps), local, total

    qps, local, total = asyncio.run(run())
    return {
        "metric": "forward_keyed_qps_3node",
        "value": round(qps[len(qps) // 2], 0),
        "unit": "req_per_s",
        "qps_reps": [round(q) for q in qps],
        "n_requests": total,
        "handled_locally": local,
        "forwarded": total - local,
    }


def bench_forward_ab(seed: int, full: bool) -> dict:
    """PAIRED protocol-overhead A/B (VERDICT r3 item 5): the full ringpop
    forwarding path (``_FwdCluster``) and the minimal-proxy comparator
    (``_MinimalProxy``) measured in INTERLEAVED reps inside ONE scenario
    run.  Round 3 ran them as separate sequential scenarios and
    container-load drift between them produced a 26% gap in one artifact
    and ~4% in another; interleaving rep-by-rep (the msgpack A/B's
    methodology) makes the ratio paired, so drift hits both sides of each
    pair equally.  Reference path being priced:
    ``forward/request_sender.go:148-204``."""
    import asyncio

    n_req = 5000 if full else 500
    comp_wave = 100
    per_conn = max(1, n_req // comp_wave)
    wave = 500
    waves = max(1, n_req // wave)

    async def run():
        cluster = await _FwdCluster().start()
        proxy = await _MinimalProxy().start(comp_wave)

        # interleaved reps: full, comparator, full, comparator, ...
        reps, warm_reps = (5, 4) if full else (3, 1)
        full_qps, comp_qps = [], []
        for rep in range(warm_reps + reps):
            f, _ = await cluster.rep(rep, waves, wave)
            c = await proxy.rep(rep, per_conn)
            if rep >= warm_reps:
                full_qps.append(f)
                comp_qps.append(c)

        await cluster.close()
        proxy.close()
        return full_qps, comp_qps

    full_qps, comp_qps = asyncio.run(run())
    ratios = sorted(f / c for f, c in zip(full_qps, comp_qps))
    ratio_median = ratios[len(ratios) // 2]
    return {
        "metric": "forward_vs_comparator_paired",
        # the deliverable is the PAIRED ratio: full-path qps as a fraction
        # of the minimal-proxy ceiling, measured side by side per rep
        "value": round(ratio_median, 4),
        "unit": "qps_ratio_full_over_minimal",
        "protocol_overhead_pct_median": round((1.0 - ratio_median) * 100.0, 1),
        "ratio_reps": [round(r, 4) for r in ratios],
        "forward_qps_reps": sorted(round(q) for q in full_qps),
        "comparator_qps_reps": sorted(round(q) for q in comp_qps),
        "n_requests_per_rep": n_req,
    }


def bench_mc_churn(seed: int, full: bool) -> dict:
    """Detection latency for a FIXED victim set under per-replica background
    churn — the heterogeneous Monte-Carlo study (VERDICT r3 item 7: the
    homogeneous mc scenario's 35/36/37-tick spread across 32 replicas
    measured only PRNG noise).  Replica b additionally crashes ~b/B of up
    to ``churn_max`` background nodes; the extra crashes compete for the K
    rumor slots and piggyback bandwidth, so the percentile machinery has a
    real distribution to summarize."""
    import numpy as np

    from ringpop_tpu.sim.lifecycle import LifecycleParams
    from ringpop_tpu.sim.montecarlo import detection_latency_under_churn

    n = 4096 if full else 512
    b = 32 if full else 8
    churn_max = n // 32  # up to ~3% of the cluster crashing in the background
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    out = detection_latency_under_churn(
        n=n,
        seeds=range(seed, seed + b),
        victims=victims,
        churn_max=churn_max,
        k=32,
        max_ticks=4096,
        churn_seed=seed + 777,
    )
    spread = (
        None
        if out["ticks_median"] is None or out["ticks_p90"] is None
        else out["ticks_p90"] - out["ticks_median"]
    )
    # locate the cliff (VERDICT r4 item 5): the dose at the largest jump
    # between consecutive points of the dose-response curve.  The round-4
    # curve was stepwise (36 -> 46 -> 56-63) with one dominating jump
    # (63 -> 96 between doses 103 and 107) that the summary stats hid.
    # (finder shared with the mc_chaos surface rows: scenarios.locate_cliff)
    from ringpop_tpu.sim.scenarios import locate_cliff

    cliff_at, cliff_jump = locate_cliff(out["churn_ticks"])
    # mechanism contrast at the saturating dose (2 replicas each: dose 0 +
    # dose churn_max).  Tripling maxP leaves the saturated latency
    # unchanged while doubling K collapses it — the binding constraint is
    # rumor-SLOT capacity, not the maxP propagation budget (the analog of
    # swim/disseminator.go:75-97, which in the reference governs an
    # UNBOUNDED piggyback map and therefore cannot produce this cliff).
    contrast = None
    if full:
        base_p = LifecycleParams(n=n, k=32)
        contrast = {"maxp_default": base_p.resolved_max_p()}
        for label, kw in (
            ("k32_maxp_default", dict(k=32)),
            ("k32_maxp_x3", dict(k=32, max_p=3 * base_p.resolved_max_p())),
            ("k64_maxp_default", dict(k=64)),
        ):
            o = detection_latency_under_churn(
                n=n, seeds=[seed, seed + 1], victims=victims,
                churn_max=churn_max, max_ticks=4096,
                churn_seed=seed + 778, **kw,
            )
            contrast[label] = o["churn_ticks"]
    return {
        "metric": f"mc_churn_detection_n{n}_x{b}",
        "value": -1.0 if out["ticks_median"] is None else out["ticks_median"],
        "unit": "ticks_median",
        "ticks_p90": out["ticks_p90"],
        "ticks_max": out["ticks_max"],
        "p90_minus_median": spread,
        "churn_max": churn_max,
        "replicas": out["n_replicas"],
        "all_detected": out["detected"] == out["n_replicas"],
        "detected": out["detected"],
        # the dose-response curve: per-replica [background_churn, ticks]
        "churn_ticks": out["churn_ticks"],
        "churn_cliff_at": cliff_at,
        "cliff_jump_ticks": cliff_jump,
        "k": 32,
        "cliff_contrast": contrast,
    }


def bench_mc_chaos(seed: int, full: bool) -> dict:
    """The batched chaos fleet (ISSUE 7 tentpole): the mc_churn cliff
    mapped as a churn×loss RESPONSE SURFACE instead of one slice, by ONE
    compiled program over a stacked-FaultPlan grid (``sim/scenarios.py``).

    Three measurements in one scenario:

    1. **The surface** — every (churn dose × loss rate) grid point's
       first-detection tick at 1-tick resolution, one AOT-warm-started
       fleet dispatch (``scenarios.detect_surface``, tag ``mc_chaos``;
       the record carries the front door's measured cache_hit/compile_s,
       same schema as step1m).  The loss-0 row reuses the committed
       mc_churn slice's (seed, dose, mask) pairing EXACTLY — same rng
       sequence, same victims, same params — so its cliff must land
       where SIMBENCH_r05 put it (dose 107 at full scale); the other
       rows are the new information.
    2. **Throughput A/B** — the WHOLE sweep batched (the surface run:
       one program, one dispatch, its measured AOT compile included in
       the wall clock) vs the sequential B-runs baseline it replaces
       (one trace+compile + one dispatch PER grid point —
       ``scenarios.sequential_detect(fresh_compile=True)``; the
       warm-cache sequential loop is also recorded for transparency).
       End-to-end wall clock including compile, reported as
       replicas·ticks·nodes/s; the sequential pass doubles as a
       whole-surface tick-for-tick certificate (``ticks_equal``).
    3. **Scored journal** — the same grid run for a fixed horizon with
       the r7 telemetry counters accumulated UNDER the batch axis: one
       device fetch per block for all scenarios, one
       ``chaos.score_blocks`` verdict per scenario (grid coordinates
       attached), journaled to --telemetry when given.
    """
    import jax
    import numpy as np

    from ringpop_tpu.sim import scenarios, telemetry
    from ringpop_tpu.sim.lifecycle import LifecycleParams

    n = 4096 if full else 512
    b_doses = 32 if full else 8
    churn_max = n // 32
    k = 32
    losses = (0.0, 0.02, 0.05, 0.1)
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    doses = scenarios.mc_churn_doses(b_doses, churn_max)
    # identical params to bench_mc_churn's study (threefry, default
    # suspicion): the loss-0 row IS that study, re-derived by the fleet
    params = LifecycleParams(n=n, k=k)
    plan, meta = scenarios.scenario_grid(
        n, victims=victims, doses=doses, losses=losses, churn_seed=seed + 777
    )
    seeds = scenarios.grid_seeds(meta, seed)

    # -- 1: the churn x loss surface, one batched dispatch -------------------
    t0 = time.perf_counter()
    ticks, detected, aot_info = scenarios.detect_surface(
        params, plan, seeds, victims, max_ticks=4096, check_every=1,
        aot="mc_chaos",
    )
    surface_s = time.perf_counter() - t0
    tick_vals = [int(t) if d else None for t, d in zip(ticks, detected)]
    surface = scenarios.response_surface(meta, tick_vals, rows="loss", cols="churn")
    cliffs = {}
    for loss, row in zip(surface["rows"], surface["cells"]):
        at, jump = scenarios.locate_cliff(list(zip(surface["cols"], row)))
        cliffs[str(loss)] = {"cliff_at": at, "jump_ticks": jump}

    # -- 2: batched vs sequential throughput — THE WHOLE SWEEP ---------------
    # The batched side IS the surface run above (one AOT-front-door
    # program; its measured compile_s is part of surface_wall_s).  The
    # baseline is the workflow the fleet replaces: every grid point its
    # own run with its own trace+compile (simulated honestly with
    # jax.clear_caches() per point — each point of the pre-fleet sweep
    # was its own bench invocation), plus the best-case warm-cache
    # sequential loop for transparency.  The sequential pass doubles as
    # a whole-surface certificate: every grid point's first-detection
    # tick must match the batched program's.
    t0 = time.perf_counter()
    seq_t, seq_d = scenarios.sequential_detect(
        params, plan, seeds, victims, max_ticks=4096, check_every=1,
        fresh_compile=True,
    )
    seq_ab_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    warm_t, _ = scenarios.sequential_detect(
        params, plan, seeds, victims, max_ticks=4096, check_every=1,
        fresh_compile=False,
    )
    seq_warm_s = time.perf_counter() - t0
    batched_ab_s = surface_s
    b_ab = len(meta)
    ab_equal = [int(a) for a in ticks] == [int(s) for s in seq_t] and (
        [int(a) for a in ticks] == [int(w) for w in warm_t]
    )
    # work metric: replicas x ticks actually stepped x nodes — the fleet
    # steps every replica in lockstep to the last-detecting replica's
    # tick (the full budget if any replica never detected).  The same
    # numerator prices both sides: each produces the same deliverable
    # (the B first-detection ticks), so rtn/s is sweep throughput.
    ticks_run = (
        max(int(t) for t in ticks) if bool(np.asarray(detected).all()) else 4096
    )
    ab_work = int(b_ab * ticks_run * n)

    # -- 3: scored journal over the full grid --------------------------------
    sink = _telemetry_sink(
        "mc_chaos", "lifecycle",
        {"n": n, "k": k, "seed": seed, "grid": {"doses": doses, "losses": list(losses)}},
    )
    if sink is None:
        sink = telemetry.TelemetrySink()
    horizon = 256
    try:
        t0 = time.perf_counter()
        scores = scenarios.scored_fleet(
            params, plan, meta, seeds, horizon=horizon, journal_every=16,
            sink=sink,
        )
        scored_s = time.perf_counter() - t0
    finally:
        _close_sink(sink)
    fp_surface = scenarios.response_surface(
        meta, [s["false_positive_suspects"] for s in scores],
        rows="loss", cols="churn",
    )
    detect_frac_surface = scenarios.response_surface(
        meta, [s["final_detect_frac"] for s in scores], rows="loss", cols="churn",
    )

    loss0 = cliffs.get("0.0", {})
    return {
        "metric": f"mc_chaos_surface_n{n}_g{len(meta)}",
        # headline: end-to-end speedup of the batched sub-grid over the
        # one-compile-one-dispatch-per-point baseline it replaces
        "value": round(seq_ab_s / batched_ab_s, 2),
        "unit": "x_speedup_vs_sequential",
        "n_nodes": n,
        "k": k,
        "grid": {"doses": doses, "losses": list(losses), "b_total": len(meta)},
        "surface_wall_s": round(surface_s, 2),
        "detected": int(np.asarray(detected).sum()),
        "surface": surface,
        "cliff_by_loss": cliffs,
        # the mc_churn parity anchor: the loss-0 row's cliff (must equal
        # the committed 1-D slice's churn_cliff_at at full scale)
        "churn_cliff_at": loss0.get("cliff_at"),
        "cliff_jump_ticks": loss0.get("jump_ticks"),
        # AOT front door (same schema as step1m): measured, not inferred
        "cache_hit": aot_info.get("cache_hit"),
        "compile_s": aot_info.get("compile_s"),
        "aot_error": aot_info.get("error"),
        "cache_dir": aot_info.get("cache_dir"),
        "throughput": {
            "b": b_ab,
            "max_ticks": 4096,
            "batched_s": round(batched_ab_s, 2),
            "sequential_s": round(seq_ab_s, 2),
            "sequential_warm_s": round(seq_warm_s, 2),
            "speedup": round(seq_ab_s / batched_ab_s, 2),
            "speedup_vs_warm": round(seq_warm_s / batched_ab_s, 2),
            "ticks_equal": ab_equal,
            "batched_rtn_per_s": round(ab_work / batched_ab_s, 0),
            "sequential_rtn_per_s": round(ab_work / seq_ab_s, 0),
        },
        "scored": {
            "horizon": horizon,
            "wall_s": round(scored_s, 2),
            "scores": len(scores),
            "false_positive_surface": fp_surface,
            "final_detect_frac_surface": detect_frac_surface,
        },
    }


def _fleet_sharded_twin(seed: int, n: int, k: int, ticks: int = 24) -> dict:
    """Certify the fleet's batch-axis mesh sharding partition-invariant:
    the SAME small scenario grid run unsharded and over a 2x2x2
    (batch x node x rumor) virtual mesh in a child process must land
    identical per-scenario state digests (``index_plan`` slices the
    stacked plan per member for the meta, the digests come from the
    vmapped ``tree_digest``).  Small B on purpose — the certificate is
    about the batch-sharded program, which is shape-uniform in B."""
    import os
    import subprocess
    import sys

    code = f"""
import os, json
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from ringpop_tpu.util.accel import configure_compile_cache
configure_compile_cache()
import numpy as np
from ringpop_tpu.sim import lifecycle, scenarios
from ringpop_tpu.sim.montecarlo import MonteCarlo, make_fleet_mesh

n, k, ticks, seed = {n}, {k}, {ticks}, {seed}
params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, rng="counter")
rng = np.random.default_rng(seed)
victims = sorted(rng.choice(n, size=4, replace=False).tolist())
plan, meta = scenarios.scenario_grid(
    n, victims=victims, doses=[0, n // 64, n // 32], losses=(0.0, 0.05),
    churn_seed=seed + 777,
)
seeds = scenarios.grid_seeds(meta, seed)
# rumor axis only when k supplies 32-slot words for 2 shards
shape = (2, 2, 2) if k % 64 == 0 else (2, 4, 1)
mc_u = MonteCarlo(params, seeds, telemetry=True)
mc_s = MonteCarlo(params, seeds, telemetry=True,
                  mesh=make_fleet_mesh(8, shape))
mc_u.run(ticks, plan)
mc_s.run(ticks, plan)
ru = mc_u.fetch_telemetry(plan)
rs = mc_s.fetch_telemetry(plan)
equal = all(a == b for a, b in zip(ru, rs))
print(json.dumps(dict(
    equal=equal, b=len(meta), n=n, k=k, ticks=ticks,
    digests=[r["state_digest"] for r in ru],
    mesh="x".join(str(s) for s in shape) + " (batch x node x rumor), virtual CPU devices",
)))
"""
    env = dict(os.environ)
    env.pop("BENCH_PIN", None)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1800, env=env)
    except subprocess.TimeoutExpired:
        return {"equal": False, "error": "fleet twin subprocess timed out"}
    for ln in reversed(r.stdout.strip().splitlines()):
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except json.JSONDecodeError:
                continue
    return {"equal": False,
            "error": f"fleet twin child rc={r.returncode}: " + (r.stderr or "")[-300:]}


def bench_fleet_scale(seed: int, full: bool) -> dict:
    """The r19 million-replica scenario fleet (ISSUE 14 tentpole): batch
    axis ON the partition table, resume-exact fleet checkpoints, and the
    adaptive cliff driver A/B'd against the dense grid.

    Four legs, one certificate:

    1. **Process-sharded sweep + RSS** — the SAME scored sweep run P=1
       (unbroken) and P=2 (each rank its ``process_block`` batch slice;
       the P=2 run also checkpoints MID-SWEEP — every rank writing only
       its shards — and continues).  Per-scenario digests and score
       records must be bit-equal, and the max per-rank peak RSS at P=2
       must be < 0.75 of the P=1 run's (the batch axis actually shards
       residency — the r14-style pin at fleet scale).
    2. **Kill-and-restore** — the P=2 mid-sweep checkpoint restores at
       P=1 (a DIFFERENT process count), continues, and must reproduce
       the unbroken run's digests and scores bit-exactly.
    3. **Virtual-mesh twin** — a small grid through the 2x2x2
       (batch x node x rumor) device mesh vs unsharded, per-scenario
       records equal (the GSPMD flavor of the same invariant).
    4. **Adaptive vs dense cliff search** — ``scenarios.refine_surface``
       must locate each loss row's cliff at 1-dose resolution with the
       SAME coordinates as the dense 1-dose grid at <= 1/4 the
       scenario-evaluations, every dispatch a value-only swap through
       ONE compiled fleet program (median-of-``seeds_per_point``
       replicas per point — the Ising-ensemble smoothing both sides
       share).
    """
    import os
    import tempfile

    import numpy as np

    from ringpop_tpu.sim import scenarios
    from ringpop_tpu.sim.lifecycle import LifecycleParams

    launch, _ = _mh_launch()
    worker = ["-m", "ringpop_tpu.cli.fleet_bench"]

    # -- legs 1+2: process-sharded sweep, RSS, mid-sweep save, restore -------
    if full:
        n, k, b_doses, losses = 4096, 64, 512, "0.0,0.05,0.1,0.15"
    else:
        n, k, b_doses, losses = 512, 16, 16, "0.0,0.1"
    horizon, journal_every, save_at = 32, 16, 16
    grid_args = [
        "--n", str(n), "--k", str(k), "--b-doses", str(b_doses),
        "--losses", losses, "--seed", str(seed),
        "--horizon", str(horizon), "--journal-every", str(journal_every),
        "--suspect-ticks", "10",
    ]
    ck = os.path.join(tempfile.mkdtemp(prefix="fleet_scale_"), "ck")
    t0 = time.perf_counter()
    r1 = launch(1, worker + ["sweep"] + grid_args, timeout_s=3600)
    p1_wall = time.perf_counter() - t0
    rec1 = r1[0]["records"][0]
    t0 = time.perf_counter()
    r2 = launch(
        2, worker + ["sweep", "--save-at", str(save_at), "--path", ck] + grid_args,
        timeout_s=3600,
    )
    p2_wall = time.perf_counter() - t0
    dig2: dict = {}
    scores2: list = []
    for r in r2:
        rec = r["records"][0]
        dig2.update(rec["digests"])
        scores2 += rec["scores"]
    scores2.sort(key=lambda s: s["scenario_id"])
    r3 = launch(1, worker + ["sweep-restore", "--path", ck] + grid_args,
                timeout_s=3600)
    rec3 = r3[0]["records"][0]

    b_total = rec1["b"]
    digests_equal = rec1["digests"] == dig2
    scores_equal = rec1["scores"] == scores2
    restore_exact = (
        rec1["digests"] == rec3["digests"] and rec1["scores"] == rec3["scores"]
    )
    rss_p1 = rec1["peak_rss_mb"]
    rss_p2 = max(r["records"][0]["peak_rss_mb"] for r in r2)
    rss_frac = round(rss_p2 / rss_p1, 3) if rss_p1 else None

    # -- leg 3: the virtual-mesh (GSPMD) twin --------------------------------
    twin = _fleet_sharded_twin(seed, n=n if full else 512, k=k if full else 16)

    # -- leg 4: adaptive vs dense cliff search -------------------------------
    params_ad = LifecycleParams(n=n, k=32 if full else k)
    rng = np.random.default_rng(seed)
    ad_victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    # the certified row is loss 0 — the committed dose-107 cliff.  At
    # 1-dose resolution a 10% loss row is BIMODAL past its transition
    # (per-seed congestion collapse: medians of 114/70/94... — see
    # PERF.md r19), so its dense argmax is a spike edge, not a cliff;
    # the r12 ladder-resolution dose-91 interaction remains the
    # committed story at its own resolution.
    ad_kw = dict(
        victims=ad_victims,
        losses=(0.0,),
        max_dose=128 if full else 64,
        churn_seed=seed + 777,
        max_ticks=4096,
        check_every=1,
        seeds_per_point=3 if full else 1,
    )
    t0 = time.perf_counter()
    ad = scenarios.refine_surface(params_ad, coarse=9, aot="fleet_refine", **ad_kw)
    ad_wall = time.perf_counter() - t0
    t0 = time.perf_counter()
    de = scenarios.dense_surface(params_ad, **ad_kw)
    de_wall = time.perf_counter() - t0
    cliffs_match = all(
        ad["cliffs"][l]["cliff_at"] == de["cliffs"][l]["cliff_at"]
        and ad["cliffs"][l]["cliff_at"] is not None
        for l in ad_kw["losses"]
    )
    evals_ratio = round(ad["evals_unique"] / de["evals_unique"], 4)

    certified = bool(
        digests_equal and scores_equal and restore_exact
        and rss_frac is not None and rss_frac < 0.75
        and twin.get("equal")
        and cliffs_match and evals_ratio <= 0.25
    )
    return {
        "metric": f"fleet_scale_n{n}_b{b_total}",
        "value": rss_frac,
        "unit": "rss_frac_p2_over_p1",
        "certified": certified,
        "n_nodes": n,
        "k": k,
        "b": b_total,
        "horizon": horizon,
        "journal_every": journal_every,
        "digests_equal": digests_equal,
        "scores_equal": scores_equal,
        "restore_exact": restore_exact,
        "restored_from": rec3.get("resumed"),
        "rss_p1_mb": rss_p1,
        "rss_p2_max_mb": rss_p2,
        "rss_frac": rss_frac,
        "p1_wall_s": round(p1_wall, 2),
        "p2_wall_s": round(p2_wall, 2),
        "save_s": next(
            (r["records"][0].get("save_s") for r in r2
             if r["records"][0].get("save_s") is not None), None,
        ),
        "twin": twin,
        "adaptive": {
            "cliffs": {str(l): ad["cliffs"][l] for l in ad_kw["losses"]},
            "dense_cliffs": {str(l): de["cliffs"][l] for l in ad_kw["losses"]},
            "cliffs_match": cliffs_match,
            "evals_adaptive": ad["evals_unique"],
            "evals_dense": de["evals_unique"],
            "evals_ratio": evals_ratio,
            "dispatches": ad["dispatches"],
            "width": ad["width"],
            "seeds_per_point": ad_kw["seeds_per_point"],
            "compiled_programs": ad.get("compiled_programs"),
            "adaptive_wall_s": round(ad_wall, 2),
            "dense_wall_s": round(de_wall, 2),
            "all_detected": ad.get("all_detected") and de.get("all_detected"),
            "max_dose": ad_kw["max_dose"],
            "cache_hit": ad.get("aot", {}).get("cache_hit"),
            "compile_s": ad.get("aot", {}).get("compile_s"),
        },
    }


# -- chaos-plane scenarios (sim/chaos.py) ------------------------------------


def _chaos_sharded_twin(name: str, seed: int, n=4096, k=64, ticks=24, horizon=64,
                        builder: str = "chaos") -> dict:
    """Certify the scenario's FaultPlan partition-invariant: run the SAME
    plan (same builder — ``chaos.scenario_plan``, or the topology
    family's ``topology.topo_scenario_plan`` with ``builder="topo"``)
    unsharded and over the 4×2 virtual mesh in a child process (the
    8-device CPU mesh needs ``xla_force_host_platform_device_count``
    before backend init) and compare state digests + every leaf.  Small
    config on purpose — the certificate is about the chaos-enabled
    program, which is shape-uniform in n."""
    import os
    import subprocess
    import sys

    if builder == "topo":
        plan_expr = (
            "__import__('ringpop_tpu.sim.topology', fromlist=['x'])"
            f".topo_scenario_plan({name!r}, n, seed=seed, horizon={horizon})"
        )
    else:
        plan_expr = f"chaos.scenario_plan({name!r}, n, seed=seed, horizon={horizon})"
    code = f"""
import os, json, functools
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from ringpop_tpu.util.accel import configure_compile_cache
configure_compile_cache()
import numpy as np
from jax.sharding import Mesh
from ringpop_tpu.sim import chaos, lifecycle, telemetry
from ringpop_tpu.parallel.mesh import with_exchange_mesh

n, k, ticks, seed = {n}, {k}, {ticks}, {seed}
plan = {plan_expr}
params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=6, rng="counter")
blk = jax.jit(functools.partial(lifecycle._run_block, params), static_argnames="ticks")
ref = blk(lifecycle.init_state(params, seed=seed), plan, ticks=ticks)
jax.block_until_ready(ref.learned)

devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
mesh = Mesh(devs, ("node", "rumor"))
sm_params = with_exchange_mesh(params, mesh)
sm_blk = jax.jit(functools.partial(lifecycle._run_block, sm_params), static_argnames="ticks")
sstate = jax.tree.map(jax.device_put, lifecycle.init_state(params, seed=seed),
                      lifecycle.state_shardings(mesh, k=k))
sout = sm_blk(sstate, plan, ticks=ticks)
jax.block_until_ready(sout.learned)
equal = all(bool((np.asarray(a) == np.asarray(b)).all())
            for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(sout)))
print(json.dumps(dict(
    digest_unsharded=int(telemetry.tree_digest(ref)),
    digest_sharded=int(telemetry.tree_digest(sout)),
    equal=equal, n=n, k=k, ticks=ticks,
    mesh="4x2 (node x rumor), virtual CPU devices",
)))
"""
    env = dict(os.environ)
    env.pop("BENCH_PIN", None)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1200, env=env)
    except subprocess.TimeoutExpired:
        return {"equal": False, "error": "twin subprocess timed out"}
    for ln in reversed(r.stdout.strip().splitlines()):
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except json.JSONDecodeError:
                continue
    return {"equal": False,
            "error": f"twin child rc={r.returncode}: " + (r.stderr or "")[-300:]}


def bench_serve_ring(seed: int, full: bool) -> dict:
    """The serve-the-ring paired A/B (serve/bench.py): F frontend
    PROCESSES drive keyed lookups through the shared device-resident ring
    (shared-memory micro-batching into padded ``ring_ops`` dispatches)
    vs their own per-process host bisect walk, interleaved rep by rep
    behind a cross-process barrier (the ``forward_ab`` pairing).  The
    certificate is bit-identity: every (worker, rep) owner-digest pair
    must match, every serve answer must carry the pinned membership
    generation, and a live ring update must re-certify against the
    post-update oracle.  The DGRO placement pass is scored alongside
    (key-movement-under-churn, the ring1m rebalance metric): the chosen
    candidate must move no more keys than random replica placement at
    equal token count — placement stays OFF by default."""
    from ringpop_tpu.serve.bench import run_ab
    from ringpop_tpu.serve.placement import dgro_place

    journal = None
    if _TELEMETRY_PATH is not None:
        from ringpop_tpu.sim.telemetry import TelemetryJournal

        journal = TelemetryJournal(_TELEMETRY_PATH, append=True)
        journal.header(
            "serve", "serve_ring", {"seed": seed, "full": full}
        )
    kw = (
        dict(n_servers=64, frontends=4, batch=8192, batches_per_rep=16,
             reps=5, warm_reps=1, latency_reqs=300)
        if full
        else dict(n_servers=64, frontends=4, batch=4096, batches_per_rep=8,
                  reps=3, warm_reps=1, latency_reqs=150)
    )
    try:
        rec = run_ab(seed=seed, transport="shm", journal=journal, **kw)
    finally:
        if journal is not None:
            journal.close()

    # the DGRO placement score: churn movement per candidate on one
    # batched device program; candidate 0 IS the random placement, so
    # the gate (chosen <= random) is scored against the real baseline
    _t, _o, report = dgro_place(
        [f"10.8.{i // 256}.{i % 256}:3000" for i in range(kw["n_servers"])],
        100, candidates=8, probes=1 << 14, churn_frac=0.02, seed=seed,
    )
    placement = {
        "chosen": report["chosen"],
        "movement_random": report["movement_random"],
        "movement_chosen": report["movement_chosen"],
        "movement_gate_ok": report["movement_chosen"]
        <= report["movement_random"] + 1e-9,
        "imbalance_random": report["imbalance_random"],
        "imbalance_chosen": report["imbalance_chosen"],
        "excess_movement_all_zero": all(
            e == 0.0 for e in report["excess_movement"]
        ),
        "default": "random",  # the serving path never runs DGRO unless asked
    }
    certified = bool(
        rec["digest_equal"]
        and rec["generation_pinned"]
        and rec["update_certified"]
        and rec["latency_b1"]["owners_match_oracle"]
    )
    return {
        "metric": "serve_ring_shared_device_tier",
        "value": rec["speedup_median"],
        "unit": "qps_ratio_serve_over_bisect",
        "certified": certified,
        "placement": placement,
        **rec,
    }


def bench_serve_fanin(seed: int, full: bool) -> dict:
    """Serve at production fan-in (r17): the three-legged certificate of
    the LookupN serve plane.

    1. **mesh** — P∈{1, 2, 4} serve ranks each owning a contiguous ring
       block (the r14 ``process_block`` rule over the token index space)
       cross-forward mis-routed keys over the fabric (``exchange_async``
       + the r15 codec) and answer owner + R successors through the
       fused LookupN dispatch.  Certificate: every rank's combined
       (owner, successors, generation) stream digest at every P equals
       the single-process oracle — which is itself pinned to the pure
       host ``LookupNUniqueAt`` walk.  The keys/s/host scaling curve and
       per-host wire bytes are recorded per P (threads on a 2-core
       container: the curve is honest measurement, not a scaling claim —
       real-chip pricing is the ksweep ``serve_fanin`` section).
    2. **forwarding** — the per-owner batch coalescing pricing: mesh
       messages per rank are 2·(P-1)·rounds regardless of key volume,
       recorded against the one-message-per-forwarded-key naive plane
       (strictly below is part of the certificate).
    3. **quorum** — R-replica reads on LookupN preference lists under a
       FaultPlan killing owners mid-read (staggered, restarting): acks
       must stay ≥ ⌈(R+1)/2⌉ on EVERY wave, answers must agree, and the
       full-replication recovery curve is scored through
       ``chaos.score_blocks``.
    """
    from ringpop_tpu.forward.batch import quorum_chaos_run
    from ringpop_tpu.serve.mesh import run_serve_mesh

    kw = (
        dict(n_servers=64, replica_points=100, n=3, streams=4, rounds=6,
             keys_per_stream=8192)
        if full
        else dict(n_servers=16, replica_points=20, n=3, streams=4, rounds=3,
                  keys_per_stream=2048)
    )
    journal = None
    if _TELEMETRY_PATH is not None:
        from ringpop_tpu.sim.telemetry import TelemetryJournal

        journal = TelemetryJournal(_TELEMETRY_PATH, append=True)
        journal.header("serve", "serve_fanin", {"seed": seed, "full": full, **kw})

    try:
        curve = []
        oracle_digest = None
        digests_equal = True
        messages_ok = True
        for nprocs in (1, 2, 4):
            recs = run_serve_mesh(nprocs, seed=seed, **kw)
            if oracle_digest is None:
                oracle_digest = recs[0]["digest"]
            digests_equal = digests_equal and all(
                r["digest"] == oracle_digest for r in recs
            )
            wall = max(r["wall_s"] for r in recs)
            keys_total = sum(r["keys_total"] for r in recs)
            wire_mb = [round(r["wire"]["bytes_sent"] / 1e6, 3) for r in recs]
            msgs = sum(r["messages_sent"] for r in recs)
            naive = sum(r["messages_naive"] for r in recs)
            if nprocs > 1:
                messages_ok = messages_ok and msgs < naive
            point = {
                "nprocs": nprocs,
                "keys_total": keys_total,
                "wall_s_max": wall,
                "keys_per_s_aggregate": round(keys_total / max(wall, 1e-9)),
                "keys_per_s_per_host": round(
                    keys_total / max(wall, 1e-9) / nprocs
                ),
                "keys_forwarded": sum(r["keys_forwarded_out"] for r in recs),
                "messages": msgs,
                "messages_naive": naive,
                "wire_mb_per_host": wire_mb,
                "raw_mb_per_host": [
                    round(r["wire"]["raw_bytes_sent"] / 1e6, 3) for r in recs
                ],
                "digests": sorted({r["digest"] for r in recs}),
            }
            curve.append(point)
            if journal is not None:
                journal._write({"kind": "serve_mesh", **point})

        quorum = quorum_chaos_run(
            n_servers=8, replica_points=16, r=3,
            keys_per_tick=kw["keys_per_stream"] // 16,
            horizon=32 if full else 24, seed=seed,
        )
        if journal is not None:
            for blk in quorum["blocks"]:
                journal._write({"kind": "serve_forward", **blk})
            journal._write(quorum["score"])
        quorum_ok = bool(
            quorum["owners_killed"] and quorum["quorum_held"]
            and quorum["answers_agree"] and quorum["rpcs"] < quorum["rpcs_naive"]
        )
        certified = bool(digests_equal and messages_ok and quorum_ok)
        ttd = quorum["score"]["time_to_detect_median"]
        return {
            "metric": "serve_fanin",
            "value": curve[-1]["keys_per_s_per_host"],
            "unit": "keys_per_s_per_host_at_p4",
            "certified": certified,
            "oracle_digest": oracle_digest,
            "digests_equal": digests_equal,
            "messages_below_naive": messages_ok,
            "scaling_curve": curve,
            "lookup_n": kw["n"],
            "n_servers": kw["n_servers"],
            "replica_points": kw["replica_points"],
            "quorum": {
                k: quorum[k]
                for k in ("r", "quorum", "n_servers", "owners_killed",
                          "quorum_held", "answers_agree", "rpcs",
                          "rpcs_naive", "rpc_ratio")
            },
            "quorum_recovery_ticks_median": ttd,
            "quorum_acks_min": quorum["score"].get("quorum_acks_min"),
        }
    finally:
        if journal is not None:
            journal.close()


def _run_chaos_scenario(scenario: str, plan_name: str, n: int, k: int,
                        horizon: int, seed: int, suspect_ticks: int = 10,
                        journal_every: int = 16) -> dict:
    """Shared runner for the chaos scenarios: run the lifecycle engine
    under the plan for ``horizon`` ticks with telemetry on (journaled to
    the --telemetry file when given), score the journal
    (``chaos.score_blocks``), append the verdict record, and attach the
    sharded-twin digest certificate."""
    import jax

    from ringpop_tpu.sim import chaos, telemetry
    from ringpop_tpu.sim.lifecycle import LifecycleSim

    plan = chaos.scenario_plan(plan_name, n, seed=seed, horizon=horizon)
    sink = _telemetry_sink(scenario, "lifecycle", {"n": n, "k": k, "seed": seed})
    if sink is None:
        sink = telemetry.TelemetrySink()  # records still needed for scoring
    sim = LifecycleSim(n=n, k=k, seed=seed, suspect_ticks=suspect_ticks,
                       rng="counter", telemetry=sink)
    try:
        sim.run(journal_every, plan)  # compile + first block
        jax.block_until_ready(sim.state.learned)
        t0 = time.perf_counter()
        for _ in range(horizon // journal_every - 1):
            sim.run(journal_every, plan)
        jax.block_until_ready(sim.state.learned)
        elapsed = time.perf_counter() - t0
        score = chaos.score_blocks(sink.records, plan, n=n, scenario=scenario)
        if sink.journal is not None:
            sink.journal.score(score)
    finally:
        _close_sink(sink)
    twin = _chaos_sharded_twin(plan_name, seed)
    return {
        "metric": f"chaos_{scenario}",
        "value": round(elapsed, 3),
        "unit": "s",
        "n_nodes": n,
        "n_rumor_slots": k,
        "ticks": horizon,
        "events": len(score["events"]),
        "time_to_detect_median": score["time_to_detect_median"],
        "rumor_half_life_median": score["rumor_half_life_median"],
        "false_positive_suspects": score["false_positive_suspects"],
        "rejoin_convergence_ticks": score["rejoin_convergence_ticks"],
        "final_detect_frac": score["final_detect_frac"],
        "sharded_digest_equal": twin.get("equal"),
        "sharded_twin": twin,
    }


def bench_churn100k(seed: int, full: bool) -> dict:
    """Crash/restart churn waves at scale: staggered crash cohorts (a few
    permanently down), scored for time-to-detect per wave, rumor
    half-life, and re-join convergence after the last restart."""
    n = 100_000 if full else 8192
    k = 256 if full else 64
    return _run_chaos_scenario("churn100k", "churn", n, k, horizon=256, seed=seed)


def bench_flap1k(seed: int, full: bool) -> dict:
    """Flapping members under background loss: the false-positive
    suspicion/refutation churn Lifeguard targets, scored."""
    del full  # 1k nodes IS the scenario
    return _run_chaos_scenario("flap1k", "flap", 1000, 64, horizon=256, seed=seed,
                               suspect_ticks=8)


def bench_asym_partition(seed: int, full: bool) -> dict:
    """A DIRECTED partition window (majority→minority blocked,
    minority→majority delivering) over a small permanent crash cohort:
    false accusations pile up and refute through the open direction, the
    crashes must be detected THROUGH the window, then it heals."""
    n = 50_000 if full else 4096
    return _run_chaos_scenario("asym_partition", "asym", n, 64, horizon=256,
                               seed=seed)


def bench_topo_chaos(seed: int, full: bool) -> dict:
    """Topology-realistic fault overlays (the topology-round tentpole):
    the correlated-failure scenario family — per-zone loss, per-rack
    switch flap, symmetric + one-way WAN partitions, and matched
    independent-crash controls, every member riding the compiled
    rack/zone/region tier legs (``sim/topology.py``) — scored through
    the B≥32 batched fleet with per-tier telemetry.

    Three certificates in one scenario:

    1. **The fleet run** — B stacked plans through ``scored_fleet`` with
       the per-tier suspicion counters armed: every verdict carries
       ``suspects_by_tier`` / ``false_positive_by_tier`` /
       ``time_to_detect_by_tier`` (the per-tier split the acceptance
       bar names).
    2. **Correlated ≠ independent** — the discriminator the uniform-loss
       grid cannot express: a zone cut leaves no live same-rack/
       same-zone observers, so its suspicion flow arrives only from
       across the boundary, while the SAME number of independent
       crashes draws near-tier suspicion everywhere.  Recorded as the
       near-tier (same-rack + cross-rack) share of suspicion flow per
       event family; ``distinguishes`` = the independent control's
       near share strictly exceeds the zone cut's.
    3. **Sharded twin** — the canonical ``smoke`` topology plan run
       unsharded vs the 4×2 virtual mesh (child process), digests +
       every leaf bit-equal: the tier legs are partition-invariant like
       every other fault leg.
    """
    import numpy as np

    from ringpop_tpu.sim import chaos, scenarios, topology
    from ringpop_tpu.sim.lifecycle import LifecycleParams

    n = 4096 if full else 512
    k = 32
    horizon = 256
    reps = 2 if full else 1
    topo = topology.default_topology(n)
    plans, meta = topology.topo_scenario_specs(
        topo, seed=seed, horizon=horizon, reps=reps
    )
    stacked = chaos.stack_plans(plans)
    b = chaos.plan_batch_size(stacked)
    seeds = [seed + i for i in range(len(plans))]
    params = LifecycleParams(n=n, k=k, suspect_ticks=10, rng="counter")

    sink = _telemetry_sink(
        "topo_chaos", "lifecycle",
        {"n": n, "k": k, "seed": seed, "b": b,
         "tree": {"regions": topo.spec.regions,
                  "zones": topo.spec.total_zones,
                  "racks": topo.spec.total_racks},
         "tier_drop": [float(x) for x in topo.tier_drop]},
    )
    # sink may stay None (no --telemetry): scored_fleet collects its own
    # per-scenario blocks for scoring — an unread in-memory sink would
    # only add per-block host work inside the timed fleet window
    try:
        t0 = time.perf_counter()
        scores = scenarios.scored_fleet(
            params, stacked, meta, seeds, horizon=horizon, journal_every=16,
            sink=sink, scenario="topo_chaos",
        )
        fleet_s = time.perf_counter() - t0
    finally:
        _close_sink(sink)

    # -- the per-tier split must be present on every verdict ------------------
    split_present = all(
        isinstance(s.get("suspects_by_tier"), dict)
        and isinstance(s.get("false_positive_by_tier"), dict)
        and isinstance(s.get("time_to_detect_by_tier"), dict)
        for s in scores
    )

    # -- correlated vs independent: near-tier suspicion share ------------------
    def near_share(score) -> Optional[float]:
        by_tier = score.get("suspects_by_tier") or {}
        total = float(sum(by_tier.values()))
        if total <= 0:
            return None
        return (by_tier.get("same_rack", 0) + by_tier.get("cross_rack", 0)) / total

    def family(event: str) -> list:
        vals = [
            near_share(s) for s, m in zip(scores, meta) if m["event"] == event
        ]
        return [v for v in vals if v is not None]

    zone_near = family("zone_loss")
    ind_near = family("independent")
    zone_med = float(np.median(zone_near)) if zone_near else None
    ind_med = float(np.median(ind_near)) if ind_near else None
    distinguishes = (
        zone_med is not None and ind_med is not None and ind_med > zone_med
    )

    # per-family median time-to-detect (the crash families share the
    # schedule, so this is the correlated-vs-independent latency story)
    def fam_ttd(event: str):
        vals = [
            s["time_to_detect_median"]
            for s, m in zip(scores, meta)
            if m["event"] == event and s.get("time_to_detect_median") is not None
        ]
        return float(np.median(vals)) if vals else None

    # the one-way WAN window must attribute its refutations to the
    # unreachable direction (the asym semantics through the topology
    # builder)
    wan_scores = [s for s, m in zip(scores, meta) if m["event"] == "wan_oneway"]
    wan_split_ok = all(
        s.get("refutations_unreachable_dir") is not None
        and s.get("refutations_reachable_dir") is not None
        for s in wan_scores
    )

    twin = _chaos_sharded_twin("smoke", seed, builder="topo")
    return {
        "metric": f"topo_chaos_n{n}_b{b}",
        "value": round(fleet_s, 2),
        "unit": "s_fleet_wall",
        "n_nodes": n,
        "k": k,
        "b": b,
        "horizon": horizon,
        "tree": {
            "regions": topo.spec.regions,
            "zones": topo.spec.total_zones,
            "racks": topo.spec.total_racks,
            "tier_drop": [round(float(x), 6) for x in topo.tier_drop],
        },
        "events": sorted({m["event"] for m in meta}),
        "per_tier_split_present": split_present,
        "near_tier_share": {
            "zone_loss_median": zone_med,
            "independent_median": ind_med,
        },
        "distinguishes_correlated": bool(distinguishes),
        "ttd_median_by_event": {
            ev: fam_ttd(ev)
            for ev in ("zone_loss", "independent", "wan", "wan_oneway")
        },
        "wan_direction_split_present": bool(wan_split_ok),
        "sharded_digest_equal": twin.get("equal"),
        "sharded_twin": twin,
        "certified": bool(
            split_present and distinguishes and wan_split_ok and twin.get("equal")
        ),
    }


def bench_multihost16m(seed: int, full: bool) -> dict:
    """Multi-host DCN scale-out certificate (r14): the same seeded delta
    scenario at 1/2/4 REAL OS processes through ``jax.distributed``
    bring-up + ``make_multihost_mesh`` + the canonical partition table,
    with the exchange legs bridged at host level
    (``sim/delta_multihost``) because this backend cannot execute
    cross-process XLA programs — on a pod the identical arithmetic runs
    as the one jitted step (certified sharded==unsharded by
    ``sharded100k``; the fabric twins certify the PROCESS axis).

    Three legs, all recorded:

    1. **twin** — paired 1/2/4-process runs of one seeded scenario
       (victims + loss): every process count must produce THE SAME
       global state digest, equal to the in-process engine's (the DCN
       analog of the 4x2 virtual-mesh twins).
    2. **snapshot** — 2-process block-sharded orbax save restored at 4
       processes and continued: digest must equal an unbroken engine
       run's.
    3. **scale** — delta convergence at 16M nodes (full; 1M on the CPU
       smoke tier) at P=1 and P=2: bit-identical digests, per-process
       peak RSS (the sharding-actually-shards evidence), and measured
       fabric MB/tick per host.
    """
    import os as _os
    import sys as _sys

    import numpy as np

    _sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))), "scripts"))
    from multihost_launch import launch

    base = ["-m", "ringpop_tpu.cli.multihost_bench"]

    # -- leg 1: the 1/2/4-process twin ---------------------------------------
    tn, tk, tticks, victims, drop = 65536, 64, 24, 64, 0.05
    common = ["--n", str(tn), "--k", str(tk), "--seed", str(seed),
              "--victims", str(victims), "--drop", str(drop)]
    twin = {}
    for nprocs in (1, 2, 4):
        t0 = time.perf_counter()
        ranks = launch(nprocs, base + ["twin", *common, "--ticks", str(tticks)],
                       timeout_s=900)
        recs = [r["records"][-1] for r in ranks]
        # a rank disagreement must land in the RECORD as a failed
        # certificate (with every rank's digest visible), not abort the
        # scenario — same discipline as the snapshot leg below
        twin[str(nprocs)] = {
            "digest": recs[0]["digest"],
            "ranks_agree": len({r["digest"] for r in recs}) == 1,
            "rank_digests": [r["digest"] for r in recs],
            "peak_rss_mb": [r["peak_rss_mb"] for r in recs],
            "wall_s": round(time.perf_counter() - t0, 2),
        }
    # engine anchor, in-process
    import functools

    import jax
    import jax.numpy as jnp

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, init_state, step
    from ringpop_tpu.sim.telemetry import tree_digest

    tparams = DeltaParams(n=tn, k=tk, rng="counter")
    rng = np.random.default_rng(seed + 999)
    up = np.ones(tn, bool)
    up[rng.choice(tn, size=victims, replace=False)] = False
    tfaults = DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(drop))
    st = init_state(tparams, seed=seed)
    stp = jax.jit(functools.partial(step, tparams))
    for _ in range(tticks):
        st = stp(st, tfaults)
    engine_digest = int(tree_digest(st))
    twin_certified = all(
        v["ranks_agree"] and v["digest"] == engine_digest for v in twin.values()
    )

    # -- leg 2: cross-process-count snapshot ---------------------------------
    import shutil
    import tempfile

    ckpt = tempfile.mkdtemp(prefix="mh16m_ckpt_")
    shutil.rmtree(ckpt)
    t1, t2 = 16, 8
    snap_common = ["--n", str(tn), "--k", str(tk), "--seed", str(seed),
                   "--victims", str(victims)]
    try:
        ranks = launch(2, base + ["snapshot-save", *snap_common,
                                  "--ticks", str(t1), "--path", ckpt], timeout_s=900)
        saved_digest = ranks[0]["records"][-1]["digest"]
        ranks = launch(4, base + ["snapshot-restore", *snap_common,
                                  "--extra-ticks", str(t2), "--path", ckpt],
                       timeout_s=900)
        rest = [r["records"][-1] for r in ranks]
        st2 = init_state(tparams, seed=seed)
        f2 = DeltaFaults(up=jnp.asarray(up))
        for _ in range(t1 + t2):
            st2 = stp(st2, f2)
        unbroken = int(tree_digest(st2))
        snapshot = {
            "save_procs": 2,
            "restore_procs": 4,
            "digest_at_save": saved_digest,
            "digest_at_restore": rest[0]["digest_at_restore"],
            "digest_continued": rest[0]["digest"],
            "digest_unbroken_reference": unbroken,
            "certified": bool(
                rest[0]["digest_at_restore"] == saved_digest
                and rest[0]["digest"] == unbroken
                and len({r["digest"] for r in rest}) == 1
            ),
        }
    finally:
        shutil.rmtree(ckpt, ignore_errors=True)

    # -- leg 3: the scale axis — 16M through the DCN fabric ------------------
    sn = 16_000_000 if full else 1_000_000
    sk = 64
    scale = {}
    scale_common = ["--n", str(sn), "--k", str(sk), "--seed", str(seed),
                    "--max-ticks", "4096", "--journal-every", "64"]
    for nprocs in (1, 2):
        t0 = time.perf_counter()
        ranks = launch(nprocs, base + ["converge", *scale_common],
                       timeout_s=3600, env_extra={"MULTIHOST_TIMEOUT": "3600"})
        results = [
            next(rec for rec in reversed(r["records"]) if rec["kind"] == "result")
            for r in ranks
        ]
        scale[str(nprocs)] = {
            "ticks": results[0]["ticks"],
            "converged": results[0]["converged"],
            "digest": results[0]["digest"],
            "ranks_agree": len({r["digest"] for r in results}) == 1,
            "wall_s": round(time.perf_counter() - t0, 2),
            "ms_per_tick": results[0]["ms_per_tick"],
            "peak_rss_mb": [r["peak_rss_mb"] for r in results],
            "fabric_mb_per_tick": [r["fabric_mb_per_tick"] for r in results],
        }
    scale_certified = (
        scale["1"]["digest"] == scale["2"]["digest"]
        and scale["1"]["ranks_agree"]
        and scale["2"]["ranks_agree"]
        and scale["1"]["converged"]
        and scale["2"]["converged"]
    )
    rss_1p = max(scale["1"]["peak_rss_mb"])
    rss_2p = max(scale["2"]["peak_rss_mb"])

    return {
        "metric": f"multihost_dcn_{sn // 1_000_000}m",
        # headline: per-process peak RSS at 2 processes as a fraction of
        # the single-process footprint for the SAME converged run
        "value": round(rss_2p / rss_1p, 3),
        "unit": "rss_frac_2proc_over_1proc",
        "certified": bool(twin_certified and snapshot["certified"] and scale_certified),
        "engine_digest": engine_digest,
        "twin_certified": twin_certified,
        "twin": twin,
        "snapshot": snapshot,
        "scale": scale,
        "scale_certified": scale_certified,
        "exchange_path": "host-bridged fabric (backend cannot run "
        "cross-process XLA; mesh path certified by sharded100k)",
        "n_nodes": sn,
        "n_rumors": sk,
    }


# the canonical engine-anchored twin scenario every fabric A/B certifies
# against — ONE definition, so the dcn_wire and swing_overlap artifacts
# cannot drift onto different anchors
_MH_TWIN = {"n": 65536, "k": 64, "ticks": 24, "victims": 64, "drop": 0.05}


def _mh_launch():
    """The multihost launcher + worker argv base shared by the fabric
    scenarios (one spawn path: scripts/multihost_launch.py)."""
    import os as _os
    import sys as _sys

    _sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))), "scripts"))
    from multihost_launch import launch

    return launch, ["-m", "ringpop_tpu.cli.multihost_bench"]


def _mh_twin_common(seed: int) -> list:
    t = _MH_TWIN
    return ["--n", str(t["n"]), "--k", str(t["k"]), "--seed", str(seed),
            "--victims", str(t["victims"]), "--drop", str(t["drop"])]


@_functools.lru_cache(maxsize=None)
def _mh_twin_anchor(seed: int) -> int:
    """The in-process engine digest of the canonical twin scenario —
    cached so a run covering both fabric scenarios computes it once."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, init_state, step
    from ringpop_tpu.sim.telemetry import tree_digest

    t = _MH_TWIN
    params = DeltaParams(n=t["n"], k=t["k"], rng="counter")
    rng = np.random.default_rng(seed + 999)
    up = np.ones(t["n"], bool)
    up[rng.choice(t["n"], size=t["victims"], replace=False)] = False
    st = init_state(params, seed=seed)
    stp = jax.jit(functools.partial(step, params))
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(t["drop"]))
    for _ in range(t["ticks"]):
        st = stp(st, faults)
    return int(tree_digest(st))


def bench_dcn_wire(seed: int, full: bool) -> dict:
    """r15: the sparsity-aware wire codec A/B over the host-bridged DCN
    fabric (``parallel/fabric`` ROWS/RUNS/XOR codec + device-side window
    slicing in ``sim/delta_multihost``).  Unlike the ICI items this is
    NOT behind the TPU gate: fabric bytes and wall-clock are measured at
    host level on this container.

    Two legs, both recorded:

    1. **twin** — the r14 twin scenario (65536 nodes, victims + loss) at
       P=2 with the codec ON and OFF: both digests must equal the
       in-process engine's (the codec is bit-transparent by
       construction; this certifies it at artifact scale).
    2. **scale A/B** — delta convergence at 16M nodes (full; 1M on the
       CPU smoke tier) at P=2, codec-on vs codec-off, per-tick journals:
       wire MB/tick/host must be >= 2x lower with the codec averaged
       over the run (the dissemination wave far more — the per-tick
       deltas are in the artifact), end-to-end wall-clock no slower than
       raw, digests bit-identical.  ``certify_cost_model``'s ``dcn_wire``
       judge refutes on any violation.
    """
    launch, base = _mh_launch()

    # -- leg 1: engine-anchored twin, codec on vs off ------------------------
    common = _mh_twin_common(seed)
    twin = {}
    for codec in ("on", "off"):
        ranks = launch(
            2,
            base + ["twin", *common, "--ticks", str(_MH_TWIN["ticks"]),
                    "--codec", codec],
            timeout_s=900,
        )
        recs = [r["records"][-1] for r in ranks]
        twin[codec] = {
            "digest": recs[0]["digest"],
            "ranks_agree": len({r["digest"] for r in recs}) == 1,
        }
    engine_digest = _mh_twin_anchor(seed)
    twin_certified = all(
        v["ranks_agree"] and v["digest"] == engine_digest for v in twin.values()
    )

    # -- leg 2: the scale A/B ------------------------------------------------
    sn = 16_000_000 if full else 1_000_000
    sk = 64
    scale_common = ["--n", str(sn), "--k", str(sk), "--seed", str(seed),
                    "--max-ticks", "4096", "--journal-every", "1",
                    "--journal-light"]
    scale = {}
    for codec in ("on", "off"):
        t0 = time.perf_counter()
        ranks = launch(2, base + ["converge", *scale_common, "--codec", codec],
                       timeout_s=3600)
        results = [
            next(rec for rec in reversed(r["records"]) if rec["kind"] == "result")
            for r in ranks
        ]
        r0 = results[0]
        scale[codec] = {
            "ticks": r0["ticks"],
            "converged": r0["converged"],
            "digest": r0["digest"],
            "ranks_agree": len({r["digest"] for r in results}) == 1,
            "wall_s": round(time.perf_counter() - t0, 2),
            "worker_wall_s": [r["wall_s"] for r in results],
            "ms_per_tick": r0["ms_per_tick"],
            "wire_mb_per_tick": [r["fabric_mb_per_tick"] for r in results],
            "raw_mb_per_tick": [r["fabric_raw_mb_per_tick"] for r in results],
            "codec_ratio": r0["fabric_codec_ratio"],
            "codec_counts": r0["fabric_codec_counts"],
            "d2h_mb": [round(r["d2h_bytes"] / 1e6, 2) for r in results],
            "peak_rss_mb": [r["peak_rss_mb"] for r in results],
        }
        # the dissemination wave: rank 0's per-tick wire/raw deltas (the
        # codec run's wave is the PERF.md "ratio by phase" evidence)
        blocks = [rec for rec in ranks[0]["records"] if rec["kind"] == "block"]
        scale[codec]["per_tick"] = [
            {
                "tick": b["tick"],
                "coverage": b["coverage"],
                "wire_mb": round(b["fabric_wire_sent_delta"] / 1e6, 3),
                "raw_mb": round(b["fabric_raw_sent_delta"] / 1e6, 3),
                "ratio": b["fabric_codec_ratio"],
            }
            for b in blocks
        ]
    digests_equal = bool(
        scale["on"]["digest"] == scale["off"]["digest"]
        and scale["on"]["ranks_agree"] and scale["off"]["ranks_agree"]
        and scale["on"]["converged"] and scale["off"]["converged"]
        and scale["on"]["ticks"] == scale["off"]["ticks"]
    )
    wire_on = max(scale["on"]["wire_mb_per_tick"])
    wire_off = max(scale["off"]["wire_mb_per_tick"])
    wire_ratio = round(wire_off / wire_on, 3) if wire_on else None
    # the ratio the codec run measures against ITSELF (raw accounting of
    # the same messages) — cross-checks the two-run ratio without noise
    inline_ratio = scale["on"]["codec_ratio"]
    # worker wall (convergence loop only) — launcher wall adds the
    # coordinator bring-up, identical both sides but noisier
    wall_on = max(scale["on"]["worker_wall_s"])
    wall_off = max(scale["off"]["worker_wall_s"])
    wall_ratio = round(wall_on / wall_off, 3) if wall_off else None
    dissem = [p for p in scale["on"]["per_tick"] if p["coverage"] < 0.999]
    dissem_ratio = (
        round(sum(p["raw_mb"] for p in dissem) / max(sum(p["wire_mb"] for p in dissem), 1e-9), 2)
        if dissem else None
    )

    return {
        "metric": f"dcn_wire_{sn // 1_000_000}m",
        # headline: measured wire MB/tick/host compression, codec vs raw
        "value": wire_ratio,
        "unit": "wire_compression_x",
        "certified": bool(
            twin_certified and digests_equal
            and wire_ratio is not None and wire_ratio >= 2.0
            and wall_ratio is not None and wall_ratio <= 1.05
        ),
        "engine_digest": engine_digest,
        "twin": twin,
        "twin_certified": twin_certified,
        "scale": scale,
        "digests_equal": digests_equal,
        "wire_mb_per_tick_on": wire_on,
        "wire_mb_per_tick_off": wire_off,
        "wire_ratio": wire_ratio,
        "inline_codec_ratio": inline_ratio,
        "dissemination_ratio": dissem_ratio,
        "wall_ratio_on_over_off": wall_ratio,
        "n_nodes": sn,
        "n_rumors": sk,
    }


def bench_swing_overlap(seed: int, full: bool) -> dict:
    """r16: the exchange-schedule + cross-tick-pipelining A/B over the
    host-bridged DCN fabric (``plan_window_swing`` distance-halving
    relays + ``exchange_async`` completions in ``parallel/fabric``,
    ``schedule=``/``overlap=`` on ``sim/delta_multihost``).  Host-level
    like ``dcn_wire`` — NOT behind the TPU gate (the real-pod DCN pricing
    of the same schedules is the ksweep ``swing_exchange`` section).

    Three legs, all recorded:

    1. **twin** — the engine-anchored scenario (65536 nodes, victims +
       loss) at P=2 under every (schedule, overlap) combination: every
       digest must equal the in-process engine's (both knobs are
       bit-transparent by construction; this certifies it at artifact
       scale).
    2. **overlap A/B** — delta convergence at P=2 (1M full / 256k
       smoke), cyclic schedule, overlap on vs off, reps INTERLEAVED
       (off/on/off/on/...) so container drift hits both sides:
       digests bit-identical, per-tick journals carry the r16
       drain/overlap keys, and the pipelined min-of-reps wall must not
       exceed the sequential one (overlap must not lose —
       ``certify_cost_model``'s ``swing_overlap`` judge refutes).
    3. **swing A/B** — P=4 convergence (256k full / 64k smoke), cyclic
       vs swing: digests bit-identical, the relay overhead priced
       explicitly (swing raw bytes / cyclic raw bytes — the extra hops
       are REAL bytes on this mesh, the schedule's win is leg-count on a
       physical ring), wall recorded and judged within noise of cyclic.
    """
    launch, base = _mh_launch()

    # -- leg 1: engine-anchored twin grid ------------------------------------
    common = _mh_twin_common(seed)
    twin = {}
    for schedule in ("cyclic", "swing"):
        for overlap in ("off", "on"):
            ranks = launch(
                2,
                base + ["twin", *common, "--ticks", str(_MH_TWIN["ticks"]),
                        "--schedule", schedule, "--overlap", overlap],
                timeout_s=900,
            )
            recs = [r["records"][-1] for r in ranks]
            twin[f"{schedule}/{overlap}"] = {
                "digest": recs[0]["digest"],
                "ranks_agree": len({r["digest"] for r in recs}) == 1,
                "leg_ms": recs[0]["fabric_leg_ms"],
                "overlap_hidden_ms": recs[0]["overlap_hidden_ms"],
            }
    engine_digest = _mh_twin_anchor(seed)
    twin_certified = all(
        v["ranks_agree"] and v["digest"] == engine_digest for v in twin.values()
    )

    def _converge(n, nprocs, schedule, overlap, journal=True):
        args = ["converge", "--n", str(n), "--k", "64", "--seed", str(seed),
                "--max-ticks", "4096", "--schedule", schedule,
                "--overlap", overlap]
        if journal:
            args += ["--journal-every", "1", "--journal-light"]
        ranks = launch(nprocs, base + args, timeout_s=3600)
        results = [
            next(rec for rec in reversed(r["records"]) if rec["kind"] == "result")
            for r in ranks
        ]
        blocks = [rec for rec in ranks[0]["records"] if rec["kind"] == "block"]
        return results, blocks

    # -- leg 2: overlap A/B (cross-tick pipelining must not lose) ------------
    n2 = 1_048_576 if full else 262_144
    reps = 5
    # warm the persistent compile cache for both modes so the timed reps
    # measure stepping, not XLA compiles (one untimed launch each)
    _converge(n2, 2, "cyclic", "off", journal=False)
    _converge(n2, 2, "cyclic", "on", journal=False)
    ab: dict = {"sequential": {"walls": []}, "pipelined": {"walls": []}}
    for rep in range(reps):
        for mode, overlap in (("sequential", "off"), ("pipelined", "on")):
            results, blocks = _converge(n2, 2, "cyclic", overlap)
            r0 = results[0]
            side = ab[mode]
            side["walls"].append(max(r["wall_s"] for r in results))
            side["digest"] = r0["digest"]
            side["ranks_agree"] = len({r["digest"] for r in results}) == 1
            side["ticks"] = r0["ticks"]
            side["converged"] = r0["converged"]
            side["leg_ms"] = r0["fabric_leg_ms"]
            side["overlap_hidden_ms"] = r0["overlap_hidden_ms"]
            side["wire_mb_per_tick"] = r0["fabric_mb_per_tick"]
            side["journal_keys_present"] = bool(blocks) and all(
                "fabric_leg_ms" in b and "overlap_hidden_ms" in b
                and "schedule" in b
                for b in blocks
            )
    for side in ab.values():
        side["wall_min"] = min(side["walls"])
        side["wall_median"] = sorted(side["walls"])[len(side["walls"]) // 2]
    ab["digests_equal"] = bool(
        ab["sequential"]["digest"] == ab["pipelined"]["digest"]
        and ab["sequential"]["ranks_agree"] and ab["pipelined"]["ranks_agree"]
        and ab["sequential"]["ticks"] == ab["pipelined"]["ticks"]
        and ab["sequential"]["converged"] and ab["pipelined"]["converged"]
    )
    # min-of-reps: "can the pipelined path run at least as fast" — the
    # noise-floor estimator (the shared container's drift makes single
    # reps meaningless; medians also recorded)
    ab["wall_ratio_min"] = round(
        ab["pipelined"]["wall_min"] / ab["sequential"]["wall_min"], 3
    )
    ab["wall_ratio_median"] = round(
        ab["pipelined"]["wall_median"] / ab["sequential"]["wall_median"], 3
    )
    overlap_ok = bool(
        ab["digests_equal"]
        and ab["sequential"]["journal_keys_present"]
        and ab["pipelined"]["journal_keys_present"]
        and ab["wall_ratio_min"] <= 1.0
        # the overlap actually hid drain (the gauge is live, not zero)
        and ab["pipelined"]["overlap_hidden_ms"] > 0.0
    )

    # -- leg 3: swing A/B at P=4 (relays exist there; P=2 degenerates) -------
    n4 = 262_144 if full else 65_536
    _converge(n4, 4, "cyclic", "off", journal=False)
    _converge(n4, 4, "swing", "off", journal=False)
    sw: dict = {}
    for schedule in ("cyclic", "swing"):
        walls = []
        for rep in range(3):
            results, _ = _converge(n4, 4, schedule, "off", journal=False)
            r0 = results[0]
            walls.append(max(r["wall_s"] for r in results))
        sw[schedule] = {
            "walls": walls,
            "wall_min": min(walls),
            "digest": r0["digest"],
            "ranks_agree": len({r["digest"] for r in results}) == 1,
            "ticks": r0["ticks"],
            "wire_mb_per_tick": r0["fabric_mb_per_tick"],
            "raw_mb_per_tick": r0["fabric_raw_mb_per_tick"],
            "leg_ms": r0["fabric_leg_ms"],
        }
    sw["digests_equal"] = bool(
        sw["cyclic"]["digest"] == sw["swing"]["digest"]
        and sw["cyclic"]["ranks_agree"] and sw["swing"]["ranks_agree"]
        and sw["cyclic"]["ticks"] == sw["swing"]["ticks"]
    )
    # the relay overhead, explicitly priced: raw bytes the swing hops
    # move per tick over the direct cyclic plan's
    sw["relay_raw_ratio"] = round(
        sw["swing"]["raw_mb_per_tick"] / sw["cyclic"]["raw_mb_per_tick"], 3
    )
    sw["wall_ratio_min"] = round(
        sw["swing"]["wall_min"] / sw["cyclic"]["wall_min"], 3
    )
    swing_ok = bool(sw["digests_equal"] and sw["wall_ratio_min"] <= 1.05)

    return {
        "metric": "swing_overlap",
        # headline: pipelined/sequential wall at the P=2 scale point
        "value": ab["wall_ratio_min"],
        "unit": "pipelined_over_sequential_wall_min",
        "certified": bool(twin_certified and overlap_ok and swing_ok),
        "engine_digest": engine_digest,
        "twin": twin,
        "twin_certified": twin_certified,
        "overlap_ab": {"n": n2, "nprocs": 2, **ab},
        "swing_ab": {"n": n4, "nprocs": 4, **sw},
        "overlap_certified": overlap_ok,
        "swing_certified": swing_ok,
        "n_nodes": n2,
        "n_rumors": 64,
    }


def bench_gameday(seed: int, full: bool) -> dict:
    """r22 closed-loop game day: inject a correlated failure (r18
    topology scenarios) into a live P=2 fleet with the alert-rule
    engine + OpsController attached and judge TIME-TO-MITIGATE against
    the digest-identical no-controller twin.  The controller acts on
    the probe-timeout spike one journal block after the cut; the twin
    waits for SWIM's organic declaration (suspect_ticks + spread), so
    a working loop is strictly earlier.  Certification (zone cut):
    mitigated strictly earlier, controller-on == controller-off ==
    bare-HEAD digests bit for bit, drain effect probe reads 0, and the
    alert→action→effect chain reconstructs from the journal alone.
    ``full`` adds the switch-flap scenario (reported, not gating — a
    flap HEALS itself; draining on it is aggressive-but-sound, the
    zone cut is the canonical judged event)."""
    from ringpop_tpu.obs.gameday import bare_digests, gameday_pair

    n = 128 if full else 64
    horizon = 64 if full else 48
    scenarios_run = ("zone_cut", "switch_flap") if full else ("zone_cut",)
    out: dict = {"metric": "gameday", "n_nodes": n, "horizon": horizon}
    for scenario in scenarios_run:
        pair = gameday_pair(scenario=scenario, n=n, seed=seed, horizon=horizon)
        head = bare_digests(scenario=scenario, n=n, seed=seed, horizon=horizon)
        on, off = pair["on"], pair["off"]
        drains = [
            a for a in on["actions"]
            if a["action"] == "drain" and a["ok"]
        ]
        effects = [
            a for a in on["actions"]
            if a["action"] == "effect" and a["ok"]
        ]
        chain_ok = bool(on["chains"]) and all(
            ch and ch[0]["kind"] == "alert"
            and any(c["kind"] == "action" for c in ch)
            for ch in on["chains"]
        )
        out[scenario] = {
            "cut_at": on["cut_at"],
            "ttm_on": pair["ttm_on"],
            "ttm_off": pair["ttm_off"],
            "mitigated_earlier": pair["mitigated_earlier"],
            "digest_equal": pair["digest_equal"],
            "digest_matches_head": off["digests"] == head,
            "alerts": len(on["alerts"]),
            "twin_actions": len(off["actions"]),
            "drains_ok": len(drains),
            "effects_ok": len(effects),
            "chain_ok": chain_ok,
            "stray_rules": sorted(
                {a["rule"] for a in on["alerts"]} - {"probe-timeout-spike"}
            ),
        }
    zc = out["zone_cut"]
    out["value"] = round(zc["ttm_on"] / max(zc["ttm_off"], 1), 3)
    out["unit"] = "controller_over_twin_ttm"
    out["certified"] = bool(
        zc["mitigated_earlier"]
        and zc["digest_equal"]
        and zc["digest_matches_head"]
        and zc["twin_actions"] == 0
        and zc["drains_ok"] >= 1
        and zc["effects_ok"] >= 1
        and zc["chain_ok"]
    )
    return out


BENCHES = {
    "host10": bench_host10,
    "loss1k": bench_loss1k,
    "montecarlo": bench_montecarlo,
    "sweep100k": bench_sweep100k,
    "partition1m": bench_partition1m,
    "ring1m": bench_ring1m,
    "forward": bench_forward_qps,
    "forward_comparator": bench_forward_comparator,
    "forward_ab": bench_forward_ab,
    "serve_ring": bench_serve_ring,
    "serve_fanin": bench_serve_fanin,
    "mc_churn": bench_mc_churn,
    "mc_chaos": bench_mc_chaos,
    "fleet_scale": bench_fleet_scale,
    "partition_lc": bench_partition_lifecycle,
    "sharded100k": bench_sharded100k,
    "delta16m": bench_delta16m,
    "multihost16m": bench_multihost16m,
    "dcn_wire": bench_dcn_wire,
    "swing_overlap": bench_swing_overlap,
    "churn100k": bench_churn100k,
    "flap1k": bench_flap1k,
    "asym_partition": bench_asym_partition,
    "topo_chaos": bench_topo_chaos,
    "gameday": bench_gameday,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="simbench", description=__doc__)
    p.add_argument("--only", choices=sorted(BENCHES), default=None)
    p.add_argument("--full", action="store_true", help="full BASELINE sizes even on CPU")
    p.add_argument("--cpu", action="store_true", help="pin the CPU backend")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--out",
        default=None,
        help="also write all scenario results to this JSON file "
        "(the committed SIMBENCH_r{N}.json artifacts)",
    )
    p.add_argument(
        "--telemetry",
        metavar="OUT.jsonl",
        default=None,
        help="write the sim-plane run journal (JSONL; one header per "
        "scenario + one record per fetched tick-block — see "
        "OBSERVABILITY.md) for the engine-driving scenarios; the "
        "telemetry leg rides the device scan, so the measured paths "
        "stay bit-identical to a telemetry-off run",
    )
    args = p.parse_args(argv)

    if args.telemetry:
        global _TELEMETRY_PATH
        _TELEMETRY_PATH = args.telemetry
        open(args.telemetry, "w").close()  # truncate; scenarios append

    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"  # before any jax backend init
        import jax

        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"  # pinned — no point probing the accelerator
    else:
        platform = _platform()
    full = args.full or platform in ("tpu", "axon")
    names = [args.only] if args.only else list(BENCHES)
    results = []
    for name in names:
        t0 = time.perf_counter()
        try:
            result = BENCHES[name](args.seed, full)
        except Exception as e:  # a dying accelerator mid-scenario must not
            # take the remaining scenarios (host-plane ones need no jax)
            result = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
        result.setdefault("bench", name)
        result["platform"] = platform
        result["full_scale"] = full
        result["wall_s"] = round(time.perf_counter() - t0, 2)
        _emit(result)
        results.append(result)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"platform": platform, "full_scale": full, "scenarios": results}, f, indent=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
