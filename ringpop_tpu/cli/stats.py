"""Stats reporter implementations for the CLI
(parity: reference ``scripts/testpop/statter.go:48-59`` file-statsd adapter +
UDP statsd option ``scripts/testpop/testpop.go``).

Both reporters own an OS resource (file handle / UDP socket) and support
``close()`` plus the context-manager protocol — the CLI entry points close
them on exit so long-lived testpop processes don't leak descriptors, and
``FileStats.close`` flushes so the tail of a run survives process exit.
``close`` is idempotent; post-close emits are dropped (stats must never
take the node down)."""

from __future__ import annotations

import socket
import time
from typing import Optional, TextIO

from ringpop_tpu.options import StatsReporter


class FileStats(StatsReporter):
    """Timestamped stat lines to a file (parity: statter.go FileStatter)."""

    def __init__(self, path: str):
        self._f: Optional[TextIO] = open(path, "a", buffering=1)

    def _write(self, kind: str, key: str, value) -> None:
        if self._f is None or self._f.closed:
            return
        self._f.write(f"{time.time():.6f} {kind} {key} {value}\n")

    def incr(self, key: str, value: int = 1) -> None:
        self._write("count", key, value)

    def gauge(self, key: str, value: float) -> None:
        self._write("gauge", key, value)

    def timing(self, key: str, seconds: float) -> None:
        self._write("timing", key, seconds)

    def close(self) -> None:
        if self._f is None or self._f.closed:
            return
        self._f.flush()
        self._f.close()

    def __enter__(self) -> "FileStats":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class UDPStatsd(StatsReporter):
    """Plain statsd wire format over UDP (``key:value|type``)."""

    def __init__(self, hostport: str):
        host, port = hostport.rsplit(":", 1)
        self._addr = (host, int(port))
        self._sock: Optional[socket.socket] = socket.socket(
            socket.AF_INET, socket.SOCK_DGRAM
        )

    def _send(self, payload: str) -> None:
        if self._sock is None:
            return
        try:
            self._sock.sendto(payload.encode(), self._addr)
        except OSError:
            pass  # stats must never take the node down

    def incr(self, key: str, value: int = 1) -> None:
        self._send(f"{key}:{value}|c")

    def gauge(self, key: str, value: float) -> None:
        self._send(f"{key}:{value}|g")

    def timing(self, key: str, seconds: float) -> None:
        self._send(f"{key}:{seconds * 1000:.3f}|ms")

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self) -> "UDPStatsd":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
