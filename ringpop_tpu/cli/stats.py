"""Stats reporter implementations for the CLI
(parity: reference ``scripts/testpop/statter.go:48-59`` file-statsd adapter +
UDP statsd option ``scripts/testpop/testpop.go``).

Both reporters own an OS resource (file handle / UDP socket) and support
``close()`` plus the context-manager protocol — the CLI entry points close
them on exit so long-lived testpop processes don't leak descriptors, and
``FileStats.close`` flushes so the tail of a run survives process exit.
``close`` is idempotent; post-close emits are dropped (stats must never
take the node down)."""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional, TextIO

from ringpop_tpu.options import StatsReporter

# the live plane's snapshot-able reporter (r20) lives with its endpoint
# in obs/ but is re-exported here next to its streaming siblings — the
# three of them are the CLI's reporter menu
from ringpop_tpu.obs.aggregate import AggregatingStats  # noqa: F401


class FileStats(StatsReporter):
    """Timestamped stat lines to a file (parity: statter.go FileStatter)."""

    def __init__(self, path: str):
        self._f: Optional[TextIO] = open(path, "a", buffering=1)

    def _write(self, kind: str, key: str, value) -> None:
        if self._f is None or self._f.closed:
            return
        self._f.write(f"{time.time():.6f} {kind} {key} {value}\n")

    def incr(self, key: str, value: int = 1) -> None:
        self._write("count", key, value)

    def gauge(self, key: str, value: float) -> None:
        self._write("gauge", key, value)

    def timing(self, key: str, seconds: float) -> None:
        self._write("timing", key, seconds)

    def close(self) -> None:
        if self._f is None or self._f.closed:
            return
        self._f.flush()
        self._f.close()

    def __enter__(self) -> "FileStats":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class UDPStatsd(StatsReporter):
    """Plain statsd wire format over UDP, with multi-metric datagrams.

    Metrics coalesce into statsd multi-metric packets (newline-separated
    ``key:value|type`` lines in one datagram — the statsd wire spec's
    batching form): a burst like the sim plane's ~19-key block emission
    costs ONE datagram instead of 19.  The buffer flushes when the next
    line would overflow ``max_datagram`` (1432 = typical ethernet MTU
    minus IP+UDP headers, per the statsd guidance), when an emit arrives
    ``flush_s`` after the last flush, on explicit :meth:`flush`, and on
    :meth:`close` — so a quiet reporter's tail is bounded by the next
    emit or the owner's close, and a busy one batches every window.

    Hardened (r20): NO path raises mid-run — a dead/closed socket, an
    unresolvable host, or an OS send failure drops the metric (stats
    must never take the node down; the constructor still raises on a
    malformed hostport, which is a config error, not a runtime one)."""

    def __init__(
        self, hostport: str, *, max_datagram: int = 1432, flush_s: float = 0.25
    ):
        host, port = hostport.rsplit(":", 1)
        self._addr = (host, int(port))
        self.max_datagram = max_datagram
        self.flush_s = flush_s
        self._buf: list[bytes] = []
        self._buf_bytes = 0
        self._last_flush = 0.0  # epoch 0: the first emit flushes at once
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = socket.socket(
            socket.AF_INET, socket.SOCK_DGRAM
        )

    def _emit(self, line: str) -> None:
        data = line.encode()
        out: list = []
        with self._lock:
            sock = self._sock
            if sock is None:
                return  # post-close emits are dropped
            if self._buf and (
                self._buf_bytes + 1 + len(data) > self.max_datagram
            ):
                out.append(self._swap_locked())
            self._buf.append(data)
            self._buf_bytes += len(data) + (1 if len(self._buf) > 1 else 0)
            if time.time() - self._last_flush >= self.flush_s:
                out.append(self._swap_locked())
        self._send(out, sock)

    def _swap_locked(self) -> Optional[bytes]:
        """Detach the pending datagram (caller holds the lock).  The
        sendto happens AFTER the lock is released — a kernel send under
        the emit lock would stall every other emitting thread behind
        socket-buffer backpressure (RPH302)."""
        self._last_flush = time.time()
        if not self._buf:
            self._buf_bytes = 0
            return None
        payload = b"\n".join(self._buf)
        self._buf, self._buf_bytes = [], 0
        return payload

    def _send(self, payloads, sock) -> None:
        for payload in payloads:
            if payload is None:
                continue
            try:
                sock.sendto(payload, self._addr)
            except (OSError, ValueError):
                pass  # stats must never take the node down (dead socket incl.)

    def flush(self) -> None:
        with self._lock:
            sock = self._sock
            payload = self._swap_locked() if sock is not None else None
        if sock is not None:
            self._send([payload], sock)

    def incr(self, key: str, value: int = 1) -> None:
        self._emit(f"{key}:{value}|c")

    def gauge(self, key: str, value: float) -> None:
        self._emit(f"{key}:{value}|g")

    def timing(self, key: str, seconds: float) -> None:
        self._emit(f"{key}:{seconds * 1000:.3f}|ms")

    def close(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
            payload = self._swap_locked() if sock is not None else None
        if sock is not None:
            self._send([payload], sock)
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self) -> "UDPStatsd":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
