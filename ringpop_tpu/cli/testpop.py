"""testpop — standalone node binary for integration testing
(parity: reference ``scripts/testpop/testpop.go:38-118``).

Run one framework node that listens on a TCP hostport, bootstraps from a
JSON hosts file, and gossips until killed.  Flags mirror the reference:
listen address, hosts file, stats to UDP statsd or a timestamped file, and
suspect/faulty/tombstone period overrides.

    python -m ringpop_tpu.cli.testpop --listen 127.0.0.1:3000 \
        --hosts /tmp/hosts.json [--stats-file FILE | --stats-udp HOST:PORT] \
        [--suspect-period S] [--faulty-period S] [--tombstone-period S]
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from ringpop_tpu.discovery import JSONFile
from ringpop_tpu.net import TCPChannel
from ringpop_tpu.options import Options
from ringpop_tpu.ringpop import Ringpop
from ringpop_tpu.swim.node import BootstrapOptions
from ringpop_tpu.swim.state_transitions import StateTimeouts


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="testpop", description=__doc__)
    p.add_argument("--listen", required=True, help="hostport to listen on")
    p.add_argument("--hosts", required=True, help="path to JSON bootstrap hosts file")
    p.add_argument("--app", default="testpop", help="ringpop app name")
    p.add_argument("--stats-file", default=None, help="write stats to this file")
    p.add_argument("--stats-udp", default=None, help="send statsd to this hostport")
    p.add_argument("--suspect-period", type=float, default=0.0, help="seconds (0=default 5s)")
    p.add_argument("--faulty-period", type=float, default=0.0, help="seconds (0=default 24h)")
    p.add_argument("--tombstone-period", type=float, default=0.0, help="seconds (0=default 60s)")
    p.add_argument("--join-timeout", type=float, default=0.0, help="seconds per join attempt")
    p.add_argument(
        "--wire",
        choices=["json", "msgpack"],
        default=None,
        help="frame codec to SEND (receivers auto-detect; default json or "
        "$RINGPOP_TPU_WIRE)",
    )
    return p.parse_args(argv)


async def amain(args) -> int:
    stats = None
    if args.stats_file:
        from ringpop_tpu.cli.stats import FileStats

        stats = FileStats(args.stats_file)
    elif args.stats_udp:
        from ringpop_tpu.cli.stats import UDPStatsd

        stats = UDPStatsd(args.stats_udp)

    host, port = args.listen.rsplit(":", 1)
    channel = TCPChannel(app=args.app, codec=args.wire)
    await channel.listen(host, int(port))
    print(f"testpop listening on {channel.hostport}", flush=True)

    rp = Ringpop(
        args.app,
        channel,
        Options(
            stats_reporter=stats,
            state_timeouts=StateTimeouts(
                suspect=args.suspect_period,
                faulty=args.faulty_period,
                tombstone=args.tombstone_period,
            ),
        ),
    )
    joined = await rp.bootstrap(
        BootstrapOptions(
            discover_provider=JSONFile(args.hosts), join_timeout=args.join_timeout
        )
    )
    print(f"testpop ready; joined {len(joined)} nodes: {joined}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    try:
        await stop.wait()
    finally:
        rp.destroy()
        await channel.close()
        if stats is not None:
            # flush + release the reporter's file handle / UDP socket
            stats.close()
    return 0


def main(argv=None) -> int:
    return asyncio.run(amain(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
