"""Bootstrap host discovery (parity: reference ``discovery/types.go``).

``DiscoverProvider`` abstracts where the bootstrap host list comes from; the
two reference implementations — static list and JSON file — are provided
(``discovery/statichosts/lib.go``, ``discovery/jsonfile/lib.go``).
"""

from __future__ import annotations

import json
from typing import Callable, Protocol, Sequence


class DiscoverProvider(Protocol):
    def hosts(self) -> list[str]: ...


class StaticHosts:
    """Fixed host list (parity: ``discovery/statichosts/lib.go``)."""

    def __init__(self, *hosts: str):
        if len(hosts) == 1 and isinstance(hosts[0], (list, tuple)):
            hosts = tuple(hosts[0])
        self._hosts = list(hosts)

    def hosts(self) -> list[str]:
        return list(self._hosts)


class JSONFile:
    """Hosts from a JSON array file, re-read on every call
    (parity: ``discovery/jsonfile/lib.go``)."""

    def __init__(self, path: str):
        self.path = path

    def hosts(self) -> list[str]:
        with open(self.path) as f:
            hosts = json.load(f)
        if not isinstance(hosts, list) or not all(isinstance(h, str) for h in hosts):
            raise ValueError(f"{self.path}: expected a JSON array of hostport strings")
        return hosts


class CallableProvider:
    """Adapter for a plain function returning hosts."""

    def __init__(self, fn: Callable[[], Sequence[str]]):
        self._fn = fn

    def hosts(self) -> list[str]:
        return list(self._fn())


def as_provider(source) -> DiscoverProvider:
    """Coerce a provider, list of hosts, path-like, or callable into a
    DiscoverProvider."""
    if hasattr(source, "hosts"):
        return source
    if callable(source):
        return CallableProvider(source)
    if isinstance(source, str):
        return JSONFile(source)
    if isinstance(source, (list, tuple)):
        return StaticHosts(*source)
    raise TypeError(f"cannot make a DiscoverProvider from {type(source)!r}")
