"""Sentinel errors (parity: reference ``errors.go:27-35``)."""


class RingpopError(Exception):
    pass


class NotBootstrappedError(RingpopError):
    """(parity: ErrNotBootstrapped)"""

    def __str__(self) -> str:
        return "ringpop is not bootstrapped"


class EphemeralIdentityError(RingpopError):
    """(parity: ErrEphemeralIdentity) — port 0 identities cannot be gossiped."""

    def __str__(self) -> str:
        return "cannot get ringpop identity from ephemeral port"


class InvalidStateError(RingpopError):
    pass


# -- the unified transport error family (r17) ---------------------------------
#
# One peer-lifecycle/error model for every transport — the DCN fabric,
# the serve TCP framing, the shm ring.  Defined HERE (an import-free
# leaf) so the jax-free surfaces (net/channel.py, forward/batch.py,
# serve/shm.py — what frontend processes import without paying a
# backend init) can share the family with parallel/fabric.py, which
# re-exports them under their historical import path.


class FabricError(RuntimeError):
    """Any fabric-layer (or unified-transport) failure with peer
    context attached."""


class FabricPeerLost(FabricError):
    """A peer's socket closed mid-run — the peer process died (or shut
    its transport down) while this side still expected messages from
    it.  Channel flavor: connect refused / connection dropped."""


class FabricTimeout(FabricError):
    """A live but SILENT peer: nothing arrived (or a send could not
    drain) within the deadline.  Distinct from a tag desync — the
    schedule may still agree; the peer is wedged or partitioned."""


class FabricDesync(FabricError):
    """A message arrived with the WRONG tag: the peers' deterministic
    schedules disagree (a leg skipped or reordered).  Both endpoints
    are alive — that is what distinguishes this from the two above."""
