"""Sentinel errors (parity: reference ``errors.go:27-35``)."""


class RingpopError(Exception):
    pass


class NotBootstrappedError(RingpopError):
    """(parity: ErrNotBootstrapped)"""

    def __str__(self) -> str:
        return "ringpop is not bootstrapped"


class EphemeralIdentityError(RingpopError):
    """(parity: ErrEphemeralIdentity) — port 0 identities cannot be gossiped."""

    def __str__(self) -> str:
        return "cannot get ringpop identity from ephemeral port"


class InvalidStateError(RingpopError):
    pass
