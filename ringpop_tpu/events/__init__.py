"""Typed event bus (parity: reference ``events/events.go:26-69``).

Every layer emits dataclass events into listener buses; the facade subscribes
to node/ring/forwarder buses and translates events to stats — the reference's
composition mechanism (``ringpop.go:170-180``), kept here because it decouples
the sim plane cleanly: the sim emits the same event types per step batch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Protocol, Type


class EventListener(Protocol):
    def handle_event(self, event: Any) -> None: ...


class EventEmitter:
    """Listener registry + emit.  Dispatch is synchronous by default (the swim
    node emits synchronously, ``swim/node.go:266-270``); wrap listeners with
    :func:`async_listener` for the facade's async dispatch
    (``ringpop.go:297-301``)."""

    def __init__(self) -> None:
        self._listeners: list[EventListener] = []

    def register_listener(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def deregister_listener(self, listener: EventListener) -> None:
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def emit(self, event: Any) -> None:
        for l in list(self._listeners):
            l.handle_event(event)


class _FnListener:
    def __init__(self, event_type: Type, fn: Callable[[Any], None]):
        self.event_type = event_type
        self.fn = fn

    def handle_event(self, event: Any) -> None:
        if isinstance(event, self.event_type):
            self.fn(event)


def on(emitter: EventEmitter, event_type: Type, fn: Callable[[Any], None]) -> _FnListener:
    """Subscribe ``fn`` to events of ``event_type`` (parity: the reference's
    test helper ``swim/events.go:240-246``)."""
    l = _FnListener(event_type, fn)
    emitter.register_listener(l)
    return l


# ---------------------------------------------------------------------------
# Facade-level events (parity: events/events.go:38-69)
# ---------------------------------------------------------------------------


@dataclass
class RingChangedEvent:
    servers_added: list = field(default_factory=list)
    servers_updated: list = field(default_factory=list)
    servers_removed: list = field(default_factory=list)


@dataclass
class RingChecksumEvent:
    old_checksum: int = 0
    new_checksum: int = 0


@dataclass
class LookupEvent:
    key: str = ""
    duration: float = 0.0


@dataclass
class LookupNEvent:
    key: str = ""
    n: int = 0
    duration: float = 0.0


@dataclass
class LookupNBatchEvent:
    """One batched preference-list computation (``lookup_n_batch``):
    ``duration`` covers the whole batch of ``n_keys`` keys."""

    n_keys: int = 0
    n: int = 0
    duration: float = 0.0


@dataclass
class SimTickBlockEvent:
    """One fetched sim-plane telemetry block (``sim/telemetry.py``): the
    per-tick protocol counters accumulated on device over a tick-block,
    reduced and brought to the host in one fetch.  The sim analog of the
    host plane's per-RPC swim events — emitted per block, not per tick,
    because the sim plane's whole point is that ticks never touch the
    host.  ``record`` is the flat scalar dict documented in
    OBSERVABILITY.md ("journal record schema")."""

    record: dict = field(default_factory=dict)


@dataclass
class Ready:
    pass


@dataclass
class Destroyed:
    pass
