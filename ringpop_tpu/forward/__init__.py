from ringpop_tpu.forward.forwarder import (
    Forwarder,
    Options,
    Sender,
    FORWARDED_HEADER,
    set_forwarded_header,
    has_forwarded_header,
)
from ringpop_tpu.forward.batch import (
    BatchForwarder,
    BlockRouter,
    HOPS_HEADER,
    MaxHopsExceededError,
    QuorumReader,
    quorum_size,
)
from ringpop_tpu.forward.request_sender import DestinationsDivergedError

__all__ = [
    "Forwarder",
    "Options",
    "Sender",
    "FORWARDED_HEADER",
    "set_forwarded_header",
    "has_forwarded_header",
    "DestinationsDivergedError",
    "BatchForwarder",
    "BlockRouter",
    "HOPS_HEADER",
    "MaxHopsExceededError",
    "QuorumReader",
    "quorum_size",
]
