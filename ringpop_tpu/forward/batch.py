"""Batched forwarding plane: per-owner coalescing, hop-guarded, quorum reads.

The scalar :class:`~ringpop_tpu.forward.Forwarder` proxies ONE keyed
request per call (reference ``forward/forwarder.go`` parity).  At serve
fan-in that is the wrong unit: a frontend holding the wrong ring block
would pay one RPC per mis-routed KEY.  This module is the batch analog —
the reference forwarder's semantics (retry with backoff, the
``ringpop-forwarded`` loop breaker) applied to COALESCED per-owner
key-hash batches over the ``net/channel.py`` framing:

* :class:`BatchForwarder` — ships one batch to one destination with
  retry/backoff and a MAX-HOP guard (``ringpop-hops`` header: the batch
  plane's generalization of the binary forwarded header — a mis-routed
  batch may legitimately hop once mid-churn, a loop dies at
  ``max_hops``).  Array payloads ride ``encode_array`` (raw bytes under
  msgpack, base64 under JSON, or the fabric's self-describing r15 codec
  under ``codec="fabric"`` — see ``net.channel``), and per-RPC counters
  (``rpcs``/``keys_forwarded``/``retries``) make the O(owners)-not-
  O(keys) claim measurable.
* :class:`BlockRouter` — HandleOrForward for a block-owning frontend
  (the r14 ``process_block`` rule over the ring's token index space):
  keys whose ring walk starts inside the local block answer locally, the
  rest coalesce into per-owner batches — ONE forward RPC per owner per
  flush.  Doubles as the receive-side handler: a forwarded batch whose
  keys moved again re-forwards with the hop count incremented.
* :class:`QuorumReader` — replica reads on LookupN preference lists:
  each key's R replica owners come from the exact ``host_lookup_n``
  walk, reads coalesce per owner (one RPC per owner per wave), and a key
  acks at ``quorum_size(r)`` = ⌈(R+1)/2⌉ responses.  ``quorum_wave``
  returns per-key ack counts + agreement, so a FaultPlan killing owners
  mid-read (``sim/chaos.py``) is scored — recovery rides
  ``chaos.score_blocks`` over the wave journal.

Span tracing (r20, ``obs/trace.py``): every class takes an optional
``Tracer``.  A batch holding a sampled key (sampling is a pure function
of the key hash — reruns trace the same requests) carries the
``ringpop-trace`` header (trace id + parent span id) NEXT TO
``ringpop-hops``, and each leg — frontend route, per-owner forward RPC,
receive-side handle, quorum wave — emits a ``kind:"span"`` record whose
``hops`` field is exactly the hop count the header carried.  Tracing off
(the default) is the identical code path with zero records.

Top-level imports stay jax-free (frontends import this without paying a
backend init; ``obs.trace`` is numpy+stdlib); the quorum chaos harness
imports ``sim.chaos`` lazily.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.forward.forwarder import FORWARDED_HEADER
from ringpop_tpu.net.channel import (
    CallError,
    RemoteError,
    decode_array,
    encode_array,
)
from ringpop_tpu.obs.trace import TRACE_HEADER, salt_of

_logger = logging_mod.logger("forward.batch")

HOPS_HEADER = "ringpop-hops"
DEFAULT_MAX_HOPS = 4
# the reference's 3/6/12 s schedule is sized for a lone app request; a
# coalesced batch stalls every rider, so the batch plane retries fast by
# default (still caller-configurable, same shape as forwarder.Options)
DEFAULT_BATCH_RETRY_DELAYS = (0.05, 0.2, 0.8)


class MaxHopsExceededError(Exception):
    """A batch crossed ``max_hops`` forwards — a routing loop (two nodes
    that each believe the other owns the block), not transient churn."""


def quorum_size(r: int) -> int:
    """⌈(R+1)/2⌉ — the majority-ack bar for an R-replica read."""
    return (r + 2) // 2


def hop_count(headers: Optional[dict]) -> int:
    try:
        return int((headers or {}).get(HOPS_HEADER, 0))
    except (TypeError, ValueError):
        return 0


class BatchForwarder:
    """One coalesced key-hash batch to one destination, with the
    reference retry engine and the hop guard."""

    def __init__(
        self,
        channel,
        *,
        service: str = "serve",
        endpoint: str = "/lookup",
        max_retries: int = 2,
        retry_delays: Sequence[float] = DEFAULT_BATCH_RETRY_DELAYS,
        timeout: float = 3.0,
        max_hops: int = DEFAULT_MAX_HOPS,
        fabric_arrays: bool = False,
        tracer=None,
    ):
        self.channel = channel
        self.service = service
        self.endpoint = endpoint
        self.max_retries = max_retries
        self.retry_delays = tuple(retry_delays)
        self.timeout = timeout
        self.max_hops = max_hops
        # fabric_arrays: ship the hash batch through the fabric's r15
        # wire codec (net.channel encode_array(fabric=True)) — the
        # decoder is self-describing, so the unmodified serve endpoints
        # answer either lane
        self.fabric_arrays = fabric_arrays
        # tracer: an obs.trace.Tracer — batches holding a sampled key
        # carry the ringpop-trace header and emit a "forward" span per
        # RPC (None = tracing off, the bit-identical default)
        self.tracer = tracer
        self._codec = getattr(channel, "codec", "json")
        self.rpcs = 0
        self.keys_forwarded = 0
        self.retries = 0
        self.batches_failed = 0

    def stats(self) -> dict:
        return {
            "rpcs": self.rpcs,
            "keys_forwarded": self.keys_forwarded,
            "retries": self.retries,
            "batches_failed": self.batches_failed,
        }

    async def forward_batch(
        self, dest: str, hashes, n: int = 1, hops: int = 0, parent=None,
        salt: int = 0,
    ):
        """-> (owners int32[B] or int32[B, n], generation).  ``hops`` is
        how many forwards this batch has ALREADY crossed; the guard fires
        before the wire so a loop costs ``max_hops`` RPCs total, not a
        timeout storm.  ``parent`` (a span id) parents this RPC's span
        when a tracer is attached and the batch holds a sampled key."""
        if hops >= self.max_hops:
            raise MaxHopsExceededError(
                f"batch of {len(hashes)} keys crossed {hops} forwards "
                f"(max_hops={self.max_hops}) — routing loop"
            )
        headers = {FORWARDED_HEADER: "true", HOPS_HEADER: str(hops + 1)}
        span = None
        if self.tracer is not None:
            # the span's hops field is EXACTLY the ringpop-hops value on
            # the wire — the acceptance join checks that equality
            span = self.tracer.begin(
                "forward", hashes, parent=parent, hops=hops + 1,
                salt=salt_of(dest, hops + 1, salt), dest=dest,
                endpoint=self.endpoint, n=n,
            )
            if span is not None:
                headers[TRACE_HEADER] = span.header_value()
        body = {
            "h": encode_array(
                hashes, self._codec, "<u4", fabric=self.fabric_arrays
            ),
            "n": n,
        }
        attempt = 0
        while True:
            try:
                self.rpcs += 1
                res = await self.channel.call(
                    dest, self.service, self.endpoint, body,
                    headers=headers, timeout=self.timeout,
                )
                break
            except RemoteError as e:
                # the remote HANDLER executed and raised (e.g. a deeper
                # hop guard): deterministic, and retrying would multiply
                # every hop level's RPCs by the retry count — a routing
                # loop must cost max_hops RPCs total, not 3^max_hops
                self.batches_failed += 1
                if span is not None:
                    span.finish(ok=False, retries=attempt, error=str(e))
                raise
            except CallError as e:
                if attempt >= self.max_retries:
                    self.batches_failed += 1
                    if span is not None:
                        span.finish(ok=False, retries=attempt, error=str(e))
                    raise
                delay = self.retry_delays[min(attempt, len(self.retry_delays) - 1)]
                attempt += 1
                self.retries += 1
                _logger.debug(
                    f"batch to {dest} failed ({e}); retry {attempt} in {delay}s"
                )
                await asyncio.sleep(delay)
        owners = decode_array(res["o"], "<i4")
        self.keys_forwarded += len(hashes)
        if n > 1:
            owners = owners.reshape(-1, n)
        # a BlockRouter handler answers with PER-KEY generations ("g") —
        # a re-forwarded (hops >= 2) batch can legitimately mix the
        # generations of several answerers mid-churn; plain serve
        # endpoints return the scalar "gen" (their whole answer came
        # from one snapshot)
        gens = decode_array(res["g"], "<i4") if "g" in res else int(res["gen"])
        if span is not None:
            g = gens if isinstance(gens, int) else (
                int(gens.max(initial=0)) if gens.size else 0
            )
            span.finish(ok=True, retries=attempt, gen=g)
        return owners, gens


def rank_of_hashes(tokens: np.ndarray, hashes, nprocs: int) -> np.ndarray:
    """Owner RANK per key hash under the contiguous equal-block rule the
    r14 partition table imposes (``parallel.partition.process_block``)
    applied to the ring's token INDEX space: the rank whose block holds
    the first token >= hash (wrapping to index 0).  ``len(tokens)`` must
    divide over ``nprocs`` — same rigidity, surfaced the same way."""
    count = int(tokens.shape[0])
    if count % nprocs:
        raise ValueError(
            f"ring of {count} tokens does not divide over {nprocs} serve "
            "processes (pick replica_points divisible by the process count)"
        )
    idx = np.searchsorted(tokens, np.asarray(hashes, np.uint32), side="left")
    idx = np.where(idx >= count, 0, idx)
    return (idx // (count // nprocs)).astype(np.int32)


def rank_load(tokens: np.ndarray, hashes, nprocs: int) -> np.ndarray:
    """Per-serve-process key-share histogram (length ``nprocs``) of a
    hash population under :func:`rank_of_hashes` — the load-skew signal
    the closed-loop rules engine watches (``obs/rules.py``
    CrossRankSkew gauges one rank's share against the fleet mean).
    Block shares renumber with the token count on membership changes,
    so drain EFFECTS are probed per server name (``lookup_batch``), not
    through this block view."""
    return np.bincount(
        rank_of_hashes(tokens, hashes, nprocs), minlength=nprocs
    ).astype(np.int64)


class BlockRouter:
    """HandleOrForward over ring blocks: the frontend-side (and
    receive-side) routing plane of the serve mesh's TCP flavor.

    ``local_lookup(hashes, n) -> (owners, gen)`` answers keys whose walk
    starts in this rank's block; everything else coalesces into ONE
    forwarded batch per owning rank.  The returned generation is per-key
    (cross-forwarded keys carry the remote answerer's generation — in a
    settled mesh all equal, and the fan-in certificate checks exactly
    that)."""

    def __init__(
        self,
        rank: int,
        nprocs: int,
        tokens_fn: Callable[[], np.ndarray],
        local_lookup,
        peer_addrs: Sequence[str],
        forwarder: BatchForwarder,
    ):
        if len(peer_addrs) != nprocs:
            raise ValueError(f"need one address per rank, got {len(peer_addrs)}")
        self.rank = rank
        self.nprocs = nprocs
        self.tokens_fn = tokens_fn  # () -> the CURRENT sorted live tokens
        self.local_lookup = local_lookup
        self.peer_addrs = list(peer_addrs)
        self.forwarder = forwarder
        self.keys_local = 0
        self.keys_forwarded = 0

    async def route(self, hashes, n: int = 1, hops: int = 0, parent=None):
        """-> (owners int32[B] or [B, n], gens int32[B]) in input order.
        ``gens`` is exact per key even across re-forwards — the handler
        ships the per-key array back, never a collapsed scalar.
        ``parent`` parents this route's span (a frontend call passes
        None; the receive-side handler passes its own span)."""
        hashes = np.asarray(hashes, np.uint32)
        b = hashes.shape[0]
        tracer = self.forwarder.tracer
        route_span = None
        if tracer is not None:
            route_span = tracer.begin(
                "route", hashes, parent=parent, hops=hops,
                salt=salt_of("route", self.rank, hops), rank=self.rank,
            )
        route_parent = None if route_span is None else route_span.span
        ranks = rank_of_hashes(self.tokens_fn(), hashes, self.nprocs)
        owners = np.full((b, n) if n > 1 else b, -1, np.int32)
        gens = np.full(b, -1, np.int32)
        local = ranks == self.rank
        if local.any():
            rows, gen = await _maybe_await(
                self.local_lookup(hashes[local], n)
            )
            owners[local] = rows
            gens[local] = gen
            self.keys_local += int(local.sum())
        remote_ranks = sorted(set(ranks[~local].tolist()))
        if remote_ranks:
            # one coalesced RPC per owning rank, issued concurrently
            groups = {r: np.flatnonzero(ranks == r) for r in remote_ranks}
            results = await asyncio.gather(
                *(
                    self.forwarder.forward_batch(
                        self.peer_addrs[r], hashes[ix], n=n, hops=hops,
                        parent=route_parent,
                    )
                    for r, ix in groups.items()
                )
            )
            for (r, ix), (rows, gen) in zip(groups.items(), results):
                owners[ix] = rows
                gens[ix] = gen
                self.keys_forwarded += len(ix)
        if route_span is not None:
            route_span.finish(
                keys_local=int(local.sum()),
                keys_forwarded=int((~local).sum()),
                owners=len(remote_ranks),
            )
        return owners, gens

    def handler(self):
        """A ``(service, endpoint)`` handler: answer a forwarded batch,
        re-forwarding keys that moved AGAIN with the hop count bumped
        (the loop guard lives in the forwarder)."""

        async def handle(body: dict, headers: dict) -> dict:
            hashes = decode_array(body["h"], "<u4")
            n = int(body.get("n", 1))
            hops = hop_count(headers)
            tracer = self.forwarder.tracer
            handle_span = None
            if tracer is not None:
                # traced iff the ringpop-trace header is present — the
                # sender made the sampling decision; the header's span
                # id (the sender's forward span) becomes the parent
                handle_span = tracer.follow(
                    headers, "handle", salt=salt_of("handle", self.rank, hops),
                    rank=self.rank, nkeys=int(hashes.shape[0]),
                )
            owners, gens = await self.route(
                hashes, n=n, hops=hops,
                parent=None if handle_span is None else handle_span.span,
            )
            if handle_span is not None:
                handle_span.finish(
                    gen=int(gens.max(initial=0)) if gens.size else 0
                )
            codec = getattr(self.forwarder.channel, "codec", "json")
            return {
                "o": encode_array(owners, codec, "<i4"),
                # per-key generations: a re-forwarded batch may mix the
                # generations of several answerers — collapsing to one
                # scalar here would stamp keys with a generation they
                # were NOT answered at; "gen" stays for plain-endpoint
                # schema compatibility (consumers of "g" ignore it)
                "g": encode_array(gens, codec, "<i4"),
                "gen": int(gens.max(initial=0)) if gens.size else 0,
            }

        return handle


async def _maybe_await(res):
    if asyncio.iscoroutine(res) or isinstance(res, asyncio.Future):
        return await res
    return res


# -- quorum replica reads -----------------------------------------------------


class QuorumReader:
    """R-replica reads over LookupN preference lists, coalesced per owner.

    This is the HASH-BATCH analog of ``ringpop_tpu.replica.Replicator``
    (the reference-parity plane: string keys, opaque app bodies, one
    scalar ``Forwarder`` call per destination, explicit R/W thresholds).
    The grouping rule is the same as ``Replicator._group_replicas`` —
    every (key, replica) assignment groups by owning server, one RPC per
    destination per wave — but the unit is a uint32 hash batch over
    :class:`BatchForwarder`, the threshold is the majority bar
    ``quorum_size(r)`` = ⌈(R+1)/2⌉ rather than a free R value, and ack
    accounting is PER KEY (the chaos scorer consumes it).  A semantic
    change to either plane (grouping, ack policy) should be mirrored in
    the other — their docstrings cross-reference for exactly that
    reason.

    One wave = one batch of keys: each key's R unique replica owners come
    from the exact host walk (``ops.ring_ops.host_lookup_n`` — the
    LookupNUniqueAt parity oracle), every (key, replica) assignment
    groups by owning SERVER, and each owner gets ONE read RPC per wave
    carrying all its assigned keys.  A key acks once per owner that
    answered; success = acks >= ⌈(R+1)/2⌉ (``quorum_size``).  Answer
    agreement is part of the certificate: an acked key's responses must
    all carry the same owner id."""

    def __init__(
        self,
        forwarder: BatchForwarder,
        server_addrs: Sequence[str],
        *,
        r: int = 3,
    ):
        if r < 1:
            raise ValueError(f"r must be >= 1, got {r}")
        self.forwarder = forwarder
        self.server_addrs = list(server_addrs)
        self.r = r
        self.quorum = quorum_size(r)

    async def quorum_wave(
        self, tokens, owners, n_servers: int, hashes, parent=None,
        salt: int = 0,
    ) -> dict:
        """One read wave.  Returns the wave record: per-key ack counts,
        quorum/full-ack fractions, agreement, and the RPC count (the
        O(owners) pricing evidence).  With a traced forwarder, the wave
        emits a ``quorum_wave`` span parenting each per-owner read RPC —
        the quorum-read leg of the acceptance chain."""
        from ringpop_tpu.ops.ring_ops import host_lookup_n

        hashes = np.asarray(hashes, np.uint32)
        b = hashes.shape[0]
        tracer = self.forwarder.tracer
        wave_span = None
        if tracer is not None:
            wave_span = tracer.begin(
                "quorum_wave", hashes, parent=parent,
                salt=salt_of("wave", salt), r=self.r, quorum=self.quorum,
            )
        wave_parent = None if wave_span is None else wave_span.span
        pref = host_lookup_n(tokens, owners, hashes, self.r, n_servers)  # [B, r]
        # group (key, replica) assignments by owning server
        by_owner: dict[int, list[int]] = {}
        for slot in range(self.r):
            for i in np.flatnonzero(pref[:, slot] >= 0):
                by_owner.setdefault(int(pref[i, slot]), []).append(int(i))
        acks = np.zeros(b, np.int32)
        answered: dict[int, list[np.ndarray]] = {i: [] for i in range(b)}

        async def read_one(owner: int, keys: list[int]):
            ix = np.asarray(keys, np.int64)
            try:
                rows, _gen = await self.forwarder.forward_batch(
                    self.server_addrs[owner], hashes[ix], n=1,
                    parent=wave_parent, salt=salt,
                )
            except (CallError, MaxHopsExceededError):
                return  # a dead/partitioned replica simply contributes no ack
            for k, row in zip(keys, np.asarray(rows, np.int32)):
                acks[k] += 1
                answered[k].append(row)

        waves = [read_one(o, ks) for o, ks in sorted(by_owner.items())]
        rpcs = len(waves)
        await asyncio.gather(*waves)
        agree = all(
            len({int(v) for v in vals}) <= 1 for vals in answered.values()
        )
        if wave_span is not None:
            wave_span.finish(
                owners=rpcs,
                acks_min=int(acks.min()) if b else 0,
                quorum_ok=bool((acks >= self.quorum).all()) if b else True,
            )
        return {
            "keys": int(b),
            "r": self.r,
            "quorum": self.quorum,
            "rpcs": rpcs,
            "acks_min": int(acks.min()) if b else 0,
            "acks_mean": round(float(acks.mean()), 3) if b else 0.0,
            "quorum_ok_frac": round(float((acks >= self.quorum).mean()), 4)
            if b else 1.0,
            "full_ack_frac": round(float((acks >= min(self.r, n_servers)).mean()), 4)
            if b else 1.0,
            "answers_agree": bool(agree),
        }


def quorum_chaos_run(
    *,
    n_servers: int = 8,
    replica_points: int = 16,
    r: int = 3,
    keys_per_tick: int = 64,
    horizon: int = 32,
    journal_every: int = 2,
    seed: int = 0,
    plan=None,
    network=None,
) -> dict:
    """Score quorum reads under a FaultPlan that kills owners mid-read.

    Spins S in-process serve nodes on a ``LocalNetwork`` (each answering
    its reads from the shared committed ring), drives one read wave per
    tick while the plan's timeline black-holes crashed servers (and
    un-black-holes restarts), journals one ``kind:"block"`` record per
    ``journal_every`` ticks with ``detect_frac`` = the FULL-ack fraction
    (so ``chaos.score_blocks``'s time-to-detect reads as ticks-to-full-
    replication-recovery after each crash) plus the quorum fields, and
    reduces the journal through the r10 scorer.  The acceptance bar —
    reads still acking at ⌈(R+1)/2⌉ while the primary is dead — is the
    returned ``quorum_held``."""
    from ringpop_tpu.net.channel import LocalChannel, LocalNetwork
    from ringpop_tpu.ops.ring_ops import build_ring_tokens
    from ringpop_tpu.sim import chaos

    rng = np.random.default_rng(seed)
    servers = [f"10.17.0.{i}:3000" for i in range(n_servers)]
    toks, owns = build_ring_tokens(servers, replica_points)
    tokens32 = np.asarray(toks, np.uint32)
    owners32 = np.asarray(owns, np.int32)

    if plan is None:
        # two staggered NON-overlapping owner kills with restarts: at most
        # one of any key's R=3 distinct replicas is dead at a time, so the
        # quorum bar (2 acks) must hold throughout while the FULL-ack
        # fraction dips per crash and recovers at the restart — exactly
        # the recovery curve score_blocks prices
        down = max(4, horizon // 8)
        plan = chaos.churn_plan(
            n_servers, n_churn=2, n_permanent=0, first=4,
            stagger=down + 2, waves=2, down_ticks=down, seed=seed,
        )

    net = network if network is not None else LocalNetwork(seed=seed)
    chans = []
    for i, addr in enumerate(servers):
        chan = LocalChannel(net, addr, app="serve-quorum")

        def make_handler(sid: int):
            async def handle(body, headers):
                h = decode_array(body["h"], "<u4")
                idx = np.searchsorted(tokens32, h, side="left")
                idx = np.where(idx >= tokens32.shape[0], 0, idx)
                return {"o": encode_array(owners32[idx], "json", "<i4"), "gen": 0}

            return handle

        chan.register("serve", "/lookup", make_handler(i))
        chans.append(chan)
    client = LocalChannel(net, "10.17.0.99:1", app="quorum-client")
    fwd = BatchForwarder(client, max_retries=0, timeout=0.05)
    reader = QuorumReader(fwd, servers, r=r)

    records: list[dict] = []
    waves: list[dict] = []

    async def drive():
        prev_down: set[int] = set()
        acc = []
        for tick in range(horizon):
            up = chaos.up_at_host(plan, tick, n_servers)
            down = set(np.flatnonzero(~up).tolist())
            for s in down - prev_down:
                net.black_hole(servers[s])
            for s in prev_down - down:
                net.unblack_hole(servers[s])
            prev_down = down
            hashes = rng.integers(0, 2**32, size=keys_per_tick, dtype=np.uint32)
            wave = await reader.quorum_wave(
                tokens32, owners32, n_servers, hashes
            )
            wave["tick"] = tick
            wave["down"] = sorted(down)
            waves.append(wave)
            acc.append(wave)
            if (tick + 1) % journal_every == 0:
                records.append(
                    {
                        "kind": "block",
                        "tick": tick,
                        "ticks": journal_every,
                        # full replication restored == the scorer's
                        # "detection complete" level
                        "detect_frac": min(w["full_ack_frac"] for w in acc),
                        "quorum_ok_frac": min(w["quorum_ok_frac"] for w in acc),
                        "quorum_acks_min": min(w["acks_min"] for w in acc),
                        "rpcs": sum(w["rpcs"] for w in acc),
                        "keys": sum(w["keys"] for w in acc),
                    }
                )
                acc = []

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(drive())
    finally:
        loop.close()
    score = chaos.score_blocks(records, plan, n=n_servers, scenario="quorum_read")
    killed_any = any(w["down"] for w in waves)
    quorum_held = all(w["quorum_ok_frac"] >= 1.0 for w in waves)
    agree = all(w["answers_agree"] for w in waves)
    total_rpcs = sum(w["rpcs"] for w in waves)
    total_keys = sum(w["keys"] for w in waves)
    return {
        "r": r,
        "quorum": quorum_size(r),
        "n_servers": n_servers,
        "horizon": horizon,
        "keys_per_tick": keys_per_tick,
        "owners_killed": killed_any,
        "quorum_held": quorum_held,
        "answers_agree": agree,
        "rpcs": total_rpcs,
        "keys_read": total_keys,
        # the O(owners) pricing: naive per-(key, replica) RPCs vs coalesced
        "rpcs_naive": total_keys * r,
        "rpc_ratio": round(total_rpcs / max(total_keys * r, 1), 5),
        "score": score,
        "waves": waves,
        "blocks": records,
    }
