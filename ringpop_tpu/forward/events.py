"""Forwarder events (parity: reference ``forward/events.go`` — 11 types)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class RequestForwardedEvent:
    pass


@dataclass
class InflightRequestsChangedEvent:
    inflight: int = 0


@dataclass
class InflightRequestsMiscountEvent:
    operation: str = ""


@dataclass
class SuccessEvent:
    pass


@dataclass
class FailedEvent:
    pass


@dataclass
class MaxRetriesEvent:
    max_retries: int = 0


@dataclass
class RetryAttemptEvent:
    pass


@dataclass
class RetryAbortEvent:
    reason: str = ""


@dataclass
class RetrySuccessEvent:
    num_retries: int = 0


@dataclass
class RerouteEvent:
    old_destination: str = ""
    new_destination: str = ""


@dataclass
class RetryScheduledEvent:
    delay: float = 0.0
