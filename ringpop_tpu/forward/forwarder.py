"""Request forwarding entry point (parity: reference ``forward/forwarder.go``).

Proxies a keyed request to the owning node.  Defaults mirror the reference:
3 retries on a 3/6/12 s schedule, 3 s per-attempt timeout
(``forwarder.go:56-62``).  The ``ringpop-forwarded`` header breaks forwarding
loops (``forwarder.go:186-203``); generated adapters and the keyed-handler
decorator check it before routing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.events import EventEmitter
from ringpop_tpu.forward import events as ev
from ringpop_tpu.forward.request_sender import RequestSender

FORWARDED_HEADER = "ringpop-forwarded"

# reference defaults (forwarder.go:56-62)
DEFAULT_MAX_RETRIES = 3
DEFAULT_RETRY_SCHEDULE = (3.0, 6.0, 12.0)
DEFAULT_TIMEOUT = 3.0


def set_forwarded_header(headers: Optional[dict]) -> dict:
    """(parity: ``forwarder.go:186-193`` SetForwardedHeader)"""
    headers = dict(headers or {})
    headers[FORWARDED_HEADER] = "true"
    return headers


def has_forwarded_header(headers: Optional[dict]) -> bool:
    """(parity: ``forwarder.go:196-203`` HasForwardedHeader)"""
    return bool(headers) and headers.get(FORWARDED_HEADER) == "true"


class Sender(Protocol):
    """What the forwarder needs from its host
    (parity: ``forwarder.go:39-45``)."""

    def who_am_i(self) -> str: ...

    def lookup(self, key: str) -> str: ...


@dataclass
class Options:
    """(parity: ``forward/forwarder.go:48-54``)"""

    max_retries: int = DEFAULT_MAX_RETRIES
    retry_schedule: tuple = DEFAULT_RETRY_SCHEDULE
    timeout: float = DEFAULT_TIMEOUT
    reroute_retries: bool = False
    headers: dict = field(default_factory=dict)


class Forwarder:
    def __init__(self, sender: Sender, channel):
        self.sender = sender
        self.channel = channel
        self.emitter = EventEmitter()
        self._inflight = 0
        self.logger = logging_mod.logger("forwarder")

    def register_listener(self, listener) -> None:
        self.emitter.register_listener(listener)

    def emit(self, event) -> None:
        self.emitter.emit(event)

    # inflight gauge with miscount guard (forwarder.go:125-151)
    def _increment_inflight(self) -> None:
        self._inflight += 1
        self.emit(ev.InflightRequestsChangedEvent(self._inflight))

    def _decrement_inflight(self) -> None:
        if self._inflight <= 0:
            self.emit(ev.InflightRequestsMiscountEvent("decrement"))
            return
        self._inflight -= 1
        self.emit(ev.InflightRequestsChangedEvent(self._inflight))

    @property
    def inflight(self) -> int:
        return self._inflight

    async def forward_request(
        self,
        body: dict,
        destination: str,
        service: str,
        endpoint: str,
        keys: list[str],
        options: Optional[Options] = None,
    ) -> dict:
        """Proxy ``body`` to ``destination`` with the retry engine
        (parity: ``forwarder.go:156-174`` ForwardRequest)."""
        opts = options or Options()
        self.emit(ev.RequestForwardedEvent())
        self._increment_inflight()
        sender = RequestSender(
            sender=self.sender,
            channel=self.channel,
            emitter=self.emitter,
            destination=destination,
            service=service,
            endpoint=endpoint,
            body=body,
            keys=keys,
            options=opts,
        )
        try:
            res = await sender.send()
            self.emit(ev.SuccessEvent())
            return res
        except Exception:
            self.emit(ev.FailedEvent())
            raise
        finally:
            self._decrement_inflight()
