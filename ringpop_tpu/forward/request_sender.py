"""One forwarding attempt + the retry engine
(parity: reference ``forward/request_sender.go``).

Retries sleep per the schedule, then **re-look-up all keys**: if the keys'
destinations diverged while we were retrying, abort with
:class:`DestinationsDivergedError` (``request_sender.go:222-243``); with
reroute enabled a moved-but-consistent destination is chased
(``request_sender.go:245-254``).
"""

from __future__ import annotations

import asyncio

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.forward import events as ev


class DestinationsDivergedError(Exception):
    """(parity: ``request_sender.go:39`` errDestinationsDiverged)"""

    def __str__(self) -> str:
        return "key destinations have diverged"


class MaxRetriesError(Exception):
    def __str__(self) -> str:
        return "max retries exceeded"


class RequestSender:
    def __init__(
        self, sender, channel, emitter, destination, service, endpoint, body, keys, options
    ):
        self.sender = sender
        self.channel = channel
        self.emitter = emitter
        self.destination = destination
        self.service = service
        self.endpoint = endpoint
        self.body = body
        self.keys = keys
        self.options = options
        self.retries = 0
        self.logger = logging_mod.logger("forwarder")

    async def send(self) -> dict:
        """(parity: ``request_sender.go:95-145`` Send)"""
        from ringpop_tpu.forward.forwarder import set_forwarded_header

        headers = set_forwarded_header(self.options.headers)
        try:
            res = await self.channel.call(
                self.destination,
                self.service,
                self.endpoint,
                self.body,
                headers=headers,
                timeout=self.options.timeout,
            )
            if self.retries > 0:
                self.emitter.emit(ev.RetrySuccessEvent(self.retries))
            return res
        except Exception as forward_error:
            if self.retries < self.options.max_retries:
                return await self.schedule_retry()
            self.logger.warn(
                "max retries exceeded for request to %s %s", self.destination, self.endpoint
            )
            self.emitter.emit(ev.MaxRetriesEvent(self.options.max_retries))
            raise MaxRetriesError() from forward_error

    async def schedule_retry(self) -> dict:
        """(parity: ``request_sender.go:206-220`` ScheduleRetry)"""
        schedule = self.options.retry_schedule
        delay = schedule[min(self.retries, len(schedule) - 1)]
        self.emitter.emit(ev.RetryScheduledEvent(delay))
        await asyncio.sleep(delay)
        return await self.attempt_retry()

    async def attempt_retry(self) -> dict:
        """(parity: ``request_sender.go:222-243`` AttemptRetry)"""
        self.retries += 1
        self.emitter.emit(ev.RetryAttemptEvent())

        dests = self.lookup_keys(self.keys)
        if len(dests) != 1:
            self.emitter.emit(ev.RetryAbortEvent(str(DestinationsDivergedError())))
            raise DestinationsDivergedError()

        if self.options.reroute_retries and dests[0] != self.destination:
            return await self.reroute_retry(dests[0])
        return await self.send()

    async def reroute_retry(self, destination: str) -> dict:
        """(parity: ``request_sender.go:245-254``)"""
        self.emitter.emit(ev.RerouteEvent(self.destination, destination))
        self.destination = destination
        return await self.send()

    def lookup_keys(self, keys: list[str]) -> list[str]:
        """Deduped destinations of all keys
        (parity: ``request_sender.go:259-278``)."""
        dests = set()
        for key in keys:
            try:
                dest = self.sender.lookup(key)
            except Exception:
                continue
            if dest:
                dests.add(dest)
        return sorted(dests)
