"""Multi-process integration harness
(parity: reference ``test/run-integration-tests`` — real local clusters of
testpop processes driven through convergence/failure scenarios,
``test/run-integration-tests:12,99-113``).

Spawns N ``testpop`` subprocesses on loopback ports, gives them a shared
JSON hosts file, and offers scenario primitives: wait-for-convergence (all
nodes report the same membership checksum over ``/admin/stats``), kill,
and reap checks.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Optional

from ringpop_tpu.net import TCPChannel


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ProcessCluster:
    def __init__(
        self,
        n: int,
        suspect_period: float = 1.0,
        app: str = "testpop",
        wire: Optional[str] = None,
    ):
        self.n = n
        self.app = app
        self.suspect_period = suspect_period
        self.wire = wire
        self.hosts = [f"127.0.0.1:{free_port()}" for _ in range(n)]
        self.procs: dict[str, subprocess.Popen] = {}
        self._tmpdir = tempfile.mkdtemp(prefix="ringpop-itest-")
        self.hosts_file = os.path.join(self._tmpdir, "hosts.json")
        with open(self.hosts_file, "w") as f:
            json.dump(self.hosts, f)
        self._client: Optional[TCPChannel] = None

    def start(self) -> None:
        env = dict(os.environ)
        env.setdefault("PYTHONUNBUFFERED", "1")
        for hp in self.hosts:
            self.procs[hp] = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "ringpop_tpu.cli.testpop",
                    "--listen",
                    hp,
                    "--hosts",
                    self.hosts_file,
                    "--app",
                    self.app,
                    "--suspect-period",
                    str(self.suspect_period),
                    "--join-timeout",
                    "1.0",
                ]
                + (["--wire", self.wire] if self.wire else []),
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )

    async def client(self) -> TCPChannel:
        if self._client is None:
            self._client = TCPChannel(app=self.app)
        return self._client

    async def stats(self, hostport: str, timeout: float = 2.0) -> dict:
        client = await self.client()
        return await client.call(hostport, "ringpop", "/admin/stats", {}, timeout=timeout)

    async def wait_converged(
        self, hosts: Optional[list[str]] = None, expect_members: Optional[int] = None, timeout: float = 30.0
    ) -> dict[str, dict]:
        """Poll /admin/stats until every polled node reports the same
        membership checksum (and optionally a member count)."""
        hosts = hosts or self.hosts
        deadline = time.time() + timeout
        last: dict[str, dict] = {}
        while time.time() < deadline:
            try:
                last = {hp: await self.stats(hp) for hp in hosts}
            except Exception:
                await asyncio.sleep(0.3)
                continue
            checksums = {s["membership"]["checksum"] for s in last.values()}
            counts_ok = expect_members is None or all(
                len(s["membership"]["members"]) == expect_members for s in last.values()
            )
            if len(checksums) == 1 and counts_ok:
                return last
            await asyncio.sleep(0.3)
        raise AssertionError(
            f"no convergence in {timeout}s: "
            f"{ {hp: s.get('membership', {}).get('checksum') for hp, s in last.items()} }"
        )

    async def wait_member_status(
        self, observer: str, member: str, status: str, timeout: float = 30.0
    ) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                s = await self.stats(observer)
                for m in s["membership"]["members"]:
                    if m["address"] == member and m["status"] == status:
                        return
            except Exception:
                pass
            await asyncio.sleep(0.3)
        raise AssertionError(f"{observer} never saw {member} as {status}")

    def kill(self, hostport: str, sig=signal.SIGKILL) -> None:
        self.procs[hostport].send_signal(sig)

    async def shutdown(self) -> None:
        if self._client is not None:
            await self._client.close()
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    def dump_output(self) -> str:
        out = []
        for hp, p in self.procs.items():
            if p.stdout and p.poll() is not None:
                out.append(f"--- {hp} ---\n{p.stdout.read().decode(errors='replace')}")
        return "\n".join(out)
