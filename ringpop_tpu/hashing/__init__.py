"""Hashing front-end: FarmHash Fingerprint32, native-accelerated.

Dispatches to the C++ core (``ringpop_tpu.native``) when the lazily-built
library is available, else to the pure-Python/numpy reference implementation
(``ringpop_tpu.hashing.farm``).  Both produce identical bits — the test
suite cross-checks them — so checksums and ring tokens stay wire-compatible
with the reference (``swim/memberlist.go:86``, ``hashring/hashring.go:107``)
regardless of which backend serves a call.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from ringpop_tpu.hashing import farm as _farm
from ringpop_tpu.hashing.farm import fingerprint32_batch, pack_strings  # re-export

_backend: str | None = None


def _use_native() -> bool:
    global _backend
    if _backend is None:
        from ringpop_tpu import native

        _backend = "native" if native.available() else "python"
    return _backend == "native"


def fingerprint32(data: bytes | str) -> int:
    """FarmHash Fingerprint32 of ``data`` (farmhashmk::Hash32)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    if _use_native():
        from ringpop_tpu import native

        return native.fingerprint32(data)
    return _farm.fingerprint32(data)


def fingerprint32_many(strings: Iterable[str | bytes]) -> np.ndarray:
    """Batch Fingerprint32 -> uint32[n]."""
    strings = list(strings)
    if not strings:
        return np.empty(0, dtype=np.uint32)
    if _use_native():
        from ringpop_tpu import native

        return native.fingerprint32_many(strings)
    mat, lens = pack_strings(strings)
    return fingerprint32_batch(mat, lens).astype(np.uint32)


def ring_tokens(servers: Sequence[str], replica_points: int) -> np.ndarray:
    """uint32[n_servers, replica_points] of farm32(addr + str(i)) — the
    hashring vnode tokens (parity: ``hashring.go:148-154``)."""
    if _use_native():
        from ringpop_tpu import native

        return native.ring_tokens(servers, replica_points)
    flat = fingerprint32_many([f"{s}{i}" for s in servers for i in range(replica_points)])
    return flat.reshape(len(servers), replica_points)


__all__ = [
    "fingerprint32",
    "fingerprint32_batch",
    "fingerprint32_many",
    "pack_strings",
    "ring_tokens",
]
