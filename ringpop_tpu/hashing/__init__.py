from ringpop_tpu.hashing.farm import fingerprint32, fingerprint32_batch

__all__ = ["fingerprint32", "fingerprint32_batch"]
