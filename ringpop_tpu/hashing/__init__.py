"""Hashing front-end: FarmHash Fingerprint32, native-accelerated.

Dispatches to the C++ core (``ringpop_tpu.native``) when the lazily-built
library is available, else to the pure-Python/numpy reference implementation
(``ringpop_tpu.hashing.farm``).  Both produce identical bits — the test
suite cross-checks them — so checksums and ring tokens stay wire-compatible
with the reference (``swim/memberlist.go:86``, ``hashring/hashring.go:107``)
regardless of which backend serves a call.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from ringpop_tpu.hashing import farm as _farm
from ringpop_tpu.hashing.farm import fingerprint32_batch, pack_strings  # re-export

_backend: str | None = None


def _use_native() -> bool:
    global _backend
    if _backend is None:
        from ringpop_tpu import native

        _backend = "native" if native.available() else "python"
    return _backend == "native"


def fingerprint32(data: bytes | str) -> int:
    """FarmHash Fingerprint32 of ``data`` (farmhashmk::Hash32)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    if _use_native():
        from ringpop_tpu import native

        return native.fingerprint32(data)
    return _farm.fingerprint32(data)


def fingerprint32_many(strings: Iterable[str | bytes]) -> np.ndarray:
    """Batch Fingerprint32 -> uint32[n]."""
    strings = list(strings)
    if not strings:
        return np.empty(0, dtype=np.uint32)
    if _use_native():
        from ringpop_tpu import native

        return native.fingerprint32_many(strings)
    mat, lens = pack_strings(strings)
    return fingerprint32_batch(mat, lens).astype(np.uint32)


def membership_checksum(entries: Sequence[str]) -> int:
    """farm32 over sorted entries joined with trailing ';' — the membership
    checksum canonical form (parity: ``swim/memberlist.go:106-128``).  The
    native path sorts, joins, and hashes in one C++ call; the fallback builds
    the same string in Python."""
    if _use_native():
        from ringpop_tpu import native

        return native.membership_checksum(entries)
    return fingerprint32("".join(s + ";" for s in sorted(entries)))


def ring_lookup_n_batch(
    tokens: np.ndarray,
    owners: np.ndarray,
    n_servers: int,
    hashes: np.ndarray,
    nwant: int,
) -> np.ndarray:
    """Exact batched N-owner ring walk -> int32[nkeys, nwant] server indices,
    -1-padded (parity: ``hashring.go:271-301``).  Native C++ walk with a
    per-owner stamp array; Python fallback does the same walk per key."""
    if _use_native():
        from ringpop_tpu import native

        return native.ring_lookup_n_batch(tokens, owners, n_servers, hashes, nwant)
    tokens32 = np.asarray(tokens, dtype=np.uint32)
    owners32 = np.asarray(owners, dtype=np.uint32)
    hashes32 = np.asarray(hashes, dtype=np.uint32)
    nwant = max(nwant, 0)
    out = np.full((hashes32.shape[0], nwant), -1, dtype=np.int32)
    t = tokens32.shape[0]
    if t == 0 or n_servers == 0 or nwant == 0:
        return out
    want = min(nwant, n_servers)
    starts = np.searchsorted(tokens32, hashes32, side="left") % t
    for k, start in enumerate(starts):
        seen: set[int] = set()
        for i in range(t):
            owner = int(owners32[(start + i) % t])
            if owner not in seen:
                seen.add(owner)
                out[k, len(seen) - 1] = owner
                if len(seen) == want:
                    break
    return out


def ring_tokens(servers: Sequence[str], replica_points: int) -> np.ndarray:
    """uint32[n_servers, replica_points] of farm32(addr + str(i)) — the
    hashring vnode tokens (parity: ``hashring.go:148-154``)."""
    if _use_native():
        from ringpop_tpu import native

        return native.ring_tokens(servers, replica_points)
    flat = fingerprint32_many([f"{s}{i}" for s in servers for i in range(replica_points)])
    return flat.reshape(len(servers), replica_points)


__all__ = [
    "fingerprint32",
    "fingerprint32_batch",
    "fingerprint32_many",
    "membership_checksum",
    "pack_strings",
    "ring_lookup_n_batch",
    "ring_tokens",
]
