"""FarmHash Fingerprint32 — platform-independent 32-bit fingerprint.

This is the hash the reference uses everywhere (``dgryski/go-farm``
Fingerprint32: ring tokens ``hashring/hashring.go:107``, membership checksum
``swim/memberlist.go:86``, facade ring ``ringpop.go:172``).  Fingerprint32 is
defined as the ``farmhashmk::Hash32`` routine of Google FarmHash, implemented
here from the published algorithm in two forms:

* :func:`fingerprint32` — pure-Python scalar, the semantic reference.
* :func:`fingerprint32_batch` — numpy-vectorized over a padded uint8 matrix,
  grouped by control-flow bucket (length class and >24-byte loop count), used
  to build million-server rings host-side in one shot.

Keeping the exact reference hash matters for wire/checksum compatibility with
existing ringpop deployments (checksum comparison drives full syncs,
``swim/disseminator.go:168-181``).
"""

from __future__ import annotations

import numpy as np

_M32 = 0xFFFFFFFF
C1 = 0xCC9E2D51
C2 = 0x1B873593


def _ror(v: int, s: int) -> int:
    v &= _M32
    return ((v >> s) | (v << (32 - s))) & _M32


def _fmix(h: int) -> int:
    h &= _M32
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def _mur(a: int, h: int) -> int:
    a = (a * C1) & _M32
    a = _ror(a, 17)
    a = (a * C2) & _M32
    h ^= a
    h = _ror(h, 19)
    return (h * 5 + 0xE6546B64) & _M32


def _fetch32(data: bytes, i: int) -> int:
    return int.from_bytes(data[i : i + 4], "little")


def _hash32_len_0_to_4(data: bytes, seed: int = 0) -> int:
    b = seed
    c = 9
    for ch in data:
        v = ch - 256 if ch >= 128 else ch  # signed char semantics
        b = (b * C1 + v) & _M32
        c ^= b
    return _fmix(_mur(b, _mur(len(data), c)))


def _hash32_len_5_to_12(data: bytes, seed: int = 0) -> int:
    n = len(data)
    a = (n + 0) & _M32
    b = (n * 5) & _M32
    c = 9
    d = (b + seed) & _M32
    a = (a + _fetch32(data, 0)) & _M32
    b = (b + _fetch32(data, n - 4)) & _M32
    c = (c + _fetch32(data, (n >> 1) & 4)) & _M32
    return _fmix(seed ^ _mur(c, _mur(b, _mur(a, d))))


def _hash32_len_13_to_24(data: bytes, seed: int = 0) -> int:
    n = len(data)
    a = _fetch32(data, (n >> 1) - 4)
    b = _fetch32(data, 4)
    c = _fetch32(data, n - 8)
    d = _fetch32(data, n >> 1)
    e = _fetch32(data, 0)
    f = _fetch32(data, n - 4)
    h = (d * C1 + n + seed) & _M32
    a = (_ror(a, 12) + f) & _M32
    h = (_mur(c, h) + a) & _M32
    a = (_ror(a, 3) + c) & _M32
    h = (_mur(e, h) + a) & _M32
    a = (_ror((a + f) & _M32, 12) + d) & _M32
    h = (_mur(b ^ seed, h) + a) & _M32
    return _fmix(h)


def fingerprint32(data: bytes | str) -> int:
    """FarmHash Fingerprint32 of ``data`` (farmhashmk::Hash32)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    n = len(data)
    if n <= 4:
        return _hash32_len_0_to_4(data)
    if n <= 12:
        return _hash32_len_5_to_12(data)
    if n <= 24:
        return _hash32_len_13_to_24(data)

    h = n & _M32
    g = (C1 * n) & _M32
    f = g
    a0 = (_ror((_fetch32(data, n - 4) * C1) & _M32, 17) * C2) & _M32
    a1 = (_ror((_fetch32(data, n - 8) * C1) & _M32, 17) * C2) & _M32
    a2 = (_ror((_fetch32(data, n - 16) * C1) & _M32, 17) * C2) & _M32
    a3 = (_ror((_fetch32(data, n - 12) * C1) & _M32, 17) * C2) & _M32
    a4 = (_ror((_fetch32(data, n - 20) * C1) & _M32, 17) * C2) & _M32
    h ^= a0
    h = _ror(h, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    h ^= a2
    h = _ror(h, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    g ^= a1
    g = _ror(g, 19)
    g = (g * 5 + 0xE6546B64) & _M32
    g ^= a3
    g = _ror(g, 19)
    g = (g * 5 + 0xE6546B64) & _M32
    f = (f + a4) & _M32
    f = (_ror(f, 19) + 113) & _M32
    iters = (n - 1) // 20
    off = 0
    for _ in range(iters):
        a = _fetch32(data, off)
        b = _fetch32(data, off + 4)
        c = _fetch32(data, off + 8)
        d = _fetch32(data, off + 12)
        e = _fetch32(data, off + 16)
        h = (h + a) & _M32
        g = (g + b) & _M32
        f = (f + c) & _M32
        h = (_mur(d, h) + e) & _M32
        g = (_mur(c, g) + a) & _M32
        f = (_mur((b + (e * C1)) & _M32, f) + d) & _M32
        f = (f + g) & _M32
        g = (g + f) & _M32
        off += 20
    g = (_ror(g, 11) * C1) & _M32
    g = (_ror(g, 17) * C1) & _M32
    f = (_ror(f, 11) * C1) & _M32
    f = (_ror(f, 17) * C1) & _M32
    h = _ror((h + g) & _M32, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    h = (_ror(h, 17) * C1) & _M32
    h = _ror((h + f) & _M32, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    h = (_ror(h, 17) * C1) & _M32
    return h


# ---------------------------------------------------------------------------
# Vectorized batch version
# ---------------------------------------------------------------------------

_U32 = np.uint32


def _vror(v, s: int):
    v = v.astype(_U32)
    return ((v >> _U32(s)) | (v << _U32(32 - s))).astype(_U32)


def _vfmix(h):
    h = h.astype(_U32)
    h ^= h >> _U32(16)
    h = (h * _U32(0x85EBCA6B)).astype(_U32)
    h ^= h >> _U32(13)
    h = (h * _U32(0xC2B2AE35)).astype(_U32)
    h ^= h >> _U32(16)
    return h


def _vmur(a, h):
    a = (a.astype(_U32) * _U32(C1)).astype(_U32)
    a = _vror(a, 17)
    a = (a * _U32(C2)).astype(_U32)
    h = h.astype(_U32) ^ a
    h = _vror(h, 19)
    return (h * _U32(5) + _U32(0xE6546B64)).astype(_U32)


def _vfetch32(mat: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Little-endian 32-bit fetch at per-row byte offsets ``idx``."""
    r = np.arange(mat.shape[0])
    b0 = mat[r, idx].astype(_U32)
    b1 = mat[r, idx + 1].astype(_U32)
    b2 = mat[r, idx + 2].astype(_U32)
    b3 = mat[r, idx + 3].astype(_U32)
    return (b0 | (b1 << _U32(8)) | (b2 << _U32(16)) | (b3 << _U32(24))).astype(_U32)


def _vbatch_0_to_4(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    n = mat.shape[0]
    b = np.zeros(n, dtype=_U32)
    c = np.full(n, 9, dtype=_U32)
    maxlen = int(lens.max()) if n else 0
    for i in range(maxlen):
        active = lens > i
        v = mat[:, i].astype(np.int8).astype(np.int32).astype(_U32)
        nb = (b * _U32(C1) + v).astype(_U32)
        b = np.where(active, nb, b)
        c = np.where(active, c ^ nb, c)
    return _vfmix(_vmur(b, _vmur(lens.astype(_U32), c)))


def _vbatch_5_to_12(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    ln = lens.astype(_U32)
    a = ln.copy()
    b = (ln * _U32(5)).astype(_U32)
    c = np.full(mat.shape[0], 9, dtype=_U32)
    d = b.copy()
    a = (a + _vfetch32(mat, np.zeros_like(lens))).astype(_U32)
    b = (b + _vfetch32(mat, lens - 4)).astype(_U32)
    c = (c + _vfetch32(mat, (lens >> 1) & 4)).astype(_U32)
    return _vfmix(_vmur(c, _vmur(b, _vmur(a, d))))


def _vbatch_13_to_24(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    ln = lens.astype(_U32)
    a = _vfetch32(mat, (lens >> 1) - 4)
    b = _vfetch32(mat, np.full_like(lens, 4))
    c = _vfetch32(mat, lens - 8)
    d = _vfetch32(mat, lens >> 1)
    e = _vfetch32(mat, np.zeros_like(lens))
    f = _vfetch32(mat, lens - 4)
    h = (d * _U32(C1) + ln).astype(_U32)
    a = (_vror(a, 12) + f).astype(_U32)
    h = (_vmur(c, h) + a).astype(_U32)
    a = (_vror(a, 3) + c).astype(_U32)
    h = (_vmur(e, h) + a).astype(_U32)
    a = (_vror((a + f).astype(_U32), 12) + d).astype(_U32)
    h = (_vmur(b, h) + a).astype(_U32)
    return _vfmix(h)


def _vbatch_gt_24(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """All rows must share the same iteration count (len-1)//20; caller
    buckets."""
    ln = lens.astype(_U32)
    h = ln.copy()
    g = (ln * _U32(C1)).astype(_U32)
    f = g.copy()
    a0 = (_vror((_vfetch32(mat, lens - 4) * _U32(C1)).astype(_U32), 17) * _U32(C2)).astype(_U32)
    a1 = (_vror((_vfetch32(mat, lens - 8) * _U32(C1)).astype(_U32), 17) * _U32(C2)).astype(_U32)
    a2 = (_vror((_vfetch32(mat, lens - 16) * _U32(C1)).astype(_U32), 17) * _U32(C2)).astype(_U32)
    a3 = (_vror((_vfetch32(mat, lens - 12) * _U32(C1)).astype(_U32), 17) * _U32(C2)).astype(_U32)
    a4 = (_vror((_vfetch32(mat, lens - 20) * _U32(C1)).astype(_U32), 17) * _U32(C2)).astype(_U32)
    h = (_vror(h ^ a0, 19) * _U32(5) + _U32(0xE6546B64)).astype(_U32)
    h = (_vror(h ^ a2, 19) * _U32(5) + _U32(0xE6546B64)).astype(_U32)
    g = (_vror(g ^ a1, 19) * _U32(5) + _U32(0xE6546B64)).astype(_U32)
    g = (_vror(g ^ a3, 19) * _U32(5) + _U32(0xE6546B64)).astype(_U32)
    f = (f + a4).astype(_U32)
    f = (_vror(f, 19) + _U32(113)).astype(_U32)
    iters = int((int(lens[0]) - 1) // 20)
    off = np.zeros_like(lens)
    for _ in range(iters):
        a = _vfetch32(mat, off)
        b = _vfetch32(mat, off + 4)
        c = _vfetch32(mat, off + 8)
        d = _vfetch32(mat, off + 12)
        e = _vfetch32(mat, off + 16)
        h = (h + a).astype(_U32)
        g = (g + b).astype(_U32)
        f = (f + c).astype(_U32)
        h = (_vmur(d, h) + e).astype(_U32)
        g = (_vmur(c, g) + a).astype(_U32)
        f = (_vmur((b + (e * _U32(C1)).astype(_U32)).astype(_U32), f) + d).astype(_U32)
        f = (f + g).astype(_U32)
        g = (g + f).astype(_U32)
        off = off + 20
    g = (_vror(g, 11) * _U32(C1)).astype(_U32)
    g = (_vror(g, 17) * _U32(C1)).astype(_U32)
    f = (_vror(f, 11) * _U32(C1)).astype(_U32)
    f = (_vror(f, 17) * _U32(C1)).astype(_U32)
    h = _vror((h + g).astype(_U32), 19)
    h = (h * _U32(5) + _U32(0xE6546B64)).astype(_U32)
    h = (_vror(h, 17) * _U32(C1)).astype(_U32)
    h = _vror((h + f).astype(_U32), 19)
    h = (h * _U32(5) + _U32(0xE6546B64)).astype(_U32)
    h = (_vror(h, 17) * _U32(C1)).astype(_U32)
    return h


def fingerprint32_batch(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Vectorized Fingerprint32 over N byte strings.

    ``mat`` is (N, L) uint8, right-padded with at least 4 zero bytes beyond
    each row's length; ``lens`` is (N,) int.  Rows are grouped by control-flow
    bucket and each bucket is hashed in lockstep.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    lens = np.asarray(lens, dtype=np.int64)
    n = mat.shape[0]
    out = np.zeros(n, dtype=_U32)
    if n == 0:
        return out
    if mat.shape[1] < int(lens.max()) + 4:
        mat = np.pad(mat, ((0, 0), (0, 4)))

    cls = np.where(lens <= 4, 0, np.where(lens <= 12, 1, np.where(lens <= 24, 2, 3)))
    for c, fn in ((0, _vbatch_0_to_4), (1, _vbatch_5_to_12), (2, _vbatch_13_to_24)):
        idx = np.nonzero(cls == c)[0]
        if idx.size:
            out[idx] = fn(mat[idx], lens[idx])
    idx3 = np.nonzero(cls == 3)[0]
    if idx3.size:
        iters = (lens[idx3] - 1) // 20
        for it in np.unique(iters):
            sub = idx3[iters == it]
            out[sub] = _vbatch_gt_24(mat[sub], lens[sub])
    return out


def pack_strings(strings: list[bytes | str]) -> tuple[np.ndarray, np.ndarray]:
    """Pack variable-length strings into the (mat, lens) form
    :func:`fingerprint32_batch` consumes."""
    bs = [s.encode("utf-8") if isinstance(s, str) else s for s in strings]
    lens = np.array([len(b) for b in bs], dtype=np.int64)
    width = (int(lens.max()) if bs else 0) + 4
    mat = np.zeros((len(bs), width), dtype=np.uint8)
    for i, b in enumerate(bs):
        mat[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return mat, lens
