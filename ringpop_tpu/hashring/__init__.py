"""Consistent hash ring as a sorted token array.

Parity: reference ``hashring/`` (``hashring.go`` + the red-black tree
``rbtree.go``).  Same semantics — ``replica_points`` virtual nodes per server
at ``farm32(addr + str(i))`` (``hashring.go:148-154``), lookup = first unique
owners at token >= ``farm32(key)`` with wraparound (``hashring.go:279-301``,
``rbtree.go:262-288``), checksum = farm32 over the sorted ``;``-joined server
list (``hashring.go:102-120``) — but the rbtree is replaced by a sorted
uint64 token array + parallel owner-index array:

* single lookup is ``bisect`` O(log T);
* **batched lookup is vectorizable** (`numpy searchsorted` here,
  ``ringpop_tpu.ops.ring_ops`` for the jnp/TPU version) — the reference's
  pointer-chasing tree cannot batch at all;
* membership changes maintain the sorted token array INCREMENTALLY — removed
  servers' rows are masked out and added servers' pre-sorted token blocks are
  merge-inserted at their ``searchsorted`` positions, O(T + A·log T) with no
  global re-sort; ``_rebuild`` (the from-scratch argsort) is kept as the
  oracle the incremental path is pinned bit-identical to
  (``tests/test_hashring.py``).

Token collisions between (server, replica) pairs are resolved by (token,
server) order, deterministically.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Iterable, Optional

import numpy as np

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.events import EventEmitter, RingChangedEvent, RingChecksumEvent
from ringpop_tpu.hashing import (
    fingerprint32,
    fingerprint32_many,
    ring_lookup_n_batch,
    ring_tokens,
)


class Configuration:
    """Ring construction config (parity: ``hashring.go:40-46``)."""

    def __init__(self, replica_points: int = 100, hashfunc: Optional[Callable] = None):
        self.replica_points = replica_points
        self.hashfunc = hashfunc or fingerprint32


class HashRing:
    """Sorted-token-array consistent hash ring."""

    def __init__(self, hashfunc: Optional[Callable] = None, replica_points: int = 100):
        self.hashfunc = hashfunc or fingerprint32
        self.replica_points = replica_points
        self._lock = threading.RLock()
        self._server_tokens: dict[str, np.ndarray] = {}  # addr -> uint32[replica_points]
        # raw uint32 token values (uint64 dtype), sorted by the composite
        # (token << 32 | server_id) so equal tokens order by server id
        self._tokens = np.empty(0, dtype=np.uint64)
        self._owners = np.empty(0, dtype=np.int64)
        self._tokens32 = np.empty(0, dtype=np.uint32)
        self._owners32 = np.empty(0, dtype=np.uint32)
        self._tokens_list: list[int] = []
        self._owners_list: list[int] = []
        self._server_list: list[str] = []  # index -> addr for _owners
        self._checksum = 0
        self.emitter = EventEmitter()
        self.logger = logging_mod.logger("ring")
        self._compute_checksum()

    # -- events -------------------------------------------------------------

    def register_listener(self, listener) -> None:
        self.emitter.register_listener(listener)

    def _emit(self, event) -> None:
        self.emitter.emit(event)

    # -- construction -------------------------------------------------------

    def _tokens_for(self, server: str) -> np.ndarray:
        toks = self._server_tokens.get(server)
        if toks is None:
            if self.hashfunc is fingerprint32:
                toks = ring_tokens([server], self.replica_points)[0].astype(np.uint64)
            else:
                # mask to 32 bits — the ring's token space (the same mask
                # _hash_keys applies to key hashes; an unmasked 64-bit token
                # array would truncate unsorted into the _tokens32 cache)
                toks = np.array(
                    [
                        self.hashfunc(f"{server}{i}") & 0xFFFFFFFF
                        for i in range(self.replica_points)
                    ],
                    dtype=np.uint64,
                )
            self._server_tokens[server] = toks
        return toks

    def _rebuild(self) -> None:
        """Rebuild the sorted token/owner arrays from the server set — the
        from-scratch argsort.  The mutation path maintains the arrays
        incrementally (:meth:`_apply_incremental`); this full rebuild is
        kept as the INDEPENDENT oracle the incremental path is pinned
        bit-identical to (``tests/test_hashring.py`` calls it directly on
        the comparison ring — it has no production call sites)."""
        servers = sorted(self._server_tokens)
        self._server_list = servers
        if not servers:
            self._tokens = np.empty(0, dtype=np.uint64)
            self._owners = np.empty(0, dtype=np.int64)
            self._refresh_caches()
            return
        toks = np.concatenate([self._server_tokens[s] for s in servers])
        owners = np.repeat(np.arange(len(servers), dtype=np.int64), self.replica_points)
        # composite sort key (token, server-id) for deterministic collision order
        composite = (toks.astype(np.uint64) << np.uint64(32)) | owners.astype(np.uint64)
        order = np.argsort(composite, kind="stable")
        self._tokens = toks[order]
        self._owners = owners[order]
        self._refresh_caches()

    def _refresh_caches(self) -> None:
        # uint32 views cached once per mutation for the batched native walks,
        # plus plain-int lists for the bisect single-key fast path (python
        # ints compare ~30x faster than numpy scalars under bisect)
        self._tokens32 = np.ascontiguousarray(self._tokens, dtype=np.uint32)
        self._owners32 = np.ascontiguousarray(self._owners, dtype=np.uint32)
        self._tokens_list = self._tokens.tolist()
        self._owners_list = self._owners.tolist()

    def _apply_incremental(self, added: list[str], removed: list[str]) -> None:
        """Update the sorted token/owner arrays in place for one batch of
        membership changes, without the global re-sort:

        1. renumber surviving owner ids through an old→new lookup table
           (server ids are positions in the sorted server list, so one
           add/remove shifts every later id).  The renumbering is
           STRICTLY MONOTONE over survivors — both lists are sorted, so
           relative order is preserved — which is what keeps the masked
           survivors in composite (token, owner) order with no tie
           repair inside equal-token runs;
        2. mask out removed servers' rows;
        3. merge-insert the added servers' pre-sorted token blocks at their
           ``searchsorted`` positions.

        Bit-identical to :meth:`_rebuild` by construction, pinned by
        ``tests/test_hashring.py`` against randomized churn sequences
        including collision-heavy token spaces."""
        # a server in BOTH lists of one batch (added then removed — e.g. a
        # flapping node in one SWIM membership update) is a net no-op: it
        # is no longer in _server_tokens, so it must not reach the
        # merge-insert (the event still reports both legs, as the rebuild
        # path always did)
        added = [s for s in added if s in self._server_tokens]
        old_servers = self._server_list
        new_servers = sorted(self._server_tokens)
        new_index = {s: i for i, s in enumerate(new_servers)}
        if old_servers:
            lut = np.array(
                [new_index.get(s, -1) for s in old_servers], dtype=np.int64
            )
            mapped = lut[self._owners]
            keep = mapped >= 0
            kept_toks = self._tokens[keep]
            kept_owners = mapped[keep]
        else:
            kept_toks = np.empty(0, dtype=np.uint64)
            kept_owners = np.empty(0, dtype=np.int64)
        if added:
            a_srv = sorted(added)
            a_toks = np.concatenate([self._server_tokens[s] for s in a_srv])
            a_owners = np.repeat(
                np.array([new_index[s] for s in a_srv], dtype=np.int64),
                self.replica_points,
            )
            a_comp = (a_toks << np.uint64(32)) | a_owners.astype(np.uint64)
            a_order = np.argsort(a_comp, kind="stable")
            a_toks, a_owners, a_comp = a_toks[a_order], a_owners[a_order], a_comp[a_order]
            kept_comp = (kept_toks << np.uint64(32)) | kept_owners.astype(np.uint64)
            pos = np.searchsorted(kept_comp, a_comp, side="left")
            total = kept_toks.size + a_toks.size
            out_t = np.empty(total, dtype=np.uint64)
            out_o = np.empty(total, dtype=np.int64)
            a_target = pos + np.arange(a_toks.size)
            mask = np.ones(total, dtype=bool)
            mask[a_target] = False
            out_t[a_target] = a_toks
            out_o[a_target] = a_owners
            out_t[mask] = kept_toks
            out_o[mask] = kept_owners
        else:
            out_t, out_o = kept_toks, kept_owners
        self._server_list = new_servers
        self._tokens = out_t
        self._owners = out_o
        self._refresh_caches()

    def _hash_keys(self, keys: list[str]) -> np.ndarray:
        """uint32 hashes of ``keys`` under this ring's hash function — batch
        fast path for the default farm32, per-key call for a custom func."""
        if self.hashfunc is fingerprint32:
            return fingerprint32_many(keys)
        return np.array(
            [self.hashfunc(k) & 0xFFFFFFFF for k in keys], dtype=np.uint32
        )

    def _compute_checksum(self) -> None:
        old = self._checksum
        joined = ";".join(sorted(self._server_tokens))
        self._checksum = fingerprint32(joined.encode("utf-8"))
        self._emit(RingChecksumEvent(old_checksum=old, new_checksum=self._checksum))

    # -- mutation (parity: hashring.go:122-223) -----------------------------

    def add_server(self, address: str) -> bool:
        return self.add_remove_servers([address], [])

    def remove_server(self, address: str) -> bool:
        return self.add_remove_servers([], [address])

    def add_remove_servers(self, add: Iterable[str], remove: Iterable[str]) -> bool:
        """Batch add/remove; emits one RingChangedEvent
        (parity: ``hashring.go:192-223`` AddRemoveServers)."""
        with self._lock:
            added, removed = [], []
            for a in add or []:
                if a not in self._server_tokens:
                    self._tokens_for(a)
                    added.append(a)
            for r in remove or []:
                if r in self._server_tokens:
                    del self._server_tokens[r]
                    removed.append(r)
            if not added and not removed:
                return False
            self._apply_incremental(added, removed)
            self._compute_checksum()
            self._emit(RingChangedEvent(servers_added=added, servers_removed=removed))
            return True

    # -- queries ------------------------------------------------------------

    def has_server(self, address: str) -> bool:
        with self._lock:
            return address in self._server_tokens

    def servers(self) -> list[str]:
        with self._lock:
            return sorted(self._server_tokens)

    def server_count(self) -> int:
        with self._lock:
            return len(self._server_tokens)

    def checksum(self) -> int:
        with self._lock:
            return self._checksum

    def lookup(self, key: str) -> Optional[str]:
        """Owner of ``key`` (parity: ``hashring.go:260-266``)."""
        owners = self.lookup_n(key, 1)
        return owners[0] if owners else None

    def lookup_n(self, key: str, n: int) -> list[str]:
        """N unique owners walking the ring upward from farm32(key) with
        wraparound, in ring order (parity: ``hashring.go:271-301``; the
        reference returns map order — ring order here is deterministic)."""
        return self._lookup_n_hash(self.hashfunc(key) & 0xFFFFFFFF, n)

    def _lookup_n_hash(self, h: int, n: int) -> list[str]:
        """The exact ring walk from a precomputed 32-bit hash — the oracle
        the device op (``ops/ring_ops.py`` ring_lookup_n) is tested against."""
        with self._lock:
            nservers = len(self._server_list)
            if nservers == 0 or n <= 0:
                return []
            if n == 1:
                # single-owner fast path: the first token >= h owns the key,
                # no uniqueness walk needed (the app data-path hot call,
                # SURVEY §3.4)
                toks = self._tokens_list
                if not toks:  # servers with replica_points=0 -> no tokens
                    return []
                idx = bisect.bisect_left(toks, h)
                if idx == len(toks):
                    idx = 0
                return [self._server_list[self._owners_list[idx]]]
            if n >= nservers:
                # walk order from the key for determinism, all servers
                n = nservers
            start = int(np.searchsorted(self._tokens, np.uint64(h), side="left"))
            out: list[str] = []
            seen: set[int] = set()
            t = self._tokens.shape[0]
            for i in range(t):
                owner = int(self._owners[(start + i) % t])
                if owner not in seen:
                    seen.add(owner)
                    out.append(self._server_list[owner])
                    if len(out) == n:
                        break
            return out

    def lookup_n_batch(self, keys: list[str], n: int) -> list[list[str]]:
        """Exact N-owner walk for many keys in one native call — the batched
        preference-list path the replicator's fan-out uses (parity:
        ``hashring.go:271-301``, batched).  Each row is ``lookup_n(key, n)``."""
        with self._lock:
            if not self._server_list or not keys or n <= 0:
                return [[] for _ in keys]
            # clamp like lookup_n does — the output buffer is [nkeys, n]
            n = min(n, len(self._server_list))
            rows = ring_lookup_n_batch(
                self._tokens32,
                self._owners32,
                len(self._server_list),
                self._hash_keys(keys),
                n,
            )
            return [
                [self._server_list[int(o)] for o in row if o >= 0] for row in rows
            ]

    def lookup_batch(self, keys: list[str]) -> list[Optional[str]]:
        """Vectorized single-owner lookup for many keys at once — the batched
        fast path the rbtree could never offer."""
        with self._lock:
            if not self._server_list or not self._tokens.shape[0]:
                return [None] * len(keys)
            hashes = self._hash_keys(keys).astype(np.uint64)
            idx = np.searchsorted(self._tokens, hashes, side="left")
            idx = np.where(idx == self._tokens.shape[0], 0, idx)
            owners = self._owners[idx]
            return [self._server_list[int(o)] for o in owners]

    # -- raw arrays for the TPU ops path ------------------------------------

    def token_arrays(self) -> tuple[np.ndarray, np.ndarray, list[str]]:
        """(tokens uint32-sorted-as-uint64, owner-ids, server list) snapshot
        for handoff to ``ringpop_tpu.ops.ring_ops`` device-side lookup."""
        with self._lock:
            return self._tokens.copy(), self._owners.copy(), list(self._server_list)


def new(hashfunc: Optional[Callable] = None, replica_points: int = 100) -> HashRing:
    return HashRing(hashfunc, replica_points)
