"""Named-logger facility (parity: reference ``logging/facility.go:68-100``).

One underlying logger; per-name minimum levels settable at runtime.  Built on
the stdlib ``logging`` module rather than a bespoke backend — the reference's
``bark`` facade maps 1:1 onto stdlib levels.
"""

from __future__ import annotations

import logging as _stdlog
import threading
from typing import Optional

_LEVELS = {
    "debug": _stdlog.DEBUG,
    "info": _stdlog.INFO,
    "warn": _stdlog.WARNING,
    "warning": _stdlog.WARNING,
    "error": _stdlog.ERROR,
    "fatal": _stdlog.CRITICAL,
    "off": _stdlog.CRITICAL + 10,
}


def parse_level(name: str) -> int:
    """Parse a level name (parity: ``logging/level.go``)."""
    try:
        return _LEVELS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown log level {name!r}")


class Facility:
    """Per-name min-level dispatch over one base logger
    (parity: ``logging/facility.go``)."""

    def __init__(self, base: Optional[_stdlog.Logger] = None):
        self._base = base or _stdlog.getLogger("ringpop")
        self._levels: dict[str, int] = {}
        self._lock = threading.RLock()

    def set_logger(self, base: _stdlog.Logger) -> None:
        with self._lock:
            self._base = base

    def set_level(self, name: str, level: int | str) -> None:
        if isinstance(level, str):
            level = parse_level(level)
        with self._lock:
            self._levels[name] = level

    def set_levels(self, levels: dict[str, int | str]) -> None:
        for k, v in levels.items():
            self.set_level(k, v)

    def min_level(self, name: str) -> int:
        with self._lock:
            return self._levels.get(name, _stdlog.ERROR)

    def logger(self, name: str) -> "NamedLogger":
        return NamedLogger(self, name)

    def log(self, name: str, level: int, msg: str, *args, **fields) -> None:
        if level < self.min_level(name):
            return
        extra = f" {fields}" if fields else ""
        self._base.log(level, f"[{name}] {msg}{extra}", *args)


class NamedLogger:
    """Logger bound to a facility name (parity: ``logging/named.go``)."""

    def __init__(self, facility: Facility, name: str, fields: Optional[dict] = None):
        self._facility = facility
        self.name = name
        self._fields = fields or {}

    def with_field(self, key, value) -> "NamedLogger":
        f = dict(self._fields)
        f[key] = value
        return NamedLogger(self._facility, self.name, f)

    def with_fields(self, **fields) -> "NamedLogger":
        f = dict(self._fields)
        f.update(fields)
        return NamedLogger(self._facility, self.name, f)

    def _log(self, level: int, msg: str, *args) -> None:
        self._facility.log(self.name, level, msg, *args, **self._fields)

    def debug(self, msg: str, *args) -> None:
        self._log(_stdlog.DEBUG, msg, *args)

    def info(self, msg: str, *args) -> None:
        self._log(_stdlog.INFO, msg, *args)

    def warn(self, msg: str, *args) -> None:
        self._log(_stdlog.WARNING, msg, *args)

    warning = warn

    def error(self, msg: str, *args) -> None:
        self._log(_stdlog.ERROR, msg, *args)


_default = Facility()


def logger(name: str) -> NamedLogger:
    """Package-global named logger (parity: ``logging/default.go``)."""
    return _default.logger(name)


def set_logger(base: _stdlog.Logger) -> None:
    _default.set_logger(base)


def set_level(name: str, level: int | str) -> None:
    _default.set_level(name, level)


def set_levels(levels: dict) -> None:
    _default.set_levels(levels)
