"""Native (C++) host-plane runtime core, loaded via ctypes.

The reference keeps its hot host paths in compiled Go; our host plane keeps
them in a small C++ library (``farmhash.cpp``): scalar + batch FarmHash
Fingerprint32 and the hashring token builder (parity:
``hashring/hashring.go:148-154``, ``swim/memberlist.go:86``).  The library is
compiled lazily with ``g++`` on first use and cached next to this file; every
entry point has a pure-Python/numpy fallback in ``ringpop_tpu.hashing.farm``,
so the framework works without a toolchain (set ``RINGPOP_TPU_NO_NATIVE=1``
to force the fallback).

ctypes releases the GIL for the duration of each call, so batch hashing can
additionally be driven from a thread pool by callers that want host-core
parallelism.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterable, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "farmhash.cpp")
_SO = os.path.join(_DIR, "_rpnative.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # compile to a per-pid temp path and rename into place: concurrent
    # builders may race but each rename publishes a complete library
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, _SO)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return os.path.exists(_SO)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        try:
            _lib = _try_load()
        finally:
            _tried = True
        return _lib


def _try_load() -> Optional[ctypes.CDLL]:
    if os.environ.get("RINGPOP_TPU_NO_NATIVE"):
        return None
    src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0.0
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime:
        if not _build():
            return None
    try:
        return _bind(ctypes.CDLL(_SO))
    except OSError:
        return None
    except AttributeError:
        # a cached .so from an older source revision can pass the mtime
        # staleness check (same-second checkout, archive/copy tools that
        # preserve mtimes) yet miss newer symbols — rebuild once, and fall
        # back to the pure-Python backend rather than raise if that fails
        if not _build():
            return None
        try:
            return _bind(ctypes.CDLL(_SO))
        except (OSError, AttributeError):
            return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(dtype=np.uint64, flags="C_CONTIGUOUS")
    u32p = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")
    lib.rp_fingerprint32.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rp_fingerprint32.restype = ctypes.c_uint32
    lib.rp_fingerprint32_batch.argtypes = [u8p, u64p, ctypes.c_uint64, u32p]
    lib.rp_fingerprint32_batch.restype = None
    lib.rp_ring_tokens.argtypes = [u8p, u64p, ctypes.c_uint64, ctypes.c_uint32, u32p]
    lib.rp_ring_tokens.restype = None
    i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    lib.rp_membership_checksum.argtypes = [u8p, u64p, ctypes.c_uint64]
    lib.rp_membership_checksum.restype = ctypes.c_uint32
    lib.rp_ring_lookup_n.argtypes = [
        u32p,  # tokens (sorted)
        u32p,  # owners
        ctypes.c_uint64,  # ntokens
        ctypes.c_uint32,  # n_servers
        u32p,  # hashes
        ctypes.c_uint64,  # nkeys
        ctypes.c_uint32,  # nwant
        i32p,  # out [nkeys, nwant]
    ]
    lib.rp_ring_lookup_n.restype = None
    return lib


def available() -> bool:
    return _load() is not None


def fingerprint32(data: bytes) -> int:
    """Scalar native hash; caller guarantees :func:`available`."""
    lib = _load()
    return int(lib.rp_fingerprint32(data, len(data)))


def _pack(strings: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray]:
    offsets = np.zeros(len(strings) + 1, dtype=np.uint64)
    np.cumsum([len(s) for s in strings], out=offsets[1:])
    buf = np.frombuffer(b"".join(strings), dtype=np.uint8) if strings else np.empty(0, np.uint8)
    return buf, offsets


def fingerprint32_many(strings: Iterable[str | bytes]) -> np.ndarray:
    """Batch native hash over arbitrary strings -> uint32[n]."""
    bs = [s.encode("utf-8") if isinstance(s, str) else s for s in strings]
    lib = _load()
    buf, offsets = _pack(bs)
    out = np.empty(len(bs), dtype=np.uint32)
    if len(bs):
        lib.rp_fingerprint32_batch(buf, offsets, len(bs), out)
    return out


def ring_tokens(servers: Sequence[str], replica_points: int) -> np.ndarray:
    """uint32[n_servers, replica_points] of farm32(addr + str(i)) — the ring
    build hot path in one native call."""
    lib = _load()
    bs = [s.encode("utf-8") for s in servers]
    buf, offsets = _pack(bs)
    out = np.empty(len(bs) * replica_points, dtype=np.uint32)
    if len(bs):
        lib.rp_ring_tokens(buf, offsets, len(bs), replica_points, out)
    return out.reshape(len(bs), replica_points)


def membership_checksum(entries: Sequence[str | bytes]) -> int:
    """farm32 over the canonical sorted-and-';'-joined member entries — one
    native call replacing the host-side sort + join + hash (parity:
    ``swim/memberlist.go:106-128``)."""
    bs = [e.encode("utf-8") if isinstance(e, str) else e for e in entries]
    lib = _load()
    buf, offsets = _pack(bs)
    return int(lib.rp_membership_checksum(buf, offsets, len(bs)))


def ring_lookup_n_batch(
    tokens: np.ndarray,
    owners: np.ndarray,
    n_servers: int,
    hashes: np.ndarray,
    nwant: int,
) -> np.ndarray:
    """Exact batched N-owner ring walk -> int32[nkeys, nwant] of server
    indices, -1-padded when the ring has fewer than ``nwant`` servers
    (parity: ``hashring.go:271-301``)."""
    lib = _load()
    nwant = max(nwant, 0)
    tokens32 = np.ascontiguousarray(tokens, dtype=np.uint32)
    owners32 = np.ascontiguousarray(owners, dtype=np.uint32)
    hashes32 = np.ascontiguousarray(hashes, dtype=np.uint32)
    out = np.empty((hashes32.shape[0], nwant), dtype=np.int32)
    if hashes32.shape[0] and nwant:
        lib.rp_ring_lookup_n(
            tokens32,
            owners32,
            tokens32.shape[0],
            n_servers,
            hashes32,
            hashes32.shape[0],
            nwant,
            out,
        )
    return out
