// Native host-plane hash core: FarmHash Fingerprint32 (farmhashmk::Hash32).
//
// This is the hash the reference uses everywhere (dgryski/go-farm
// Fingerprint32: ring tokens hashring/hashring.go:107, membership checksum
// swim/memberlist.go:86, facade ring ringpop.go:172).  Implemented from the
// published algorithm — the same routine as the pure-Python semantic
// reference in ringpop_tpu/hashing/farm.py, which the tests cross-check
// against this library byte-for-byte.
//
// Exposed C ABI (consumed via ctypes from ringpop_tpu.native):
//   rp_fingerprint32        — one string
//   rp_fingerprint32_batch  — packed concatenated strings (offsets[n+1])
//   rp_ring_tokens          — farm32(addr + decimal(i)) for every (server,
//                             replica) pair: the hashring build hot path
//                             (parity: hashring.go:148-154)
//   rp_membership_checksum  — sort member entry strings, join with ';',
//                             farm32 the canonical form: the membership
//                             checksum hot path (parity: memberlist.go:106-128)
//   rp_ring_lookup_n        — exact N-unique-owner ring walk for a batch of
//                             key hashes (parity: hashring.go:271-301,
//                             rbtree.go:262-288)
//
// Build: g++ -O3 -shared -fPIC -o _rpnative.so farmhash.cpp
// (done lazily by ringpop_tpu/native/__init__.py)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <numeric>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xcc9e2d51u;
constexpr uint32_t C2 = 0x1b873593u;

inline uint32_t ror32(uint32_t v, int s) {
  return s == 0 ? v : (v >> s) | (v << (32 - s));
}

inline uint32_t fmix(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

inline uint32_t mur(uint32_t a, uint32_t h) {
  a *= C1;
  a = ror32(a, 17);
  a *= C2;
  h ^= a;
  h = ror32(h, 19);
  return h * 5 + 0xe6546b64u;
}

inline uint32_t fetch32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);  // little-endian hosts only (x86-64 / aarch64)
  return v;
}

uint32_t hash32_len_0_to_4(const uint8_t* data, uint64_t n, uint32_t seed) {
  uint32_t b = seed;
  uint32_t c = 9;
  for (uint64_t i = 0; i < n; i++) {
    int8_t v = static_cast<int8_t>(data[i]);  // signed char semantics
    b = b * C1 + static_cast<uint32_t>(static_cast<int32_t>(v));
    c ^= b;
  }
  return fmix(mur(b, mur(static_cast<uint32_t>(n), c)));
}

uint32_t hash32_len_5_to_12(const uint8_t* data, uint64_t n, uint32_t seed) {
  uint32_t a = static_cast<uint32_t>(n), b = a * 5, c = 9, d = b + seed;
  a += fetch32(data);
  b += fetch32(data + n - 4);
  c += fetch32(data + ((n >> 1) & 4));
  return fmix(seed ^ mur(c, mur(b, mur(a, d))));
}

uint32_t hash32_len_13_to_24(const uint8_t* data, uint64_t n, uint32_t seed) {
  uint32_t a = fetch32(data + (n >> 1) - 4);
  uint32_t b = fetch32(data + 4);
  uint32_t c = fetch32(data + n - 8);
  uint32_t d = fetch32(data + (n >> 1));
  uint32_t e = fetch32(data);
  uint32_t f = fetch32(data + n - 4);
  uint32_t h = d * C1 + static_cast<uint32_t>(n) + seed;
  a = ror32(a, 12) + f;
  h = mur(c, h) + a;
  a = ror32(a, 3) + c;
  h = mur(e, h) + a;
  a = ror32(a + f, 12) + d;
  h = mur(b ^ seed, h) + a;
  return fmix(h);
}

uint32_t hash32(const uint8_t* data, uint64_t n) {
  if (n <= 4) return hash32_len_0_to_4(data, n, 0);
  if (n <= 12) return hash32_len_5_to_12(data, n, 0);
  if (n <= 24) return hash32_len_13_to_24(data, n, 0);

  uint32_t h = static_cast<uint32_t>(n), g = C1 * h, f = g;
  uint32_t a0 = ror32(fetch32(data + n - 4) * C1, 17) * C2;
  uint32_t a1 = ror32(fetch32(data + n - 8) * C1, 17) * C2;
  uint32_t a2 = ror32(fetch32(data + n - 16) * C1, 17) * C2;
  uint32_t a3 = ror32(fetch32(data + n - 12) * C1, 17) * C2;
  uint32_t a4 = ror32(fetch32(data + n - 20) * C1, 17) * C2;
  h ^= a0;
  h = ror32(h, 19);
  h = h * 5 + 0xe6546b64u;
  h ^= a2;
  h = ror32(h, 19);
  h = h * 5 + 0xe6546b64u;
  g ^= a1;
  g = ror32(g, 19);
  g = g * 5 + 0xe6546b64u;
  g ^= a3;
  g = ror32(g, 19);
  g = g * 5 + 0xe6546b64u;
  f += a4;
  f = ror32(f, 19) + 113;
  uint64_t iters = (n - 1) / 20;
  const uint8_t* p = data;
  do {
    uint32_t a = fetch32(p);
    uint32_t b = fetch32(p + 4);
    uint32_t c = fetch32(p + 8);
    uint32_t d = fetch32(p + 12);
    uint32_t e = fetch32(p + 16);
    h += a;
    g += b;
    f += c;
    h = mur(d, h) + e;
    g = mur(c, g) + a;
    f = mur(b + e * C1, f) + d;
    f += g;
    g += f;
    p += 20;
  } while (--iters != 0);
  g = ror32(g, 11) * C1;
  g = ror32(g, 17) * C1;
  f = ror32(f, 11) * C1;
  f = ror32(f, 17) * C1;
  h = ror32(h + g, 19);
  h = h * 5 + 0xe6546b64u;
  h = ror32(h, 17) * C1;
  h = ror32(h + f, 19);
  h = h * 5 + 0xe6546b64u;
  h = ror32(h, 17) * C1;
  return h;
}

}  // namespace

extern "C" {

uint32_t rp_fingerprint32(const uint8_t* data, uint64_t len) {
  return hash32(data, len);
}

// strings i lives at buf[offsets[i] : offsets[i+1]]; offsets has n+1 entries
void rp_fingerprint32_batch(const uint8_t* buf, const uint64_t* offsets,
                            uint64_t n, uint32_t* out) {
  for (uint64_t i = 0; i < n; i++) {
    out[i] = hash32(buf + offsets[i], offsets[i + 1] - offsets[i]);
  }
}

// out has n_servers * replica_points entries, row-major by server:
// out[s * replica_points + r] = farm32(server_s + decimal(r))
void rp_ring_tokens(const uint8_t* buf, const uint64_t* offsets,
                    uint64_t n_servers, uint32_t replica_points,
                    uint32_t* out) {
  std::vector<uint8_t> tmp;
  for (uint64_t s = 0; s < n_servers; s++) {
    uint64_t len = offsets[s + 1] - offsets[s];
    tmp.resize(len + 24);
    std::memcpy(tmp.data(), buf + offsets[s], len);
    for (uint32_t r = 0; r < replica_points; r++) {
      int d = std::snprintf(reinterpret_cast<char*>(tmp.data()) + len, 24,
                            "%u", r);
      out[s * replica_points + r] = hash32(tmp.data(), len + d);
    }
  }
}

// Membership checksum: entries are the unsorted per-member canonical strings
// ("addr+status+incarnation", tombstones pre-filtered by the caller); this
// sorts them lexicographically, joins each with a trailing ';', and returns
// farm32 of the joined form — byte-identical to hashing the string that
// memberlist.gen_checksum_string() builds (parity: memberlist.go:106-128).
uint32_t rp_membership_checksum(const uint8_t* buf, const uint64_t* offsets,
                                uint64_t n) {
  std::vector<uint32_t> order(n);
  std::iota(order.begin(), order.end(), 0u);
  std::sort(order.begin(), order.end(), [&](uint32_t a, uint32_t b) {
    uint64_t la = offsets[a + 1] - offsets[a];
    uint64_t lb = offsets[b + 1] - offsets[b];
    int c = std::memcmp(buf + offsets[a], buf + offsets[b],
                        la < lb ? la : lb);
    if (c != 0) return c < 0;
    return la < lb;
  });
  uint64_t total = offsets[n] + n;  // all bytes + one ';' per entry
  std::vector<uint8_t> joined;
  joined.reserve(total);
  for (uint64_t i = 0; i < n; i++) {
    uint32_t e = order[i];
    joined.insert(joined.end(), buf + offsets[e], buf + offsets[e + 1]);
    joined.push_back(';');
  }
  return hash32(joined.data(), joined.size());
}

// Exact ring walk for a batch of precomputed key hashes: for each hash,
// binary-search the first token >= hash, then walk forward (with wraparound)
// collecting the first `nwant` distinct owners in ring order.  Owner indices
// land in out[k * nwant + j]; rows are padded with -1 when the ring holds
// fewer than nwant distinct servers.  A stamp array replaces a per-query
// seen-set so the walk is allocation-free per key.
void rp_ring_lookup_n(const uint32_t* tokens, const uint32_t* owners,
                      uint64_t ntokens, uint32_t n_servers,
                      const uint32_t* hashes, uint64_t nkeys, uint32_t nwant,
                      int32_t* out) {
  std::vector<uint64_t> stamp(n_servers, ~0ull);
  for (uint64_t k = 0; k < nkeys; k++) {
    int32_t* row = out + k * nwant;
    uint32_t found = 0;
    if (ntokens != 0 && n_servers != 0) {
      const uint32_t* lb =
          std::lower_bound(tokens, tokens + ntokens, hashes[k]);
      uint64_t start = static_cast<uint64_t>(lb - tokens) % ntokens;
      uint32_t want = nwant < n_servers ? nwant : n_servers;
      for (uint64_t i = 0; i < ntokens && found < want; i++) {
        uint32_t owner = owners[(start + i) % ntokens];
        if (stamp[owner] != k) {
          stamp[owner] = k;
          row[found++] = static_cast<int32_t>(owner);
        }
      }
    }
    for (uint32_t j = found; j < nwant; j++) row[j] = -1;
  }
}

}  // extern "C"
