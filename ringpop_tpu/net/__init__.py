from ringpop_tpu.net.channel import (
    CallError,
    RemoteError,
    CallTimeoutError,
    PeerUnreachableError,
    BaseChannel,
    TCPChannel,
    LocalNetwork,
    LocalChannel,
    encode_array,
    decode_array,
)

__all__ = [
    "CallError",
    "RemoteError",
    "CallTimeoutError",
    "PeerUnreachableError",
    "BaseChannel",
    "TCPChannel",
    "LocalNetwork",
    "LocalChannel",
    "encode_array",
    "decode_array",
]
