from ringpop_tpu.net.channel import (
    CallError,
    RemoteError,
    CallTimeoutError,
    BaseChannel,
    TCPChannel,
    LocalNetwork,
    LocalChannel,
)

__all__ = [
    "CallError",
    "RemoteError",
    "CallTimeoutError",
    "BaseChannel",
    "TCPChannel",
    "LocalNetwork",
    "LocalChannel",
]
