"""Distributed communication backend — JSON-RPC over asyncio TCP.

Parity: the reference's TChannel usage (``shared/interfaces.go``,
``shared/shared.go:11-22``).  The reference multiplexes three payload formats
over TChannel subchannels; here one framed JSON transport carries all traffic:

* protocol RPCs (``/protocol/{ping,ping-req,join}`` — json bodies, same
  schemas as ``swim/ping_sender.go:35-40`` etc.),
* forwarded app requests (opaque body + headers, the ``tchannel/raw`` path of
  ``forward/request_sender.go:148-204``),
* admin endpoints.

Design notes, mirroring reference decisions:
* transport-level retries are OFF — ringpop does its own retry/backoff
  (``shared/shared.go:11-22`` disables TChannel retries); a failed call
  surfaces as :class:`CallError` immediately.
* handlers are namespaced by (service, endpoint) — the subchannel equivalent
  (isolated ``ringpop`` subchannel, ``ringpop.go:163``).

Two implementations:
* :class:`TCPChannel` — real sockets on the fabric's RPC plane (r21:
  persistent per-peer links, vectored sends, pooled receive arenas —
  ``parallel/fabric.py`` owns the socket loop; this module owns only the
  frame-dict schema and the JSON/msgpack body encodings), request
  multiplexing by id.
* :class:`LocalChannel`/:class:`LocalNetwork` — in-process loopback with
  first-class fault injection (drop probability, partitions, black holes) —
  the test-harness analog of the reference's RFC-5737 black-hole addresses
  (``swim/test_utils.go:219-227``) but deterministic.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import os
import random
import threading
from typing import Awaitable, Callable, Optional

from ringpop_tpu import logging as logging_mod

_logger = logging_mod.logger("net")

Handler = Callable[[dict, dict], Awaitable[dict]]

# one shared compact encoder for every frame: json.dumps() rebuilds an
# encoder per call and emits spaces after separators; reusing a configured
# JSONEncoder cuts per-frame CPU and bytes on the wire.  ensure_ascii stays
# True: error strings can carry surrogateescape-decoded bytes (e.g. OSError
# filenames) that \\uXXXX-escape fine but crash a strict utf-8 encode.
_encode_frame = json.JSONEncoder(separators=(",", ":")).encode


def _frame_bytes(frame: dict) -> bytes:
    data = _encode_frame(frame).encode("ascii") + b"\n"
    if len(data) > MAX_FRAME_BYTES:
        # fail fast at the SENDER with the actual cause — the receiver would
        # otherwise just drop the connection with a generic close
        raise ValueError(f"frame of {len(data)} bytes exceeds MAX_FRAME_BYTES")
    return data


# -- wire codecs -------------------------------------------------------------
#
# Two frame encodings share one socket format, distinguished by the first
# byte so mixed-codec clusters interoperate (each side *sends* its configured
# codec and *reads* whatever arrives):
#
# * JSON (default): one compact object per line, first byte always ``{`` —
#   the reference-parity wire (the golden corpus in tests/golden pins it).
# * msgpack (opt-in): ``0xC1`` magic + uint32-be length + msgpack payload.
#   0xC1 is the one byte the msgpack spec reserves as "never used", and no
#   JSON frame can start with it.  ~2-3x cheaper to encode/decode than JSON
#   for the small protocol bodies, which is material at forwarding qps.
#
# Select per-channel via ``TCPChannel(codec="msgpack")`` or process-wide via
# ``RINGPOP_TPU_WIRE=msgpack``.

_MSGPACK_MAGIC = b"\xc1"

try:
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - baked into this image, but optional
    _msgpack = None


def _msgpack_frame_bytes(frame: dict) -> bytes:
    payload = _msgpack.packb(frame, use_bin_type=True)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES")
    return _MSGPACK_MAGIC + len(payload).to_bytes(4, "big") + payload


def _encoder_for(codec: str):
    if codec == "msgpack":
        if _msgpack is None:
            raise ValueError("msgpack codec requested but msgpack is not importable")
        return _msgpack_frame_bytes
    if codec == "json":
        return _frame_bytes
    raise ValueError(f"unknown wire codec {codec!r} (expected 'json' or 'msgpack')")


def default_codec() -> str:
    return os.environ.get("RINGPOP_TPU_WIRE", "json")


_warned_msgpack_missing = False

# one frame (either codec) may not exceed this — bounds what a desynced or
# malicious peer can make the reader buffer, while leaving room for the
# biggest legitimate payload (a full-sync membership of a very large host
# cluster).  Also used as the StreamReader limit so long JSON lines work
# (asyncio's 64 KiB default would break large full syncs).
MAX_FRAME_BYTES = 64 * 1024 * 1024

def _decode_frame_body(data) -> Optional[dict]:
    """Decode one frame body of either encoding; None on garbage.

    r21: the fabric RPC plane delimits bodies exactly (one body per
    transport frame), so this is pure decode — no stream reading.  The
    first byte keeps the mixed-codec auto-detection (``{`` = JSON object,
    ``0xC1`` = msgpack magic + uint32-be length) byte-compatible with the
    pre-fold frame format, so the golden corpus and mixed-codec clusters
    are unaffected.  ``data`` may be a memoryview into a pooled receive
    arena — it is only valid for the duration of the call, and both
    decoders materialize fresh objects from it."""
    if len(data) == 0:
        return None
    first = data[0]
    if first == 0x7B:  # "{" — one compact JSON object (+ trailing newline)
        try:
            frame = json.loads(bytes(data))
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None
        return frame if isinstance(frame, dict) else None
    if first == 0xC1:  # _MSGPACK_MAGIC
        if len(data) < 5:
            return None
        ln = int.from_bytes(bytes(data[1:5]), "big")
        if ln > MAX_FRAME_BYTES or len(data) < 5 + ln:
            return None
        if _msgpack is None:
            # fail LOUDLY: dropping the connection surfaces the
            # misconfiguration to the peer as a hard failure immediately,
            # where skipping frames would blackhole its requests into
            # timeouts (an asymmetric partition SWIM would churn on)
            global _warned_msgpack_missing
            if not _warned_msgpack_missing:
                _warned_msgpack_missing = True
                _logger.warning(
                    "received a msgpack frame but msgpack is not importable "
                    "here; closing connections from msgpack-codec peers"
                )
            return None
        try:
            frame = _msgpack.unpackb(data[5:5 + ln], raw=False)
        except Exception:
            return None
        return frame if isinstance(frame, dict) else None
    return None  # unknown framing — treat as a broken peer


# -- array payload helpers ---------------------------------------------------
#
# The serve tier ships key-hash batches and owner vectors in frame bodies;
# as JSON int lists a 4096-key batch costs ~44 KB and a slow parse.  These
# helpers carry fixed-width little-endian arrays under EITHER codec: raw
# bytes when the frame is msgpack (bin type, zero re-encode), base64 text
# when it is JSON (~1.33x the raw bytes, one C-accelerated decode).  The
# decoder is self-describing on the value type, so mixed-codec
# client/server pairs interoperate like the frames themselves do.
#
# r17 (the unified-transport slice): arrays can additionally ride the
# FABRIC's r15 wire codec (``parallel/fabric.py`` — zero-row/zero-run
# suppression with a measured raw fallback): ``encode_array(...,
# fabric=True)`` wraps the fabric-framed payload in a one-key dict, so
# ``decode_array`` stays self-describing (a dict value IS a fabric
# array; bytes/str stay the plain little-endian lanes) and forwarded
# batches get the serve mesh's codec for free over the SAME endpoints.

_FABRIC_ARRAY_KEY = "_fab"

# r21 (one transport plane): the fabric's codec stack is IMPORTED, not
# re-implemented — channel.py owns no array codec and no socket loop.
# ``frame_array``/``unframe_array`` are the same bytes an array costs
# inside a fabric exchange message; ``RpcEndpoint`` is the persistent-link
# transport TCPChannel rides; ``TransportLedger`` is the merged per-class
# byte ledger.  parallel.fabric is numpy-only (parallel/__init__ is lazy),
# so this import keeps frontends jax-free (pinned by
# tests/test_unified_transport.py).
from ringpop_tpu.parallel.fabric import (  # noqa: E402
    RpcEndpoint,
    TransportLedger,
    frame_array,
    unframe_array,
)


def encode_array(arr, codec: str, dtype: str = "<u4", fabric: bool = False):
    """A frame-body value for a numeric array under ``codec``.
    ``fabric=True`` routes the payload through the fabric's r15 wire
    codec instead of the plain little-endian lane (dtype/shape become
    self-describing; sparse payloads shrink, dense ones pay only the
    measured-fallback header)."""
    import numpy as _np

    if fabric:
        data = frame_array(_np.asarray(arr, dtype=dtype))
        if codec == "msgpack":
            return {_FABRIC_ARRAY_KEY: data}
        import base64 as _b64

        return {_FABRIC_ARRAY_KEY: _b64.b64encode(data).decode("ascii")}
    data = _np.ascontiguousarray(_np.asarray(arr), dtype=dtype).tobytes()
    if codec == "msgpack":
        return data
    import base64 as _b64

    return _b64.b64encode(data).decode("ascii")


def decode_array(value, dtype: str = "<u4"):
    """Inverse of :func:`encode_array` (accepts every representation —
    plain bytes, base64 text, or the fabric-coded dict; mixed-codec and
    mixed-lane client/server pairs interoperate)."""
    import numpy as _np

    if isinstance(value, dict):
        data = value[_FABRIC_ARRAY_KEY]
        if not isinstance(data, (bytes, bytearray, memoryview)):
            import base64 as _b64

            data = _b64.b64decode(data)
        out = unframe_array(bytes(data))
        # fabric frames carry their own dtype; the caller's expectation
        # reinterprets (two's-complement view, same as the plain lane's
        # frombuffer) rather than converting
        out = out.reshape(-1)
        return out if out.dtype.str == dtype else out.view(_np.dtype(dtype))
    if isinstance(value, (bytes, bytearray, memoryview)):
        data = bytes(value)
    else:
        import base64 as _b64

        data = _b64.b64decode(value)
    return _np.frombuffer(data, dtype=dtype)


# the r17 unified error model: channel failures ARE fabric failures —
# one peer-lifecycle/error family across the serve TCP framing, the shm
# ring and the DCN fabric, so callers branch on FabricTimeout /
# FabricPeerLost semantics regardless of which transport carried the
# request.  The family lives in the import-free leaf ringpop_tpu.errors
# (NOT parallel.fabric — importing anything under parallel executes its
# __init__ and drags jax into every frontend that imports this module).
from ringpop_tpu.errors import (  # noqa: E402
    FabricError,
    FabricPeerLost,
    FabricTimeout,
)

# span tracing (r20): the header constant + salt helper are jax-free
# (obs/trace.py) — BaseChannel.dispatch emits a transport-level server
# span for requests that arrive with the ringpop-trace header
from ringpop_tpu.obs.trace import TRACE_HEADER, salt_of  # noqa: E402


class CallError(FabricError):
    """A call failed to complete (network error, black hole, timeout)."""


class CallTimeoutError(CallError, FabricTimeout):
    """Nothing answered within the deadline — the channel flavor of a
    silent peer (``FabricTimeout``)."""


class PeerUnreachableError(CallError, FabricPeerLost):
    """Connect refused / connection dropped — the channel flavor of a
    dead peer (``FabricPeerLost``)."""


class RemoteError(CallError):
    """The remote handler raised; carries the remote error message."""


class BaseChannel:
    """Handler registry + dispatch shared by both transports.

    ``tracer`` (an ``obs.trace.Tracer``; default None = off) emits one
    ``kind:"span"`` record per dispatched request that arrived with the
    ``ringpop-trace`` header — the transport-level server leg, between
    the sender's RPC span (its parent, from the header) and whatever the
    handler itself traces.  The sampling decision was the CALLER's: a
    headerless request costs one dict lookup and nothing else."""

    def __init__(self, app: str = ""):
        self.app = app
        self.hostport: str = ""
        self._handlers: dict[tuple[str, str], Handler] = {}
        self.tracer = None

    def register(self, service: str, endpoint: str, handler: Handler) -> None:
        self._handlers[(service, endpoint)] = handler

    def registered_endpoints(self) -> list[tuple[str, str]]:
        return sorted(self._handlers)

    async def dispatch(self, service: str, endpoint: str, body: dict, headers: dict) -> dict:
        handler = self._handlers.get((service, endpoint))
        if handler is None:
            raise RemoteError(f"no handler for {service}::{endpoint}")
        span = None
        if self.tracer is not None and TRACE_HEADER in (headers or {}):
            # the header gate keeps untraced requests at ONE dict lookup
            # (the documented cost) — salt hashing only runs for traced
            # ones.  hops rides the salt so the same endpoint serving
            # the same trace at two hop levels gets two distinct span
            # ids (the parent folded into the id covers the rest).
            span = self.tracer.follow(
                headers, "server",
                salt=salt_of(self.hostport, endpoint,
                             str(headers.get("ringpop-hops", ""))),
                endpoint=endpoint, service=service, hostport=self.hostport,
            )
        try:
            res = handler(body, headers)
            if inspect.isawaitable(res):  # sync handlers are fine too
                res = await res
        except Exception as e:
            if span is not None:
                span.finish(ok=False, error=str(e))
            raise
        if span is not None:
            span.finish(ok=True)
        return res

    async def call(
        self,
        peer: str,
        service: str,
        endpoint: str,
        body: dict,
        headers: Optional[dict] = None,
        timeout: Optional[float] = None,
    ) -> dict:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# TCP transport
# ---------------------------------------------------------------------------


class TCPChannel(BaseChannel):
    """Framed RPC channel on the fabric core (parity: TChannel peer pool,
    ``swim/ping_sender.go:83``).

    r21 (one transport plane): the channel no longer owns a socket loop —
    connection handling, framing, retry surface and the peer registry all
    live in the fabric's :class:`~ringpop_tpu.parallel.fabric.RpcEndpoint`
    (persistent per-link sender/reader threads, vectored sends, pooled
    receive arenas, sticky ``FabricError`` failures).  What remains here
    is the channel's SEMANTIC layer: the request/response frame-dict
    schema, the JSON/msgpack body encodings (unchanged bytes — the golden
    corpus and mixed-codec clusters are unaffected), handler dispatch,
    and the asyncio bridge (replies hop from reader threads onto the
    event loop via ``call_soon_threadsafe``).

    Wire format change vs pre-r21: each body now rides ONE fabric
    transport frame (16-byte ``_HDR``: RPC tag + request id, blob count,
    body length) instead of being self-delimiting on a bare socket.  The
    body bytes themselves are byte-identical.

    r23 latency tiers: plain-sync handlers dispatch directly on the
    link's reader thread (the server-side loop hop survives only for
    coroutine handlers and traced requests), and :meth:`call_sync` gives
    blocking callers inline completion — the reader thread fulfills a
    condition-variable future in place, zero event-loop hops end to end.
    ``flush_us`` enables small-frame coalescing on this endpoint's
    links; ``shm_lane`` negotiates the same-host shm frame lane;
    ``spin_us`` tunes the readers' spin-then-park window.  Every knob
    preserves the body bytes bit-for-bit — lanes move frames, never
    reshape them."""

    def __init__(self, app: str = "", codec: Optional[str] = None,
                 ledger: Optional[TransportLedger] = None, *,
                 flush_us: float = 0.0, shm_lane: Optional[bool] = None,
                 spin_us: Optional[float] = None):
        super().__init__(app)
        self.codec = codec or default_codec()
        self._encode = _encoder_for(self.codec)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # ``ledger`` merges this channel's wire bytes into a shared
        # per-class TransportLedger (class "rpc"); default = private.
        self._ep = RpcEndpoint(
            self._on_request, ledger=ledger, ledger_class="rpc",
            max_body_bytes=MAX_FRAME_BYTES,
            flush_us=flush_us, shm_lane=shm_lane, spin_us=spin_us,
        )
        # legacy frame-level accounting (the pre-r21 keys, body bytes
        # only): kept per-channel so existing journal consumers and the
        # monotone-sampling pins are unmoved.  r23: sync callers and
        # reader-thread dispatch bump these off the loop too, so the
        # counters take a lock (reads stay lock-free int snapshots).
        # The transport-level truth (incl. the 16 B/frame fabric header
        # and the receive side) is ``self.ledger.stats()``.
        self.bytes_sent = 0
        self.frames_sent = 0
        self._legacy_lock = threading.Lock()

    def _count_sent(self, nbytes: int) -> None:
        with self._legacy_lock:
            self.bytes_sent += nbytes
            self.frames_sent += 1

    @property
    def ledger(self) -> TransportLedger:
        return self._ep.ledger

    def wire_stats(self) -> dict:
        """Counter snapshot, shaped like ``Fabric.wire_stats`` so serve
        journals can state per-transport bytes the same way."""
        return {"bytes_sent": self.bytes_sent, "frames_sent": self.frames_sent}

    # -- server side --------------------------------------------------------

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._loop = asyncio.get_event_loop()
        self.hostport = self._ep.listen(host, port)
        return self.hostport

    def listen_sync(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Loop-less listen (r23): serve PLAIN-SYNC handlers entirely on
        the links' reader threads — no asyncio anywhere in the request
        path.  Coroutine handlers need :meth:`listen` (they have no loop
        to run on here; their requests would time out at the caller)."""
        self.hostport = self._ep.listen(host, port)
        return self.hostport

    async def close(self) -> None:
        # endpoint close joins link threads (bounded); keep it off the loop
        await asyncio.get_event_loop().run_in_executor(None, self._ep.close)

    def close_sync(self) -> None:
        """Blocking close for loop-less channels (``listen_sync`` /
        pure-``call_sync`` users)."""
        self._ep.close()

    def _on_request(self, link, rid: int, payload) -> None:
        """Inbound request, on the link's reader thread.  ``payload`` is a
        memoryview into the pooled arena — decode NOW.  r23: a plain-sync
        handler (untraced request) dispatches RIGHT HERE and responds
        inline — zero loop hops; coroutine handlers, traced requests and
        missing-handler errors keep the event-loop path."""
        frame = _decode_frame_body(payload)
        if frame is None:
            # garbage breaks only its own connection (pre-r21 reader
            # semantics): raising fails this link, nothing else
            raise FabricError("rpc request body undecodable — dropping the connection")
        handler = self._handlers.get((frame.get("svc", ""), frame.get("ep", "")))
        headers = frame.get("headers") or {}
        if (
            handler is not None
            and not inspect.iscoroutinefunction(handler)
            and (self.tracer is None or TRACE_HEADER not in headers)
        ):
            res = {"id": frame.get("id"), "kind": "res"}
            try:
                body = handler(frame.get("body") or {}, headers)
            except Exception as e:
                res["ok"] = False
                res["err"] = str(e)
            else:
                if inspect.isawaitable(body):
                    # a sync-def handler handed back an awaitable: only
                    # the loop can finish it
                    self._finish_awaitable(frame, link, rid, body)
                    return
                res["ok"] = True
                res["body"] = body
            self._respond(link, rid, res)
            return
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            asyncio.run_coroutine_threadsafe(self._serve_frame(frame, link, rid), loop)
        except RuntimeError:
            pass  # loop shut down mid-flight

    def _finish_awaitable(self, frame: dict, link, rid: int, body) -> None:
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        async def finish() -> None:
            res = {"id": frame.get("id"), "kind": "res"}
            try:
                res["body"] = await body
                res["ok"] = True
            except Exception as e:
                res["ok"] = False
                res["err"] = str(e)
            self._respond(link, rid, res)

        try:
            asyncio.run_coroutine_threadsafe(finish(), loop)
        except RuntimeError:
            pass

    async def _serve_frame(self, frame: dict, link, rid: int) -> None:
        res = {"id": frame.get("id"), "kind": "res"}
        try:
            body = await self.dispatch(
                frame.get("svc", ""), frame.get("ep", ""), frame.get("body") or {}, frame.get("headers") or {}
            )
            res["ok"] = True
            res["body"] = body
        except Exception as e:  # handler error propagates as app error
            res["ok"] = False
            res["err"] = str(e)
        self._respond(link, rid, res)

    def _respond(self, link, rid: int, res: dict) -> None:
        try:
            payload = self._encode(res)
        except Exception as e:
            # an unencodable handler result (or error string with surrogate
            # bytes under msgpack) must still produce a response — the JSON
            # encoder with ensure_ascii handles any str; never hang the caller.
            # The id itself may be the unencodable part (a msgpack peer can
            # send bytes ids): only pass through JSON-safe ids.
            rid_body = res.get("id")
            if not isinstance(rid_body, (str, int, float)):
                rid_body = None
            payload = _frame_bytes(
                {"id": rid_body, "kind": "res", "ok": False,
                 "err": f"response encode failed: {type(e).__name__}"}
            )
        # Count BEFORE handing the frame to the link: once respond() writes
        # the socket the client can observe the reply and read wire_stats()
        # from another thread — counting after the write races that read
        # (the ledger counts at write time and would show one more frame).
        self._count_sent(len(payload))
        link.respond(rid, payload)

    # -- client side --------------------------------------------------------

    async def _get_link(self, peer: str):
        link = self._ep.get(peer)
        if link is not None:
            return link
        loop = asyncio.get_event_loop()
        try:
            # blocking dial off the loop; the endpoint caches one live
            # link per peer (dial races resolve to the established one)
            return await loop.run_in_executor(None, self._ep.connect, peer)
        except FabricPeerLost as e:
            raise PeerUnreachableError(str(e)) from e

    async def call(self, peer, service, endpoint, body, headers=None, timeout=None) -> dict:
        link = await self._get_link(peer)
        rid = link.alloc_id()
        frame = {
            "id": rid,
            "kind": "req",
            "svc": service,
            "ep": endpoint,
            "body": body,
            "headers": headers or {},
        }
        try:
            encoded = self._encode(frame)
        except Exception as e:
            raise CallError(f"encode request for {peer}: {type(e).__name__}: {e}") from e
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()

        def _post(setter, value):
            def apply():
                if not fut.done():
                    setter(value)
            try:
                loop.call_soon_threadsafe(apply)
            except RuntimeError:
                pass  # loop already closed; nobody is awaiting

        def on_reply(payload, lane="tcp"):
            # reader-thread callback: payload is an arena memoryview (or
            # the link's sticky error) — decode here, resolve on the loop
            if isinstance(payload, BaseException):
                err = payload if isinstance(payload, CallError) else (
                    PeerUnreachableError(str(payload)))
                if err is not payload and err.__cause__ is None:
                    err.__cause__ = payload
                _post(fut.set_exception, err)
                return
            res = _decode_frame_body(payload)
            if res is None:
                _post(fut.set_exception,
                      PeerUnreachableError(f"undecodable response frame from {peer}"))
                raise FabricError("rpc response undecodable — dropping the connection")
            if res.get("ok"):
                _post(fut.set_result, res.get("body") or {})
            else:
                _post(fut.set_exception, RemoteError(res.get("err", "remote error")))

        link.request(rid, encoded, on_reply)
        self._count_sent(len(encoded))
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            link.forget(rid)
            raise CallTimeoutError(f"call {peer} {endpoint} timed out after {timeout}s")

    def call_sync(self, peer, service, endpoint, body, headers=None,
                  timeout=None, urgent=False) -> dict:
        """Blocking call with INLINE COMPLETION (r23): the reply is
        fulfilled directly on the link's reader thread via an Event —
        no event loop in the round trip at all.  Pair with a sync
        handler on the far side (or ``listen_sync``) for the zero-hop
        path: caller-thread write → reader-thread wake.

        ``urgent=True`` bypasses small-frame coalescing on channels
        built with ``flush_us > 0`` (the probe escape hatch).  Must be
        called OFF the event loop (it blocks)."""
        try:
            link = self._ep.connect(peer)
        except FabricPeerLost as e:
            raise PeerUnreachableError(str(e)) from e
        rid = link.alloc_id()
        frame = {
            "id": rid,
            "kind": "req",
            "svc": service,
            "ep": endpoint,
            "body": body,
            "headers": headers or {},
        }
        try:
            encoded = self._encode(frame)
        except Exception as e:
            raise CallError(f"encode request for {peer}: {type(e).__name__}: {e}") from e
        done = threading.Event()
        slot = [None, None]  # [result_body, error]

        def on_reply(payload, lane="tcp"):
            # reader thread (tcp) or shm-lane reader thread: decode and
            # fulfil right here — the waiter wakes on a futex, not a loop
            if isinstance(payload, BaseException):
                err = payload if isinstance(payload, CallError) else (
                    PeerUnreachableError(str(payload)))
                if err is not payload and err.__cause__ is None:
                    err.__cause__ = payload
                slot[1] = err
                done.set()
                return
            res = _decode_frame_body(payload)
            if res is None:
                slot[1] = PeerUnreachableError(
                    f"undecodable response frame from {peer}")
                done.set()
                raise FabricError("rpc response undecodable — dropping the connection")
            if res.get("ok"):
                slot[0] = res.get("body") or {}
            else:
                slot[1] = RemoteError(res.get("err", "remote error"))
            self.ledger.add("rpc", lane=lane, inline_completions=1)
            done.set()

        link.request(rid, encoded, on_reply, urgent=urgent)
        self._count_sent(len(encoded))
        if not done.wait(timeout):
            link.forget(rid)
            raise CallTimeoutError(f"call {peer} {endpoint} timed out after {timeout}s")
        if slot[1] is not None:
            raise slot[1]
        return slot[0] if slot[0] is not None else {}


# ---------------------------------------------------------------------------
# In-process transport with fault injection
# ---------------------------------------------------------------------------


class LocalNetwork:
    """Registry of in-process channels + fault model.

    Faults are first-class (BASELINE configs list packet-loss and partition
    scenarios): per-pair partitions, global drop probability, and black-hole
    addresses that swallow traffic (timeout) instead of refusing it."""

    def __init__(self, seed: int = 0):
        self.channels: dict[str, "LocalChannel"] = {}
        self.rng = random.Random(seed)
        self.drop_rate = 0.0
        self._partitions: list[set[str]] = []  # node -> group via membership
        self._black_holes: set[str] = set()
        self.latency: float = 0.0  # injected per-call delay (seconds)

    def register(self, channel: "LocalChannel") -> None:
        self.channels[channel.hostport] = channel

    def unregister(self, hostport: str) -> None:
        self.channels.pop(hostport, None)

    # -- fault injection ----------------------------------------------------

    def partition(self, *groups: list[str]) -> None:
        """Split the network: nodes in different groups cannot talk."""
        self._partitions = [set(g) for g in groups]

    def heal_partition(self) -> None:
        self._partitions = []

    def black_hole(self, *hostports: str) -> None:
        self._black_holes.update(hostports)

    def unblack_hole(self, *hostports: str) -> None:
        self._black_holes.difference_update(hostports)

    def _connected(self, a: str, b: str) -> bool:
        if not self._partitions:
            return True
        ga = next((i for i, g in enumerate(self._partitions) if a in g), None)
        gb = next((i for i, g in enumerate(self._partitions) if b in g), None)
        # nodes not named in any group can talk to everyone
        return ga is None or gb is None or ga == gb

    async def deliver(
        self, src: str, dst: str, service: str, endpoint: str, body: dict, headers: dict, timeout: Optional[float]
    ) -> dict:
        if self.latency:
            await asyncio.sleep(self.latency)
        if dst in self._black_holes or src in self._black_holes or not self._connected(src, dst):
            # black hole: behave like a timeout, not a refusal
            await asyncio.sleep(min(timeout or 0.01, 0.01))
            raise CallTimeoutError(f"{src}->{dst} black-holed")
        if self.drop_rate and self.rng.random() < self.drop_rate:
            await asyncio.sleep(min(timeout or 0.01, 0.01))
            raise CallTimeoutError(f"{src}->{dst} dropped")
        target = self.channels.get(dst)
        if target is None:
            raise PeerUnreachableError(f"connect {dst}: connection refused")
        try:
            res = await target.dispatch(
                service, endpoint, json.loads(_encode_frame(body)), dict(headers)
            )
        except CallError:
            raise
        except Exception as e:  # remote handler error, as the TCP path reports it
            raise RemoteError(str(e)) from e
        return json.loads(_encode_frame(res))


class LocalChannel(BaseChannel):
    """In-process channel attached to a LocalNetwork."""

    def __init__(self, network: LocalNetwork, hostport: str, app: str = ""):
        super().__init__(app)
        self.network = network
        self.hostport = hostport
        network.register(self)

    async def listen(self, host: str = "", port: int = 0) -> str:
        return self.hostport

    async def close(self) -> None:
        self.network.unregister(self.hostport)

    async def call(self, peer, service, endpoint, body, headers=None, timeout=None) -> dict:
        return await self.network.deliver(
            self.hostport, peer, service, endpoint, body, headers or {}, timeout
        )
