"""Live operations plane: in-memory stat aggregation, a pull-based HTTP
endpoint, span tracing through the forwarding planes, and a crash flight
recorder.

Every observability surface this repo grew through r19 is post-hoc — the
JSONL journals are read after the run ends.  The roadmap's current
workloads cannot wait that long: week-long resumable fleet sweeps
(``scenarios.FleetSweep``), multi-rank serve meshes under live traffic
(``serve/mesh.py``), and real-OS-process launches via
``scripts/multihost_launch.py``.  This package is the LIVE half of the
telemetry plane, and it obeys the same bar the device plane set in r7:

* **Bit-transparency.**  Everything here is host-plane only — it reads
  records the engines already fetch and headers the transports already
  carry.  A tracing-on, live-plane-on run is digest-identical to an
  all-off run, and the device-side jaxpr is untouched (pinned by
  ``tests/test_telemetry.py`` and the smoke gates).
* **Never take the node down.**  Endpoint handlers, the cross-rank
  collector, and the flight recorder swallow their own failures — an
  ops-plane socket error must never kill a week-long sweep.
* **jax-free imports.**  Frontend processes (the serve tier's jax-free
  contract) import these modules without paying a backend init; anything
  that needs the sim plane imports it lazily at call time.

Pieces:

* :mod:`~ringpop_tpu.obs.aggregate` — :class:`AggregatingStats`, the
  snapshot-able ``StatsReporter`` (``util/metrics`` Histogram/Meter
  backed) both stat planes can feed, plus the Prometheus text renderer.
* :mod:`~ringpop_tpu.obs.endpoint` — :class:`LiveOps`: the per-rank
  pull endpoint (``/metrics`` ``/healthz`` ``/progress``) with rank-0
  cross-rank aggregation riding the fabric's tagged-message demux.
* :mod:`~ringpop_tpu.obs.trace` — :class:`Tracer`: the ``ringpop-trace``
  header (trace id + parent span id) next to ``ringpop-hops``,
  deterministically sampled by key hash so reruns trace the SAME
  requests; ``kind:"span"`` records for the existing JSONL journals.
* :mod:`~ringpop_tpu.obs.flight` — :class:`FlightRecorder`: a bounded
  per-rank ring of the most recent block/span/stat records, dumped to a
  post-mortem JSONL on ``FabricPeerLost``/``FabricTimeout``/uncaught
  exception, so a rank that dies mid-sweep leaves its last seconds
  behind.  Also :func:`git_commit`, the journal-header provenance
  helper.
* :mod:`~ringpop_tpu.obs.rules` — :class:`RuleEngine` (r22): declarative
  alert rules (threshold / rate-of-change / staleness / cross-rank
  skew) with hysteresis, evaluated over the endpoint's snapshots and
  health views; transitions land as span-carrying ``kind:"alert"``
  records.
* :mod:`~ringpop_tpu.obs.controller` — :class:`OpsController` (r22):
  alert-driven mitigations through pre-existing seams (DGRO re-score,
  ring drain, elastic resize); every action is a ``kind:"action"``
  record parented on its alert's span, so :func:`chain` reconstructs
  alert → action → effect from the journal alone.
* :mod:`~ringpop_tpu.obs.gameday` — the scored game day: a correlated
  failure injected into a live P=2 fleet, controller judged on
  time-to-mitigate against a digest-identical no-controller twin.
"""

_EXPORTS = {
    "AggregatingStats": "ringpop_tpu.obs.aggregate",
    "render_prometheus": "ringpop_tpu.obs.aggregate",
    "LiveOps": "ringpop_tpu.obs.endpoint",
    "Tracer": "ringpop_tpu.obs.trace",
    "Span": "ringpop_tpu.obs.trace",
    "JsonlSink": "ringpop_tpu.obs.trace",
    "TRACE_HEADER": "ringpop_tpu.obs.trace",
    "trace_id_of": "ringpop_tpu.obs.trace",
    "FlightRecorder": "ringpop_tpu.obs.flight",
    "git_commit": "ringpop_tpu.obs.flight",
    "SPAN_KINDS": "ringpop_tpu.obs.trace",
    "chain": "ringpop_tpu.obs.trace",
    "RuleEngine": "ringpop_tpu.obs.rules",
    "Threshold": "ringpop_tpu.obs.rules",
    "RateOfChange": "ringpop_tpu.obs.rules",
    "Staleness": "ringpop_tpu.obs.rules",
    "CrossRankSkew": "ringpop_tpu.obs.rules",
    "OpsController": "ringpop_tpu.obs.controller",
    "run_gameday": "ringpop_tpu.obs.gameday",
    "gameday_pair": "ringpop_tpu.obs.gameday",
}


def __getattr__(name):
    # lazy like the serve package: importing the package costs nothing
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod), name)


__all__ = list(_EXPORTS)
