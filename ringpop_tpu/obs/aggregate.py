"""AggregatingStats: the snapshot-able stats sink of the live plane.

The existing reporters (``cli/stats.py``) stream every emission OUT —
a file line or a UDP datagram per stat — which is the right shape for an
external statsd, and the wrong one for a pull endpoint: ``/metrics``
needs the CURRENT value of every key on demand.  This reporter keeps the
run's counters/gauges/timings in memory, backed by the same
``util/metrics`` primitives the host plane already uses (uniform-sample
:class:`~ringpop_tpu.util.metrics.Histogram` for timings, 1-minute EWMA
:class:`~ringpop_tpu.util.metrics.Meter` per counter), and renders
snapshots in the Prometheus text exposition format.

Both stat planes feed it through their existing seams: the host plane
via ``Options(stats_reporter=...)``, the sim plane via
``telemetry.emit_stats`` (the ``LiveOps`` endpoint wires the latter).
Thread-safe — the serve tier emits from its asyncio loop while the HTTP
endpoint snapshots from its own thread.
"""

from __future__ import annotations

import re
import threading
from typing import Optional

from ringpop_tpu.util.metrics import Histogram, Meter

# timing summary quantiles rendered into snapshots / the endpoint
TIMING_QUANTILES = (0.5, 0.95, 0.99)


class AggregatingStats:
    """In-memory ``StatsReporter`` with a consistent ``snapshot()``.

    Counters sum, gauges keep the last value, timings feed a reservoir
    histogram (``sample_size`` values retained) and every counter key
    additionally drives a 1-minute rate meter.  Duck-typed to
    ``options.StatsReporter`` (incr/gauge/timing) so every existing
    emitter — facade, sim bridge, serve tier — plugs in unchanged."""

    def __init__(self, sample_size: int = 128, clock=None):
        self._lock = threading.Lock()
        self._sample_size = sample_size
        self._clock = clock
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self._timings: dict[str, Histogram] = {}
        self._meters: dict[str, Meter] = {}

    def incr(self, key: str, value: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + value
            meter = self._meters.get(key)
            if meter is None:
                meter = self._meters[key] = Meter(clock=self._clock)
            meter.mark(value)

    def gauge(self, key: str, value: float) -> None:
        with self._lock:
            self.gauges[key] = float(value)

    def timing(self, key: str, seconds: float) -> None:
        with self._lock:
            h = self._timings.get(key)
            if h is None:
                # seed the reservoir rng off the key so reruns sample the
                # same way per key regardless of creation order
                h = self._timings[key] = Histogram(
                    sample_size=self._sample_size,
                    seed=sum(key.encode()) & 0x7FFFFFFF,
                )
            h.update(float(seconds))

    def snapshot(self) -> dict:
        """A plain-JSON view of every key: counters with 1-minute rates,
        gauges, and timing summaries (count/mean/min/max + quantiles)."""
        with self._lock:
            timings = {
                k: {
                    "count": h.count,
                    "mean": h.mean(),
                    "min": h.min(),
                    "max": h.max(),
                    **{
                        f"p{int(q * 100)}": h.percentile(q)
                        for q in TIMING_QUANTILES
                    },
                }
                for k, h in self._timings.items()
            }
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timings": timings,
                "rates_1m": {k: m.rate1() for k, m in self._meters.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self._timings.clear()
            self._meters.clear()


# -- Prometheus text exposition ----------------------------------------------

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(key: str) -> str:
    """A stats key as a legal Prometheus metric name: every illegal
    character becomes ``_`` (``ringpop.sim.ping.send`` →
    ``ringpop_sim_ping_send``), a leading digit gets a ``_`` prefix."""
    name = _NAME_BAD.sub("_", key)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def render_prometheus(snapshots: dict[int, dict]) -> str:
    """``{rank: snapshot}`` → Prometheus text exposition.

    Every sample carries a ``rank`` label; when more than one rank is
    present an UNLABELED aggregate sample follows per counter/gauge
    metric (counters sum, gauges sum — the cross-rank totals the
    live-smoke certifies against the ranks' journal sums).  Timings
    render as real Prometheus ``summary`` families: quantile-labeled
    samples (``name{rank="0",quantile="0.5"}``) plus a ``<name>_count``
    line per rank, with mean/min/max kept as auxiliary ``<name>_mean``
    etc. gauges.  There is deliberately NO ``<name>_sum`` sample: the
    backing :class:`~ringpop_tpu.util.metrics.Histogram` is a uniform
    reservoir (``sample_size`` retained values), so an exact sum over
    all observations does not exist — the exposition comment on each
    summary states this so scrapers don't infer rates from it."""
    lines: list[str] = []
    ranks = sorted(snapshots)
    multi = len(ranks) > 1

    def emit_family(kind: str, prom_type: str, agg: bool) -> None:
        keys = sorted({k for r in ranks for k in snapshots[r].get(kind, {})})
        for key in keys:
            name = prom_name(key)
            lines.append(f"# TYPE {name} {prom_type}")
            total = 0.0
            seen = False
            for r in ranks:
                v = snapshots[r].get(kind, {}).get(key)
                if v is None:
                    continue
                seen = True
                total += float(v)
                lines.append(f'{name}{{rank="{r}"}} {_fmt(v)}')
            if agg and multi and seen:
                lines.append(f"{name} {_fmt(total)}")

    emit_family("counters", "counter", agg=True)
    emit_family("gauges", "gauge", agg=True)
    # timing summaries: quantile-labeled samples + _count, per rank
    tkeys = sorted({k for r in ranks for k in snapshots[r].get("timings", {})})
    for key in tkeys:
        base = prom_name(key)
        lines.append(f"# TYPE {base} summary")
        lines.append(
            f"# {base}: reservoir-sampled quantiles "
            "(uniform sample, not an exact sum — no _sum line; "
            "do not derive rates from this family)"
        )
        aux = sorted(
            {
                s
                for r in ranks
                for s in snapshots[r].get("timings", {}).get(key, {})
                if s not in ("count",) and not s.startswith("p")
            }
        )
        for r in ranks:
            entry = snapshots[r].get("timings", {}).get(key)
            if not entry:
                continue
            for stat in sorted(entry):
                if not stat.startswith("p") or not stat[1:].isdigit():
                    continue
                q = int(stat[1:]) / 100.0
                lines.append(
                    f'{base}{{rank="{r}",quantile="{_fmt(q)}"}} '
                    f"{_fmt(entry[stat])}"
                )
            if "count" in entry:
                lines.append(
                    f'{base}_count{{rank="{r}"}} {_fmt(entry["count"])}'
                )
        for stat in aux:
            name = f"{base}_{stat}"
            lines.append(f"# TYPE {name} gauge")
            for r in ranks:
                v = snapshots[r].get("timings", {}).get(key, {}).get(stat)
                if v is not None:
                    lines.append(f'{name}{{rank="{r}"}} {_fmt(v)}')
    rkeys = sorted({k for r in ranks for k in snapshots[r].get("rates_1m", {})})
    for key in rkeys:
        name = prom_name(key) + "_rate1m"
        lines.append(f"# TYPE {name} gauge")
        for r in ranks:
            v = snapshots[r].get("rates_1m", {}).get(key)
            if v is not None:
                lines.append(f'{name}{{rank="{r}"}} {_fmt(v)}')
    return "\n".join(lines) + "\n"


def merge_counter_totals(snapshots: dict[int, dict]) -> dict[str, float]:
    """Cross-rank counter sums — the aggregation the endpoint's
    unlabeled samples expose, callable directly for tests/tools."""
    out: dict[str, float] = {}
    for snap in snapshots.values():
        for k, v in snap.get("counters", {}).items():
            out[k] = out.get(k, 0.0) + float(v)
    return out
