"""OpsController: the actuator half of the closed observability loop.

``obs/rules.py`` turns telemetry into alert transitions; this module
turns firing alerts into mitigations, through seams that ALL predate
it — the controller adds policy, not mechanism:

* ``dgro_rescore`` — per-rank load or arc-diameter skew: drop the
  sticky DGRO candidate and re-score placement at current membership
  (:meth:`~ringpop_tpu.serve.state.RingStore.rescore_placement`; the
  arxiv 2410.11142 scorer was already landed and sticky — telemetry is
  now the trigger that pays the movement).
* ``drain`` — a degrading rank: route its ring block away via a
  :meth:`~ringpop_tpu.serve.state.RingStore.drain` generation commit
  BEFORE SWIM declares it faulty, then probe the new placement
  (``forward.batch.rank_load``) and record the drained rank's key share
  as the action's EFFECT.
* ``resize`` — a rank stale on ``/healthz``: invoke the r19
  checkpoint-at-P / resume-at-P′ path (injected as a callable — the
  harness owns process lifecycle; the controller owns the decision).

Every action lands as a ``kind:"action"`` journal record whose span
PARENTS the triggering alert's span — ``obs.trace.chain()`` therefore
reconstructs alert → decision → action → effect from the journal
alone, which is the game-day acceptance bar.  A mitigation that itself
raises emits ``ok: false`` and dumps the flight ring under
``scope="controller"`` (its own once-per-process slot — it must never
burn the engine-crash dump, pinned in ``tests/test_closed_loop.py``).

jax-free: numpy + stdlib only.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ringpop_tpu.obs.rules import FLEET
from ringpop_tpu.obs.trace import salt_of, span_id_of

# mitigation names — the policy dict maps rule ids onto these
ACTIONS = ("dgro_rescore", "drain", "resize")


class OpsController:
    """Alert-driven mitigation dispatch with per-subject cooldowns.

    ``policy`` maps rule id → action name (:data:`ACTIONS`); alerts
    whose rule has no policy entry are ignored (they remain visible in
    the journal — not every alert warrants a reflex).  Seams:

    * ``ring_store`` — a :class:`~ringpop_tpu.serve.state.RingStore`
      (or duck-type with ``rescore_placement()``/``drain(servers)``);
    * ``server_of`` — rank → server name, for ``drain`` (the rules
      engine alerts about RANKS; the ring speaks server names);
    * ``resize`` — callable ``(stale_rank) -> detail dict``, the r19
      checkpoint/resume invocation;
    * ``drain_probe`` — callable ``(server) -> int``, the drained
      server's key share over a probe population against the POST-drain
      ring (the harness's ``ring.lookup_batch`` count), the drain's
      effect measurement: 0 means the block really routed away;
    * ``recorder`` — a FlightRecorder; failed mitigations dump under
      ``scope="controller"``.

    ``cooldown`` suppresses re-dispatch of the same (action, subject)
    for that many :meth:`on_alerts` rounds — an alert that stays firing
    across evaluations must not re-drain every block."""

    def __init__(
        self,
        *,
        sink: Callable[[dict], None],
        policy: dict[str, str],
        rank: int = 0,
        ring_store=None,
        server_of: Optional[Callable[[int], str]] = None,
        resize: Optional[Callable[[int], dict]] = None,
        drain_probe: Optional[Callable[[], "list"]] = None,
        recorder=None,
        cooldown: int = 4,
    ):
        bad = sorted(set(policy.values()) - set(ACTIONS))
        if bad:
            raise ValueError(f"unknown actions in policy: {bad}")
        self.sink = sink
        self.policy = dict(policy)
        self.rank = rank
        self.ring_store = ring_store
        self.server_of = server_of
        self.resize = resize
        self.drain_probe = drain_probe
        self.recorder = recorder
        self.cooldown = cooldown
        self._round = 0
        self._last_round: dict[tuple[str, int], int] = {}
        self._drained: set[int] = set()
        self.actions_taken = 0
        self.actions_failed = 0
        self.history: list[dict] = []

    # -- dispatch -------------------------------------------------------------

    def on_alerts(
        self, alerts: list[dict], *, tick: Optional[int] = None
    ) -> list[dict]:
        """Feed one evaluation round's alert records (the return value
        of ``RuleEngine.evaluate``); returns the action records emitted.
        Only ``state == "firing"`` transitions dispatch — a clear is
        information, not work."""
        self._round += 1
        out: list[dict] = []
        for alert in alerts:
            if alert.get("state") != "firing":
                continue
            action = self.policy.get(alert.get("rule"))
            if action is None:
                continue
            subject = int(alert.get("about_rank", FLEET))
            key = (action, subject)
            last = self._last_round.get(key)
            if last is not None and self._round - last < self.cooldown:
                continue
            self._last_round[key] = self._round
            out.extend(self._dispatch(action, subject, alert, tick))
        return out

    def _dispatch(
        self, action: str, subject: int, alert: dict, tick
    ) -> list[dict]:
        records: list[dict] = []
        ok, detail, err, server = False, {}, None, None
        try:
            if action == "dgro_rescore":
                rec = self.ring_store.rescore_placement()
                ok = rec is not None
                if ok:
                    detail = {
                        "gen": rec["gen"],
                        "placement": rec.get("placement", {}),
                    }
            elif action == "drain":
                if subject in self._drained:
                    return records  # already routed away
                server = self.server_of(subject)
                rec = self.ring_store.drain([server])
                ok = rec is not None
                if ok:
                    self._drained.add(subject)
                    detail = {"server": server, "gen": rec["gen"]}
            elif action == "resize":
                detail = dict(self.resize(subject) or {})
                ok = True
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            if self.recorder is not None:
                # the controller's OWN dump slot: a broken mitigation is
                # forensically interesting, but must not consume the
                # once-per-process engine-crash dump
                self.recorder.dump(
                    f"controller:{action}", error=e, scope="controller"
                )
        act = self._emit(action, subject, alert, ok, detail, err, tick)
        records.append(act)
        if ok:
            self.actions_taken += 1
        else:
            self.actions_failed += 1
        if ok and action == "drain" and self.drain_probe is not None:
            records.append(self._probe_drain(subject, server, act, tick))
        return records

    def _probe_drain(self, subject: int, server: str, act: dict, tick) -> dict:
        """Measure the drain's effect: the drained server's key share
        over a probe population against the POST-drain ring must be 0."""
        try:
            share = int(self.drain_probe(server))
            ok, detail, err = share == 0, {"server": server, "share": share}, None
        except Exception as e:
            ok, detail, err = False, {}, f"{type(e).__name__}: {e}"
        trace = act["trace"]
        record = {
            "kind": "action",
            "action": "effect",
            "of": act["action"],
            "rule": act["rule"],
            "about_rank": subject,
            "ok": ok,
            "detail": detail,
            "error": err,
            "tick": tick,
            "rank": self.rank,
            "trace": trace,
            "span": span_id_of(
                trace, "effect", salt=salt_of("effect", subject),
                parent=act["span"],
            ),
            "parent": act["span"],
            "t": time.time(),
        }
        self._sink(record)
        return record

    def _emit(
        self, action: str, subject: int, alert: dict, ok: bool,
        detail: dict, err, tick,
    ) -> dict:
        trace = alert["trace"]  # the action joins the ALERT's trace
        record = {
            "kind": "action",
            "action": action,
            "rule": alert.get("rule"),
            "about_rank": subject,
            "ok": ok,
            "detail": detail,
            "error": err,
            "tick": tick,
            "rank": self.rank,
            "trace": trace,
            "span": span_id_of(
                trace, "action",
                salt=salt_of(action, subject, self._round),
                parent=alert["span"],
            ),
            "parent": alert["span"],
            "t": time.time(),
        }
        self._sink(record)
        self.history.append(record)
        return record

    def _sink(self, record: dict) -> None:
        try:
            self.sink(record)
        except Exception:
            pass  # the ops plane never takes the run down
