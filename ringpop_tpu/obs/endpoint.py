"""LiveOps: the per-rank pull endpoint of the live operations plane.

One :class:`LiveOps` per rank bundles the plane's moving parts — an
:class:`~ringpop_tpu.obs.aggregate.AggregatingStats` fed by both stat
planes, an optional :class:`~ringpop_tpu.obs.flight.FlightRecorder`,
sweep progress state — and serves them over a pull-based HTTP endpoint
(stdlib ``http.server`` on a daemon thread; Prometheus scrapes it, a
human curls it):

* ``/metrics``  — Prometheus text exposition of every counter/gauge/
  timing this rank holds; on rank 0 of a multi-rank job the samples of
  EVERY rank (rank-labeled) plus unlabeled cross-rank aggregates.
* ``/healthz``  — JSON liveness: rank, uptime, and — on rank 0 — the
  seconds since each peer rank's last snapshot (a dead rank's age grows
  and its ``live`` flag drops; the scrape-side alert primitive).
* ``/progress`` — JSON sweep progress: ``ticks_done``/``horizon``/
  ``last_checkpoint_tick`` per rank — the "is the week-long sweep still
  moving" question answered without touching the job.

Cross-rank aggregation rides the fabric's tagged-message demux — the
same deterministic-round transport the engines use, on its OWN
:class:`~ringpop_tpu.parallel.fabric.Fabric` (namespace ``"obs"``), so
the engines' wire/raw byte accounting and codec streams are untouched.
Every rank calls :meth:`sync` at the same protocol point (a journal
block boundary — ``FleetSweep`` does this automatically); non-zero
ranks enqueue their snapshot toward rank 0 and return WITHOUT waiting
(the drain rides the fabric's persistent sender threads), rank 0
enqueues tagged receive expectations and harvests whatever has landed —
``sync`` never blocks on a slow or dead peer.  The ops plane must never
take the run down: any fabric failure marks the plane degraded and is
swallowed (the flight recorder, if armed, has already captured it).

jax-free imports (``parallel.fabric`` is numpy-only and loaded lazily);
safe for serve frontends.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

import numpy as np

from ringpop_tpu.obs.aggregate import AggregatingStats, render_prometheus

# obs rounds live far above the engine tag spaces (delta legs are
# tick<<8|leg, the serve mesh uses rnd<<8|0x10/0x20 and 0x7FFF0000 for
# digests); they are also on their OWN fabric, so this is belt and braces
_TAG_OBS = 0x7FE0_0000

# a rank whose last snapshot is older than this many seconds reports
# live=false on /healthz (rank 0 only sees peers at sync cadence, so the
# caller should size it to a few journal blocks)
DEFAULT_STALE_S = 60.0


class LiveOps:
    """One rank's live-operations endpoint + cross-rank collector.

    Single-rank (``nprocs == 1`` or ``kv is None``): just the local
    stats/progress over HTTP.  Multi-rank: pass the job's coordination
    KV (``LocalKV`` for threaded twins, ``DistributedKV`` on a real
    job) and every rank must construct its ``LiveOps`` — the obs fabric
    rendezvous is collective, like any fabric bring-up."""

    def __init__(
        self,
        rank: int = 0,
        nprocs: int = 1,
        *,
        stats: Optional[AggregatingStats] = None,
        recorder=None,
        kv=None,
        namespace: str = "obs",
        timeout_ms: int = 3_600_000,
        stale_s: float = DEFAULT_STALE_S,
        ledger=None,
        rejoin: bool = False,
    ):
        self.rank, self.nprocs = rank, nprocs
        # r21: snapshot traffic accounts into the merged TransportLedger
        # under class "obs" — pass the job's shared ledger to get one
        # cross-plane byte view, or leave None for a private one
        self.ledger = ledger
        self.stats = stats if stats is not None else AggregatingStats()
        self.recorder = recorder
        self.stale_s = stale_s
        self.started = time.time()
        self.progress_state: dict = {
            "ticks_done": 0,
            "horizon": 0,
            "last_checkpoint_tick": None,
        }
        self._lock = threading.Lock()
        self._seq = 0
        self._degraded: Optional[str] = None
        # rank 0: peer snapshots {rank: {"t_recv", "snap", "progress"}}
        self._peers: dict[int, dict] = {}
        self._pending: list = []  # rank 0: (seq, ExchangeHandle, epochs)
        self._dead: set[int] = set()
        # rank-restart support: a rank that died and came back under the
        # same rank id constructs LiveOps(..., rejoin=True) — its fabric
        # advertises a rejoin listener instead of redoing bring-up, and
        # rank 0 dials dead peers' adverts from sync().  _epoch counts
        # link incarnations per peer so a failure on a pre-reconnect
        # round can't re-mark the fresh link dead; _adopted gates the
        # restarted rank's seq adoption from the dial's token.
        self._rejoin = rejoin
        self._adopted = not rejoin
        self._epoch: dict[int, int] = {}
        self._server = None
        self._server_thread = None
        self.fabric = None
        if kv is not None and nprocs > 1:
            from ringpop_tpu.parallel.fabric import Fabric

            # a LONG timeout (default 1 h) + notify_failures=False:
            # sweep ranks sync at their own block cadence, so minutes of
            # skew (uneven slices, a checkpoint save) are ROUTINE on
            # this side channel — they must neither mark a progressing
            # peer dead nor burn the flight recorder's once-per-process
            # dump (that hook exists for ENGINE fabric failures; a dead
            # peer still surfaces here promptly as FabricPeerLost when
            # its socket closes, and as a growing /healthz age always)
            self.fabric = Fabric(
                rank, nprocs, kv, namespace=namespace,
                timeout_ms=timeout_ms, codec=True, notify_failures=False,
                ledger=ledger, ledger_class="obs", rejoin=rejoin,
            )
            self.ledger = self.fabric.ledger

    # -- progress + record ingestion ------------------------------------------

    def progress(
        self,
        ticks_done: int,
        horizon: int,
        last_checkpoint_tick: Optional[int] = None,
    ) -> None:
        """Update this rank's sweep progress (``FleetSweep`` calls this
        per journal block); mirrored into gauges so ``/metrics`` carries
        it too."""
        with self._lock:
            self.progress_state["ticks_done"] = int(ticks_done)
            self.progress_state["horizon"] = int(horizon)
            if last_checkpoint_tick is not None:
                self.progress_state["last_checkpoint_tick"] = int(
                    last_checkpoint_tick
                )
        self.stats.gauge("ringpop.obs.progress.ticks-done", ticks_done)
        self.stats.gauge("ringpop.obs.progress.horizon", horizon)
        if last_checkpoint_tick is not None:
            self.stats.gauge(
                "ringpop.obs.progress.last-checkpoint-tick",
                last_checkpoint_tick,
            )

    def block_record(self, record: dict) -> None:
        """Ingest one fetched telemetry block record: into the flight
        recorder ring and — via the sim plane's own key table — into the
        aggregated counters ``/metrics`` serves.  The telemetry import
        is lazy at CALL time (records only exist where jax already is;
        module import stays jax-free)."""
        if self.recorder is not None:
            # fetched records are kind-less until a journal stamps them;
            # the flight ring uses the same vocabulary
            self.recorder({"kind": "block", **record})
        try:
            from ringpop_tpu.sim.telemetry import emit_stats

            emit_stats(self.stats, record)
        except Exception:
            pass  # the ops plane never takes the run down

    # -- cross-rank sync (the fabric-tagged collector) -------------------------

    def _payload(self) -> np.ndarray:
        body = {
            "t": time.time(),
            "snap": self.stats.snapshot(),
            "progress": dict(self.progress_state),
        }
        return np.frombuffer(
            json.dumps(body).encode("utf-8"), dtype=np.uint8
        ).copy()

    def sync(self) -> None:
        """One obs round — call at the SAME protocol point on every rank
        (a journal block boundary).  Non-blocking: rank > 0 enqueues its
        snapshot toward rank 0 (the drain rides the persistent sender
        threads; a sticky failure surfaces at the next enqueue and
        degrades the plane, never the run), rank 0 enqueues the round's
        receive expectations and harvests any completed earlier rounds."""
        if self.fabric is None or self._degraded is not None:
            return
        if self.rank != 0 and not self._adopted:
            # rejoining rank: no link until rank 0 dials our advert —
            # skip the round entirely (consuming seqs while link-less
            # would desync the tag sequence we're about to adopt)
            if not self.fabric.has_link(0):
                return
            self._seq = self.fabric.rejoin_token
            self._adopted = True
        if self.rank == 0 and self._dead:
            # dial any dead peer that has published a NEW rejoin advert;
            # token = the seq this very round will use, so the restarted
            # rank adopts the live tag sequence.  Per-peer try/except:
            # a failed dial is routine (peer still down), never degrades
            with self._lock:
                dead = sorted(self._dead)
            for peer in dead:
                try:
                    if self.fabric.reconnect_peer(peer, token=self._seq):
                        with self._lock:
                            self._epoch[peer] = self._epoch.get(peer, 0) + 1
                        if self.recorder is not None:
                            self.recorder(
                                {
                                    "kind": "obs_peer_rejoin",
                                    "peer": peer,
                                    "seq": self._seq,
                                    "t": time.time(),
                                }
                            )
                except Exception:
                    pass
        seq = self._seq
        self._seq += 1
        tag = (_TAG_OBS + seq) & 0xFFFFFFFF
        try:
            if self.rank != 0:
                self.fabric.exchange_async(tag, {0: [self._payload()]}, [])
                return
            peers = [p for p in range(self.nprocs) if p != 0]
            h = self.fabric.exchange_async(tag, {}, peers)
            with self._lock:
                self._pending.append((seq, h, dict(self._epoch)))
        except Exception as e:  # ops must never kill the sweep
            self._degraded = f"{type(e).__name__}: {e}"
            return
        self._harvest()

    def _harvest(self) -> None:
        """Fold every COMPLETED pending obs round into the peer table
        (rank 0 only; called from sync and from the HTTP handlers so a
        scrape between syncs still sees the freshest landed data).
        Completed rounds are REMOVED from the pending list, never the
        list replaced wholesale — a sync() appending concurrently from
        the sweep thread must not lose its round to a racing scrape."""
        with self._lock:
            pending = list(self._pending)
        done: set[int] = set()
        for seq, h, epochs in pending:
            got = h.poll()
            if got is None:
                continue
            done.add(id(h))
            for peer, val in got.items():
                if isinstance(val, BaseException):
                    with self._lock:
                        # a round enqueued against a PRE-reconnect link
                        # incarnation fails when the old link shuts
                        # down — that must not re-mark the fresh link's
                        # peer dead (epoch bumped at reconnect)
                        if epochs.get(peer, 0) == self._epoch.get(peer, 0):
                            self._dead.add(peer)
                    if self.recorder is not None:
                        self.recorder(
                            {
                                "kind": "obs_peer_lost",
                                "peer": peer,
                                "seq": seq,
                                "error": f"{type(val).__name__}: {val}",
                                "t": time.time(),
                            }
                        )
                    continue
                try:
                    body = json.loads(bytes(val[0].tobytes()).decode("utf-8"))
                except Exception:
                    continue
                with self._lock:
                    prev = self._peers.get(peer)
                    # rounds can complete out of order; keep the newest
                    if prev is None or prev.get("seq", -1) < seq:
                        self._peers[peer] = {
                            "seq": seq,
                            "t_recv": time.time(),
                            "snap": body.get("snap", {}),
                            "progress": body.get("progress", {}),
                            "t_sent": body.get("t"),
                        }
                    self._dead.discard(peer)
        if done:
            with self._lock:
                self._pending = [
                    e for e in self._pending if id(e[1]) not in done
                ]

    # -- views ----------------------------------------------------------------

    def _mirror_ledger(self) -> None:
        """r23: mirror the shared TransportLedger's per-class / per-LANE
        rows into gauges so ``/metrics`` exposes the lane split (tcp vs
        shm bytes/frames, ``inline_completions``, ``coalesced_frames``)
        without a second scrape surface.  Gauge names:
        ``ringpop.transport.<class>.<lane>.<field>``."""
        led = self.ledger
        if led is None or not hasattr(led, "stats"):
            return
        try:
            st = led.stats()
        except Exception:
            return  # the ops plane never takes the run down
        for klass, row in st.get("classes", {}).items():
            for lane, lrow in (row.get("lanes") or {}).items():
                for field, v in lrow.items():
                    self.stats.gauge(
                        f"ringpop.transport.{klass}.{lane}."
                        f"{field.replace('_', '-')}",
                        v,
                    )
        self.stats.gauge(
            "ringpop.transport.copy-bytes", st.get("copy_bytes", 0)
        )

    def snapshots(self) -> dict[int, dict]:
        """{rank: stats snapshot} — self fresh, peers as last collected."""
        if self.rank == 0 and self.fabric is not None:
            self._harvest()
        self._mirror_ledger()
        out = {self.rank: self.stats.snapshot()}
        with self._lock:
            for peer, entry in self._peers.items():
                out[peer] = entry["snap"]
        return out

    def health(self) -> dict:
        now = time.time()
        if self.rank == 0 and self.fabric is not None:
            self._harvest()
        with self._lock:
            ranks = {
                str(self.rank): {"age_s": 0.0, "live": True, "self": True}
            }
            for peer, entry in self._peers.items():
                age = round(now - entry["t_recv"], 3)
                ranks[str(peer)] = {
                    "age_s": age,
                    "live": peer not in self._dead and age < self.stale_s,
                }
            for peer in self._dead:
                if str(peer) not in ranks:
                    ranks[str(peer)] = {"age_s": None, "live": False}
            if self.rank == 0:
                # a rank that wedged BEFORE its first sync never enters
                # _peers or _dead — it must read as not-live once the
                # grace window (one staleness period from start) passes,
                # not stay invisible while /healthz green-lights the job
                grace = (now - self.started) < self.stale_s
                for peer in range(self.nprocs):
                    if peer != self.rank and str(peer) not in ranks:
                        ranks[str(peer)] = {
                            "age_s": None, "live": grace, "pending": True,
                        }
            degraded = self._degraded
        return {
            "ok": all(r["live"] for r in ranks.values()) and degraded is None,
            "rank": self.rank,
            "nprocs": self.nprocs,
            "uptime_s": round(now - self.started, 3),
            "degraded": degraded,
            "ranks": ranks,
        }

    def progress_view(self) -> dict:
        if self.rank == 0 and self.fabric is not None:
            self._harvest()
        now = time.time()
        with self._lock:
            ranks = {str(self.rank): dict(self.progress_state)}
            for peer, entry in self._peers.items():
                ranks[str(peer)] = {
                    **entry["progress"],
                    "age_s": round(now - entry["t_recv"], 3),
                }
        return {"rank": self.rank, "nprocs": self.nprocs, "ranks": ranks}

    # -- HTTP -----------------------------------------------------------------

    def serve(self, port: int = 0, host: str = "127.0.0.1") -> str:
        """Start the endpoint on a daemon thread; returns ``host:port``
        (port 0 picks a free one — tests/smokes read it back here)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        ops = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet: scrapes are not app logs
                pass

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        body = render_prometheus(ops.snapshots()).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif path == "/healthz":
                        h = ops.health()
                        body = (json.dumps(h, sort_keys=True) + "\n").encode()
                        ctype = "application/json"
                    elif path == "/progress":
                        body = (
                            json.dumps(ops.progress_view(), sort_keys=True)
                            + "\n"
                        ).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass
                except Exception:
                    # a rendering bug answers 500; it must never
                    # propagate into the serving thread
                    try:
                        self.send_error(500)
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._server_thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"liveops-r{self.rank}",
        )
        self._server_thread.start()
        addr = self._server.server_address
        return f"{addr[0]}:{addr[1]}"

    def close(self) -> None:
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:
                pass
            self._server = None
        if self.fabric is not None:
            try:
                self.fabric.close()
            except Exception:
                pass
            self.fabric = None

    def __enter__(self) -> "LiveOps":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
