"""Crash flight recorder: a rank's last seconds, dumped post-mortem.

A rank that dies mid-sweep today leaves only whatever its journal had
flushed — and the journal cadence is sized for amortization (hundreds of
ticks per block), not forensics.  The :class:`FlightRecorder` keeps a
bounded in-memory ring of the MOST RECENT records crossing the
observability plane (telemetry block records, span records, stat/ops
events — anything dict-shaped) and writes them to a post-mortem JSONL
when the process is about to be useless:

* a fabric peer failure (``FabricPeerLost``/``FabricTimeout`` — the
  surviving side records what it saw the moment its peer vanished),
  via :func:`ringpop_tpu.parallel.fabric.add_failure_hook`;
* an uncaught exception (``sys.excepthook`` / ``threading.excepthook``
  — the dying side's own last seconds).

The dump is one JSONL file: a ``kind:"flight_header"`` record (reason,
rank, pid, wall time, :func:`git_commit`, buffer bounds) followed by the
buffered records oldest-first — the same schema the live journals use,
so every existing journal reader parses it (OBSERVABILITY.md documents
the format).  Dumping is once-per-process by default (the FIRST failure
is the interesting one; later hooks re-dump only with ``force=True``)
and never raises — a broken disk must not mask the original crash.

jax-free: stdlib only.  :func:`git_commit` lives here (not in the
jax-importing ``sim/telemetry.py``) so both the flight header and the
telemetry journal header share one provenance probe.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Optional

from ringpop_tpu.errors import FabricPeerLost, FabricTimeout

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def git_commit(repo: str = _REPO) -> Optional[str]:
    """The commit hash of the repo's HEAD, read straight from the
    ``.git`` directory (no subprocess — must work in minimal containers
    and never be slow): resolves ``HEAD`` through loose refs and
    ``packed-refs``.  None when the tree is not a git checkout — the
    journal header records that honestly rather than guessing."""
    git_dir = os.path.join(repo, ".git")
    try:
        # worktrees/submodules: .git may be a pointer file
        if os.path.isfile(git_dir):
            with open(git_dir) as f:
                line = f.read().strip()
            if line.startswith("gitdir:"):
                git_dir = os.path.normpath(
                    os.path.join(repo, line.split(":", 1)[1].strip())
                )
        # linked worktrees keep HEAD in their private gitdir but store
        # refs/packed-refs in the COMMON dir (named by `commondir`)
        common = git_dir
        common_file = os.path.join(git_dir, "commondir")
        if os.path.isfile(common_file):
            with open(common_file) as f:
                common = os.path.normpath(
                    os.path.join(git_dir, f.read().strip())
                )
        with open(os.path.join(git_dir, "HEAD")) as f:
            head = f.read().strip()
        if not head.startswith("ref:"):
            return head or None  # detached HEAD: the hash itself
        ref = head.split(":", 1)[1].strip()
        for base in (git_dir, common):
            loose = os.path.join(base, *ref.split("/"))
            if os.path.exists(loose):
                with open(loose) as f:
                    return f.read().strip() or None
        packed = os.path.join(common, "packed-refs")
        if os.path.exists(packed):
            with open(packed) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith(("#", "^")):
                        sha, _, name = line.partition(" ")
                        if name == ref:
                            return sha
        return None
    except OSError:
        return None


class FlightRecorder:
    """Bounded ring of recent observability records + post-mortem dump.

    ``capacity`` bounds memory (records are shallow-copied dicts; at the
    default 1024 a fleet block record ≈ 1 KB keeps the ring around a
    megabyte).  The recorder is itself a record sink — pass it wherever
    a ``TelemetrySink.fn``, a ``Tracer`` sink, or a stats hook takes a
    callable."""

    def __init__(
        self,
        capacity: int = 1024,
        *,
        rank: int = 0,
        path: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.rank = rank
        # default landing spot: RINGPOP_FLIGHT_DIR or the cwd
        self.path = path or os.path.join(
            os.environ.get("RINGPOP_FLIGHT_DIR", "."),
            f"flight-rank{rank}-pid{os.getpid()}.jsonl",
        )
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        # first dump path PER SCOPE: the engine's once-per-process dump
        # ("engine" — fabric failures, uncaught exceptions) must survive
        # a controller mitigation dumping first, so each scope gets its
        # own once-only slot and its own default filename
        self.dumps: dict[str, str] = {}
        self._installed: list = []

    @property
    def dumped(self) -> Optional[str]:
        """Path of the first ENGINE-scope dump (the once-per-process
        crash dump; controller-scope dumps do not consume it)."""
        return self.dumps.get("engine")

    # -- recording ------------------------------------------------------------

    def record(self, rec: dict) -> None:
        """Append one record (any dict with a ``kind``; missing kinds
        are stamped ``"event"``).  Never raises."""
        try:
            entry = {"kind": "event", **rec}
            with self._lock:
                entry["flight_seq"] = self._seq
                self._seq += 1
                self._ring.append(entry)
        except Exception:
            pass

    __call__ = record  # sink duck-type (Tracer sink / TelemetrySink.fn)

    def event(self, kind: str, **fields) -> None:
        self.record({"kind": kind, "t": time.time(), **fields})

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    @property
    def seq(self) -> int:
        return self._seq

    # -- dumping --------------------------------------------------------------

    def dump(
        self,
        reason: str,
        *,
        error: Optional[BaseException] = None,
        path: Optional[str] = None,
        force: bool = False,
        scope: str = "engine",
    ) -> Optional[str]:
        """Write the post-mortem JSONL; returns its path (None when a
        previous dump in the same ``scope`` already exists and ``force``
        is False, or on any write failure — never raises).  Dumping is
        once-per-process PER SCOPE: the default ``"engine"`` scope is
        the crash dump the fabric/excepthook triggers own; a failing
        controller mitigation dumps under ``scope="controller"`` with a
        ``-controller``-suffixed filename, leaving the engine dump
        unburned for a real fabric failure."""
        try:
            with self._lock:
                if scope in self.dumps and not force:
                    return None
                target = path or self.path
                if path is None and scope != "engine":
                    root, ext = os.path.splitext(self.path)
                    target = f"{root}-{scope}{ext}"
                records = list(self._ring)
                seq = self._seq
            header = {
                "kind": "flight_header",
                "reason": reason,
                "scope": scope,
                "error": None if error is None else (
                    f"{type(error).__name__}: {error}"
                ),
                "rank": self.rank,
                "pid": os.getpid(),
                "t": time.time(),
                "git_commit": git_commit(),
                "capacity": self.capacity,
                "records": len(records),
                "dropped": max(0, seq - len(records)),
            }
            tmp = f"{target}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(header, sort_keys=True) + "\n")
                for rec in records:
                    f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
            os.replace(tmp, target)
            with self._lock:
                self.dumps.setdefault(scope, target)
            return target
        except Exception:
            return None

    # -- hook installation -----------------------------------------------------

    def install(
        self,
        *,
        fabric: bool = True,
        excepthook: bool = True,
        threads: bool = True,
    ) -> "FlightRecorder":
        """Arm the dump triggers.  ``fabric`` registers with the DCN
        fabric's failure hooks (dump on ``FabricPeerLost``/
        ``FabricTimeout`` — the surviving rank's view of a dead peer);
        ``excepthook``/``threads`` chain the process hooks (the dying
        rank's own view), calling the PREVIOUS hook afterwards so
        default tracebacks still print."""
        if fabric:
            from ringpop_tpu.parallel import fabric as _fabric

            def on_fabric(err: BaseException) -> None:
                if isinstance(err, (FabricPeerLost, FabricTimeout)):
                    self.dump(f"fabric:{type(err).__name__}", error=err)

            _fabric.add_failure_hook(on_fabric)
            self._installed.append(("fabric", on_fabric))
        if excepthook:
            prev = sys.excepthook

            def hook(etype, evalue, etb, _prev=prev):
                self.dump("uncaught_exception", error=evalue)
                _prev(etype, evalue, etb)

            sys.excepthook = hook
            self._installed.append(("excepthook", prev))
        if threads:
            prev_t = threading.excepthook

            def thook(args, _prev=prev_t):
                self.dump("uncaught_thread_exception", error=args.exc_value)
                _prev(args)

            threading.excepthook = thook
            self._installed.append(("threads", prev_t))
        return self

    def uninstall(self) -> None:
        """Undo :meth:`install` (tests; reverse order)."""
        for kind, obj in reversed(self._installed):
            if kind == "fabric":
                from ringpop_tpu.parallel import fabric as _fabric

                _fabric.remove_failure_hook(obj)
            elif kind == "excepthook":
                sys.excepthook = obj
            elif kind == "threads":
                threading.excepthook = obj
        self._installed.clear()
