"""Game days: score the closed loop against a no-controller twin.

A game day injects an r18 correlated-failure scenario (zone cut,
switch flap) into a live P=2 fleet — LocalKV threads, the same obs
fabric code paths real OS processes run — with the FULL reflex arc
attached on rank 0: ``AggregatingStats`` → ``RuleEngine`` →
``OpsController`` → ``RingStore`` drain, all evaluated at the
``FleetSweep.on_block`` protocol point.  The judged quantity is
**time-to-mitigate**:

* controller run — ticks from the injected cut to the controller's
  successful ``drain`` action (the cut zone's serve node routed away by
  a generation commit);
* twin run (same seeds, controller off) — ticks from the cut until
  SWIM's organic route-around completes (``detect_frac`` reaches 1.0 in
  the journal: every faulty member declared, membership fully reflects
  the cut).

The controller acts on the probe-timeout SPIKE (visible one journal
block after the cut), while declaration must wait out ``suspect_ticks``
plus dissemination — so a working loop mitigates strictly earlier, and
:func:`gameday_pair` asserts it.  Both runs must land bit-identical
sim digests (the loop is host-side policy over seams that existed
before it; it can trigger serve-plane commits, never sim arithmetic) —
the digest bar that lets the controller ship without re-baselining a
single committed artifact.

Mapping note: the sim fleet and the serve mesh are joined by
CONVENTION here — one serve node per topology zone (``z0``…), and the
harness tells the controller which zone a fleet-wide degradation names
(``server_of``).  A live mesh (ROADMAP "run the protocol for real")
derives that subject from per-rank ``/healthz`` staleness instead; the
rules/controller layers are identical either way.

jax-free at import; the sim stack loads inside :func:`run_gameday`.
"""

from __future__ import annotations

import threading
from typing import Optional

from ringpop_tpu.obs.controller import OpsController
from ringpop_tpu.obs.endpoint import LiveOps
from ringpop_tpu.obs.flight import FlightRecorder
from ringpop_tpu.obs.rules import CrossRankSkew, RateOfChange, RuleEngine, Staleness
from ringpop_tpu.obs.trace import chain

SCENARIOS = ("zone_cut", "switch_flap")

# the gameday's serve-mesh convention: one serve node per zone of the
# default 2x2x2 topology tree
N_ZONES = 4


def _build_plan(scenario: str, n: int, cut_at: int, journal_every: int):
    from ringpop_tpu.sim import chaos, topology

    topo = topology.default_topology(n)
    if scenario == "zone_cut":
        solo = topology.zone_loss_plan(topo, 0, at=cut_at)
    elif scenario == "switch_flap":
        solo = topology.switch_flap_plan(
            topo, 0, period=4 * journal_every,
            down=2 * journal_every, start=cut_at,
        )
    else:
        raise ValueError(f"scenario must be one of {SCENARIOS}; got {scenario!r}")
    # B=2: the same correlated event under two seeds, one scenario per
    # fleet rank (the minimal process-sliced sweep)
    plan = chaos.stack_plans([solo, solo])
    meta = [
        {"scenario_id": i, "event": scenario, "rep": i} for i in range(2)
    ]
    return topo, plan, meta


def organic_mitigation_tick(
    blocks: list[dict], cut_at: int = 0
) -> Optional[int]:
    """The twin's mitigation point: end tick of the first journal block
    AFTER the cut where every faulty member is both declared
    (``census_faulty > 0``) and detected (``detect_frac >= 1.0`` —
    membership, and therefore the reference system's ring, fully
    reflects the cut).  ``detect_frac`` is trivially 1.0 while nothing
    is faulty, so both conditions are required.  None if the horizon
    ends first."""
    for rec in blocks:
        if (
            int(rec.get("tick", -1)) > cut_at
            and float(rec.get("census_faulty", 0.0)) > 0.0
            and float(rec.get("detect_frac", 0.0)) >= 1.0
        ):
            return int(rec["tick"])
    return None


def run_gameday(
    *,
    scenario: str = "zone_cut",
    n: int = 64,
    seed: int = 0,
    horizon: int = 48,
    journal_every: int = 8,
    cut_at: Optional[int] = None,
    controller: bool = True,
    flight_dir: Optional[str] = None,
) -> dict:
    """One P=2 game-day run; returns the scorecard dict.

    Keys: ``digests`` (both ranks merged), ``alerts``/``actions``
    (journal records), ``mitigation_tick`` (controller) /
    ``organic_tick`` (always), ``ttm`` (whichever applies),
    ``chain`` (the drain action's reconstructed span chain), plus the
    run config.  ``controller=False`` runs the digest-twin: identical
    fleet, rules still evaluated (alerts are observation), no actions.
    """
    import numpy as np

    from ringpop_tpu.parallel.fabric import LocalKV
    from ringpop_tpu.parallel.partition import process_block
    from ringpop_tpu.sim import chaos, scenarios
    from ringpop_tpu.sim.lifecycle import LifecycleParams
    from ringpop_tpu.serve.state import RingStore

    if cut_at is None:
        cut_at = 2 * journal_every  # one full baseline delta before it
    _topo, plan, meta = _build_plan(scenario, n, cut_at, journal_every)
    params = LifecycleParams(n=n, k=32, suspect_ticks=10, rng="counter")
    seeds = [seed, seed + 101]
    nprocs = 2
    ns = f"gameday-{scenario}-{seed}-{int(controller)}"

    # -- the serve plane the controller acts on (rank 0, host-side) ----------
    store = RingStore(
        [f"z{z}" for z in range(N_ZONES)], replica_points=32,
        placement="dgro", placement_kw={"candidates": 2, "probes": 1 << 10},
    )
    probe_keys = [f"probe-{i}" for i in range(512)]
    probe_hashes = np.asarray(
        [store.ring.hashfunc(k) & 0xFFFFFFFF for k in probe_keys], np.uint32
    )

    def drain_probe(server: str) -> int:
        owners = store.ring.lookup_batch(probe_keys)
        return sum(1 for o in owners if o == server)

    # -- the reflex arc (rank 0) ----------------------------------------------
    journal: list[dict] = []  # kind:"alert"/"action" records, in order
    recorder = FlightRecorder(
        capacity=256, rank=0,
        path=None if flight_dir is None else f"{flight_dir}/gameday-flight.jsonl",
    )

    def sink(rec: dict) -> None:
        journal.append(rec)
        recorder(rec)

    engine = RuleEngine(
        [
            # the fast signal: probe-timeout delta jumps 5-20x the block
            # after a zone cut (self-calibrating — see rules.py)
            RateOfChange(
                id="probe-timeout-spike", key="ringpop.sim.ping.timeout",
                source="counters", spike_ratio=4.0,
                floor=max(1.0, 0.01 * n * journal_every),
                per_rank=False, hold=1,
            ),
            # quiet-by-construction rules ride along: a healthy gameday
            # must NOT fire them (asserted by the smoke)
            CrossRankSkew(
                id="serve-load-skew", key="ringpop.serve.keys.share",
                source="gauges", ratio=1.5, hold=2,
            ),
            Staleness(id="rank-stale", hold=2),
        ],
        sink=sink,
    )
    ctl = (
        OpsController(
            sink=sink,
            policy={
                "probe-timeout-spike": "drain",
                "serve-load-skew": "dgro_rescore",
                "rank-stale": "resize",
            },
            ring_store=store,
            # fleet-wide degradation maps to the cut zone's serve node
            # (harness convention — see the module docstring)
            server_of=lambda _subject: "z0",
            drain_probe=drain_probe,
            recorder=recorder,
            cooldown=1_000_000,  # one shot per game day
        )
        if controller
        else None
    )

    mitigation = {"tick": None}
    kv = LocalKV()
    opses: list = [None, None]
    sweeps: list = [None, None]
    digests: list = [None, None]
    errs: list = [None, None]
    ready = threading.Barrier(nprocs, timeout=120)

    def make_on_block(rank: int, ops: "LiveOps"):
        def on_block(sweep) -> None:
            # every rank gauges its serve-process key share (the
            # CrossRankSkew input — forward.batch.rank_load over the
            # committed ring against the fixed probe population)
            try:
                from ringpop_tpu.forward.batch import rank_load

                toks, _owners, _gen, _ns = store.snapshot_host()
                share = rank_load(toks, probe_hashes, nprocs)[rank]
                ops.stats.gauge("ringpop.serve.keys.share", float(share))
            except Exception:
                pass  # the ops plane never takes the run down
            if rank != 0:
                return
            alerts = engine.evaluate(
                ops.snapshots(), health=ops.health(),
                tick=sweep.ticks_done,
            )
            if ctl is None:
                return
            for act in ctl.on_alerts(alerts, tick=sweep.ticks_done):
                if (
                    act.get("action") == "drain"
                    and act.get("ok")
                    and mitigation["tick"] is None
                ):
                    mitigation["tick"] = sweep.ticks_done

        return on_block

    def worker(rank: int) -> None:
        try:
            ops = LiveOps(
                rank, nprocs, kv=kv, namespace=ns,
                recorder=recorder if rank == 0 else None,
            )
            opses[rank] = ops
            ready.wait()
            lo, hi = process_block(len(meta), rank, nprocs)
            sweep = scenarios.FleetSweep(
                params, chaos.slice_plan(plan, lo, hi), meta[lo:hi],
                seeds[lo:hi], horizon=horizon,
                journal_every=journal_every, scenario="gameday",
                global_b=len(meta), obs=ops,
                on_block=make_on_block(rank, ops),
            )
            sweep.run()
            sweeps[rank] = sweep
            digests[rank] = sweep.digests()
        except BaseException as e:  # noqa: BLE001
            errs[rank] = e
        finally:
            if opses[rank] is not None:
                opses[rank].close()

    threads = [
        threading.Thread(target=worker, args=(r,), name=f"gameday-r{r}")
        for r in range(nprocs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    if any(errs):
        raise RuntimeError(f"gameday rank died: {errs}")

    merged_digests: dict[int, int] = {}
    for d in digests:
        merged_digests.update(d or {})
    blocks0 = sweeps[0].blocks[0]
    organic = organic_mitigation_tick(blocks0, cut_at)
    mit = mitigation["tick"]
    ttm = (
        (mit - cut_at)
        if controller and mit is not None
        else ((organic if organic is not None else horizon) - cut_at)
    )
    drain_actions = [
        r for r in journal
        if r.get("kind") == "action" and r.get("action") == "drain"
    ]
    chains = [chain(journal, a["trace"]) for a in drain_actions]
    return {
        "scenario": scenario,
        "controller": controller,
        "n": n,
        "seed": seed,
        "horizon": horizon,
        "journal_every": journal_every,
        "cut_at": cut_at,
        "digests": merged_digests,
        "alerts": [r for r in journal if r.get("kind") == "alert"],
        "actions": [r for r in journal if r.get("kind") == "action"],
        "mitigation_tick": mit,
        "organic_tick": organic,
        "ttm": ttm,
        "chains": chains,
        "ring_gen": store.gen,
        "flight_dumps": dict(recorder.dumps),
    }


def bare_digests(
    *, scenario: str = "zone_cut", n: int = 64, seed: int = 0,
    horizon: int = 48, journal_every: int = 8,
    cut_at: Optional[int] = None,
) -> dict:
    """The HEAD oracle: the identical fleet on P=1 with NO obs plane,
    no rules, no controller — what today's committed code computes.
    The controller-off twin (and, by the host-side-only construction,
    the controller-on run) must match these digests bit for bit; the
    smoke asserts it, which is what lets r22 ship without re-baselining
    any committed artifact."""
    from ringpop_tpu.sim import scenarios
    from ringpop_tpu.sim.lifecycle import LifecycleParams

    if cut_at is None:
        cut_at = 2 * journal_every
    _topo, plan, meta = _build_plan(scenario, n, cut_at, journal_every)
    params = LifecycleParams(n=n, k=32, suspect_ticks=10, rng="counter")
    sweep = scenarios.FleetSweep(
        params, plan, meta, [seed, seed + 101], horizon=horizon,
        journal_every=journal_every, scenario="gameday",
    )
    sweep.run()
    return sweep.digests()


def gameday_pair(
    *, scenario: str = "zone_cut", n: int = 64, seed: int = 0,
    horizon: int = 48, journal_every: int = 8,
) -> dict:
    """Controller run + digest-identical twin, judged.  Returns the two
    scorecards plus the verdict fields the smoke/simbench/certify
    layers all read: ``digest_equal``, ``ttm_on``/``ttm_off``, and
    ``mitigated_earlier`` (the acceptance bar: strictly better)."""
    on = run_gameday(
        scenario=scenario, n=n, seed=seed, horizon=horizon,
        journal_every=journal_every, controller=True,
    )
    off = run_gameday(
        scenario=scenario, n=n, seed=seed, horizon=horizon,
        journal_every=journal_every, controller=False,
    )
    return {
        "scenario": scenario,
        "on": on,
        "off": off,
        "digest_equal": on["digests"] == off["digests"],
        "ttm_on": on["ttm"],
        "ttm_off": off["ttm"],
        "mitigated_earlier": on["ttm"] < off["ttm"],
    }
