"""Declarative alert rules over the live operations plane (r22).

r20 gave every rank ``/metrics``, ``/healthz`` and ``/progress``; this
module is the first consumer that is not a human: a small rule engine
evaluated over :class:`~ringpop_tpu.obs.aggregate.AggregatingStats`
snapshots and the :class:`~ringpop_tpu.obs.endpoint.LiveOps` health/
progress views, at the same protocol point the obs plane already syncs
(a journal block boundary).  Four predicate families cover the signals
the controller acts on:

* :class:`Threshold` — a counter/gauge/timing statistic crosses a bound;
* :class:`RateOfChange` — the per-evaluation delta of a monotone
  counter leaves a band (a stalled rate is the "rank stopped making
  progress" signal; a spiking one is the suspicion-storm signal);
* :class:`Staleness` — a rank's ``/healthz`` liveness drops (dead or
  stale by snapshot age);
* :class:`CrossRankSkew` — one rank's value diverges from the fleet
  mean by more than a ratio (per-rank serve load, arc diameter).

Every rule runs through one hysteresis state machine
(:class:`_RuleState`): a FIRING threshold with a minimum hold window
(the predicate must hold for ``hold`` consecutive evaluations before
the alert fires) and a separate CLEAR threshold/window — a flapping
signal therefore cannot thrash the controller, which is the whole
point of putting hysteresis here rather than in each mitigation.

Each state TRANSITION (clear→firing, firing→clear) emits exactly one
``kind:"alert"`` journal record carrying the rule id, the observed
value, and a deterministic span (``obs/trace.py`` ids derived from the
rule id + subject + firing ordinal — reruns land identical alert
spans).  Controller actions parent onto that span, so
``obs.trace.chain()`` reconstructs alert → action from the journal
alone.

jax-free: numpy + stdlib only, like the rest of ``obs/``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ringpop_tpu.obs.trace import salt_of, span_id_of, trace_id_of

# the whole-fleet subject of rules that do not name a rank
FLEET = -1


def _resolve(snapshot: dict, source: str, key: str):
    """One rank's observed value for (source, key); None when absent.
    ``source`` is a snapshot family (``counters``/``gauges``/
    ``rates_1m``) or a timing statistic path ``timings.<stat>`` —
    e.g. ``timings.p99`` reads ``snapshot["timings"][key]["p99"]``."""
    if source.startswith("timings."):
        entry = snapshot.get("timings", {}).get(key)
        if entry is None:
            return None
        return entry.get(source.split(".", 1)[1])
    v = snapshot.get(source, {}).get(key)
    return None if v is None else float(v)


class _RuleState:
    """The hysteresis state machine one (rule, subject) pair owns:
    ``update(firing_pred, clear_pred)`` per evaluation, transition
    reported only after the respective hold window is satisfied."""

    __slots__ = ("firing", "_hold_fire", "_hold_clear", "fired_count")

    def __init__(self):
        self.firing = False
        self._hold_fire = 0
        self._hold_clear = 0
        self.fired_count = 0  # firing ordinal — salts the alert span

    def update(
        self, fire: bool, clear: bool, hold: int, hold_clear: int
    ) -> Optional[str]:
        """-> "firing" / "clear" on a transition, else None."""
        if not self.firing:
            self._hold_fire = self._hold_fire + 1 if fire else 0
            if self._hold_fire >= hold:
                self.firing = True
                self._hold_fire = 0
                self.fired_count += 1
                return "firing"
            return None
        self._hold_clear = self._hold_clear + 1 if clear else 0
        if self._hold_clear >= hold_clear:
            self.firing = False
            self._hold_clear = 0
            return "clear"
        return None


@dataclass
class Rule:
    """Base declarative rule: id + hysteresis windows.  Subclasses
    implement :meth:`observe` returning ``{subject: value}`` — one
    hysteresis machine per subject (a rank id, or :data:`FLEET`)."""

    id: str
    hold: int = 1        # consecutive firing evaluations before "firing"
    hold_clear: int = 1  # consecutive clear evaluations before "clear"

    def observe(self, ctx: "EvalContext") -> dict:
        raise NotImplementedError

    def fire_pred(self, value) -> bool:
        raise NotImplementedError

    def clear_pred(self, value) -> bool:
        # default clear = not firing (no hysteresis band)
        return not self.fire_pred(value)


_OPS: dict[str, Callable] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


@dataclass
class Threshold(Rule):
    """``value <op> firing`` on one rank's (or every rank's) stat.

    ``clear`` is the OTHER edge of the hysteresis band (defaults to the
    firing threshold — no band).  ``per_rank=True`` evaluates every
    rank's snapshot separately (one alert per rank); otherwise only
    rank 0's."""

    key: str = ""
    source: str = "gauges"
    op: str = ">"
    firing: float = 0.0
    clear: Optional[float] = None
    per_rank: bool = False

    def observe(self, ctx: "EvalContext") -> dict:
        if self.per_rank:
            out = {}
            for r, snap in ctx.snapshots.items():
                v = _resolve(snap, self.source, self.key)
                if v is not None:
                    out[r] = v
            return out
        snap = ctx.snapshots.get(0)
        if snap is None:
            return {}
        v = _resolve(snap, self.source, self.key)
        return {} if v is None else {FLEET: v}

    def fire_pred(self, value) -> bool:
        return _OPS[self.op](value, self.firing)

    def clear_pred(self, value) -> bool:
        edge = self.firing if self.clear is None else self.clear
        return not _OPS[self.op](value, edge)


@dataclass
class RateOfChange(Rule):
    """The per-evaluation DELTA of a monotone counter leaves
    ``[low, high]``.  A stalled counter (delta 0 while the run should
    progress) and a spiking one (suspicion storm) both land here; the
    previous observation is kept per rank inside the rule.

    ``spike_ratio`` switches to SELF-CALIBRATING mode (``low``/``high``
    ignored): the observed value becomes the ratio of this delta to the
    previous delta (denominator floored at ``floor`` so a quiet
    baseline can't divide to infinity), and the rule fires on
    ``ratio > spike_ratio``.  An absolute threshold on e.g. probe
    timeouts depends on fleet size, probe fan-out and baseline loss;
    the ratio of consecutive block deltas does not — a zone cut is a
    5–20× step on any of them."""

    key: str = ""
    source: str = "counters"
    low: Optional[float] = None
    high: Optional[float] = None
    spike_ratio: Optional[float] = None
    floor: float = 1.0
    per_rank: bool = True
    _prev: dict = field(default_factory=dict, repr=False)
    _prev_delta: dict = field(default_factory=dict, repr=False)

    def observe(self, ctx: "EvalContext") -> dict:
        out = {}
        ranks = ctx.snapshots if self.per_rank else {0: ctx.snapshots.get(0)}
        for r, snap in ranks.items():
            if snap is None:
                continue
            v = _resolve(snap, self.source, self.key)
            if v is None:
                continue
            subject = r if self.per_rank else FLEET
            prev = self._prev.get(subject)
            self._prev[subject] = v
            if prev is None:
                continue  # first observation has no delta
            delta = v - prev
            if self.spike_ratio is None:
                out[subject] = delta
                continue
            prev_delta = self._prev_delta.get(subject)
            self._prev_delta[subject] = delta
            if prev_delta is None:
                continue  # ratio needs two consecutive deltas
            out[subject] = delta / max(prev_delta, self.floor)
        return out

    def fire_pred(self, value) -> bool:
        if self.spike_ratio is not None:
            return value > self.spike_ratio
        if self.low is not None and value < self.low:
            return True
        if self.high is not None and value > self.high:
            return True
        return False


@dataclass
class Staleness(Rule):
    """A rank's ``/healthz`` liveness drops: ``live == False`` for the
    hold window (dead fabric link, or snapshot age past the stale
    bound).  Observes the health view, not the snapshots — subjects are
    peer ranks only (a rank is never stale to itself)."""

    def observe(self, ctx: "EvalContext") -> dict:
        if ctx.health is None:
            return {}
        out = {}
        for rank_s, entry in ctx.health.get("ranks", {}).items():
            if entry.get("self"):
                continue
            out[int(rank_s)] = 0.0 if entry.get("live") else 1.0
        return out

    def fire_pred(self, value) -> bool:
        return value >= 1.0


@dataclass
class CrossRankSkew(Rule):
    """One rank's value exceeds ``ratio`` × the fleet mean (over the
    ranks that report the key).  The serve-load / arc-diameter skew
    trigger: fires per skewed rank, so the controller knows WHICH rank
    to re-place or drain."""

    key: str = ""
    source: str = "gauges"
    ratio: float = 1.5
    clear_ratio: Optional[float] = None  # default: ratio (no band)
    min_ranks: int = 2

    def observe(self, ctx: "EvalContext") -> dict:
        vals = {}
        for r, snap in ctx.snapshots.items():
            v = _resolve(snap, self.source, self.key)
            if v is not None:
                vals[r] = v
        if len(vals) < self.min_ranks:
            return {}
        mean = sum(vals.values()) / len(vals)
        if mean <= 0:
            return {}
        return {r: v / mean for r, v in vals.items()}

    def fire_pred(self, value) -> bool:
        return value > self.ratio

    def clear_pred(self, value) -> bool:
        edge = self.ratio if self.clear_ratio is None else self.clear_ratio
        return value <= edge


class EvalContext:
    """What one evaluation sees: per-rank snapshots + the rank-0 views."""

    __slots__ = ("snapshots", "health", "progress", "tick")

    def __init__(self, snapshots, health=None, progress=None, tick=None):
        self.snapshots = snapshots or {}
        self.health = health
        self.progress = progress
        self.tick = tick


class RuleEngine:
    """Evaluate a rule set per protocol point; emit transition records.

    ``sink`` takes one record dict per alert transition (a
    ``TelemetryJournal.span``-style callable, a ``JsonlSink``, a
    ``FlightRecorder``, a plain list ``.append`` — same contract as a
    ``Tracer`` sink).  Sink failures are swallowed and counted: the ops
    plane never takes the run down.

    Alert record schema (OBSERVABILITY.md "alert records")::

        {"kind": "alert", "rule": <id>, "state": "firing"|"clear",
         "value": <observed>, "about_rank": <rank or -1 fleet-wide>,
         "tick": <protocol tick or None>, "rank": <emitting rank>,
         "trace": ..., "span": ..., "parent": None, "t": <wall>}

    Span ids are pure functions of (rule id, subject, firing ordinal):
    reruns produce identical alert spans, and a clear record shares its
    firing's trace so one ``chain()`` pulls the whole episode.
    """

    def __init__(self, rules, *, sink, rank: int = 0):
        ids = [r.id for r in rules]
        if len(ids) != len(set(ids)):
            raise ValueError(f"duplicate rule ids: {sorted(ids)}")
        self.rules = list(rules)
        self.sink = sink
        self.rank = rank
        self._states: dict[tuple[str, int], _RuleState] = {}
        self.alerts_emitted = 0
        self.alerts_dropped = 0

    def state(self, rule_id: str, subject: int = FLEET) -> Optional[bool]:
        """True/False = firing/clear; None = never observed."""
        st = self._states.get((rule_id, subject))
        return None if st is None else st.firing

    def firing(self) -> list[tuple[str, int]]:
        """Every (rule id, subject) currently in the firing state."""
        return sorted(
            key for key, st in self._states.items() if st.firing
        )

    def evaluate(
        self,
        snapshots: dict[int, dict],
        *,
        health: Optional[dict] = None,
        progress: Optional[dict] = None,
        tick: Optional[int] = None,
    ) -> list[dict]:
        """One evaluation over the fleet's current views; returns the
        alert records emitted this round (also delivered to the sink)."""
        ctx = EvalContext(snapshots, health, progress, tick)
        out: list[dict] = []
        for rule in self.rules:
            try:
                observed = rule.observe(ctx)
            except Exception:
                continue  # a broken rule must not starve the others
            for subject, value in sorted(observed.items()):
                st = self._states.setdefault(
                    (rule.id, subject), _RuleState()
                )
                transition = st.update(
                    rule.fire_pred(value),
                    rule.clear_pred(value),
                    rule.hold,
                    rule.hold_clear,
                )
                if transition is None:
                    continue
                out.append(
                    self._emit(rule, subject, value, transition, st, tick)
                )
        return out

    def _emit(
        self, rule: Rule, subject: int, value, transition: str,
        st: _RuleState, tick,
    ) -> dict:
        # deterministic ids: the trace names the episode (rule, subject,
        # firing ordinal), the span names the transition within it —
        # a clear shares its firing's trace so chain() joins them
        trace = trace_id_of(salt_of("alert", rule.id, subject, st.fired_count))
        record = {
            "kind": "alert",
            "rule": rule.id,
            "state": transition,
            "value": round(float(value), 6),
            "about_rank": subject,
            "tick": tick,
            "rank": self.rank,
            "trace": trace,
            "span": span_id_of(trace, "alert", salt=salt_of(transition)),
            "parent": None,
            "t": time.time(),
        }
        try:
            self.sink(record)
            self.alerts_emitted += 1
        except Exception:
            self.alerts_dropped += 1
        return record
