"""Span tracing for the forwarding planes — the ``ringpop-trace`` header.

The reference ringpop forwards ONE keyed request per RPC and marks it
with the binary ``ringpop-forwarded`` header; r17's batch plane
generalized that to the ``ringpop-hops`` hop counter.  This module adds
the third header of the family: ``ringpop-trace`` carries ``<trace
id>:<parent span id>`` (8-hex-digit words) alongside ``ringpop-hops``
through ``forward/batch.py``, ``serve/mesh.py`` and ``net/channel.py``,
and every traced leg emits a ``kind:"span"`` record into the same JSONL
journals the telemetry plane already writes — joinable against the
serve tier's ``ring_update`` generation records via the ``gen`` field.

Design rules:

* **Deterministic sampling by key hash.**  A key is traced iff
  ``key_hash % sample == 0`` and its trace id is ``mix32(key_hash)`` — a
  pure function of the key, no RNG, no clock.  Reruns trace the SAME
  requests, and two processes looking at the same batch (the fabric's
  serve mesh, where no header crosses the wire) derive the SAME trace
  and span ids from content alone, so their records join without any
  in-band propagation.
* **Deterministic span ids.**  ``span_id = mix32(trace ^ crc32(leg) ^
  mix32(salt))`` — both endpoints of a fabric leg can compute each
  other's ids from (leg name, round/rank salt), which is how the mesh's
  answer spans parent onto the request spans they answer.
* **Bit-transparency.**  Tracing reads key hashes the planes already
  hold and writes host-side records; owners/generations/digests are
  untouched (pinned by the trace smoke and the serve-mesh digest test).
* **jax-free.**  Imported by ``net/channel.py``/``forward/batch.py``
  under the frontend jax-free contract; numpy + stdlib only.

``mix32`` is the murmur3 fmix32 mixer — the same public-domain constants
as ``sim/packbits.mix32`` (the one device-side copy), reimplemented here
in numpy because this module must not import jax.
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from typing import Callable, Optional

import numpy as np

TRACE_HEADER = "ringpop-trace"
HOPS_HEADER = "ringpop-hops"  # owned by forward.batch; read here for spans
DEFAULT_SAMPLE = 256


def mix32(x) -> np.ndarray:
    """murmur3 fmix32 over uint32 (vectorized; same constants as
    ``packbits.mix32`` — keep the two in sync)."""
    x = np.asarray(x, np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(0x85EB_CA6B)
        x = x ^ (x >> np.uint32(13))
        x = x * np.uint32(0xC2B2_AE35)
        x = x ^ (x >> np.uint32(16))
    return x


def trace_id_of(key_hash: int) -> int:
    """The (rerun-stable) trace id of one key hash."""
    return int(mix32(np.uint32(key_hash)))


def salt_of(*parts) -> int:
    """Fold strings/ints into one deterministic uint32 salt — distinct
    spans of the SAME (trace, leg) pair (different dest, rank, round,
    hop level) must get distinct ids, so call sites salt with whatever
    distinguishes them."""
    s = np.uint32(0)
    for p in parts:
        if isinstance(p, str):
            v = np.uint32(zlib.crc32(p.encode()) & 0xFFFFFFFF)
        else:
            v = np.uint32(int(p) & 0xFFFFFFFF)
        s = mix32(s ^ v)
    return int(s)


def span_id_of(
    trace: int, leg: str, salt: int = 0, parent: Optional[int] = None
) -> int:
    """Deterministic span id: both endpoints of a headerless transport
    (the fabric) compute the same value from (trace, leg, salt).  A
    non-None ``parent`` is folded in too, so two spans of the same
    (trace, leg, salt) reached through DIFFERENT upstream paths — e.g.
    a route-forward and a quorum-forward of the same key to the same
    dest at the same hop level — get distinct ids (root spans and the
    remotely-derived mesh request spans have no parent, so their ids
    stay computable from content alone)."""
    return int(
        mix32(
            np.uint32(trace)
            ^ np.uint32(zlib.crc32(leg.encode()) & 0xFFFFFFFF)
            ^ mix32(np.uint32(salt & 0xFFFFFFFF))
            ^ (
                np.uint32(0)
                if parent is None
                else mix32(np.uint32(parent & 0xFFFFFFFF))
            )
        )
    )


def format_header(trace: int, span: int) -> str:
    return f"{trace & 0xFFFFFFFF:08x}:{span & 0xFFFFFFFF:08x}"


def parse_header(headers: Optional[dict]) -> Optional[tuple[int, int]]:
    """``(trace, parent span)`` from a headers dict, or None when the
    request is untraced / the header is malformed (never raises — a
    garbled ops header must not fail a real request)."""
    raw = (headers or {}).get(TRACE_HEADER)
    if not raw or not isinstance(raw, str):
        return None
    parts = raw.split(":")
    if len(parts) != 2:
        return None
    try:
        return int(parts[0], 16) & 0xFFFFFFFF, int(parts[1], 16) & 0xFFFFFFFF
    except ValueError:
        return None


def _hops_of(headers: Optional[dict]) -> int:
    try:
        return int((headers or {}).get(HOPS_HEADER, 0))
    except (TypeError, ValueError):
        return 0


class Span:
    """One in-flight traced leg: ids chosen at ``begin``/``follow``,
    record emitted at ``finish`` (with the measured duration)."""

    __slots__ = ("tracer", "record", "_t0", "_done")

    def __init__(self, tracer: "Tracer", record: dict):
        self.tracer = tracer
        self.record = record
        self._t0 = time.perf_counter()
        self._done = False

    @property
    def trace(self) -> int:
        return self.record["trace"]

    @property
    def span(self) -> int:
        return self.record["span"]

    def header_value(self) -> str:
        """What goes into ``headers[TRACE_HEADER]`` for the downstream
        leg: this span becomes the callee's parent."""
        return format_header(self.trace, self.span)

    def finish(self, **fields) -> dict:
        """Emit the span record (idempotent: the first call wins)."""
        if self._done:
            return self.record
        self._done = True
        rec = self.record
        rec["dur_ms"] = round((time.perf_counter() - self._t0) * 1e3, 3)
        rec.update(fields)
        self.tracer._emit(rec)
        return rec


class Tracer:
    """Span factory + sampling policy + sink fan-out.

    ``sink`` is any callable taking one record dict (a
    :class:`JsonlSink`, ``TelemetryJournal.span``, a
    ``FlightRecorder``, or a :func:`tee` of several).  ``sample`` is
    the 1-in-N key-hash sampling denominator (1 = trace everything —
    tests; 0/None = disabled, every ``begin`` returns None)."""

    def __init__(
        self,
        sink: Callable[[dict], None],
        sample: int = DEFAULT_SAMPLE,
        rank: Optional[int] = None,
    ):
        self.sink = sink
        self.sample = int(sample) if sample else 0
        self.rank = rank
        self.spans_emitted = 0
        self.spans_dropped = 0  # sink failures swallowed (ops never kills)

    # -- sampling -------------------------------------------------------------

    def sample_mask(self, hashes) -> np.ndarray:
        h = np.asarray(hashes, np.uint32)
        if self.sample <= 0:
            return np.zeros(h.shape, bool)
        if self.sample == 1:
            return np.ones(h.shape, bool)
        return (h % np.uint32(self.sample)) == 0

    def sampled_keys(self, hashes) -> np.ndarray:
        h = np.asarray(hashes, np.uint32)
        return h[self.sample_mask(h)]

    # -- span construction ----------------------------------------------------

    def begin(
        self,
        leg: str,
        hashes,
        *,
        parent: Optional[int] = None,
        salt: int = 0,
        hops: int = 0,
        **fields,
    ) -> Optional[Span]:
        """Start a span for a key batch: None unless the batch holds at
        least one sampled key.  ``trace`` is the FIRST sampled key's
        trace id (the reference's one-trace-per-request shape); every
        sampled key's hash + trace id ride the record (``keys`` /
        ``traces``) so any sampled key's chain reconstructs from the
        journal alone."""
        keys = self.sampled_keys(hashes)
        if keys.size == 0:
            return None
        traces = mix32(keys)
        trace = int(traces[0])
        parent = None if parent is None else int(parent) & 0xFFFFFFFF
        record = {
            "kind": "span",
            "leg": leg,
            "trace": trace,
            "span": span_id_of(trace, leg, salt, parent=parent),
            "parent": parent,
            "hops": int(hops),
            "nkeys": int(np.asarray(hashes).shape[0]),
            "keys": [int(k) for k in keys.tolist()],
            "traces": [int(t) for t in traces.tolist()],
            "t": time.time(),
        }
        if self.rank is not None:
            record["rank"] = self.rank
        record.update(fields)
        return Span(self, record)

    def follow(
        self, headers: Optional[dict], leg: str, *, salt: int = 0, **fields
    ) -> Optional[Span]:
        """Continue a trace arriving in ``headers``: None when the
        request is untraced (the upstream made the sampling decision).
        The header's span id becomes this span's parent; ``hops`` is
        read from the ``ringpop-hops`` header the same request carries."""
        parsed = parse_header(headers)
        if parsed is None:
            return None
        trace, parent = parsed
        record = {
            "kind": "span",
            "leg": leg,
            "trace": trace,
            # the parent rides the id: the same endpoint serving the
            # same trace through two different upstream RPCs emits two
            # distinct server/handle spans
            "span": span_id_of(trace, leg, salt, parent=parent),
            "parent": parent,
            "hops": _hops_of(headers),
            "t": time.time(),
        }
        if self.rank is not None:
            record["rank"] = self.rank
        record.update(fields)
        return Span(self, record)

    def _emit(self, record: dict) -> None:
        try:
            self.sink(record)
            self.spans_emitted += 1
        except Exception:
            # the ops plane must never take a request down
            self.spans_dropped += 1


class JsonlSink:
    """A thread-safe JSONL span sink (one record per line) — the
    standalone-file flavor; runs that already hold a
    ``TelemetryJournal`` pass its ``.span`` method instead."""

    def __init__(self, path: str, *, append: bool = False):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a" if append else "w", buffering=1)

    def __call__(self, record: dict) -> None:
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            if not self._f.closed:
                self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def tee(*sinks: Callable[[dict], None]) -> Callable[[dict], None]:
    """Fan one record out to several sinks (journal + flight recorder)."""

    def fan(record: dict) -> None:
        for s in sinks:
            s(record)

    return fan


# record kinds that participate in the span graph: plain spans plus the
# closed-loop pair (obs/rules.py alerts, obs/controller.py actions) —
# each carries trace/span/parent fields, so one chain() walk joins
# alert -> decision -> action -> effect with the forwarding-plane spans
SPAN_KINDS = ("span", "alert", "action")


def chain(records: list[dict], trace: int) -> list[dict]:
    """Reconstruct one trace's span chain from journal records: the
    span-carrying records (``kind`` in :data:`SPAN_KINDS`) whose
    ``trace`` (or ``traces`` list) matches, PLUS their ancestors by
    parent link — a batch-level RPC span records only the batch's
    primary trace, but it carries every rider key, so a rider's chain
    pulls it in through the parent pointer of its own spans.  Ordered
    parent-first (roots first, then children, ties in record order) —
    the join the trace smoke and the acceptance test walk."""
    all_spans = [
        r for r in records
        if r.get("kind") in SPAN_KINDS and "span" in r and "trace" in r
    ]
    by_span: dict[int, dict] = {}
    for s in all_spans:
        by_span.setdefault(s["span"], s)
    keep_ids: set[int] = set()
    for s in all_spans:
        if s.get("trace") == trace or trace in (s.get("traces") or []):
            # the span itself + its ancestor closure
            node, seen = s, set()
            while node is not None and node["span"] not in seen:
                keep_ids.add(node["span"])
                seen.add(node["span"])
                p = node.get("parent")
                node = by_span.get(p) if p is not None else None
    spans = [s for s in all_spans if s["span"] in keep_ids]

    def depth(s: dict) -> int:
        d, seen = 0, {s["span"]}
        p = s.get("parent")
        while p is not None and p in by_span and p not in seen:
            d += 1
            seen.add(p)
            p = by_span[p].get("parent")
        return d

    order = sorted(range(len(spans)), key=lambda i: (depth(spans[i]), i))
    return [spans[i] for i in order]
