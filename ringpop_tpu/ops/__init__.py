from ringpop_tpu.ops.hash_ops import fingerprint32_device, keyed_owner_lookup
from ringpop_tpu.ops.ring_ops import ring_lookup, ring_lookup_n, build_ring_tokens

__all__ = [
    "ring_lookup",
    "ring_lookup_n",
    "build_ring_tokens",
    "fingerprint32_device",
    "keyed_owner_lookup",
]
