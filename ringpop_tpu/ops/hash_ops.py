"""On-device FarmHash Fingerprint32 + fused keyed ring routing.

The host plane hashes with the native C++ core (``ringpop_tpu/native``);
this module computes the SAME bit-exact Fingerprint32 on the accelerator,
so the entire keyed data path — hash the key, find the owner on the ring —
runs on-device for millions of keys per call with no host round trip
(reference equivalents are scalar: ``hashring.go:107`` farm.Fingerprint32 +
``hashring.go:279-301`` per-key tree walk).

Design notes for TPU:

* the four length-class branches of farmhashmk::Hash32 are evaluated for
  every row and selected with ``where`` — branchless, vector-friendly,
  ~4× compute for zero divergence (hash math is cheap; HBM is not);
* the >24-byte mixing loop runs ``(L_max-1)//20`` iterations at STATIC
  byte offsets (0, 20, 40, …) with per-row activity masks, so XLA sees a
  fixed-trip loop over column slices — no dynamic gathers in the hot loop;
* only the six tail fetches use per-row dynamic offsets
  (``take_along_axis`` gathers).

``fingerprint32_pallas`` (in ``hash_pallas.py``) runs the same mixing loop
as a fused Pallas kernel; this jnp version is the portable path and the
correctness oracle for it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_MIX5 = np.uint32(5)
_MIXC = np.uint32(0xE6546B64)


def _ror(v, s: int):
    return (v >> U32(s)) | (v << U32(32 - s))


def _fmix(h):
    h ^= h >> U32(16)
    h = h * np.uint32(0x85EBCA6B)
    h ^= h >> U32(13)
    h = h * np.uint32(0xC2B2AE35)
    h ^= h >> U32(16)
    return h


def _mur(a, h):
    a = a * _C1
    a = _ror(a, 17)
    a = a * _C2
    h = h ^ a
    h = _ror(h, 19)
    return h * _MIX5 + _MIXC


def _fetch32_at(mat, idx):
    """Little-endian u32 at per-row byte offsets (dynamic gather)."""
    idx = jnp.maximum(idx, 0).astype(jnp.int32)
    cols = idx[:, None] + jnp.arange(4, dtype=jnp.int32)[None, :]
    b = jnp.take_along_axis(mat, cols, axis=1).astype(U32)  # [B, 4]
    return b[:, 0] | (b[:, 1] << U32(8)) | (b[:, 2] << U32(16)) | (b[:, 3] << U32(24))


def _fetch32_col(mat, off: int):
    """Little-endian u32 at one static byte offset (column slice)."""
    b = mat[:, off : off + 4].astype(U32)
    return b[:, 0] | (b[:, 1] << U32(8)) | (b[:, 2] << U32(16)) | (b[:, 3] << U32(24))


def _hash_0_4(mat, lens):
    b = jnp.zeros(mat.shape[0], U32)
    c = jnp.full(mat.shape[0], 9, U32)
    for i in range(min(4, mat.shape[1])):
        active = lens > i
        v = mat[:, i].astype(jnp.int8).astype(jnp.int32).astype(U32)  # signed char
        nb = b * _C1 + v
        b = jnp.where(active, nb, b)
        c = jnp.where(active, c ^ nb, c)
    return _fmix(_mur(b, _mur(lens.astype(U32), c)))


def _hash_5_12(mat, lens):
    ln = lens.astype(U32)
    a = ln + _fetch32_at(mat, jnp.zeros_like(lens))
    b = ln * U32(5) + _fetch32_at(mat, lens - 4)
    c = U32(9) + _fetch32_at(mat, (lens >> 1) & 4)
    d = ln * U32(5)
    return _fmix(_mur(c, _mur(b, _mur(a, d))))


def _hash_13_24(mat, lens):
    ln = lens.astype(U32)
    a = _fetch32_at(mat, (lens >> 1) - 4)
    b = _fetch32_at(mat, jnp.full_like(lens, 4))
    c = _fetch32_at(mat, lens - 8)
    d = _fetch32_at(mat, lens >> 1)
    e = _fetch32_at(mat, jnp.zeros_like(lens))
    f = _fetch32_at(mat, lens - 4)
    h = d * _C1 + ln
    a = _ror(a, 12) + f
    h = _mur(c, h) + a
    a = _ror(a, 3) + c
    h = _mur(e, h) + a
    a = _ror(a + f, 12) + d
    h = _mur(b, h) + a
    return _fmix(h)


def _tail_words(mat, lens):
    """The five rotated tail constants of the >24 path (dynamic fetches)."""
    def rot(off):
        return _ror(_fetch32_at(mat, lens - off) * _C1, 17) * _C2

    return rot(4), rot(8), rot(16), rot(12), rot(20)


def _hash_gt24(mat, lens, max_iters: int):
    ln = lens.astype(U32)
    a0, a1, a2, a3, a4 = _tail_words(mat, lens)
    h = ln
    g = _C1 * ln
    f = g
    h = _ror(h ^ a0, 19) * _MIX5 + _MIXC
    h = _ror(h ^ a2, 19) * _MIX5 + _MIXC
    g = _ror(g ^ a1, 19) * _MIX5 + _MIXC
    g = _ror(g ^ a3, 19) * _MIX5 + _MIXC
    f = _ror(f + a4, 19) + U32(113)

    iters = (lens - 1) // 20
    for t in range(max_iters):
        off = 20 * t
        if off + 20 > mat.shape[1]:
            break
        active = iters > t
        a = _fetch32_col(mat, off)
        b = _fetch32_col(mat, off + 4)
        c = _fetch32_col(mat, off + 8)
        d = _fetch32_col(mat, off + 12)
        e = _fetch32_col(mat, off + 16)
        nh = _mur(d, h + a) + e
        ng = _mur(c, g + b) + a
        nf = _mur(b + e * _C1, f + c) + d
        nf = nf + ng
        ng = ng + nf
        h = jnp.where(active, nh, h)
        g = jnp.where(active, ng, g)
        f = jnp.where(active, nf, f)

    g = _ror(g, 11) * _C1
    g = _ror(g, 17) * _C1
    f = _ror(f, 11) * _C1
    f = _ror(f, 17) * _C1
    h = _ror(h + g, 19) * _MIX5 + _MIXC
    h = _ror(h, 17) * _C1
    h = _ror(h + f, 19) * _MIX5 + _MIXC
    h = _ror(h, 17) * _C1
    return h


@jax.jit
def fingerprint32_device(mat, lens) -> jax.Array:
    """Bit-exact FarmHash Fingerprint32 of B byte strings on-device.

    ``mat`` uint8[B, L] right-padded with >= 4 zero bytes past each row's
    length; ``lens`` int32[B].  All length classes evaluate branchlessly;
    jit/vmap/shard-friendly."""
    mat = jnp.asarray(mat, jnp.uint8)
    lens = jnp.asarray(lens, jnp.int32)
    max_iters = max((mat.shape[1] - 1) // 20, 0)
    h04 = _hash_0_4(mat, lens)
    h512 = _hash_5_12(mat, lens)
    h1324 = _hash_13_24(mat, lens)
    hbig = _hash_gt24(mat, lens, max_iters)
    return jnp.where(
        lens <= 4,
        h04,
        jnp.where(lens <= 12, h512, jnp.where(lens <= 24, h1324, hbig)),
    )


def keyed_owner_lookup(tokens, owners, mat, lens) -> jax.Array:
    """The full keyed data path on-device: Fingerprint32 each key (via the
    Pallas mixing kernel when it lowers on this backend, else the jnp path),
    then the ring ownership search — int32[B] owner indices.  Both stages
    are jitted; the hash-path dispatch lives outside jit so a Mosaic compile
    failure degrades gracefully."""
    from ringpop_tpu.ops.hash_pallas import fingerprint32_auto
    from ringpop_tpu.ops.ring_ops import ring_lookup

    return ring_lookup(tokens, owners, fingerprint32_auto(mat, lens))
