"""Pallas TPU kernel for the FarmHash Fingerprint32 mixing loop.

Split of labor (see ``hash_ops.py`` for the full algorithm):

* XLA (outside): length-class branches <= 24 bytes, and the six dynamic
  tail fetches of the >24 path — gather-shaped work XLA already does well;
* Pallas (this kernel): the >24-byte mixing loop — ``(L-1)//20``
  iterations of mur/rotate chains over STATIC byte offsets, fully fused in
  VMEM over row blocks, so the key matrix is read from HBM exactly once
  regardless of iteration count (the jnp path re-slices `mat` per
  iteration and leans on XLA fusion to keep it resident).

The kernel is bit-exact against ``hash_ops.fingerprint32_device`` (which is
itself bit-exact against the scalar/native reference) — tested in
interpret mode on CPU; compiled mode engages automatically on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ringpop_tpu.ops.hash_ops import (
    _MIX5,
    _MIXC,
    _C1,
    _hash_0_4,
    _hash_13_24,
    _hash_5_12,
    _mur,
    _ror,
    _tail_words,
    U32,
)

BLOCK_ROWS = 256


def _mix_kernel(mat_ref, pre_ref, out_ref, *, max_iters: int, width: int):
    """One row block: run the >24 mixing loop to completion in VMEM."""
    a0 = pre_ref[:, 0]
    a1 = pre_ref[:, 1]
    a2 = pre_ref[:, 2]
    a3 = pre_ref[:, 3]
    a4 = pre_ref[:, 4]
    ln = pre_ref[:, 5]

    h = ln
    g = _C1 * ln
    f = g
    h = _ror(h ^ a0, 19) * _MIX5 + _MIXC
    h = _ror(h ^ a2, 19) * _MIX5 + _MIXC
    g = _ror(g ^ a1, 19) * _MIX5 + _MIXC
    g = _ror(g ^ a3, 19) * _MIX5 + _MIXC
    f = _ror(f + a4, 19) + U32(113)

    iters = (ln.astype(jnp.int32) - 1) // 20

    def fetch(off: int):
        b0 = mat_ref[:, off].astype(U32)
        b1 = mat_ref[:, off + 1].astype(U32)
        b2 = mat_ref[:, off + 2].astype(U32)
        b3 = mat_ref[:, off + 3].astype(U32)
        return b0 | (b1 << U32(8)) | (b2 << U32(16)) | (b3 << U32(24))

    for t in range(max_iters):
        off = 20 * t
        if off + 20 > width:
            break
        active = iters > t
        a = fetch(off)
        b = fetch(off + 4)
        c = fetch(off + 8)
        d = fetch(off + 12)
        e = fetch(off + 16)
        nh = _mur(d, h + a) + e
        ng = _mur(c, g + b) + a
        nf = _mur(b + e * _C1, f + c) + d
        nf = nf + ng
        ng = ng + nf
        h = jnp.where(active, nh, h)
        g = jnp.where(active, ng, g)
        f = jnp.where(active, nf, f)

    g = _ror(g, 11) * _C1
    g = _ror(g, 17) * _C1
    f = _ror(f, 11) * _C1
    f = _ror(f, 17) * _C1
    h = _ror(h + g, 19) * _MIX5 + _MIXC
    h = _ror(h, 17) * _C1
    h = _ror(h + f, 19) * _MIX5 + _MIXC
    h = _ror(h, 17) * _C1
    out_ref[:, 0] = h


@functools.partial(jax.jit, static_argnames=("interpret",))
def fingerprint32_pallas(mat, lens, interpret: bool = False) -> jax.Array:
    """Fingerprint32 with the >24-byte mixing loop as a Pallas kernel.

    Same contract as :func:`hash_ops.fingerprint32_device`.  ``interpret``
    runs the kernel in interpreter mode (CPU testing)."""
    mat = jnp.asarray(mat, jnp.uint8)
    lens = jnp.asarray(lens, jnp.int32)
    b, width = mat.shape
    max_iters = max((width - 1) // 20, 0)

    # pad rows to a block multiple (padding rows hash garbage, discarded)
    pad = (-b) % BLOCK_ROWS
    if pad:
        mat_p = jnp.pad(mat, ((0, pad), (0, 0)))
        lens_p = jnp.pad(lens, (0, pad), constant_values=25)
    else:
        mat_p, lens_p = mat, lens

    a0, a1, a2, a3, a4 = _tail_words(mat_p, lens_p)
    pre = jnp.stack([a0, a1, a2, a3, a4, lens_p.astype(U32)], axis=1)  # [B, 6]

    hbig = pl.pallas_call(
        functools.partial(_mix_kernel, max_iters=max_iters, width=width),
        grid=((b + pad) // BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, width), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS, 6), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(((b + pad), 1), jnp.uint32),
        interpret=interpret,
    )(mat_p, pre)[:b, 0]

    h04 = _hash_0_4(mat, lens)
    h512 = _hash_5_12(mat, lens)
    h1324 = _hash_13_24(mat, lens)
    return jnp.where(
        lens <= 4,
        h04,
        jnp.where(lens <= 12, h512, jnp.where(lens <= 24, h1324, hbig)),
    )


# per-width compile verdicts: the kernel's block width is maxlen+4, so each
# key-matrix width is a distinct Mosaic lowering that can independently fail
_pallas_usable: dict[int, bool] = {}


def fingerprint32_auto(mat, lens) -> jax.Array:
    """Fingerprint32 via the Pallas kernel when it compiles on this backend,
    else the pure-jnp ``hash_ops.fingerprint32_device`` path.

    The kernel uses per-column scalar uint8 loads and a block width of
    ``maxlen+4`` (not a 128-lane multiple) — patterns Mosaic may decline to
    lower on some TPU generations — so every call is guarded: a compile
    failure at any shape falls back and is remembered per width.  Results
    are bit-identical either way (both paths are tested against the scalar
    reference)."""
    from ringpop_tpu.ops.hash_ops import fingerprint32_device

    mat = jnp.asarray(mat, jnp.uint8)
    width = int(mat.shape[1]) if mat.ndim == 2 else -1
    verdict = _pallas_usable.get(width)
    if verdict is None:
        # first sighting of this width: trial-run to completion (catches
        # both Mosaic lowering and runtime failures), remember the verdict
        try:
            out = jax.block_until_ready(fingerprint32_pallas(mat, lens))
            _pallas_usable[width] = True
            return out
        except Exception:
            _pallas_usable[width] = False
    elif verdict:
        # later batch sizes of a good width retrace/recompile — still guard
        try:
            return fingerprint32_pallas(mat, lens)
        except Exception:
            _pallas_usable[width] = False
    return fingerprint32_device(mat, lens)
