"""Device-side consistent-ring lookup ops.

The host ring (``ringpop_tpu.hashring``) answers scalar lookups; these jnp
ops answer *batched* lookups on-device — millions of keys per call against a
million-vnode ring, which the reference's pointer-chasing red-black tree
(``hashring/rbtree.go``) fundamentally cannot do.

``searchsorted`` over the sorted token array is O(log T) per key and
vectorizes onto the TPU; key hashes are computed host-side with the batch
FarmHash (``ringpop_tpu.hashing``) or come from any uint32 source.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashing import ring_tokens as _ring_tokens


def _as_u32(a: jax.Array) -> jax.Array:
    """Reinterpret any 32-bit-valued integer array as uint32.

    The ring's token space is uint32, but callers routinely arrive with
    int32/int64 hashes (``np.array`` of python ints defaults to int64,
    which ``jnp.asarray`` truncates to int32 under disabled x64).  A hash
    >= 2**31 then compares SIGNED against the tokens and ``searchsorted``
    answers the wrap instead of the owner — silently, only for the top
    half of the hash space.  ``astype(uint32)`` is the two's-complement
    reinterpretation, which restores the intended value exactly for any
    lossless-truncated input; pinned by the dtype rows of the
    ``test_ring_properties`` suite."""
    return a.astype(jnp.uint32)


def ring_composite_order(tokens, owners) -> np.ndarray:
    """Stable argsort by the canonical ``(token << 32 | owner)``
    composite — THE collision order every host and device ring shares
    (``hashring._rebuild``'s rule).  Host-side callers that build or
    transform flat (token, owner) layouts sort through this ONE helper
    so a tie-break change can never diverge between copies."""
    comp = (
        np.asarray(tokens, np.uint64) << np.uint64(32)
    ) | np.asarray(owners, np.int64).astype(np.uint64)
    return np.argsort(comp, kind="stable")


def build_ring_tokens(servers: list[str], replica_points: int = 100):
    """Host-side construction of the (tokens, owners) arrays for a server
    list — same hash/replica scheme as the host ring
    (``hashring.go:148-154``); native C++ batch hash when available."""
    toks = _ring_tokens(servers, replica_points).reshape(-1).astype(np.uint32)
    owners = np.repeat(np.arange(len(servers), dtype=np.int32), replica_points)
    order = ring_composite_order(toks, owners)
    return jnp.asarray(toks[order]), jnp.asarray(owners[order])


@jax.jit
def ring_lookup(tokens: jax.Array, owners: jax.Array, key_hashes: jax.Array) -> jax.Array:
    """Owner index for each key hash: first token >= hash, wrapping to 0
    (parity: ``hashring.go:279-301`` walk semantics)."""
    idx = jnp.searchsorted(_as_u32(tokens), _as_u32(key_hashes), side="left")
    idx = jnp.where(idx == tokens.shape[0], 0, idx)
    return owners[idx]


@functools.partial(jax.jit, static_argnames=("n", "w"))
def _lookup_n_window(tokens, owners, key_hashes, n: int, w: int):
    """One windowed scan: first-``n``-unique owners within ``w`` consecutive
    tokens from each key's start position, plus the per-key unique count
    (for the exactness rescue in :func:`ring_lookup_n`)."""
    b = key_hashes.shape[0]
    start = jnp.searchsorted(_as_u32(tokens), _as_u32(key_hashes), side="left")
    pos = jnp.arange(w)
    offs = (start[:, None] + pos[None, :]) % tokens.shape[0]
    cand = owners[offs].astype(jnp.int32)  # [B, w]

    # first occurrence of each owner along the walk, via an O(w log w)
    # STABLE argsort by owner: walk positions are already ascending, so a
    # stable sort yields (owner asc, pos asc) — the head of each
    # equal-owner run is the owner's first sighting, scattered back to
    # walk position.  (The previous formulation packed (owner, pos) into
    # an int64 composite key, which with x64 disabled silently computes
    # in int32 and overflows once owner*w exceeds 2^31 — e.g. ~7k
    # servers at 100 vnodes each with a wide rescue window.  jaxlint
    # RPA104 guards against the pattern returning.)
    spos = jnp.argsort(cand, axis=1).astype(jnp.int32)
    sowner = jnp.take_along_axis(cand, spos, axis=1)
    head = jnp.concatenate(
        [jnp.ones((b, 1), bool), sowner[:, 1:] != sowner[:, :-1]], axis=1
    )
    b_idx = jnp.broadcast_to(jnp.arange(b)[:, None], cand.shape)
    first_seen = jnp.zeros((b, w), bool).at[b_idx, spos].set(head)

    # rank among first-seen owners, jit-safe scatter into slot `rank`
    rank = jnp.cumsum(first_seen, axis=1) - 1
    take = first_seen & (rank < n)
    slot = jnp.where(take, rank, n)  # overflow slot n is sliced away
    out = jnp.full((b, n + 1), -1, dtype=jnp.int32)
    out = out.at[b_idx, slot].set(jnp.where(take, cand, -1))
    return out[:, :n], first_seen.sum(axis=1)


def ring_lookup_n(
    tokens: jax.Array, owners: jax.Array, key_hashes: jax.Array, n: int, num_servers: int
) -> jax.Array:
    """First ``n`` *unique* owners walking the ring upward per key — EXACT
    (parity: ``hashring/rbtree.go:262-288`` LookupNUniqueAt + wraparound).

    Returns int32[B, n] owner ids, -1 padded when fewer than ``n`` servers
    exist.  Strategy: a windowed scan of ``w`` consecutive tokens (covers
    virtually every key at 100 vnodes/server in one pass), then — iff any
    key found fewer than ``min(n, num_servers)`` owners — the window doubles
    and rescans until satisfied or the whole ring is covered.  Each window
    size is a cached jit specialization; the doubling loop runs on the host,
    so this helper is exact without data-dependent shapes inside jit."""
    t = int(tokens.shape[0])
    if t == 0:
        return jnp.full((key_hashes.shape[0], n), -1, jnp.int32)
    need = min(n, num_servers)
    w = min(max(4 * n, 16), t)
    while True:
        out, found = _lookup_n_window(tokens, owners, key_hashes, n, w)
        if w >= t or bool((found >= need).all()):
            return out
        w = min(2 * w, t)


def host_lookup_n(tokens, owners, key_hashes, n: int, num_servers: int) -> np.ndarray:
    """Host-side exact N-unique-owner walk, batched over keys (parity:
    ``hashring/rbtree.go:262-288`` LookupNUniqueAt + wraparound) — the
    oracle every device LookupN flavor is pinned against, and the serve
    tier's ≤64-key host-mirror fast lane (``RingService.dispatch_direct``
    answers point requests from the committed generation's mirror through
    this walk, bit-identical to the device dispatch by the property-suite
    pin).  Returns int32[B, n], -1 padded when fewer than ``n`` unique
    owners exist."""
    tokens = np.asarray(tokens, dtype=np.uint32)
    owners = np.asarray(owners, dtype=np.int32)
    hashes = np.asarray(key_hashes).astype(np.uint32)
    b = int(hashes.shape[0])
    n = max(n, 0)
    out = np.full((b, n), -1, np.int32)
    t = int(tokens.shape[0])
    if t == 0 or n == 0:
        return out
    need = min(n, num_servers) if num_servers > 0 else n
    starts = np.searchsorted(tokens, hashes, side="left").astype(np.int64)
    # windowed walk with host-side doubling (the device rescue's shape):
    # per key, only a w ≈ 4n candidate window is ever materialized — at
    # 100 vnodes/server one window satisfies virtually every key, and
    # the fast-lane cost stays O(B·w), independent of ring size (a
    # full-ring owners scan per call would make a single point lookup
    # O(T) — a ~700x latency cliff at 1M vnodes)
    remaining = np.arange(b)
    w = min(max(4 * n, 16), t)
    while remaining.size:
        offs = (starts[remaining, None] + np.arange(w)) % t
        cand = owners[offs]  # [R, w]
        final = w >= t
        unfinished = []
        for row, i in enumerate(remaining):
            seen: set[int] = set()
            k = 0
            for o in cand[row].tolist():
                if o not in seen:
                    seen.add(o)
                    if k < n:
                        out[i, k] = o
                    k += 1
                    if k >= need:
                        break
            if k < need and not final:
                out[i, :] = -1  # partial prefix: rescan at a wider window
                unfinished.append(i)
        if final:
            break
        remaining = np.asarray(unfinished, np.int64)
        w = min(2 * w, t)
    return out


# ---------------------------------------------------------------------------
# Capacity-padded device ring (the serve tier's resident state)
# ---------------------------------------------------------------------------
#
# The plain ops above take exact-size arrays, so every membership change
# (T tokens -> T') retraces and recompiles the lookup — fine for a bench,
# fatal for a serving tier whose ring updates ride live SWIM churn.  The
# padded variants keep the ring at a fixed CAPACITY with a traced live
# count: tokens[count:] hold PAD_TOKEN (0xFFFFFFFF — sorts last; a real
# token of the same value still wins the side="left" search) and owners
# [count:] hold -1.  Updates swap values, never shapes, so the serving
# program compiles once per (capacity, batch-size) and a generation swap
# is pure data movement (``serve.state.ring_commit`` ping-pongs two
# donated buffer sets — churn never allocates, peak HBM is two rings,
# and a snapshot survives one concurrent commit).

PAD_TOKEN = 0xFFFFFFFF


def pad_ring_arrays(tokens, owners, capacity: int):
    """Host-side: (uint32[C], int32[C], count) from exact-size arrays."""
    tokens = np.asarray(tokens, dtype=np.uint32)
    owners = np.asarray(owners, dtype=np.int32)
    count = int(tokens.shape[0])
    if count > capacity:
        raise ValueError(f"ring of {count} tokens exceeds capacity {capacity}")
    pt = np.full(capacity, PAD_TOKEN, dtype=np.uint32)
    po = np.full(capacity, -1, dtype=np.int32)
    pt[:count] = tokens
    po[:count] = owners
    return pt, po, count


@jax.jit
def ring_lookup_padded(
    tokens: jax.Array, owners: jax.Array, count: jax.Array, key_hashes: jax.Array
) -> jax.Array:
    """:func:`ring_lookup` against a capacity-padded ring.  ``count`` is the
    traced live-token count; an empty ring answers -1 for every key."""
    idx = jnp.searchsorted(_as_u32(tokens), _as_u32(key_hashes), side="left")
    # past the live region (pads, or == C on an unpadded full ring): wrap.
    # A key hashing to PAD_TOKEN exactly still finds a real token of that
    # value first (side="left"), so the wrap only fires when no token >= h
    # exists among the live entries.
    idx = jnp.where(idx >= count, 0, idx)
    return jnp.where(count > 0, owners[idx], jnp.int32(-1))


@functools.partial(jax.jit, static_argnames=("n", "w"))
def _lookup_n_window_padded(tokens, owners, count, key_hashes, n: int, w: int):
    """The windowed scan of :func:`_lookup_n_window` with a traced live
    count: walk positions advance mod ``count`` (not capacity), so wrapped
    revisits are literal duplicates the uniqueness machinery drops."""
    b = key_hashes.shape[0]
    cnt = jnp.maximum(count, 1)
    start = jnp.searchsorted(_as_u32(tokens), _as_u32(key_hashes), side="left")
    start = jnp.where(start >= count, 0, start)
    pos = jnp.arange(w)
    offs = (start[:, None] + pos[None, :]) % cnt
    cand = jnp.where(count > 0, owners[offs].astype(jnp.int32), -1)  # [B, w]
    spos = jnp.argsort(cand, axis=1).astype(jnp.int32)
    sowner = jnp.take_along_axis(cand, spos, axis=1)
    head = jnp.concatenate(
        [jnp.ones((b, 1), bool), sowner[:, 1:] != sowner[:, :-1]], axis=1
    )
    # an empty ring's -1 candidates must not count as an owner
    head = head & (sowner >= 0)
    b_idx = jnp.broadcast_to(jnp.arange(b)[:, None], cand.shape)
    first_seen = jnp.zeros((b, w), bool).at[b_idx, spos].set(head)
    rank = jnp.cumsum(first_seen, axis=1) - 1
    take = first_seen & (rank < n)
    slot = jnp.where(take, rank, n)
    out = jnp.full((b, n + 1), -1, dtype=jnp.int32)
    out = out.at[b_idx, slot].set(jnp.where(take, cand, -1))
    return out[:, :n], first_seen.sum(axis=1)


def ring_lookup_n_padded(
    tokens: jax.Array,
    owners: jax.Array,
    count: jax.Array,
    num_servers: jax.Array,
    key_hashes: jax.Array,
    n: int,
) -> jax.Array:
    """:func:`ring_lookup_n` against a capacity-padded ring — same
    window-doubling rescue, same exactness contract (the property suite
    pins both against the host bisect walk), but shape-stable in the ring:
    ``count``/``num_servers`` are traced, so membership churn re-executes
    the same compiled windows instead of retracing."""
    c = int(tokens.shape[0])
    if c == 0 or n <= 0:
        return jnp.full((key_hashes.shape[0], max(n, 0)), -1, jnp.int32)
    need = jnp.minimum(n, num_servers)
    w = min(max(4 * n, 16), c)
    while True:
        out, found = _lookup_n_window_padded(tokens, owners, count, key_hashes, n, w)
        # w >= capacity >= count covers the whole live ring: exact
        if w >= c or bool((found >= need).all()):
            return out
        w = min(2 * w, c)
