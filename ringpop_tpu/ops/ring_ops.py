"""Device-side consistent-ring lookup ops.

The host ring (``ringpop_tpu.hashring``) answers scalar lookups; these jnp
ops answer *batched* lookups on-device — millions of keys per call against a
million-vnode ring, which the reference's pointer-chasing red-black tree
(``hashring/rbtree.go``) fundamentally cannot do.

``searchsorted`` over the sorted token array is O(log T) per key and
vectorizes onto the TPU; key hashes are computed host-side with the batch
FarmHash (``ringpop_tpu.hashing``) or come from any uint32 source.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashing import ring_tokens as _ring_tokens


def build_ring_tokens(servers: list[str], replica_points: int = 100):
    """Host-side construction of the (tokens, owners) arrays for a server
    list — same hash/replica scheme as the host ring
    (``hashring.go:148-154``); native C++ batch hash when available."""
    toks = _ring_tokens(servers, replica_points).reshape(-1).astype(np.uint32)
    owners = np.repeat(np.arange(len(servers), dtype=np.int32), replica_points)
    composite = toks.astype(np.uint64) << np.uint64(32) | owners.astype(np.uint64)
    order = np.argsort(composite, kind="stable")
    return jnp.asarray(toks[order]), jnp.asarray(owners[order])


@jax.jit
def ring_lookup(tokens: jax.Array, owners: jax.Array, key_hashes: jax.Array) -> jax.Array:
    """Owner index for each key hash: first token >= hash, wrapping to 0
    (parity: ``hashring.go:279-301`` walk semantics)."""
    idx = jnp.searchsorted(tokens, key_hashes, side="left")
    idx = jnp.where(idx == tokens.shape[0], 0, idx)
    return owners[idx]


@functools.partial(jax.jit, static_argnames=("n", "w"))
def _lookup_n_window(tokens, owners, key_hashes, n: int, w: int):
    """One windowed scan: first-``n``-unique owners within ``w`` consecutive
    tokens from each key's start position, plus the per-key unique count
    (for the exactness rescue in :func:`ring_lookup_n`)."""
    b = key_hashes.shape[0]
    start = jnp.searchsorted(tokens, key_hashes, side="left")
    pos = jnp.arange(w)
    offs = (start[:, None] + pos[None, :]) % tokens.shape[0]
    cand = owners[offs].astype(jnp.int32)  # [B, w]

    # first occurrence of each owner along the walk, via an O(w log w)
    # STABLE argsort by owner: walk positions are already ascending, so a
    # stable sort yields (owner asc, pos asc) — the head of each
    # equal-owner run is the owner's first sighting, scattered back to
    # walk position.  (The previous formulation packed (owner, pos) into
    # an int64 composite key, which with x64 disabled silently computes
    # in int32 and overflows once owner*w exceeds 2^31 — e.g. ~7k
    # servers at 100 vnodes each with a wide rescue window.  jaxlint
    # RPA104 guards against the pattern returning.)
    spos = jnp.argsort(cand, axis=1).astype(jnp.int32)
    sowner = jnp.take_along_axis(cand, spos, axis=1)
    head = jnp.concatenate(
        [jnp.ones((b, 1), bool), sowner[:, 1:] != sowner[:, :-1]], axis=1
    )
    b_idx = jnp.broadcast_to(jnp.arange(b)[:, None], cand.shape)
    first_seen = jnp.zeros((b, w), bool).at[b_idx, spos].set(head)

    # rank among first-seen owners, jit-safe scatter into slot `rank`
    rank = jnp.cumsum(first_seen, axis=1) - 1
    take = first_seen & (rank < n)
    slot = jnp.where(take, rank, n)  # overflow slot n is sliced away
    out = jnp.full((b, n + 1), -1, dtype=jnp.int32)
    out = out.at[b_idx, slot].set(jnp.where(take, cand, -1))
    return out[:, :n], first_seen.sum(axis=1)


def ring_lookup_n(
    tokens: jax.Array, owners: jax.Array, key_hashes: jax.Array, n: int, num_servers: int
) -> jax.Array:
    """First ``n`` *unique* owners walking the ring upward per key — EXACT
    (parity: ``hashring/rbtree.go:262-288`` LookupNUniqueAt + wraparound).

    Returns int32[B, n] owner ids, -1 padded when fewer than ``n`` servers
    exist.  Strategy: a windowed scan of ``w`` consecutive tokens (covers
    virtually every key at 100 vnodes/server in one pass), then — iff any
    key found fewer than ``min(n, num_servers)`` owners — the window doubles
    and rescans until satisfied or the whole ring is covered.  Each window
    size is a cached jit specialization; the doubling loop runs on the host,
    so this helper is exact without data-dependent shapes inside jit."""
    t = int(tokens.shape[0])
    if t == 0:
        return jnp.full((key_hashes.shape[0], n), -1, jnp.int32)
    need = min(n, num_servers)
    w = min(max(4 * n, 16), t)
    while True:
        out, found = _lookup_n_window(tokens, owners, key_hashes, n, w)
        if w >= t or bool((found >= need).all()):
            return out
        w = min(2 * w, t)
