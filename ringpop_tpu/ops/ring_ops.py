"""Device-side consistent-ring lookup ops.

The host ring (``ringpop_tpu.hashring``) answers scalar lookups; these jnp
ops answer *batched* lookups on-device — millions of keys per call against a
million-vnode ring, which the reference's pointer-chasing red-black tree
(``hashring/rbtree.go``) fundamentally cannot do.

``searchsorted`` over the sorted token array is O(log T) per key and
vectorizes onto the TPU; key hashes are computed host-side with the batch
FarmHash (``ringpop_tpu.hashing``) or come from any uint32 source.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashing import ring_tokens as _ring_tokens


def build_ring_tokens(servers: list[str], replica_points: int = 100):
    """Host-side construction of the (tokens, owners) arrays for a server
    list — same hash/replica scheme as the host ring
    (``hashring.go:148-154``); native C++ batch hash when available."""
    toks = _ring_tokens(servers, replica_points).reshape(-1).astype(np.uint32)
    owners = np.repeat(np.arange(len(servers), dtype=np.int32), replica_points)
    composite = toks.astype(np.uint64) << np.uint64(32) | owners.astype(np.uint64)
    order = np.argsort(composite, kind="stable")
    return jnp.asarray(toks[order]), jnp.asarray(owners[order])


@jax.jit
def ring_lookup(tokens: jax.Array, owners: jax.Array, key_hashes: jax.Array) -> jax.Array:
    """Owner index for each key hash: first token >= hash, wrapping to 0
    (parity: ``hashring.go:279-301`` walk semantics)."""
    idx = jnp.searchsorted(tokens, key_hashes, side="left")
    idx = jnp.where(idx == tokens.shape[0], 0, idx)
    return owners[idx]


def ring_lookup_n(tokens: jax.Array, owners: jax.Array, key_hashes: jax.Array, n: int, num_servers: int) -> jax.Array:
    """First ``n`` *unique* owners walking the ring upward per key.

    Scans a bounded window of ``w`` consecutive tokens (w chosen so that
    missing n distinct owners in w replica slots is vanishingly unlikely at
    100 vnodes/server); returns int32[B, n] owner ids, -1 padded."""
    w = max(4 * n, 16)
    b = key_hashes.shape[0]
    start = jnp.searchsorted(tokens, key_hashes, side="left")
    offs = (start[:, None] + jnp.arange(w)[None, :]) % tokens.shape[0]
    cand = owners[offs].astype(jnp.int32)  # [B, w]

    # first occurrence of each owner along the walk
    eq = cand[:, :, None] == cand[:, None, :]  # [B, i, j]
    prior = eq & (jnp.arange(w)[None, None, :] < jnp.arange(w)[None, :, None])
    first_seen = ~prior.any(axis=2)

    # rank among first-seen owners, jit-safe scatter into slot `rank`
    rank = jnp.cumsum(first_seen, axis=1) - 1
    take = first_seen & (rank < n)
    slot = jnp.where(take, rank, n)  # overflow slot n is sliced away
    b_idx = jnp.broadcast_to(jnp.arange(b)[:, None], cand.shape)
    out = jnp.full((b, n + 1), -1, dtype=jnp.int32)
    out = out.at[b_idx, slot].set(jnp.where(take, cand, -1))
    return out[:, :n]
