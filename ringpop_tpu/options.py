"""Facade configuration (parity: reference ``options.go``).

The reference uses functional options; the Python equivalent is a dataclass
with zero-means-default merging plus keyword arguments on
``Ringpop(...)``.  Defaults mirror ``options.go:327-339``: 100 ring replica
points, identity from the channel, stats off, checksum stat timers on
periods from ``options.go:204-281``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ringpop_tpu import util
from ringpop_tpu.errors import EphemeralIdentityError
from ringpop_tpu.swim.state_transitions import StateTimeouts
from ringpop_tpu.util.clock import Clock


class StatsReporter:
    """Pluggable stats sink (parity: ``bark.StatsReporter``)."""

    def incr(self, key: str, value: int = 1) -> None: ...

    def gauge(self, key: str, value: float) -> None: ...

    def timing(self, key: str, seconds: float) -> None: ...


class NoopStats(StatsReporter):
    """(parity: ``util.go:31-35`` noopStatsReporter)"""

    def incr(self, key, value=1):
        pass

    def gauge(self, key, value):
        pass

    def timing(self, key, seconds):
        pass


class InMemoryStats(StatsReporter):
    """Test/introspection sink: counters summed, gauges last-value, timers
    appended."""

    def __init__(self):
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self.timers: dict[str, list[float]] = {}

    def incr(self, key, value=1):
        self.counters[key] = self.counters.get(key, 0) + value

    def gauge(self, key, value):
        self.gauges[key] = value

    def timing(self, key, seconds):
        self.timers.setdefault(key, []).append(seconds)


def default_identity_resolver(channel) -> str:
    """Identity = the channel's listening hostport; ephemeral (port 0)
    identities are refused (parity: ``options.go:184-202`` + ErrEphemeralIdentity)."""
    hostport = channel.hostport
    if not hostport or hostport.endswith(":0"):
        raise EphemeralIdentityError()
    return hostport


@dataclass
class Options:
    """(defaults parity: ``options.go:327-339``)"""

    # ring
    replica_points: int = 100
    hashfunc: Optional[Callable] = None

    # identity
    identity: str = ""
    identity_resolver: Optional[Callable[[], str]] = None

    # stats / logging
    stats_reporter: Optional[StatsReporter] = None

    # swim tuning passthrough (options.go:249-281)
    state_timeouts: StateTimeouts = field(default_factory=StateTimeouts)
    suspect_period: float = 0.0
    faulty_period: float = 0.0
    tombstone_period: float = 0.0

    # stat timers (options.go:204-242)
    membership_checksum_stat_period: float = 5.0
    ring_checksum_stat_period: float = 5.0

    clock: Optional[Clock] = None
    seed: Optional[int] = None

    def resolved_state_timeouts(self) -> StateTimeouts:
        return StateTimeouts(
            suspect=util.select_duration(self.suspect_period, self.state_timeouts.suspect),
            faulty=util.select_duration(self.faulty_period, self.state_timeouts.faulty),
            tombstone=util.select_duration(
                self.tombstone_period, self.state_timeouts.tombstone
            ),
        )
