from ringpop_tpu.parallel.mesh import (
    make_mesh,
    shard_delta_state,
    sharded_delta_step,
    with_exchange_mesh,
)

__all__ = [
    "make_mesh",
    "shard_delta_state",
    "sharded_delta_step",
    "with_exchange_mesh",
]
