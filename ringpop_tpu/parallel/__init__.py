"""Sharding + multi-host plane.

Lazy exports (PEP 562, same pattern as the serve package):
``parallel.mesh`` pulls jax at import, but ``parallel.fabric`` is
numpy-only by design — the r17 unified-transport slice has JAX-FREE
frontend surfaces (``net/channel.py``'s fabric array lane,
``serve/shm.py``) reach fabric codec helpers at runtime, so importing
this package must not execute the jax-laden mesh module eagerly."""

_EXPORTS = {
    "make_mesh": "ringpop_tpu.parallel.mesh",
    "shard_delta_state": "ringpop_tpu.parallel.mesh",
    "sharded_delta_step": "ringpop_tpu.parallel.mesh",
    "with_exchange_mesh": "ringpop_tpu.parallel.mesh",
}


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod), name)


__all__ = list(_EXPORTS)
