"""Host-bridged DCN fabric for process-spanning sim runs.

A real TPU pod runs the SAME jitted step on a process-spanning mesh and
lets XLA drive the DCN — nothing here is needed there.  This module exists
for the fabric a pod does NOT give us: the multi-process CPU validation
rig (and any backend whose runtime cannot execute cross-process XLA
computations — this container's jax 0.4.37 CPU backend is one: it
enumerates global devices but refuses multiprocess programs).  The
engines' exchange legs are nearest-neighbor row windows plus a handful of
[W]-word reduces per tick, so the DCN layer is small enough to carry at
the host level: shard-local jitted kernels per process, window slices over
direct TCP peer sockets, and the jax.distributed KV store for rendezvous.

Layering:

* rendezvous — every rank publishes ``<ns>/addr/<rank>`` in the
  coordination-service KV store (tiny strings only; bulk data NEVER rides
  the KV store) and dials its lower-ranked peers once;
* data — length-framed raw-numpy messages over the peer sockets, tagged
  by the caller (``delta_multihost`` encodes ``tick << 8 | leg`` so a
  stray message from a diverged schedule trips the tag check instead of
  being consumed as a later tick's payload); deadlock-free by sending on
  background threads while the main thread receives in rank order (every
  tick's communication schedule is deterministic on all ranks, derived
  from the same counter-RNG draw);
* collectives — ``allgather`` of per-rank partial words implements the
  OR/AND row reduces and digest combines (bitwise ops reassociate
  exactly, so partial-then-combine is bit-identical to the single-host
  tree — the property every certificate leans on);
* codec (r15) — every array on the wire carries a one-byte
  self-describing codec: zero-row suppression (``ROWS``: bitmap of
  nonzero rows + packed payload — the dominant win for the ride-masked
  exchange legs, whose ``sent``/``answerable`` planes are mostly zero
  rows outside the dissemination wave), zero-word run suppression
  (``RUNS``: dense-but-patchy planes), an optional previous-payload
  XOR-delta (``XOR``: explicit epoch word, reset on snapshot restore /
  peer-count change so restore-onto-a-different-P stays certified), and
  a MEASURED raw fallback — an encoding that does not strictly shrink
  the payload is never sent.  Encode decisions are send-side local and
  decode is exact, so digests are bit-identical by construction,
  codec-on vs codec-off.

Byte accounting is first-class and split (r15): ``bytes_sent``/
``bytes_recv`` are the actual WIRE bytes; ``raw_bytes_sent``/
``raw_bytes_recv`` are what the same messages would have cost with the
codec off, so the simbench/ksweep records can state both the per-host
MB/tick on the wire and the compression ratio against the committed
42.5 MB/chip/tick mesh budget.
"""

from __future__ import annotations

import base64
import socket
import struct
import threading
import time
from typing import NamedTuple, Optional, Sequence, Union

import numpy as np

_HDR = struct.Struct(">IIQ")  # tag, n_arrays, total payload bytes
# per-array header: codec byte, dtype-str len, ndim, ENCODED payload bytes
# (dtype str + ">u8" shape words follow; then the encoded payload)
_AHDR = struct.Struct(">BIIQ")

# -- wire codec ---------------------------------------------------------------

CODEC_RAW = 0  # payload = a.tobytes()
CODEC_ROWS = 1  # ">I" nnz-rows + LSB-first row bitmap + nonzero rows packed
CODEC_RUNS = 2  # ">I" n-runs + "<u4" starts + "<u4" lens + nonzero u32 words
CODEC_XOR = 3  # ">II" epoch, inner codec + inner payload of prev-XOR diff

CODEC_NAMES = {CODEC_RAW: "raw", CODEC_ROWS: "rows",
               CODEC_RUNS: "runs", CODEC_XOR: "xor"}


class FabricError(RuntimeError):
    """Any fabric-layer failure with rank/peer context attached."""


class FabricPeerLost(FabricError):
    """A peer's socket closed mid-run — the peer process died (or shut
    its fabric down) while this rank still expected messages from it."""


class FabricTimeout(FabricError):
    """A live but SILENT peer: nothing arrived (or a send could not
    drain) within ``timeout_ms``.  Distinct from a tag desync — the
    schedule may still agree; the peer is wedged or partitioned."""


class FabricDesync(FabricError):
    """A message arrived with the WRONG tag: the peers' deterministic
    schedules disagree (a leg skipped or reordered).  Both endpoints are
    alive — that is what distinguishes this from the two above."""


class Encoded(NamedTuple):
    """A pre-encoded wire array (codec already applied).  Callers that
    hold send-side structure the encoder would otherwise recompute — the
    multihost engine's DEVICE-computed nonzero-row summaries — hand the
    fabric one of these instead of an ndarray; ``decode_array`` cannot
    tell the difference."""

    codec: int
    dtype: np.dtype
    shape: tuple
    payload: bytes
    raw_nbytes: int


def _bitmap_pack(mask: np.ndarray) -> bytes:
    """bool[rows] -> ceil(rows/8) LSB-first bytes (bit i of byte j is
    row 8j+i) — the byte order ``packbits.pack_bool``'s little-endian
    uint32 word view produces, so device-packed masks are wire-identical
    to host-packed ones."""
    return np.packbits(mask, bitorder="little").tobytes()


def _bitmap_unpack(buf: bytes, rows: int) -> np.ndarray:
    return np.unpackbits(
        np.frombuffer(buf, np.uint8), count=rows, bitorder="little"
    ).astype(bool)


def rows_wire_size(rows: int, nnz: int, row_nbytes: int) -> int:
    """Encoded-payload size of a ROWS encoding — callers with a
    device-side nonzero count use this to decide BEFORE transferring."""
    return 4 + (rows + 7) // 8 + nnz * row_nbytes


def encode_rows(
    mask: np.ndarray, rows_payload: np.ndarray, shape: tuple, dtype
) -> Encoded:
    """Build a ROWS encoding from an externally computed nonzero-row
    mask + already-compacted nonzero rows (the device-sliced hot path).
    The caller is responsible for having checked ``rows_wire_size``
    against the raw size — this constructor encodes unconditionally."""
    dtype = np.dtype(dtype)
    raw_nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    nnz = int(rows_payload.shape[0])
    payload = (
        struct.pack(">I", nnz)
        + _bitmap_pack(np.asarray(mask, bool))
        + np.ascontiguousarray(rows_payload).tobytes()
    )
    return Encoded(CODEC_ROWS, dtype, tuple(shape), payload, raw_nbytes)


def _rows_encode(a: np.ndarray) -> Optional[bytes]:
    """Zero-row suppression; None when it would not strictly shrink.
    The row mask tests the BYTE view, not values — float -0.0 is
    value-equal to zero but bit-distinct, and the decode contract is
    bit-exactness (``a`` is contiguous: encode_array guarantees it)."""
    if a.ndim < 2 or a.shape[0] < 2 or a.size == 0:
        return None
    flat = a.reshape(a.shape[0], -1)
    mask = (flat.view(np.uint8) != 0).any(axis=1)
    nnz = int(mask.sum())
    row_nbytes = a.nbytes // a.shape[0]
    if rows_wire_size(a.shape[0], nnz, row_nbytes) >= a.nbytes:
        return None
    return struct.pack(">I", nnz) + _bitmap_pack(mask) + flat[mask].tobytes()


def _rows_decode(payload: bytes, shape: tuple, dtype: np.dtype) -> np.ndarray:
    (nnz,) = struct.unpack_from(">I", payload, 0)
    nb = 4 + (shape[0] + 7) // 8
    mask = _bitmap_unpack(payload[4:nb], shape[0])
    if int(mask.sum()) != nnz:
        raise FabricError(
            f"ROWS bitmap popcount {int(mask.sum())} != header nnz {nnz} — "
            "corrupt frame"
        )
    out = np.zeros(shape, dtype)
    out[mask] = np.frombuffer(payload, dtype, offset=nb).reshape(
        (nnz,) + tuple(shape[1:])
    )
    return out


def _runs_encode(a: np.ndarray) -> Optional[bytes]:
    """Zero-WORD run suppression over the uint32 view; None when the
    dtype does not view as whole words or it would not strictly shrink.
    ``a`` must be C-contiguous (``encode_array`` guarantees it) — the
    word view and the cheap-reject count are copy-free, so a dense plane
    costs ONE pass here, not the full run detection."""
    if a.nbytes % 4 or a.nbytes == 0:
        return None
    w = a.reshape(-1).view(np.uint32)
    nz = w != 0
    nnz_words = int(np.count_nonzero(nz))
    if 4 + 8 + 4 * nnz_words >= a.nbytes:
        return None  # even a single run cannot shrink this payload
    edges = np.flatnonzero(np.diff(np.concatenate(([False], nz, [False]))))
    starts, ends = edges[0::2], edges[1::2]
    size = 4 + 8 * len(starts) + 4 * nnz_words
    if size >= a.nbytes:
        return None
    return (
        struct.pack(">I", len(starts))
        + starts.astype("<u4").tobytes()
        + (ends - starts).astype("<u4").tobytes()
        + w[nz].tobytes()
    )


def _runs_decode(payload: bytes, nbytes: int) -> np.ndarray:
    """-> the flat uint32 word view (caller reshapes/reviews)."""
    (nruns,) = struct.unpack_from(">I", payload, 0)
    starts = np.frombuffer(payload, "<u4", count=nruns, offset=4).astype(np.int64)
    lens = np.frombuffer(payload, "<u4", count=nruns, offset=4 + 4 * nruns).astype(
        np.int64
    )
    words = np.frombuffer(payload, np.uint32, offset=4 + 8 * nruns)
    out = np.zeros(nbytes // 4, np.uint32)
    if nruns:
        tot = int(lens.sum())
        off = np.arange(tot, dtype=np.int64) - np.repeat(
            np.cumsum(lens) - lens, lens
        )
        out[np.repeat(starts, lens) + off] = words
    return out


def encode_array(
    a: np.ndarray,
    prev: Optional[bytes] = None,
    epoch: int = 0,
    rows: bool = True,
) -> Encoded:
    """Best strictly-smaller encoding of ``a`` — RAW when nothing pays
    (the measured fallback).  ``prev`` (the previous payload bytes on
    this stream, same shape/dtype — the caller guarantees it was
    recorded under ``epoch``) additionally offers the XOR-delta.
    ``rows=False`` skips the ROWS attempt — for callers that already
    know the nonzero-row count (the engine's device-side summary) and
    would otherwise pay a redundant full host scan per dense piece."""
    a = np.ascontiguousarray(a)
    cands: list[tuple[int, int, bytes]] = [(a.nbytes, CODEC_RAW, b"")]
    rows_payload = _rows_encode(a) if rows else None
    if rows_payload is not None:
        cands.append((len(rows_payload), CODEC_ROWS, rows_payload))
    runs = _runs_encode(a)
    if runs is not None:
        cands.append((len(runs), CODEC_RUNS, runs))
    if prev is not None and len(prev) == a.nbytes and a.nbytes:
        diff = np.bitwise_xor(
            a.reshape(-1).view(np.uint8),
            np.frombuffer(prev, np.uint8),
        )
        # only a RUNS-compressed diff can undercut raw (an inner-RAW
        # XOR payload is raw + 8 header bytes by construction), so no
        # RUNS win means no XOR candidate; decode_array still accepts
        # an inner-RAW frame for wire-format completeness
        inner = _runs_encode(diff)
        if inner is not None:
            xor_payload = struct.pack(">II", epoch & 0xFFFFFFFF, CODEC_RUNS) + inner
            if len(xor_payload) < a.nbytes:
                cands.append((len(xor_payload), CODEC_XOR, xor_payload))
    size, codec, payload = min(cands, key=lambda c: (c[0], c[1]))
    if codec == CODEC_RAW:
        payload = a.tobytes()
    return Encoded(codec, a.dtype, a.shape, payload, a.nbytes)


def decode_array(
    codec: int,
    dtype: np.dtype,
    shape: tuple,
    payload: bytes,
    prev: Optional[bytes] = None,
    epoch: int = 0,
) -> np.ndarray:
    """Exact inverse of every encoding.  XOR requires the previous
    payload on the stream AND a matching epoch word — a mismatch means
    one side missed a codec reset (snapshot restore / peer change) and
    MUST fail loudly rather than decode garbage."""
    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    if codec == CODEC_RAW:
        return np.frombuffer(payload, dtype, count=-1).reshape(shape).copy()
    if codec == CODEC_ROWS:
        return _rows_decode(payload, tuple(shape), np.dtype(dtype))
    if codec == CODEC_RUNS:
        words = _runs_decode(payload, nbytes)
        return np.frombuffer(words.tobytes(), dtype).reshape(shape).copy()
    if codec == CODEC_XOR:
        got_epoch, inner_codec = struct.unpack_from(">II", payload, 0)
        if prev is None or got_epoch != (epoch & 0xFFFFFFFF):
            raise FabricError(
                f"codec epoch desync: XOR frame carries epoch {got_epoch} but "
                f"this rank is at epoch {epoch & 0xFFFFFFFF} with "
                f"{'no' if prev is None else 'a'} previous payload — a codec "
                "reset (snapshot restore / peer-count change) was missed on "
                "one side"
            )
        inner = payload[8:]
        if inner_codec == CODEC_RUNS:
            diff = _runs_decode(inner, nbytes).tobytes()
        else:
            diff = inner
        raw = np.bitwise_xor(
            np.frombuffer(diff, np.uint8),
            np.frombuffer(prev, np.uint8),
        ).tobytes()
        return np.frombuffer(raw, dtype).reshape(shape).copy()
    raise FabricError(f"unknown wire codec byte {codec}")


class LocalKV:
    """In-process KV + barrier standing in for the jax.distributed
    coordination client — the transport is identical, so threaded
    single-machine tests exercise the real fabric code paths."""

    def __init__(self):
        self._d = {}
        self._cv = threading.Condition()
        self._barriers: dict[str, threading.Barrier] = {}
        self._block = threading.Lock()

    def key_value_set(self, key: str, value: str) -> None:
        with self._cv:
            self._d[key] = value
            self._cv.notify_all()

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._d:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    raise TimeoutError(f"KV key {key!r} not set within {timeout_ms} ms")
            return self._d[key]

    def barrier(self, name: str, nprocs: int, timeout_ms: int) -> None:
        with self._block:
            b = self._barriers.setdefault(name, threading.Barrier(nprocs))
        b.wait(timeout=timeout_ms / 1000.0)


class DistributedKV:
    """The jax.distributed coordination-service client, duck-typed to
    LocalKV.  Values are strings; the fabric only ever stores addresses
    and base64'd digest words here."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed

            client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized — call "
                "parallel.multihost.init_distributed() first"
            )
        self._c = client

    def key_value_set(self, key: str, value: str) -> None:
        self._c.key_value_set(key, value)

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        return self._c.blocking_key_value_get(key, timeout_ms)

    def barrier(self, name: str, nprocs: int, timeout_ms: int) -> None:
        del nprocs  # the distributed barrier always spans the whole job
        self._c.wait_at_barrier(name, timeout_ms)


def _send_exact(sock: socket.socket, data) -> None:
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise FabricPeerLost("fabric peer closed the connection")
        got += r
    return bytes(buf)


class Fabric:
    """One rank's endpoint of the host-bridged DCN mesh.

    ``kv`` is a LocalKV (threaded tests) or DistributedKV (real OS
    processes).  ``namespace`` isolates concurrent fabrics in one KV store
    (tests, or a snapshot fabric next to a run fabric).
    """

    def __init__(
        self,
        rank: int,
        nprocs: int,
        kv,
        namespace: str = "fabric",
        host: str = "127.0.0.1",
        timeout_ms: int = 120_000,
        codec: bool = True,
    ):
        if not 0 <= rank < nprocs:
            raise ValueError(f"rank {rank} outside [0, {nprocs})")
        self.rank, self.nprocs = rank, nprocs
        self.kv, self.ns = kv, namespace
        self.timeout_ms = timeout_ms
        self.codec = codec
        self.bytes_sent = 0  # actual wire bytes
        self.bytes_recv = 0
        self.raw_bytes_sent = 0  # what the same messages cost codec-off
        self.raw_bytes_recv = 0
        self.codec_counts: dict[int, int] = {}  # sent arrays per codec byte
        # XOR-delta stream state: (peer, stream, array-idx) -> payload
        # bytes recorded under codec_epoch; reset_codec_state() clears both
        # sides' dicts and bumps the epoch word (collective by convention:
        # every rank resets at the same protocol point — snapshot restore)
        self.codec_epoch = 0
        self._tx_prev: dict[tuple, bytes] = {}
        self._rx_prev: dict[tuple, bytes] = {}
        self._peers: dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        if nprocs > 1:
            self._connect(host)

    # -- bring-up -------------------------------------------------------------

    def _connect(self, host: str) -> None:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(self.nprocs)
        # the timeout contract covers BOTH sides of every link: a rank
        # that dies before dialing must fail its peers' accept() at
        # timeout_ms, not hang them forever; accepted and dialed sockets
        # alike carry the timeout so a stalled (not closed) peer surfaces
        # as socket.timeout instead of a wedged _recv_exact
        srv.settimeout(self.timeout_ms / 1000.0)
        port = srv.getsockname()[1]
        self.kv.key_value_set(f"{self.ns}/addr/{self.rank}", f"{host}:{port}")
        # deterministic dial direction: every rank dials its LOWER peers;
        # the accept side learns the dialer's rank from a 4-byte hello
        for peer in range(self.rank):
            addr = self.kv.blocking_key_value_get(f"{self.ns}/addr/{peer}", self.timeout_ms)
            h, p = addr.rsplit(":", 1)
            deadline = time.monotonic() + self.timeout_ms / 1000.0
            while True:
                try:
                    s = socket.create_connection((h, int(p)), timeout=self.timeout_ms / 1000.0)
                    break
                except ConnectionRefusedError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.02)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            _send_exact(s, struct.pack(">I", self.rank))
            self._peers[peer] = s
        for _ in range(self.rank + 1, self.nprocs):
            s, _ = srv.accept()
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            (peer,) = struct.unpack(">I", _recv_exact(s, 4))
            self._peers[peer] = s
        srv.close()

    def close(self) -> None:
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
        self._peers.clear()

    def __enter__(self) -> "Fabric":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- framed numpy messages ------------------------------------------------

    def reset_codec_state(self) -> None:
        """Drop every XOR-delta stream and bump the epoch word.  Call at
        any protocol point where the payload history breaks — snapshot
        restore, engine re-init — on EVERY rank (the epoch word in each
        XOR frame turns a missed reset into a loud ``FabricError`` instead
        of silently decoded garbage)."""
        with self._lock:
            self.codec_epoch += 1
            self._tx_prev.clear()
            self._rx_prev.clear()

    def wire_stats(self) -> dict:
        """Counter snapshot for journals/bench records (wire vs raw bytes
        + per-codec sent-array counts, names not bytes)."""
        with self._lock:
            return {
                "bytes_sent": self.bytes_sent,
                "bytes_recv": self.bytes_recv,
                "raw_bytes_sent": self.raw_bytes_sent,
                "raw_bytes_recv": self.raw_bytes_recv,
                "codec_counts": {
                    CODEC_NAMES.get(c, str(c)): n
                    for c, n in sorted(self.codec_counts.items())
                },
            }

    def _encode_item(
        self, item: Union[np.ndarray, Encoded], peer: int, stream, idx: int
    ) -> Encoded:
        if isinstance(item, Encoded):
            if stream is not None:
                # the sender has no raw bytes to record as XOR history,
                # but the receiver records its decode — the two prevs
                # would diverge under MATCHING epochs, defeating the
                # epoch word's whole purpose.  Refuse rather than
                # corrupt (today's pre-encoded path, the exchange legs,
                # is stream-less by design: window shapes move with s).
                raise ValueError(
                    "pre-encoded (Encoded) items cannot ride a streamed "
                    "round: the XOR-delta payload history would diverge "
                    "between sender and receiver — send the ndarray, or "
                    "drop the stream"
                )
            return item  # pre-encoded (device-sourced ROWS) — pass through
        a = np.ascontiguousarray(item)
        if not self.codec:
            return Encoded(CODEC_RAW, a.dtype, a.shape, a.tobytes(), a.nbytes)
        prev = self._tx_prev.get((peer, stream, idx)) if stream is not None else None
        enc = encode_array(a, prev=prev, epoch=self.codec_epoch)
        if stream is not None:
            self._tx_prev[(peer, stream, idx)] = a.tobytes()
        return enc

    def _pack(self, tag: int, arrays, peer: int, stream=None) -> tuple[bytes, int]:
        """-> (wire message, raw-equivalent size)."""
        parts = []
        total = 0
        raw_total = _HDR.size
        counts: dict[int, int] = {}
        for idx, item in enumerate(arrays):
            enc = self._encode_item(item, peer, stream, idx)
            dt = enc.dtype.str.encode()
            shape = np.asarray(enc.shape, ">u8").tobytes()
            meta = _AHDR.pack(enc.codec, len(dt), len(enc.shape), len(enc.payload))
            parts.append(meta + dt + shape)
            parts.append(enc.payload)
            total += len(parts[-2]) + len(parts[-1])
            raw_total += len(meta) + len(dt) + len(shape) + enc.raw_nbytes
            counts[enc.codec] = counts.get(enc.codec, 0) + 1
        with self._lock:
            for c, k in counts.items():
                self.codec_counts[c] = self.codec_counts.get(c, 0) + k
        return _HDR.pack(tag, len(arrays), total) + b"".join(parts), raw_total

    def _send(self, peer: int, tag: int, arrays, stream=None) -> None:
        msg, raw = self._pack(tag, arrays, peer, stream)
        with self._lock:
            self.bytes_sent += len(msg)
            self.raw_bytes_sent += raw
        try:
            _send_exact(self._peers[peer], msg)
        except socket.timeout as e:
            raise FabricTimeout(
                f"rank {self.rank}: send to peer {peer} (tag {tag}) could not "
                f"drain within {self.timeout_ms} ms — peer wedged or "
                "partitioned"
            ) from e
        except FabricError:
            raise
        except OSError as e:
            raise FabricPeerLost(
                f"rank {self.rank}: send to peer {peer} (tag {tag}) failed "
                f"({e}) — peer process died mid-exchange"
            ) from e

    def _recv(self, peer: int, tag: int, stream=None) -> list[np.ndarray]:
        sock = self._peers[peer]
        try:
            hdr = _recv_exact(sock, _HDR.size)
            got_tag, n_arrays, total = _HDR.unpack(hdr)
            if got_tag != tag:
                raise FabricDesync(
                    f"fabric desync: rank {self.rank} expected tag {tag} from peer "
                    f"{peer}, got {got_tag} — a leg was skipped or reordered"
                )
            payload = _recv_exact(sock, total)
        except socket.timeout as e:
            raise FabricTimeout(
                f"rank {self.rank}: peer {peer} sent nothing for tag {tag} "
                f"within {self.timeout_ms} ms — peer dead-but-connected, "
                "wedged, or partitioned (NOT a tag desync: nothing arrived "
                "at all)"
            ) from e
        except FabricPeerLost as e:
            raise FabricPeerLost(
                f"rank {self.rank}: peer {peer} closed its socket while this "
                f"rank awaited tag {tag} — peer process died mid-exchange"
            ) from e
        out, off = [], 0
        raw_total = _HDR.size
        for idx in range(n_arrays):
            codec, dtl, ndim, nbytes = _AHDR.unpack_from(payload, off)
            off += _AHDR.size
            dt = payload[off : off + dtl].decode()
            off += dtl
            shape = tuple(np.frombuffer(payload, ">u8", count=ndim, offset=off).astype(int))
            off += 8 * ndim
            prev = self._rx_prev.get((peer, stream, idx)) if stream is not None else None
            a = decode_array(
                codec, np.dtype(dt), shape, payload[off : off + nbytes],
                prev=prev, epoch=self.codec_epoch,
            )
            if stream is not None:
                self._rx_prev[(peer, stream, idx)] = a.tobytes()
            out.append(a)
            raw_total += _AHDR.size + dtl + 8 * ndim + a.nbytes
            off += nbytes
        with self._lock:
            self.bytes_recv += len(hdr) + total
            self.raw_bytes_recv += raw_total
        return out

    # -- rounds ---------------------------------------------------------------

    def exchange(
        self,
        tag: int,
        sends: dict[int, Sequence[Union[np.ndarray, Encoded]]],
        recv_from: Sequence[int],
        stream: Optional[str] = None,
    ) -> dict[int, list[np.ndarray]]:
        """One deterministic communication round: send each payload in
        ``sends`` (background threads), receive one message from every
        peer in ``recv_from`` (in the given order), join.  Both sides must
        derive the same schedule — a mismatch surfaces as a tag desync or
        timeout, never silent misdata.  ``stream`` (a tick-stable name)
        opts the round's arrays into the XOR-delta codec: the previous
        payload per (peer, stream, index) is retained on both sides, so
        only use it for rounds whose shapes recur (the reduce words —
        retaining a full window would double memory for no ratio)."""
        if stream is not None:
            # validate BEFORE any socket work so the contract violation
            # raises synchronously on every rank instead of leaving the
            # peers blocked into a timeout (_encode_item's check would
            # only fire inside a background send thread)
            for arrays in sends.values():
                for it in arrays:
                    if isinstance(it, Encoded):
                        raise ValueError(
                            "pre-encoded (Encoded) items cannot ride a "
                            "streamed round: the XOR-delta payload history "
                            "would diverge between sender and receiver — "
                            "send the ndarray, or drop the stream"
                        )
        errs: list[BaseException] = []

        def _bg(peer, arrays):
            try:
                self._send(peer, tag, arrays, stream)
            except BaseException as e:  # surfaced after join
                errs.append(e)

        threads = [
            threading.Thread(target=_bg, args=(p, a), daemon=True)
            for p, a in sends.items()
        ]
        for t in threads:
            t.start()
        try:
            out = {p: self._recv(p, tag, stream) for p in recv_from}
        finally:
            for t in threads:
                t.join()
        if errs:
            raise errs[0]
        return out

    def allgather(
        self, tag: int, arr: np.ndarray, stream: Optional[str] = None
    ) -> list[np.ndarray]:
        """Every rank's ``arr``, ordered by rank (self included).  Tiny
        payloads only (reduce words, digest partials) — full-mesh sends."""
        if self.nprocs == 1:
            return [np.asarray(arr)]
        peers = [p for p in range(self.nprocs) if p != self.rank]
        got = self.exchange(
            tag, {p: [np.asarray(arr)] for p in peers}, peers, stream=stream
        )
        return [
            np.asarray(arr) if r == self.rank else got[r][0]
            for r in range(self.nprocs)
        ]

    def barrier(self, name: str) -> None:
        if self.nprocs > 1:
            self.kv.barrier(f"{self.ns}/{name}", self.nprocs, self.timeout_ms)

    # -- tiny named value broadcast (rank 0 -> all), via the KV store --------

    def publish(self, name: str, arr: np.ndarray) -> None:
        a = np.ascontiguousarray(arr)
        shape = ",".join(map(str, a.shape))
        body = base64.b64encode(a.tobytes()).decode()
        self.kv.key_value_set(f"{self.ns}/pub/{name}", f"{a.dtype.str}|{shape}|{body}")

    def lookup(self, name: str) -> np.ndarray:
        raw = self.kv.blocking_key_value_get(f"{self.ns}/pub/{name}", self.timeout_ms)
        descr, shape_s, body = raw.split("|", 2)
        shape = tuple(int(x) for x in shape_s.split(",") if x)
        return np.frombuffer(base64.b64decode(body), np.dtype(descr)).reshape(shape).copy()


# -- cyclic-window arithmetic (shared by both endpoints of every leg) ---------


def window_pieces(start: int, length: int, n: int) -> list[tuple[int, int]]:
    """The cyclic row window ``[start, start+length) mod n`` as ordered
    contiguous global pieces (at most two)."""
    start %= n
    if start + length <= n:
        return [(start, length)]
    return [(start, n - start), (0, start + length - n)]


def intersect(a_lo: int, a_len: int, b_lo: int, b_len: int) -> Optional[tuple[int, int]]:
    lo = max(a_lo, b_lo)
    hi = min(a_lo + a_len, b_lo + b_len)
    return (lo, hi - lo) if hi > lo else None


def plan_window(
    want_start: int, block: int, n: int, nprocs: int
) -> list[tuple[int, int, int, int]]:
    """Assembly plan for the cyclic window ``[want_start, want_start+block)``
    over equal process blocks: ordered ``(owner_rank, global_lo, length,
    window_offset)`` entries.  Derived identically on every rank — the
    sender runs it for the RECEIVER's window to learn what to send."""
    out = []
    off = 0
    for glo, glen in window_pieces(want_start, block, n):
        # owners overlapping [glo, glo+glen)
        b = n // nprocs
        first, last = glo // b, (glo + glen - 1) // b
        for r in range(first, last + 1):
            piece = intersect(glo, glen, r * b, b)
            assert piece is not None
            out.append((r, piece[0], piece[1], off + piece[0] - glo))
        off += glen
    return out
