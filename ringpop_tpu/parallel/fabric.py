"""Host-bridged DCN fabric for process-spanning sim runs.

A real TPU pod runs the SAME jitted step on a process-spanning mesh and
lets XLA drive the DCN — nothing here is needed there.  This module exists
for the fabric a pod does NOT give us: the multi-process CPU validation
rig (and any backend whose runtime cannot execute cross-process XLA
computations — this container's jax 0.4.37 CPU backend is one: it
enumerates global devices but refuses multiprocess programs).  The
engines' exchange legs are nearest-neighbor row windows plus a handful of
[W]-word reduces per tick, so the DCN layer is small enough to carry at
the host level: shard-local jitted kernels per process, window slices over
direct TCP peer sockets, and the jax.distributed KV store for rendezvous.

Layering:

* rendezvous — every rank publishes ``<ns>/addr/<rank>`` in the
  coordination-service KV store (tiny strings only; bulk data NEVER rides
  the KV store) and dials its lower-ranked peers once;
* data — length-framed raw-numpy messages over the peer sockets, tagged
  by the caller (``delta_multihost`` encodes ``tick << 8 | leg`` so a
  stray message from a diverged schedule trips the tag check instead of
  being consumed as a later tick's payload); deadlock-free by sending on
  background threads while the main thread receives in rank order (every
  tick's communication schedule is deterministic on all ranks, derived
  from the same counter-RNG draw);
* collectives — ``allgather`` of per-rank partial words implements the
  OR/AND row reduces and digest combines (bitwise ops reassociate
  exactly, so partial-then-combine is bit-identical to the single-host
  tree — the property every certificate leans on).

Byte accounting is first-class: ``bytes_sent``/``bytes_recv`` accumulate
per rank so the simbench/ksweep records can state per-host MB/tick
against the committed 42.5 MB/chip/tick mesh budget.
"""

from __future__ import annotations

import base64
import socket
import struct
import threading
import time
from typing import Optional, Sequence

import numpy as np

_HDR = struct.Struct(">IIQ")  # tag, n_arrays, total payload bytes
_AHDR = struct.Struct(">III")  # dtype-str len, ndim, nbytes (shape follows)


class LocalKV:
    """In-process KV + barrier standing in for the jax.distributed
    coordination client — the transport is identical, so threaded
    single-machine tests exercise the real fabric code paths."""

    def __init__(self):
        self._d = {}
        self._cv = threading.Condition()
        self._barriers: dict[str, threading.Barrier] = {}
        self._block = threading.Lock()

    def key_value_set(self, key: str, value: str) -> None:
        with self._cv:
            self._d[key] = value
            self._cv.notify_all()

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._d:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    raise TimeoutError(f"KV key {key!r} not set within {timeout_ms} ms")
            return self._d[key]

    def barrier(self, name: str, nprocs: int, timeout_ms: int) -> None:
        with self._block:
            b = self._barriers.setdefault(name, threading.Barrier(nprocs))
        b.wait(timeout=timeout_ms / 1000.0)


class DistributedKV:
    """The jax.distributed coordination-service client, duck-typed to
    LocalKV.  Values are strings; the fabric only ever stores addresses
    and base64'd digest words here."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed

            client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized — call "
                "parallel.multihost.init_distributed() first"
            )
        self._c = client

    def key_value_set(self, key: str, value: str) -> None:
        self._c.key_value_set(key, value)

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        return self._c.blocking_key_value_get(key, timeout_ms)

    def barrier(self, name: str, nprocs: int, timeout_ms: int) -> None:
        del nprocs  # the distributed barrier always spans the whole job
        self._c.wait_at_barrier(name, timeout_ms)


def _send_exact(sock: socket.socket, data) -> None:
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("fabric peer closed the connection")
        got += r
    return bytes(buf)


class Fabric:
    """One rank's endpoint of the host-bridged DCN mesh.

    ``kv`` is a LocalKV (threaded tests) or DistributedKV (real OS
    processes).  ``namespace`` isolates concurrent fabrics in one KV store
    (tests, or a snapshot fabric next to a run fabric).
    """

    def __init__(
        self,
        rank: int,
        nprocs: int,
        kv,
        namespace: str = "fabric",
        host: str = "127.0.0.1",
        timeout_ms: int = 120_000,
    ):
        if not 0 <= rank < nprocs:
            raise ValueError(f"rank {rank} outside [0, {nprocs})")
        self.rank, self.nprocs = rank, nprocs
        self.kv, self.ns = kv, namespace
        self.timeout_ms = timeout_ms
        self.bytes_sent = 0
        self.bytes_recv = 0
        self._peers: dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        if nprocs > 1:
            self._connect(host)

    # -- bring-up -------------------------------------------------------------

    def _connect(self, host: str) -> None:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(self.nprocs)
        # the timeout contract covers BOTH sides of every link: a rank
        # that dies before dialing must fail its peers' accept() at
        # timeout_ms, not hang them forever; accepted and dialed sockets
        # alike carry the timeout so a stalled (not closed) peer surfaces
        # as socket.timeout instead of a wedged _recv_exact
        srv.settimeout(self.timeout_ms / 1000.0)
        port = srv.getsockname()[1]
        self.kv.key_value_set(f"{self.ns}/addr/{self.rank}", f"{host}:{port}")
        # deterministic dial direction: every rank dials its LOWER peers;
        # the accept side learns the dialer's rank from a 4-byte hello
        for peer in range(self.rank):
            addr = self.kv.blocking_key_value_get(f"{self.ns}/addr/{peer}", self.timeout_ms)
            h, p = addr.rsplit(":", 1)
            deadline = time.monotonic() + self.timeout_ms / 1000.0
            while True:
                try:
                    s = socket.create_connection((h, int(p)), timeout=self.timeout_ms / 1000.0)
                    break
                except ConnectionRefusedError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.02)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            _send_exact(s, struct.pack(">I", self.rank))
            self._peers[peer] = s
        for _ in range(self.rank + 1, self.nprocs):
            s, _ = srv.accept()
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            (peer,) = struct.unpack(">I", _recv_exact(s, 4))
            self._peers[peer] = s
        srv.close()

    def close(self) -> None:
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
        self._peers.clear()

    def __enter__(self) -> "Fabric":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- framed numpy messages ------------------------------------------------

    def _pack(self, tag: int, arrays: Sequence[np.ndarray]) -> bytes:
        parts = []
        total = 0
        for a in arrays:
            a = np.ascontiguousarray(a)
            dt = a.dtype.str.encode()
            shape = np.asarray(a.shape, ">u8").tobytes()
            parts.append(_AHDR.pack(len(dt), a.ndim, a.nbytes) + dt + shape)
            parts.append(a.tobytes())
            total += len(parts[-2]) + len(parts[-1])
        return _HDR.pack(tag, len(arrays), total) + b"".join(parts)

    def _send(self, peer: int, tag: int, arrays: Sequence[np.ndarray]) -> None:
        msg = self._pack(tag, arrays)
        with self._lock:
            self.bytes_sent += len(msg)
        _send_exact(self._peers[peer], msg)

    def _recv(self, peer: int, tag: int) -> list[np.ndarray]:
        sock = self._peers[peer]
        hdr = _recv_exact(sock, _HDR.size)
        got_tag, n_arrays, total = _HDR.unpack(hdr)
        if got_tag != tag:
            raise RuntimeError(
                f"fabric desync: rank {self.rank} expected tag {tag} from peer "
                f"{peer}, got {got_tag} — a leg was skipped or reordered"
            )
        payload = _recv_exact(sock, total)
        self.bytes_recv += len(hdr) + total
        out, off = [], 0
        for _ in range(n_arrays):
            dtl, ndim, nbytes = _AHDR.unpack_from(payload, off)
            off += _AHDR.size
            dt = payload[off : off + dtl].decode()
            off += dtl
            shape = tuple(np.frombuffer(payload, ">u8", count=ndim, offset=off).astype(int))
            off += 8 * ndim
            out.append(
                np.frombuffer(payload, np.dtype(dt), count=nbytes // np.dtype(dt).itemsize, offset=off)
                .reshape(shape)
                .copy()
            )
            off += nbytes
        return out

    # -- rounds ---------------------------------------------------------------

    def exchange(
        self,
        tag: int,
        sends: dict[int, Sequence[np.ndarray]],
        recv_from: Sequence[int],
    ) -> dict[int, list[np.ndarray]]:
        """One deterministic communication round: send each payload in
        ``sends`` (background threads), receive one message from every
        peer in ``recv_from`` (in the given order), join.  Both sides must
        derive the same schedule — a mismatch surfaces as a tag desync or
        timeout, never silent misdata."""
        errs: list[BaseException] = []

        def _bg(peer, arrays):
            try:
                self._send(peer, tag, arrays)
            except BaseException as e:  # surfaced after join
                errs.append(e)

        threads = [
            threading.Thread(target=_bg, args=(p, a), daemon=True)
            for p, a in sends.items()
        ]
        for t in threads:
            t.start()
        out = {p: self._recv(p, tag) for p in recv_from}
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return out

    def allgather(self, tag: int, arr: np.ndarray) -> list[np.ndarray]:
        """Every rank's ``arr``, ordered by rank (self included).  Tiny
        payloads only (reduce words, digest partials) — full-mesh sends."""
        if self.nprocs == 1:
            return [np.asarray(arr)]
        peers = [p for p in range(self.nprocs) if p != self.rank]
        got = self.exchange(tag, {p: [np.asarray(arr)] for p in peers}, peers)
        return [
            np.asarray(arr) if r == self.rank else got[r][0]
            for r in range(self.nprocs)
        ]

    def barrier(self, name: str) -> None:
        if self.nprocs > 1:
            self.kv.barrier(f"{self.ns}/{name}", self.nprocs, self.timeout_ms)

    # -- tiny named value broadcast (rank 0 -> all), via the KV store --------

    def publish(self, name: str, arr: np.ndarray) -> None:
        a = np.ascontiguousarray(arr)
        shape = ",".join(map(str, a.shape))
        body = base64.b64encode(a.tobytes()).decode()
        self.kv.key_value_set(f"{self.ns}/pub/{name}", f"{a.dtype.str}|{shape}|{body}")

    def lookup(self, name: str) -> np.ndarray:
        raw = self.kv.blocking_key_value_get(f"{self.ns}/pub/{name}", self.timeout_ms)
        descr, shape_s, body = raw.split("|", 2)
        shape = tuple(int(x) for x in shape_s.split(",") if x)
        return np.frombuffer(base64.b64decode(body), np.dtype(descr)).reshape(shape).copy()


# -- cyclic-window arithmetic (shared by both endpoints of every leg) ---------


def window_pieces(start: int, length: int, n: int) -> list[tuple[int, int]]:
    """The cyclic row window ``[start, start+length) mod n`` as ordered
    contiguous global pieces (at most two)."""
    start %= n
    if start + length <= n:
        return [(start, length)]
    return [(start, n - start), (0, start + length - n)]


def intersect(a_lo: int, a_len: int, b_lo: int, b_len: int) -> Optional[tuple[int, int]]:
    lo = max(a_lo, b_lo)
    hi = min(a_lo + a_len, b_lo + b_len)
    return (lo, hi - lo) if hi > lo else None


def plan_window(
    want_start: int, block: int, n: int, nprocs: int
) -> list[tuple[int, int, int, int]]:
    """Assembly plan for the cyclic window ``[want_start, want_start+block)``
    over equal process blocks: ordered ``(owner_rank, global_lo, length,
    window_offset)`` entries.  Derived identically on every rank — the
    sender runs it for the RECEIVER's window to learn what to send."""
    out = []
    off = 0
    for glo, glen in window_pieces(want_start, block, n):
        # owners overlapping [glo, glo+glen)
        b = n // nprocs
        first, last = glo // b, (glo + glen - 1) // b
        for r in range(first, last + 1):
            piece = intersect(glo, glen, r * b, b)
            assert piece is not None
            out.append((r, piece[0], piece[1], off + piece[0] - glo))
        off += glen
    return out
