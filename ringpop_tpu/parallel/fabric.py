"""Host-bridged DCN fabric for process-spanning sim runs.

A real TPU pod runs the SAME jitted step on a process-spanning mesh and
lets XLA drive the DCN — nothing here is needed there.  This module exists
for the fabric a pod does NOT give us: the multi-process CPU validation
rig (and any backend whose runtime cannot execute cross-process XLA
computations — this container's jax 0.4.37 CPU backend is one: it
enumerates global devices but refuses multiprocess programs).  The
engines' exchange legs are nearest-neighbor row windows plus a handful of
[W]-word reduces per tick, so the DCN layer is small enough to carry at
the host level: shard-local jitted kernels per process, window slices over
direct TCP peer sockets, and the jax.distributed KV store for rendezvous.

Layering:

* rendezvous — every rank publishes ``<ns>/addr/<rank>`` in the
  coordination-service KV store (tiny strings only; bulk data NEVER rides
  the KV store) and dials its lower-ranked peers once;
* data — length-framed raw-numpy messages over the peer sockets, tagged
  by the caller (``delta_multihost`` encodes ``tick << 8 | leg`` so a
  stray message from a diverged schedule trips the tag check instead of
  being consumed as a later tick's payload); deadlock-free by draining
  sends on per-peer PERSISTENT sender threads while per-peer receiver
  threads demux tagged expectations in FIFO order (every tick's
  communication schedule is deterministic on all ranks, derived from the
  same counter-RNG draw, and TCP preserves per-peer message order — so
  the demux is a queue, not a search);
* completions (r16) — ``exchange_async`` enqueues a round and returns an
  :class:`ExchangeHandle`; ``exchange`` is exactly
  ``exchange_async(...).wait()``.  ``wait(join_sends=False)`` joins only
  the receives, which is what lets the multihost engine overlap tick
  t+1's shard-local compute with tick t's wire drain (the cross-TICK
  pipelining of PAPERS "Pipelined Gossiping").  A sender-thread failure
  is sticky: it fails the round's handle AND every later enqueue to that
  peer, so an unjoined drain error cannot vanish;
* schedules (r16) — :func:`plan_window_swing` is the distance-halving
  (Swing-style, hypercube dimension-fixing) relay alternative to the
  direct :func:`plan_window` assembly: O(log P) rounds of exactly ONE
  partner each at power-of-two distances, relay ranks forwarding
  coalesced pieces, vs the cyclic plan's arbitrary-distance direct
  sends.  ``allgather(schedule="swing")`` is the matching
  recursive-doubling variant — bitwise OR/AND reduces reassociate
  exactly, so the combine stays bit-identical under either schedule;
* collectives — ``allgather`` of per-rank partial words implements the
  OR/AND row reduces and digest combines (bitwise ops reassociate
  exactly, so partial-then-combine is bit-identical to the single-host
  tree — the property every certificate leans on);
* codec (r15) — every array on the wire carries a one-byte
  self-describing codec: zero-row suppression (``ROWS``: bitmap of
  nonzero rows + packed payload — the dominant win for the ride-masked
  exchange legs, whose ``sent``/``answerable`` planes are mostly zero
  rows outside the dissemination wave), zero-word run suppression
  (``RUNS``: dense-but-patchy planes), an optional previous-payload
  XOR-delta (``XOR``: explicit epoch word, reset on snapshot restore /
  peer-count change so restore-onto-a-different-P stays certified), and
  a MEASURED raw fallback — an encoding that does not strictly shrink
  the payload is never sent.  Encode decisions are send-side local and
  decode is exact, so digests are bit-identical by construction,
  codec-on vs codec-off.

Byte accounting is first-class and split (r15): ``bytes_sent``/
``bytes_recv`` are the actual WIRE bytes; ``raw_bytes_sent``/
``raw_bytes_recv`` are what the same messages would have cost with the
codec off, so the simbench/ksweep records can state both the per-host
MB/tick on the wire and the compression ratio against the committed
42.5 MB/chip/tick mesh budget.
"""

from __future__ import annotations

import base64
import functools
import json
import os
import queue
import socket
import struct
import tempfile
import threading
import time
from typing import NamedTuple, Optional, Sequence, Union

import numpy as np

_HDR = struct.Struct(">IIQ")  # tag, n_arrays, total payload bytes
# per-array header: codec byte, dtype-str len, ndim, ENCODED payload bytes
# (dtype str + ">u8" shape words follow; then the encoded payload)
_AHDR = struct.Struct(">BIIQ")

# -- wire codec ---------------------------------------------------------------

CODEC_RAW = 0  # payload = a.tobytes()
CODEC_ROWS = 1  # ">I" nnz-rows + LSB-first row bitmap + nonzero rows packed
CODEC_RUNS = 2  # ">I" n-runs + "<u4" starts + "<u4" lens + nonzero u32 words
CODEC_XOR = 3  # ">II" epoch, inner codec + inner payload of prev-XOR diff

CODEC_NAMES = {CODEC_RAW: "raw", CODEC_ROWS: "rows",
               CODEC_RUNS: "runs", CODEC_XOR: "xor"}


# the error family lives in the import-free leaf ``ringpop_tpu.errors``
# (r17: shared with the jax-free channel/shm/forward surfaces); re-
# exported here under the historical import path every caller uses
from ringpop_tpu.errors import (  # noqa: F401  (re-export)
    FabricDesync,
    FabricError,
    FabricPeerLost,
    FabricTimeout,
)


class Encoded(NamedTuple):
    """A pre-encoded wire array (codec already applied).  Callers that
    hold send-side structure the encoder would otherwise recompute — the
    multihost engine's DEVICE-computed nonzero-row summaries — hand the
    fabric one of these instead of an ndarray; ``decode_array`` cannot
    tell the difference."""

    codec: int
    dtype: np.dtype
    shape: tuple
    payload: bytes
    raw_nbytes: int


def _bitmap_pack(mask: np.ndarray) -> bytes:
    """bool[rows] -> ceil(rows/8) LSB-first bytes (bit i of byte j is
    row 8j+i) — the byte order ``packbits.pack_bool``'s little-endian
    uint32 word view produces, so device-packed masks are wire-identical
    to host-packed ones."""
    return np.packbits(mask, bitorder="little").tobytes()


def _bitmap_unpack(buf: bytes, rows: int) -> np.ndarray:
    return np.unpackbits(
        np.frombuffer(buf, np.uint8), count=rows, bitorder="little"
    ).astype(bool)


def rows_wire_size(rows: int, nnz: int, row_nbytes: int) -> int:
    """Encoded-payload size of a ROWS encoding — callers with a
    device-side nonzero count use this to decide BEFORE transferring."""
    return 4 + (rows + 7) // 8 + nnz * row_nbytes


def encode_rows(
    mask: np.ndarray, rows_payload: np.ndarray, shape: tuple, dtype
) -> Encoded:
    """Build a ROWS encoding from an externally computed nonzero-row
    mask + already-compacted nonzero rows (the device-sliced hot path).
    The caller is responsible for having checked ``rows_wire_size``
    against the raw size — this constructor encodes unconditionally."""
    dtype = np.dtype(dtype)
    raw_nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    nnz = int(rows_payload.shape[0])
    payload = (
        struct.pack(">I", nnz)
        + _bitmap_pack(np.asarray(mask, bool))
        + np.ascontiguousarray(rows_payload).tobytes()
    )
    return Encoded(CODEC_ROWS, dtype, tuple(shape), payload, raw_nbytes)


def _rows_encode(a: np.ndarray) -> Optional[bytes]:
    """Zero-row suppression; None when it would not strictly shrink.
    The row mask tests the BYTE view, not values — float -0.0 is
    value-equal to zero but bit-distinct, and the decode contract is
    bit-exactness (``a`` is contiguous: encode_array guarantees it)."""
    if a.ndim < 2 or a.shape[0] < 2 or a.size == 0:
        return None
    flat = a.reshape(a.shape[0], -1)
    mask = (flat.view(np.uint8) != 0).any(axis=1)
    nnz = int(mask.sum())
    row_nbytes = a.nbytes // a.shape[0]
    if rows_wire_size(a.shape[0], nnz, row_nbytes) >= a.nbytes:
        return None
    return struct.pack(">I", nnz) + _bitmap_pack(mask) + flat[mask].tobytes()


def _rows_decode(payload: bytes, shape: tuple, dtype: np.dtype) -> np.ndarray:
    (nnz,) = struct.unpack_from(">I", payload, 0)
    nb = 4 + (shape[0] + 7) // 8
    mask = _bitmap_unpack(payload[4:nb], shape[0])
    if int(mask.sum()) != nnz:
        raise FabricError(
            f"ROWS bitmap popcount {int(mask.sum())} != header nnz {nnz} — "
            "corrupt frame"
        )
    out = np.zeros(shape, dtype)
    out[mask] = np.frombuffer(payload, dtype, offset=nb).reshape(
        (nnz,) + tuple(shape[1:])
    )
    return out


def _runs_encode(a: np.ndarray) -> Optional[bytes]:
    """Zero-WORD run suppression over the uint32 view; None when the
    dtype does not view as whole words or it would not strictly shrink.
    ``a`` must be C-contiguous (``encode_array`` guarantees it) — the
    word view and the cheap-reject count are copy-free, so a dense plane
    costs ONE pass here, not the full run detection."""
    if a.nbytes % 4 or a.nbytes == 0:
        return None
    w = a.reshape(-1).view(np.uint32)
    nz = w != 0
    nnz_words = int(np.count_nonzero(nz))
    if 4 + 8 + 4 * nnz_words >= a.nbytes:
        return None  # even a single run cannot shrink this payload
    edges = np.flatnonzero(np.diff(np.concatenate(([False], nz, [False]))))
    starts, ends = edges[0::2], edges[1::2]
    size = 4 + 8 * len(starts) + 4 * nnz_words
    if size >= a.nbytes:
        return None
    return (
        struct.pack(">I", len(starts))
        + starts.astype("<u4").tobytes()
        + (ends - starts).astype("<u4").tobytes()
        + w[nz].tobytes()
    )


def _runs_decode(payload: bytes, nbytes: int) -> np.ndarray:
    """-> the flat uint32 word view (caller reshapes/reviews)."""
    (nruns,) = struct.unpack_from(">I", payload, 0)
    starts = np.frombuffer(payload, "<u4", count=nruns, offset=4).astype(np.int64)
    lens = np.frombuffer(payload, "<u4", count=nruns, offset=4 + 4 * nruns).astype(
        np.int64
    )
    words = np.frombuffer(payload, np.uint32, offset=4 + 8 * nruns)
    out = np.zeros(nbytes // 4, np.uint32)
    if nruns:
        tot = int(lens.sum())
        off = np.arange(tot, dtype=np.int64) - np.repeat(
            np.cumsum(lens) - lens, lens
        )
        out[np.repeat(starts, lens) + off] = words
    return out


def encode_array(
    a: np.ndarray,
    prev: Optional[bytes] = None,
    epoch: int = 0,
    rows: bool = True,
) -> Encoded:
    """Best strictly-smaller encoding of ``a`` — RAW when nothing pays
    (the measured fallback).  ``prev`` (the previous payload bytes on
    this stream, same shape/dtype — the caller guarantees it was
    recorded under ``epoch``) additionally offers the XOR-delta.
    ``rows=False`` skips the ROWS attempt — for callers that already
    know the nonzero-row count (the engine's device-side summary) and
    would otherwise pay a redundant full host scan per dense piece."""
    a = np.ascontiguousarray(a)
    cands: list[tuple[int, int, bytes]] = [(a.nbytes, CODEC_RAW, b"")]
    rows_payload = _rows_encode(a) if rows else None
    if rows_payload is not None:
        cands.append((len(rows_payload), CODEC_ROWS, rows_payload))
    runs = _runs_encode(a)
    if runs is not None:
        cands.append((len(runs), CODEC_RUNS, runs))
    if prev is not None and len(prev) == a.nbytes and a.nbytes:
        diff = np.bitwise_xor(
            a.reshape(-1).view(np.uint8),
            np.frombuffer(prev, np.uint8),
        )
        # only a RUNS-compressed diff can undercut raw (an inner-RAW
        # XOR payload is raw + 8 header bytes by construction), so no
        # RUNS win means no XOR candidate; decode_array still accepts
        # an inner-RAW frame for wire-format completeness
        inner = _runs_encode(diff)
        if inner is not None:
            xor_payload = struct.pack(">II", epoch & 0xFFFFFFFF, CODEC_RUNS) + inner
            if len(xor_payload) < a.nbytes:
                cands.append((len(xor_payload), CODEC_XOR, xor_payload))
    size, codec, payload = min(cands, key=lambda c: (c[0], c[1]))
    if codec == CODEC_RAW:
        payload = a.tobytes()
    return Encoded(codec, a.dtype, a.shape, payload, a.nbytes)


def decode_array(
    codec: int,
    dtype: np.dtype,
    shape: tuple,
    payload: bytes,
    prev: Optional[bytes] = None,
    epoch: int = 0,
) -> np.ndarray:
    """Exact inverse of every encoding.  XOR requires the previous
    payload on the stream AND a matching epoch word — a mismatch means
    one side missed a codec reset (snapshot restore / peer change) and
    MUST fail loudly rather than decode garbage."""
    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    if codec == CODEC_RAW:
        return np.frombuffer(payload, dtype, count=-1).reshape(shape).copy()
    if codec == CODEC_ROWS:
        return _rows_decode(payload, tuple(shape), np.dtype(dtype))
    if codec == CODEC_RUNS:
        words = _runs_decode(payload, nbytes)
        return np.frombuffer(words.tobytes(), dtype).reshape(shape).copy()
    if codec == CODEC_XOR:
        got_epoch, inner_codec = struct.unpack_from(">II", payload, 0)
        if prev is None or got_epoch != (epoch & 0xFFFFFFFF):
            raise FabricError(
                f"codec epoch desync: XOR frame carries epoch {got_epoch} but "
                f"this rank is at epoch {epoch & 0xFFFFFFFF} with "
                f"{'no' if prev is None else 'a'} previous payload — a codec "
                "reset (snapshot restore / peer-count change) was missed on "
                "one side"
            )
        inner = payload[8:]
        if inner_codec == CODEC_RUNS:
            diff = _runs_decode(inner, nbytes).tobytes()
        else:
            diff = inner
        raw = np.bitwise_xor(
            np.frombuffer(diff, np.uint8),
            np.frombuffer(prev, np.uint8),
        ).tobytes()
        return np.frombuffer(raw, dtype).reshape(shape).copy()
    raise FabricError(f"unknown wire codec byte {codec}")


def frame_array(a: np.ndarray) -> bytes:
    """One array as a self-contained fabric frame: the per-array header
    (codec byte, dtype, shape) + best-encoding payload — byte-identical
    to what the same array costs inside an exchange message.  The r17
    unified-transport hook: ``net.channel`` rides the r15 codec through
    this for frame-body array values."""
    enc = encode_array(np.ascontiguousarray(a))
    dt = enc.dtype.str.encode()
    shape = np.asarray(enc.shape, ">u8").tobytes()
    return (
        _AHDR.pack(enc.codec, len(dt), len(enc.shape), len(enc.payload))
        + dt + shape + enc.payload
    )


def unframe_array(data: bytes) -> np.ndarray:
    """Exact inverse of :func:`frame_array`."""
    codec, dtl, ndim, nbytes = _AHDR.unpack_from(data, 0)
    off = _AHDR.size
    dt = data[off : off + dtl].decode()
    off += dtl
    shape = tuple(np.frombuffer(data, ">u8", count=ndim, offset=off).astype(int))
    off += 8 * ndim
    return decode_array(codec, np.dtype(dt), shape, data[off : off + nbytes])


class LocalKV:
    """In-process KV + barrier standing in for the jax.distributed
    coordination client — the transport is identical, so threaded
    single-machine tests exercise the real fabric code paths."""

    def __init__(self):
        self._d = {}
        self._cv = threading.Condition()
        self._barriers: dict[str, threading.Barrier] = {}
        self._block = threading.Lock()

    def key_value_set(self, key: str, value: str) -> None:
        with self._cv:
            self._d[key] = value
            self._cv.notify_all()

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._d:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    raise TimeoutError(f"KV key {key!r} not set within {timeout_ms} ms")
            return self._d[key]

    def barrier(self, name: str, nprocs: int, timeout_ms: int) -> None:
        with self._block:
            b = self._barriers.setdefault(name, threading.Barrier(nprocs))
        b.wait(timeout=timeout_ms / 1000.0)


class DistributedKV:
    """The jax.distributed coordination-service client, duck-typed to
    LocalKV.  Values are strings; the fabric only ever stores addresses
    and base64'd digest words here."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed

            client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized — call "
                "parallel.multihost.init_distributed() first"
            )
        self._c = client

    def key_value_set(self, key: str, value: str) -> None:
        self._c.key_value_set(key, value)

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        return self._c.blocking_key_value_get(key, timeout_ms)

    def barrier(self, name: str, nprocs: int, timeout_ms: int) -> None:
        del nprocs  # the distributed barrier always spans the whole job
        self._c.wait_at_barrier(name, timeout_ms)


def _send_exact(sock: socket.socket, data) -> None:
    sock.sendall(data)


# Linux IOV_MAX is 1024; staying under it keeps every sendmsg call a
# single syscall attempt instead of an EINVAL surprise on huge rounds
_IOV_CHUNK = 512


def _send_parts(sock: socket.socket, parts: Sequence) -> None:
    """Vectored send of a framed message: header + per-array metadata +
    payload buffers go to the kernel as ONE iovec (``sendmsg``) instead
    of being ``+``-concatenated into a fresh wire-sized bytes object —
    the send half of the r21 zero-copy contract.  Partial sends advance
    through memoryviews; no payload bytes are ever copied host-side."""
    bufs = [memoryview(p) for p in parts if len(p)]
    while bufs:
        sent = sock.sendmsg(bufs[:_IOV_CHUNK])
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if sent:
            bufs[0] = bufs[0][sent:]


class TransportLedger:
    """The ONE merged byte ledger of the unified transport plane (r21).

    Every transport class — the fabric's exchange streams (``exchange``),
    the RPC request/response tag family the channel rides (``rpc``), the
    obs side-channel fabric (``obs``), the serve shm ring (``shm``) —
    accounts into the same ledger under its class key, so a run can state
    its total wire traffic AND the per-tag-family split from one
    snapshot.  ``copy_bytes`` counts payload bytes that took an
    intermediate host copy on a registered-buffer path (the shm slot →
    fused dispatch hand-off); the zero-copy acceptance bar is that it
    reads 0 there — proven by the transport smoke, not claimed.

    Per-class sums are defined to equal the legacy per-transport
    counters (``Fabric.wire_stats``, the channel's ``wire_stats``, the
    shm server's slot accounting) on identical traffic — the r21
    migration contract pinned by test and by ``make transport-smoke``.

    r23 adds a per-LANE dimension under every class: the RPC plane can
    carry a frame over TCP or over the same-host shm frame lane, and the
    serve ring is a shm lane by construction.  ``add(..., lane=...)``
    attributes each delta to one lane; a class row in ``stats()`` is the
    field-wise SUM of its lanes (so every pre-r23 reconciliation holds
    unchanged) plus a ``"lanes"`` sub-dict with the split.  Two latency-
    tier liveness counters ride along: ``inline_completions`` (replies
    fulfilled directly on a reader thread for a blocked sync caller —
    zero event-loop hops) and ``coalesced_frames`` (frames that shared
    one ``sendmsg`` with at least one other frame).
    """

    FIELDS = (
        "bytes_sent", "bytes_recv", "raw_bytes_sent", "raw_bytes_recv",
        "frames_sent", "frames_recv", "copy_bytes",
        "inline_completions", "coalesced_frames",
    )
    LANES = ("tcp", "shm")

    def __init__(self):
        self._lock = threading.Lock()
        # class -> lane -> field row
        self._classes: dict[str, dict[str, dict[str, int]]] = {}

    def add(self, klass: str, lane: str = "tcp", **deltas: int) -> None:
        with self._lock:
            lanes = self._classes.setdefault(klass, {})
            row = lanes.setdefault(lane, {f: 0 for f in self.FIELDS})
            for k, v in deltas.items():
                row[k] += int(v)

    def stats(self) -> dict:
        """Snapshot: ``{"classes": {class: {field: n, "lanes": {lane:
        {field: n}}}}, "total": {field: n}, "copy_bytes": n}`` — a class
        row's fields are the sums of its lanes; ``copy_bytes`` is lifted
        to the top level because it is the zero-copy certificate, not a
        traffic counter."""
        with self._lock:
            snap = {
                k: {ln: dict(r) for ln, r in sorted(lanes.items())}
                for k, lanes in sorted(self._classes.items())
            }
        classes: dict[str, dict] = {}
        for k, lanes in snap.items():
            row: dict = {
                f: sum(r[f] for r in lanes.values()) for f in self.FIELDS
            }
            row["lanes"] = lanes
            classes[k] = row
        total = {f: sum(v[f] for v in classes.values()) for f in self.FIELDS}
        return {
            "classes": classes,
            "total": total,
            "copy_bytes": total["copy_bytes"],
        }


class _Future:
    """One pending send or receive: an event plus a value-or-error slot.
    ``value`` for a send is the monotonic completion timestamp (the
    drain-timing hook); for a receive, the decoded array list."""

    __slots__ = ("ev", "value", "err")

    def __init__(self):
        self.ev = threading.Event()
        self.value = None
        self.err: Optional[BaseException] = None

    def fulfill(self, value) -> None:
        self.value = value
        self.ev.set()

    def fail(self, err: BaseException) -> None:
        self.err = err
        self.ev.set()


class _RecvJob(NamedTuple):
    tag: int
    stream: Optional[str]
    fut: _Future


# -- failure hooks (the flight-recorder seam, r20) ----------------------------
#
# Observers of fabric-level failures: every callable registered here is
# invoked (best-effort, exceptions swallowed — a diagnostic hook must
# never mask the original failure) with the typed error at the moment a
# link goes sticky or a round's errors aggregate into a raise.  The
# obs-plane FlightRecorder registers here to dump a rank's last seconds
# the instant its peer vanishes (FabricPeerLost) or wedges
# (FabricTimeout).

_FAILURE_HOOKS: list = []


def add_failure_hook(fn) -> None:
    """Register ``fn(err: BaseException)`` to observe fabric failures."""
    _FAILURE_HOOKS.append(fn)


def remove_failure_hook(fn) -> None:
    try:
        _FAILURE_HOOKS.remove(fn)
    except ValueError:
        pass


def _notify_failure(err: BaseException) -> None:
    for fn in list(_FAILURE_HOOKS):
        try:
            fn(err)
        except Exception:
            pass


def _aggregate_raise(errs: Sequence[BaseException], notify: bool = True) -> None:
    """Raise ``errs[0]`` with every OTHER error attached: chained via
    ``__context__`` (so one traceback shows the whole multi-peer outage)
    and collected on ``peer_errors`` for programmatic access.  Before r16
    a round that failed on several sender threads raised only ``errs[0]``
    and silently dropped the rest.  ``notify=False`` (a
    ``notify_failures=False`` fabric) skips the failure hooks."""
    if not errs:
        return
    if notify:
        for e in errs:
            _notify_failure(e)
    primary = errs[0]
    rest = [e for e in errs[1:] if e is not primary]
    node = primary
    seen = {id(primary)}
    for e in rest:
        while node.__context__ is not None and id(node.__context__) not in seen:
            node = node.__context__
            seen.add(id(node))
        if id(e) not in seen:
            node.__context__ = e
            seen.add(id(e))
            node = e
    primary.peer_errors = tuple([primary, *rest])  # type: ignore[attr-defined]
    raise primary


class _PeerLink:
    """One peer's persistent send/receive machinery: a sender thread
    draining a FIFO of pre-packed wire messages and a receiver thread
    draining a FIFO of tagged expectations.  Errors are STICKY — after a
    socket failure every queued and future job on that side of the link
    fails with the same typed error (the socket state is undefined after
    a partial frame, so there is nothing to resume)."""

    def __init__(self, fabric: "Fabric", peer: int, sock: socket.socket):
        self.fabric = fabric
        self.peer = peer
        self.sock = sock
        self.sendq: "queue.Queue" = queue.Queue()
        self.recvq: "queue.Queue" = queue.Queue()
        self.send_err: Optional[BaseException] = None
        self.recv_err: Optional[BaseException] = None
        # pooled receive buffers (r21): one header buf + one growable
        # payload arena per link, reused across every frame this
        # receiver thread reads — frames no longer cost an allocation
        self._hdr_buf = bytearray(_HDR.size)
        self._arena = bytearray(1 << 16)
        self._sender = threading.Thread(
            target=self._send_loop, daemon=True,
            name=f"fabric-r{fabric.rank}-send-p{peer}",
        )
        self._receiver = threading.Thread(
            target=self._recv_loop, daemon=True,
            name=f"fabric-r{fabric.rank}-recv-p{peer}",
        )
        self._sender.start()
        self._receiver.start()

    def _drain_failed(self, q, err: BaseException) -> None:
        """Fail every still-queued job on ``q`` — a loop exiting early
        (fabric closed) must not leave later futures unfulfilled, or
        their waiters would block into a misleading timeout."""
        while True:
            try:
                job = q.get_nowait()
            except queue.Empty:
                return
            if job is None:
                continue
            # send jobs are (fut, msg, tag) tuples; recv jobs are
            # _RecvJob NamedTuples — which are ALSO tuples, so match the
            # typed one first
            fut = job.fut if isinstance(job, _RecvJob) else job[0]
            fut.fail(err)

    def _arena_for(self, n: int) -> bytearray:
        """The payload arena, grown geometrically when a frame exceeds
        it (growth counts as ONE allocation on ``RECV_ALLOCS``; steady-
        state frames then reuse it for free)."""
        if len(self._arena) < n:
            RECV_ALLOCS.bump()
            self._arena = bytearray(max(n, 2 * len(self._arena)))
        return self._arena

    def _send_loop(self) -> None:
        while True:
            job = self.sendq.get()
            if job is None:
                return
            fut, msg, tag = job
            if self.send_err is not None:
                fut.fail(self.send_err)
                continue
            try:
                _send_parts(self.sock, msg)
                fut.fulfill(time.monotonic())
            except socket.timeout as e:
                self.send_err = FabricTimeout(
                    f"rank {self.fabric.rank}: send to peer {self.peer} "
                    f"(tag {tag}) could not drain within "
                    f"{self.fabric.timeout_ms} ms — peer wedged or partitioned"
                )
                self.send_err.__cause__ = e
                if self.fabric.notify_failures:
                    _notify_failure(self.send_err)
                fut.fail(self.send_err)
            except OSError as e:
                if self.fabric._closed:
                    err = FabricError(
                        f"rank {self.fabric.rank}: fabric closed with a send "
                        f"to peer {self.peer} still queued")
                    fut.fail(err)
                    self._drain_failed(self.sendq, err)
                    return
                self.send_err = FabricPeerLost(
                    f"rank {self.fabric.rank}: send to peer {self.peer} "
                    f"(tag {tag}) failed ({e}) — peer process died mid-exchange"
                )
                self.send_err.__cause__ = e
                if self.fabric.notify_failures:
                    _notify_failure(self.send_err)
                fut.fail(self.send_err)

    def _recv_loop(self) -> None:
        while True:
            job = self.recvq.get()
            if job is None:
                return
            if self.recv_err is not None:
                job.fut.fail(self.recv_err)
                continue
            try:
                job.fut.fulfill(
                    self.fabric._recv(self.peer, job.tag, job.stream, link=self)
                )
            except FabricError as e:
                if self.fabric._closed:
                    err = FabricError(
                        f"rank {self.fabric.rank}: fabric closed with a "
                        f"receive from peer {self.peer} still pending")
                    job.fut.fail(err)
                    self._drain_failed(self.recvq, err)
                    return
                self.recv_err = e
                if self.fabric.notify_failures:
                    _notify_failure(e)
                job.fut.fail(e)
            except BaseException as e:  # decode bugs must not hang waiters
                self.recv_err = FabricError(
                    f"rank {self.fabric.rank}: receive from peer {self.peer} "
                    f"(tag {job.tag}) failed: {type(e).__name__}: {e}"
                )
                self.recv_err.__cause__ = e
                job.fut.fail(self.recv_err)

    def shutdown(self) -> None:
        # let queued sends drain briefly BEFORE the socket closes (an
        # overlapped final round may still be in the queue); a peer-dead
        # stall is bounded by the join timeout, then the close forces the
        # sender out
        self.sendq.put(None)
        self._sender.join(timeout=2.0)
        self.recvq.put(None)
        try:
            self.sock.close()
        except OSError:
            pass
        self._sender.join(timeout=2.0)
        self._receiver.join(timeout=2.0)


class ExchangeHandle:
    """The completion handle of one asynchronous fabric round.

    ``wait()`` (the default, ``join_sends=True``) reproduces the
    blocking ``exchange`` contract exactly: receives joined in order,
    sends joined, every error of the round aggregated into one raise.
    ``wait(join_sends=False)`` joins ONLY the receives — the engine's
    cross-tick overlap mode: the send drain continues on the persistent
    sender threads, ordered FIFO behind nothing (a later round's payload
    cannot overtake it), and a drain failure is sticky on the link so it
    surfaces at the next enqueue or wait touching that peer.
    """

    def __init__(self, fabric: "Fabric", tag: int, recv_futs, send_futs):
        self.fabric = fabric
        self.tag = tag
        self._recv_futs = recv_futs  # [(peer, _Future)] in recv_from order
        self._send_futs = send_futs  # [(peer, _Future)] in enqueue order
        self.issued_s = time.monotonic()
        self.waited_s = 0.0  # total wall spent blocked in wait() calls

    def _budget_s(self) -> float:
        # the socket-level timeout fires first with its richer message;
        # the margin only catches a wedged demux thread
        return self.fabric.timeout_ms / 1000.0 + 5.0

    def wait(self, join_sends: bool = True) -> dict[int, list[np.ndarray]]:
        t0 = time.monotonic()
        deadline = t0 + self._budget_s()
        errs: list[BaseException] = []
        out: dict[int, list[np.ndarray]] = {}
        try:
            for peer, fut in self._recv_futs:
                if not fut.ev.wait(timeout=max(0.0, deadline - time.monotonic())):
                    errs.append(FabricTimeout(
                        f"rank {self.fabric.rank}: completion for tag "
                        f"{self.tag} from peer {peer} not fulfilled within "
                        f"{self.fabric.timeout_ms} ms"))
                    continue
                if fut.err is not None:
                    errs.append(fut.err)
                else:
                    out[peer] = fut.value
            for peer, fut in self._send_futs:
                if not join_sends:
                    # non-blocking: surface only already-failed sends
                    if fut.ev.is_set() and fut.err is not None:
                        errs.append(fut.err)
                    continue
                if not fut.ev.wait(timeout=max(0.0, deadline - time.monotonic())):
                    errs.append(FabricTimeout(
                        f"rank {self.fabric.rank}: send to peer {peer} for "
                        f"tag {self.tag} still undrained within "
                        f"{self.fabric.timeout_ms} ms"))
                elif fut.err is not None:
                    errs.append(fut.err)
        finally:
            self.waited_s += time.monotonic() - t0
        if errs:
            _aggregate_raise(errs, notify=self.fabric.notify_failures)
        return out

    def poll(self) -> Optional[dict[int, Union[list, BaseException]]]:
        """Non-blocking completion probe of the RECEIVE side: ``None``
        while any expectation is still outstanding, else a map ``peer ->
        decoded arrays`` (or the typed error that leg failed with —
        returned, not raised, so a poller can keep serving the live
        peers).  Sends are untouched — accounting, sticky errors and the
        overlap contract behave exactly as if this was never called.
        The obs plane's rank-0 collector harvests rounds through this."""
        out: dict[int, Union[list, BaseException]] = {}
        for peer, fut in self._recv_futs:
            if not fut.ev.is_set():
                return None
            out[peer] = fut.err if fut.err is not None else fut.value
        return out

    def sends_done_s(self) -> Optional[float]:
        """Monotonic timestamp when the LAST send of this round hit the
        socket — ``None`` while any is still draining (or failed).  The
        engine's ``overlap_hidden_ms`` gauge reads this after the fact."""
        done = self.issued_s
        for _, fut in self._send_futs:
            if not fut.ev.is_set() or fut.err is not None:
                return None
            done = max(done, fut.value)
        return done


class _AllocCounter:
    """Receive-buffer allocation counter (r21 satellite): the pooled
    arena makes per-frame allocation a regression, so tests pin that a
    steady-state exchange stream allocates O(1), not O(frames)."""

    __slots__ = ("n", "_lock")

    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def bump(self) -> None:
        with self._lock:
            self.n += 1


RECV_ALLOCS = _AllocCounter()


def _recv_exact(
    sock: socket.socket, n: int, buf: Optional[bytearray] = None
) -> memoryview:
    """Read exactly ``n`` bytes into ``buf`` (a caller-pooled arena,
    reused across frames) and return a sized read view.  The view is
    valid only until the next call that reuses the same arena — decoders
    must copy anything that outlives the frame (``decode_array`` already
    materializes fresh arrays).  ``buf=None`` allocates, and every
    allocation (fresh or arena-growth, which the caller does before
    passing a bigger buf) bumps ``RECV_ALLOCS`` so the per-frame-alloc
    regression is pinnable."""
    if buf is None or len(buf) < n:
        RECV_ALLOCS.bump()
        buf = bytearray(n)
    view = memoryview(buf)[:n]
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise FabricPeerLost("fabric peer closed the connection")
        got += r
    return view


class Fabric:
    """One rank's endpoint of the host-bridged DCN mesh.

    ``kv`` is a LocalKV (threaded tests) or DistributedKV (real OS
    processes).  ``namespace`` isolates concurrent fabrics in one KV store
    (tests, or a snapshot fabric next to a run fabric).
    """

    def __init__(
        self,
        rank: int,
        nprocs: int,
        kv,
        namespace: str = "fabric",
        host: str = "127.0.0.1",
        timeout_ms: int = 120_000,
        codec: bool = True,
        notify_failures: bool = True,
        ledger: Optional[TransportLedger] = None,
        ledger_class: str = "exchange",
        rejoin: bool = False,
    ):
        if not 0 <= rank < nprocs:
            raise ValueError(f"rank {rank} outside [0, {nprocs})")
        self.rank, self.nprocs = rank, nprocs
        self.kv, self.ns = kv, namespace
        self.timeout_ms = timeout_ms
        self.codec = codec
        # the merged transport ledger (r21): every legacy counter below
        # is mirrored into it under ledger_class, so per-class ledger
        # sums equal this fabric's own wire_stats by construction —
        # pass a shared ledger to account several transports together
        # (the obs fabric registers as class "obs", the channel's RPC
        # plane as "rpc", the shm ring as "shm")
        self.ledger = ledger if ledger is not None else TransportLedger()
        self.ledger_class = ledger_class
        # notify_failures=False opts this fabric OUT of the global
        # failure hooks (obs/flight): the obs plane's own side-channel
        # fabric tolerates rank skew as routine — its timeouts must not
        # burn the flight recorder's once-per-process dump that exists
        # for ENGINE fabric failures
        self.notify_failures = notify_failures
        self.bytes_sent = 0  # actual wire bytes
        self.bytes_recv = 0
        self.raw_bytes_sent = 0  # what the same messages cost codec-off
        self.raw_bytes_recv = 0
        self.codec_counts: dict[int, int] = {}  # sent arrays per codec byte
        # XOR-delta stream state: (peer, stream, array-idx) -> payload
        # bytes recorded under codec_epoch; reset_codec_state() clears both
        # sides' dicts and bumps the epoch word (collective by convention:
        # every rank resets at the same protocol point — snapshot restore)
        self.codec_epoch = 0
        self._tx_prev: dict[tuple, bytes] = {}
        self._rx_prev: dict[tuple, bytes] = {}
        self._peers: dict[int, socket.socket] = {}
        self._links: dict[int, _PeerLink] = {}
        self._closed = False
        self._lock = threading.Lock()
        # rejoin support: a RESTARTED rank cannot redo the normal
        # bring-up (its peers' accept listeners closed after the mesh
        # came up) — rejoin=True instead advertises a fresh listener in
        # the KV and waits for a surviving rank's reconnect_peer() to
        # dial it.  The dial hello carries a 4-byte token the dialer
        # chooses (the obs plane passes its sync seq so the restarted
        # rank adopts the live tag sequence); it lands in rejoin_token.
        self.rejoin_token = 0
        self._rejoin_seen: dict[int, str] = {}
        if nprocs > 1:
            if rejoin:
                self._rejoin_listen(host)
            else:
                self._connect(host)
                for peer, s in self._peers.items():
                    self._links[peer] = _PeerLink(self, peer, s)

    # -- bring-up -------------------------------------------------------------

    def _connect(self, host: str) -> None:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(self.nprocs)
        # the timeout contract covers BOTH sides of every link: a rank
        # that dies before dialing must fail its peers' accept() at
        # timeout_ms, not hang them forever; accepted and dialed sockets
        # alike carry the timeout so a stalled (not closed) peer surfaces
        # as socket.timeout instead of a wedged _recv_exact
        srv.settimeout(self.timeout_ms / 1000.0)
        port = srv.getsockname()[1]
        self.kv.key_value_set(f"{self.ns}/addr/{self.rank}", f"{host}:{port}")
        # deterministic dial direction: every rank dials its LOWER peers;
        # the accept side learns the dialer's rank from a 4-byte hello
        for peer in range(self.rank):
            addr = self.kv.blocking_key_value_get(f"{self.ns}/addr/{peer}", self.timeout_ms)
            h, p = addr.rsplit(":", 1)
            deadline = time.monotonic() + self.timeout_ms / 1000.0
            while True:
                try:
                    s = socket.create_connection((h, int(p)), timeout=self.timeout_ms / 1000.0)
                    break
                except ConnectionRefusedError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.02)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            _send_exact(s, struct.pack(">I", self.rank))
            self._peers[peer] = s
        for _ in range(self.rank + 1, self.nprocs):
            s, _ = srv.accept()
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            (peer,) = struct.unpack(">I", _recv_exact(s, 4))
            self._peers[peer] = s
        srv.close()

    def _rejoin_listen(self, host: str) -> None:
        """Restarted-rank bring-up: advertise a one-shot listener under
        ``{ns}/rejoin/{rank}`` (stamped with ``time_ns`` so a surviving
        rank distinguishes this incarnation's advert from a stale one)
        and accept exactly one :meth:`reconnect_peer` dial in the
        background — the fabric is usable immediately, link-less, and
        ``has_link`` turns true once the dial lands."""
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(self.nprocs)
        srv.settimeout(self.timeout_ms / 1000.0)
        port = srv.getsockname()[1]
        self.kv.key_value_set(
            f"{self.ns}/rejoin/{self.rank}", f"{time.time_ns()}:{host}:{port}"
        )

        def accept_one() -> None:
            try:
                s, _ = srv.accept()
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(self.timeout_ms / 1000.0)
                peer, token = struct.unpack(">II", _recv_exact(s, 8))
                with self._lock:
                    if self._closed:
                        s.close()
                        return
                    self.rejoin_token = token
                    self._peers[peer] = s
                    self._links[peer] = _PeerLink(self, peer, s)
            except (OSError, struct.error, FabricError):
                pass
            finally:
                srv.close()

        threading.Thread(
            target=accept_one, daemon=True,
            name=f"fabric-rejoin-{self.ns}-{self.rank}",
        ).start()

    def has_link(self, peer: int) -> bool:
        """Whether a live(-looking) link to ``peer`` exists — rejoining
        ranks poll this to learn when their advert has been dialed."""
        with self._lock:
            return peer in self._links

    def reconnect_peer(self, peer: int, token: int = 0) -> bool:
        """Dial a restarted ``peer``'s rejoin advert and swap in a fresh
        link (the old link, if any, is shut down).  Returns False — and
        never raises — when no NEW advert exists (no advert published,
        or the same incarnation was already dialed) or the dial fails;
        True once the new link is installed.  ``token`` rides the hello
        into the peer's ``rejoin_token``."""
        try:
            advert = self.kv.blocking_key_value_get(f"{self.ns}/rejoin/{peer}", 1)
        except Exception:
            return False
        if advert == self._rejoin_seen.get(peer):
            return False
        try:
            _stamp, rest = advert.split(":", 1)
            h, p = rest.rsplit(":", 1)
            s = socket.create_connection(
                (h, int(p)), timeout=self.timeout_ms / 1000.0
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_ms / 1000.0)
            _send_exact(s, struct.pack(">II", self.rank, token & 0xFFFFFFFF))
        except (OSError, ValueError):
            return False
        with self._lock:
            old_link = self._links.pop(peer, None)
            old_sock = self._peers.pop(peer, None)
        if old_link is not None:
            old_link.shutdown()
        if old_sock is not None:
            try:
                old_sock.close()
            except OSError:
                pass
        with self._lock:
            if self._closed:
                s.close()
                return False
            self._peers[peer] = s
            self._links[peer] = _PeerLink(self, peer, s)
        self._rejoin_seen[peer] = advert
        return True

    def close(self) -> None:
        self._closed = True
        for link in self._links.values():
            link.shutdown()
        self._links.clear()
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
        self._peers.clear()

    def __enter__(self) -> "Fabric":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- framed numpy messages ------------------------------------------------

    def reset_codec_state(self) -> None:
        """Drop every XOR-delta stream and bump the epoch word.  Call at
        any protocol point where the payload history breaks — snapshot
        restore, engine re-init — on EVERY rank (the epoch word in each
        XOR frame turns a missed reset into a loud ``FabricError`` instead
        of silently decoded garbage)."""
        with self._lock:
            self.codec_epoch += 1
            self._tx_prev.clear()
            self._rx_prev.clear()

    def wire_stats(self) -> dict:
        """Counter snapshot for journals/bench records (wire vs raw bytes
        + per-codec sent-array counts, names not bytes)."""
        with self._lock:
            return {
                "bytes_sent": self.bytes_sent,
                "bytes_recv": self.bytes_recv,
                "raw_bytes_sent": self.raw_bytes_sent,
                "raw_bytes_recv": self.raw_bytes_recv,
                "codec_counts": {
                    CODEC_NAMES.get(c, str(c)): n
                    for c, n in sorted(self.codec_counts.items())
                },
            }

    def _encode_item(
        self, item: Union[np.ndarray, Encoded], peer: int, stream, idx: int
    ) -> Encoded:
        if isinstance(item, Encoded):
            if stream is not None:
                # the sender has no raw bytes to record as XOR history,
                # but the receiver records its decode — the two prevs
                # would diverge under MATCHING epochs, defeating the
                # epoch word's whole purpose.  Refuse rather than
                # corrupt (today's pre-encoded path, the exchange legs,
                # is stream-less by design: window shapes move with s).
                raise ValueError(
                    "pre-encoded (Encoded) items cannot ride a streamed "
                    "round: the XOR-delta payload history would diverge "
                    "between sender and receiver — send the ndarray, or "
                    "drop the stream"
                )
            return item  # pre-encoded (device-sourced ROWS) — pass through
        a = np.ascontiguousarray(item)
        if not self.codec:
            return Encoded(CODEC_RAW, a.dtype, a.shape, a.tobytes(), a.nbytes)
        prev = self._tx_prev.get((peer, stream, idx)) if stream is not None else None
        enc = encode_array(a, prev=prev, epoch=self.codec_epoch)
        if stream is not None:
            self._tx_prev[(peer, stream, idx)] = a.tobytes()
        return enc

    def _pack(
        self, tag: int, arrays, peer: int, stream=None
    ) -> tuple[list, int, int]:
        """-> (iovec parts, wire size, raw-equivalent size).  The parts
        list goes to the sender thread's vectored ``sendmsg`` as-is —
        payload buffers are never ``+``-concatenated into a wire-sized
        copy (the r21 zero-copy send path); only the small per-array
        metadata strips are joined."""
        parts: list = [None]  # the _HDR slot, filled once total is known
        total = 0
        raw_total = _HDR.size
        counts: dict[int, int] = {}
        for idx, item in enumerate(arrays):
            enc = self._encode_item(item, peer, stream, idx)
            dt = enc.dtype.str.encode()
            shape = np.asarray(enc.shape, ">u8").tobytes()
            meta = _AHDR.pack(enc.codec, len(dt), len(enc.shape), len(enc.payload))
            parts.append(meta + dt + shape)
            parts.append(enc.payload)
            total += len(parts[-2]) + len(parts[-1])
            raw_total += len(meta) + len(dt) + len(shape) + enc.raw_nbytes
            counts[enc.codec] = counts.get(enc.codec, 0) + 1
        parts[0] = _HDR.pack(tag, len(arrays), total)
        with self._lock:
            for c, k in counts.items():
                self.codec_counts[c] = self.codec_counts.get(c, 0) + k
        return parts, _HDR.size + total, raw_total

    def _recv(self, peer: int, tag: int, stream=None, link=None) -> list[np.ndarray]:
        sock = self._peers[peer]
        try:
            hdr = _recv_exact(
                sock, _HDR.size, link._hdr_buf if link is not None else None
            )
            got_tag, n_arrays, total = _HDR.unpack(hdr)
            if got_tag != tag:
                raise FabricDesync(
                    f"fabric desync: rank {self.rank} expected tag {tag} from peer "
                    f"{peer}, got {got_tag} — a leg was skipped or reordered"
                )
            # the payload lands in the link's pooled arena; every decode
            # below materializes fresh arrays before the next frame
            # reuses it (decode_array copies exactly where the caller
            # outlives the arena)
            payload = _recv_exact(
                sock, total, link._arena_for(total) if link is not None else None
            )
        except socket.timeout as e:
            raise FabricTimeout(
                f"rank {self.rank}: peer {peer} sent nothing for tag {tag} "
                f"within {self.timeout_ms} ms — peer dead-but-connected, "
                "wedged, or partitioned (NOT a tag desync: nothing arrived "
                "at all)"
            ) from e
        except FabricPeerLost as e:
            raise FabricPeerLost(
                f"rank {self.rank}: peer {peer} closed its socket while this "
                f"rank awaited tag {tag} — peer process died mid-exchange"
            ) from e
        except OSError as e:
            # RST instead of FIN: the peer died with OUR data still in
            # flight to it — same diagnosis as a clean close
            raise FabricPeerLost(
                f"rank {self.rank}: connection to peer {peer} reset while "
                f"this rank awaited tag {tag} ({e}) — peer process died "
                "mid-exchange"
            ) from e
        out, off = [], 0
        raw_total = _HDR.size
        for idx in range(n_arrays):
            codec, dtl, ndim, nbytes = _AHDR.unpack_from(payload, off)
            off += _AHDR.size
            dt = bytes(payload[off : off + dtl]).decode()
            off += dtl
            shape = tuple(np.frombuffer(payload, ">u8", count=ndim, offset=off).astype(int))
            off += 8 * ndim
            prev = self._rx_prev.get((peer, stream, idx)) if stream is not None else None
            a = decode_array(
                codec, np.dtype(dt), shape, payload[off : off + nbytes],
                prev=prev, epoch=self.codec_epoch,
            )
            if stream is not None:
                self._rx_prev[(peer, stream, idx)] = a.tobytes()
            out.append(a)
            raw_total += _AHDR.size + dtl + 8 * ndim + a.nbytes
            off += nbytes
        with self._lock:
            self.bytes_recv += len(hdr) + total
            self.raw_bytes_recv += raw_total
        self.ledger.add(
            self.ledger_class,
            bytes_recv=len(hdr) + total, raw_bytes_recv=raw_total,
            frames_recv=1,
        )
        return out

    # -- rounds ---------------------------------------------------------------

    def exchange_async(
        self,
        tag: int,
        sends: dict[int, Sequence[Union[np.ndarray, Encoded]]],
        recv_from: Sequence[int],
        stream: Optional[str] = None,
    ) -> ExchangeHandle:
        """Enqueue one deterministic communication round and return its
        completion handle: each payload in ``sends`` is packed HERE (so
        byte accounting and the XOR-delta payload history advance in
        program order — the double-buffering invariant the overlapped
        engine leans on) and drained by the peer's persistent sender
        thread; each peer in ``recv_from`` gets one tagged expectation
        queued on its receiver thread.  Both sides must derive the same
        schedule — a mismatch surfaces as a tag desync or timeout, never
        silent misdata.  ``stream`` (a tick-stable name) opts the round's
        arrays into the XOR-delta codec: the previous payload per (peer,
        stream, index) is retained on both sides, so only use it for
        rounds whose shapes recur (the reduce words — retaining a full
        window would double memory for no ratio)."""
        if self._closed:
            raise FabricError(f"rank {self.rank}: fabric is closed")
        if stream is not None:
            # validate BEFORE any socket work so the contract violation
            # raises synchronously on every rank instead of leaving the
            # peers blocked into a timeout (_encode_item's check would
            # only fire inside a sender thread)
            for arrays in sends.values():
                for it in arrays:
                    if isinstance(it, Encoded):
                        raise ValueError(
                            "pre-encoded (Encoded) items cannot ride a "
                            "streamed round: the XOR-delta payload history "
                            "would diverge between sender and receiver — "
                            "send the ndarray, or drop the stream"
                        )
        # a sticky drain failure from an earlier UNJOINED round (the
        # overlap mode) surfaces at the next enqueue, not never
        sticky = [
            link.send_err
            for link in self._links.values()
            if link.send_err is not None
        ]
        if sticky:
            _aggregate_raise(sticky, notify=self.notify_failures)
        send_futs: list[tuple[int, _Future]] = []
        # packing runs HERE, serially, and that is a deliberate trade:
        # program-order packing is what keeps the XOR history and the
        # byte counters deterministic (readable mid-drain by journals),
        # and the fan-out is small by construction — a cyclic window leg
        # sends to <= 2 peers (a block window spans at most two owner
        # blocks, any P), a swing round to exactly 1; only the tiny
        # reduce words ever fan to P-1
        for peer, arrays in sends.items():
            parts, wire, raw = self._pack(tag, arrays, peer, stream)
            with self._lock:
                self.bytes_sent += wire
                self.raw_bytes_sent += raw
            self.ledger.add(
                self.ledger_class,
                bytes_sent=wire, raw_bytes_sent=raw, frames_sent=1,
            )
            fut = _Future()
            self._links[peer].sendq.put((fut, parts, tag))
            send_futs.append((peer, fut))
        recv_futs: list[tuple[int, _Future]] = []
        for peer in recv_from:
            fut = _Future()
            self._links[peer].recvq.put(_RecvJob(tag, stream, fut))
            recv_futs.append((peer, fut))
        return ExchangeHandle(self, tag, recv_futs, send_futs)

    def exchange(
        self,
        tag: int,
        sends: dict[int, Sequence[Union[np.ndarray, Encoded]]],
        recv_from: Sequence[int],
        stream: Optional[str] = None,
    ) -> dict[int, list[np.ndarray]]:
        """The synchronous round: ``exchange_async(...).wait()`` —
        receives joined in ``recv_from`` order, sends joined, every
        failure of the round aggregated into one raise (see
        ``_aggregate_raise``)."""
        return self.exchange_async(tag, sends, recv_from, stream=stream).wait()

    def allgather(
        self,
        tag: int,
        arr: np.ndarray,
        stream: Optional[str] = None,
        schedule: str = "cyclic",
        join_sends: bool = True,
        on_round=None,
    ) -> list[np.ndarray]:
        """Every rank's ``arr``, ordered by rank (self included).  Tiny
        payloads only (reduce words, digest partials).

        ``schedule="cyclic"`` is one full-mesh round (P-1 sends, P-1
        receives).  ``schedule="swing"`` is recursive doubling: log2(P)
        rounds against ONE partner at distance 2^j each, the accumulated
        half forwarded whole — the gather analog of the Swing exchange
        (requires a power-of-two P; the returned per-rank arrays are
        byte-identical either way, so any bitwise combine over them is
        schedule-invariant).  Round j's wire tag is ``tag + j`` — callers
        keep the low nibble of their leg tags clear for it.
        ``join_sends=False`` lets the final round's drain overlap the
        caller's next compute (the engine's overlap mode); ``on_round``
        (called with each round's ExchangeHandle right after its wait)
        hands those still-draining handles to the caller — the engine's
        overlap-hidden gauge folds the reduce drain through it."""
        if self.nprocs == 1:
            return [np.asarray(arr)]
        if schedule == "swing":
            if self.nprocs & (self.nprocs - 1):
                raise ValueError(
                    f"swing allgather requires a power-of-two process "
                    f"count, got {self.nprocs}"
                )
            have = {self.rank: np.asarray(arr)}
            for j in range(self.nprocs.bit_length() - 1):
                q = self.rank ^ (1 << j)
                order = sorted(have)
                h = self.exchange_async(
                    (tag + j) & 0xFFFFFFFF,
                    {q: [have[r] for r in order]},
                    [q],
                    stream=None if stream is None else f"{stream}/sw{j}",
                )
                got = h.wait(join_sends=join_sends)
                if on_round is not None:
                    on_round(h)
                for r, a in zip(sorted(r ^ (1 << j) for r in order), got[q]):
                    have[r] = a
            return [have[r] for r in range(self.nprocs)]
        if schedule != "cyclic":
            raise ValueError(f"unknown allgather schedule {schedule!r}")
        peers = [p for p in range(self.nprocs) if p != self.rank]
        h = self.exchange_async(
            tag, {p: [np.asarray(arr)] for p in peers}, peers, stream=stream
        )
        got = h.wait(join_sends=join_sends)
        if on_round is not None:
            on_round(h)
        return [
            np.asarray(arr) if r == self.rank else got[r][0]
            for r in range(self.nprocs)
        ]

    def barrier(self, name: str) -> None:
        if self.nprocs > 1:
            self.kv.barrier(f"{self.ns}/{name}", self.nprocs, self.timeout_ms)

    # -- tiny named value broadcast (rank 0 -> all), via the KV store --------

    def publish(self, name: str, arr: np.ndarray) -> None:
        a = np.ascontiguousarray(arr)
        shape = ",".join(map(str, a.shape))
        body = base64.b64encode(a.tobytes()).decode()
        self.kv.key_value_set(f"{self.ns}/pub/{name}", f"{a.dtype.str}|{shape}|{body}")

    def lookup(self, name: str) -> np.ndarray:
        raw = self.kv.blocking_key_value_get(f"{self.ns}/pub/{name}", self.timeout_ms)
        descr, shape_s, body = raw.split("|", 2)
        shape = tuple(int(x) for x in shape_s.split(",") if x)
        return np.frombuffer(base64.b64decode(body), np.dtype(descr)).reshape(shape).copy()


# -- the RPC plane (r21): request/response tag family on the fabric core -----
#
# The channel's TCP transport (net/channel.py) used to own its OWN asyncio
# socket loop, framing, retry/timeout and peer registry.  r21 folds all of
# that onto the fabric's persistent-link machinery: an RPC frame is a
# fabric ``_HDR`` frame whose tag carries a kind byte + a 24-bit request
# id, and whose payload is ONE opaque body blob (the channel's
# self-describing JSON/msgpack frame bytes — the body encodings are
# unchanged so mixed-codec endpoints keep interoperating).  Each
# connection is an :class:`RpcLink`: a persistent sender thread draining
# vectored frames and a reader thread demuxing request vs response frames
# by tag kind — the exact shape of ``_PeerLink``, with the tagged-FIFO
# expectation queue replaced by an id-keyed pending table (requests are
# unsolicited, so the demux is a map, not a queue).  Errors are the
# fabric family and sticky per link; bytes account into the merged
# :class:`TransportLedger` under class ``"rpc"``.

TAG_RPC_REQ = 0x51 << 24  # | (id & _RPC_ID_MASK)
TAG_RPC_RES = 0x52 << 24
TAG_RPC_CTL = 0x53 << 24  # control: shm-lane negotiation (offer/ack/nak)
_RPC_KIND_MASK = 0xFF000000
_RPC_ID_MASK = 0x00FFFFFF

# one RPC body may not exceed this — same bound (and same rationale) as
# the channel's MAX_FRAME_BYTES: caps what a desynced or malicious peer
# can make the reader arena hold
MAX_RPC_BODY_BYTES = 64 * 1024 * 1024

# -- r23 latency tiers --------------------------------------------------------
#
# Reader spin window: after link activity the reader busy-polls (non-
# blocking recv attempts, each releasing the GIL at the syscall) for this
# long before parking in blocking recv — the serve shm ring's post-
# activity burst discipline applied to the TCP readers.  On request/
# response ping-pong the next frame lands inside the window, so the
# steady-state round trip never pays a kernel thread wakeup.  Small by
# design: an idle link burns at most one window per received frame.
#
# The DEFAULT is core-count-aware: a spinning reader only wins when the
# thread that will produce the next frame has its own core to run on.
# On 1-2 core containers the spinner STEALS the producer's core (and its
# GIL slice) and measurably inflates RTT — measured on the 1-core CI
# box: 66 µs p50 at spin=0 vs 195 µs at spin=60.  Explicit ``spin_us``
# or the env var always wins over the heuristic.
def _default_spin_us() -> float:
    env = os.environ.get("RINGPOP_TPU_RPC_SPIN_US")
    if env is not None:
        return float(env)
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # pragma: no cover - non-linux
        cores = os.cpu_count() or 1
    return 60.0 if cores >= 4 else 0.0

# frames at or under this (header included) are "small": they may wait up
# to the endpoint's ``flush_us`` for company and flush as ONE sendmsg
_COALESCE_MAX_FRAME = 4096 + _HDR.size
# bound one coalesced sendmsg batch (stays under the _IOV_CHUNK split)
_COALESCE_MAX_FRAMES = 128

# sender-queue sentinel: cut any open coalescing window NOW (the
# explicit-flush escape hatch for latency-critical probes)
_FLUSH = object()


class _RpcShmLane:
    """Same-host frame lane for one :class:`RpcLink` (r23).

    One shared segment holds 8 control words plus two SPSC frame rings
    (creator→attacher and attacher→creator), each ``slots`` slots of 4
    uint32 header words (``seq``, ``ack``, ``len``, reserved) and
    ``slot_bytes`` of payload.  The slot protocol is ``serve/shm.py``'s
    seq-word discipline generalized to opaque fabric frames: the writer
    fills the payload, then publishes ``seq = w + 1`` (x86-TSO-ordered
    numpy stores, payload strictly before seq); the reader dispatches a
    READ-ONLY view of the slot (zero copy — the frame is consumed before
    the ack, exactly like the serve ring's slot-lifetime contract) and
    only then publishes ``ack = seq``; a slot is writable iff
    ``seq == ack``.  Wakeups reuse the serve doorbell shape: the reader
    spins a post-activity burst window, then sets its parked word and
    blocks on a unix datagram socket; the writer pokes the bell only when
    the parked word is set (the set-parked → re-check / publish → read-
    parked orderings make the missed-wake race impossible under TSO).

    TCP stays the negotiation and fallback path: frames larger than a
    slot, or arriving while the ring is full, ride the socket — the
    demux is by tag, so cross-lane ordering is free to differ.
    """

    _MAGIC = 0x52504C31  # "RPL1"
    _CTRL_WORDS = 8  # [magic, slots, slot_bytes, parked0, parked1, 0, 0, 0]
    _SLOT_HDR_WORDS = 4
    _SEQ, _ACK, _LEN = 0, 1, 2

    def __init__(self, shm, slots: int, slot_bytes: int, side: int,
                 created: bool):
        self.shm = shm
        self.name = shm.name
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.side = side  # 0 = creator (offerer), 1 = attacher
        self._created = created
        self.tx_ready = False  # set once the peer confirmed the lane
        self.peer_bell: Optional[str] = None
        self._closing = False
        self._w = 0  # frames written to the tx ring
        words = np.frombuffer(shm.buf, dtype=np.uint32)
        byts = np.frombuffer(shm.buf, dtype=np.uint8)
        self._ctrl = words[: self._CTRL_WORDS]
        per_slot_words = self._SLOT_HDR_WORDS + slot_bytes // 4
        ring_words = slots * per_slot_words

        def ring(idx: int):
            base = self._CTRL_WORDS + idx * ring_words
            hdrs, pays = [], []
            for s in range(slots):
                w0 = base + s * per_slot_words
                hdrs.append(words[w0 : w0 + self._SLOT_HDR_WORDS])
                b0 = (w0 + self._SLOT_HDR_WORDS) * 4
                pays.append(byts[b0 : b0 + slot_bytes])
            return hdrs, pays

        tx, rx = (0, 1) if side == 0 else (1, 0)
        self._tx_hdrs, self._tx_pays = ring(tx)
        self._rx_hdrs, self._rx_pays = ring(rx)
        self._park_idx = 3 + side  # my reader's parked word
        self._peer_park_idx = 3 + (1 - side)
        self._bell = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self.my_bell_path = os.path.join(
            tempfile.gettempdir(),
            f"rp-rpc-{os.getpid()}-{self.name.lstrip('/')}-{side}.sock",
        )
        try:
            os.unlink(self.my_bell_path)
        except FileNotFoundError:
            pass
        self._bell.bind(self.my_bell_path)
        # park with a timeout: a lost doorbell datagram must degrade to a
        # periodic re-check, never a wedge
        self._bell.settimeout(0.1)
        self._bell_tx = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._bell_tx.setblocking(False)
        self._reader: Optional[threading.Thread] = None

    @classmethod
    def create(cls, slots: int = 32, slot_bytes: int = 16384) -> "_RpcShmLane":
        from multiprocessing import shared_memory

        slot_bytes = (slot_bytes + 3) & ~3  # header words need 4-alignment
        per_slot = cls._SLOT_HDR_WORDS * 4 + slot_bytes
        size = cls._CTRL_WORDS * 4 + 2 * slots * per_slot
        shm = shared_memory.SharedMemory(create=True, size=size)
        np.frombuffer(shm.buf, dtype=np.uint8)[:] = 0
        lane = cls(shm, slots, slot_bytes, side=0, created=True)
        lane._ctrl[0] = np.uint32(cls._MAGIC)
        lane._ctrl[1] = np.uint32(slots)
        lane._ctrl[2] = np.uint32(slot_bytes)
        return lane

    @classmethod
    def attach(cls, name: str, slots: int, slot_bytes: int,
               peer_bell: Optional[str] = None) -> "_RpcShmLane":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=name)
        lane = cls(shm, slots, slot_bytes, side=1, created=False)
        if (
            int(lane._ctrl[0]) != cls._MAGIC
            or int(lane._ctrl[1]) != slots
            or int(lane._ctrl[2]) != slot_bytes
        ):
            lane.close()
            raise FabricError("rpc shm lane segment mismatch")
        lane.peer_bell = peer_bell
        return lane

    # -- writer side (single producer: callers hold the link's send lock) -----

    def try_send(self, parts: Sequence, nbytes: int) -> bool:
        """Write one frame (concatenated ``parts``, ``nbytes`` total)
        into the next tx slot; False = does not fit / ring full / lane
        closing — the caller falls back to TCP."""
        if self._closing or nbytes > self.slot_bytes:
            return False
        try:
            s = self._w % self.slots
            hdr = self._tx_hdrs[s]
            if int(hdr[self._SEQ]) != int(hdr[self._ACK]):
                return False  # reader is a full ring behind
            pay = self._tx_pays[s]
            off = 0
            for p in parts:
                m = memoryview(p)
                n = len(m)
                if n:
                    pay[off : off + n] = np.frombuffer(m, dtype=np.uint8)
                    off += n
            hdr[self._LEN] = np.uint32(nbytes)
            # payload strictly before the seq publish (the serve slot
            # contract)
            hdr[self._SEQ] = np.uint32((self._w + 1) & 0xFFFFFFFF)
            self._w += 1
            if int(self._ctrl[self._peer_park_idx]) and self.peer_bell:
                try:
                    self._bell_tx.sendto(b"\x01", self.peer_bell)
                except OSError:
                    pass  # the parked reader re-checks on its own timeout
        except (TypeError, AttributeError):
            return False  # lane torn down under us: the TCP fallback owns it
        return True

    # -- reader side ----------------------------------------------------------

    def start_reader(self, link: "RpcLink") -> None:
        self._reader = threading.Thread(
            target=self._recv_loop, args=(link,), daemon=True,
            name=f"rpc-shm-recv-{self.name}")
        self._reader.start()

    def _recv_loop(self, link: "RpcLink") -> None:
        spin_s = max(link._spin_s, 20e-6)
        r = 0
        ctrl = self._ctrl
        deadline = time.perf_counter() + spin_s
        while not self._closing:
            hdr = self._rx_hdrs[r % self.slots]
            want = np.uint32((r + 1) & 0xFFFFFFFF)
            if hdr[self._SEQ] == want and hdr[self._ACK] != want:
                ok = self._consume(link, self._rx_pays[r % self.slots],
                                   int(hdr[self._LEN]))
                # republish the slot only AFTER dispatch consumed the view
                hdr[self._ACK] = want
                r += 1
                if not ok:
                    return
                deadline = time.perf_counter() + spin_s
                continue
            if time.perf_counter() < deadline:
                time.sleep(0)  # yield the GIL inside the burst window
                continue
            ctrl[self._park_idx] = 1
            # missed-wake guard: re-check AFTER publishing parked — a
            # writer that saw parked==0 published its seq before we set
            # the word, so this re-check observes the frame
            if hdr[self._SEQ] == want and hdr[self._ACK] != want:
                ctrl[self._park_idx] = 0
                continue
            try:
                self._bell.recv(64)
            except socket.timeout:
                pass
            except OSError:
                return  # bell closed: lane teardown
            ctrl[self._park_idx] = 0
            deadline = time.perf_counter() + spin_s

    def _consume(self, link: "RpcLink", pay, ln: int) -> bool:
        if not _HDR.size <= ln <= self.slot_bytes:
            link._fail(FabricError(
                f"rpc shm frame malformed ({ln} bytes) — dropping the link"))
            return False
        tag, n_blobs, total = _HDR.unpack(pay[: _HDR.size].tobytes())
        kind = tag & _RPC_KIND_MASK
        if (
            n_blobs != 1
            or total != ln - _HDR.size
            or kind not in (TAG_RPC_REQ, TAG_RPC_RES)
        ):
            link._fail(FabricError(
                f"rpc shm frame malformed (tag {tag:#x}, {n_blobs} blobs, "
                f"{total} bytes) — dropping the link"))
            return False
        link.ep.ledger.add(
            link.ep.ledger_class, lane="shm",
            bytes_recv=ln, frames_recv=1,
        )
        # read-only zero-copy view of the slot payload, valid until the
        # ack below — same lifetime contract as the TCP arena views
        view = pay[_HDR.size : ln].view()
        view.flags.writeable = False
        try:
            link._dispatch_frame(tag, memoryview(view), "shm")
        except BaseException as e:
            if not isinstance(e, FabricError):
                e = FabricError(
                    f"rpc frame from {link.peer or 'peer'} undecodable: "
                    f"{type(e).__name__}: {e}")
            link._fail(e, e.__cause__)
            return False
        return True

    def close(self) -> None:
        self._closing = True
        for s in (self._bell, self._bell_tx):
            try:
                s.close()
            except OSError:
                pass
        try:
            os.unlink(self.my_bell_path)
        except OSError:
            pass
        if (
            self._reader is not None
            and threading.current_thread() is not self._reader
        ):
            self._reader.join(timeout=2.0)
        self._ctrl = None
        self._tx_hdrs = self._tx_pays = None
        self._rx_hdrs = self._rx_pays = None
        try:
            self.shm.close()
        except BufferError:
            import gc

            gc.collect()
            try:
                self.shm.close()
            except BufferError:
                pass  # a live dispatch view defers the unmap to exit
        if self._created:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass


class RpcLink:
    """One RPC connection, either role (dialed or accepted).

    ``request`` registers a callback under a fresh 24-bit id and
    enqueues the frame on the sender thread; the reader thread invokes
    the callback with the response payload (a memoryview into the pooled
    arena, valid only for the duration of the call) or with the link's
    typed error.  Inbound REQUEST frames go to the endpoint's handler on
    the reader thread — the handler must fully consume (or copy) the
    payload before returning.  A socket failure is sticky: every pending
    and future callback on this link fails with the same FabricError."""

    def __init__(self, ep: "RpcEndpoint", sock: socket.socket,
                 peer: Optional[str] = None):
        self.ep = ep
        self.sock = sock
        self.peer = peer  # hostport this side dialed, None for accepted
        self.err: Optional[BaseException] = None
        self.sendq: "queue.Queue" = queue.Queue()
        self._pending: dict[int, object] = {}  # rid -> callback(payload|exc)
        self._next_id = 0
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()  # serializes wire writes
        self._hdr_buf = bytearray(_HDR.size)
        self._arena = bytearray(1 << 16)
        self._spin_s = ep.spin_us / 1e6
        self._flush_s = ep.flush_us / 1e6
        self._shm: Optional[_RpcShmLane] = None
        name = peer or "accepted"
        self._sender = threading.Thread(
            target=self._send_loop, daemon=True, name=f"rpc-send-{name}")
        self._reader = threading.Thread(
            target=self._recv_loop, daemon=True, name=f"rpc-recv-{name}")
        self._sender.start()
        self._reader.start()

    # -- client role ----------------------------------------------------------

    def alloc_id(self) -> int:
        """A fresh request id (24-bit, wraps; callers embed it in the
        body BEFORE sending, so allocation is a separate step)."""
        with self._lock:
            while True:
                self._next_id = (self._next_id + 1) & _RPC_ID_MASK or 1
                if self._next_id not in self._pending:
                    return self._next_id

    def request(self, rid: int, body: bytes, on_reply,
                urgent: bool = False) -> None:
        """Send ``body`` as request ``rid``; ``on_reply(payload, lane)``
        is invoked on a reader thread with the response payload
        memoryview (and the lane that carried it), or with a
        BaseException (link failure / endpoint close).  ``urgent=True``
        bypasses any coalescing hold — the latency-critical probe
        escape hatch."""
        with self._lock:
            if self.err is not None:
                err = self.err
            else:
                self._pending[rid] = on_reply
                err = None
        if err is not None:
            on_reply(err, "tcp")
            return
        self._enqueue(TAG_RPC_REQ | (rid & _RPC_ID_MASK), body, urgent=urgent)

    def forget(self, rid: int) -> None:
        """Drop a pending request (caller-side timeout): a late response
        frame for it is discarded by the demux."""
        with self._lock:
            self._pending.pop(rid, None)

    # -- server role ----------------------------------------------------------

    def respond(self, rid: int, body: bytes) -> None:
        """Send ``body`` as the response to request ``rid`` (thread-safe
        enqueue; a dead link drops the response — the caller's retry
        policy owns that failure, exactly as a dropped TCP write would)."""
        self._enqueue(TAG_RPC_RES | (rid & _RPC_ID_MASK), body)

    # -- machinery ------------------------------------------------------------

    # inline-send cap: frames up to this ride the CALLING thread when the
    # sender is idle (one socket-buffer flush, bounded stall); bigger
    # frames always take the sender thread so a slow-reading peer can
    # only ever stall the dedicated sender, not the caller's loop
    _INLINE_SEND_MAX = 256 * 1024

    def _enqueue(self, tag: int, body: bytes, urgent: bool = False) -> None:
        nbytes = _HDR.size + len(body)
        parts = [_HDR.pack(tag, 1, len(body)), body]
        # coalescing hold (r23): with a flush window configured, small
        # frames go through the sender thread so company can share their
        # sendmsg; ``urgent`` frames (probes, explicit flush) never wait
        hold = (
            self._flush_s > 0.0
            and not urgent
            and nbytes <= _COALESCE_MAX_FRAME
        )
        # opportunistic inline send: when nothing is queued and no other
        # thread is mid-write, push the frame from THIS thread — saves a
        # cross-thread wakeup per frame, which dominates small-RPC RTT.
        # RPC frames are independent (tagged demux), so a frame slipping
        # ahead of one the sender thread just dequeued is harmless.
        if (
            not hold
            and len(body) <= self._INLINE_SEND_MAX
            and self.sendq.empty()
            and self._send_lock.acquire(blocking=False)
        ):
            try:
                if self.err is None:
                    self._write_batch([(parts, nbytes)])
                return
            except (OSError, ValueError) as e:
                self._fail(FabricPeerLost(
                    f"rpc send to {self.peer or 'peer'} failed ({e})"), e)
                return
            finally:
                self._send_lock.release()
        self.sendq.put((parts, nbytes))
        if urgent:
            # could not ride inline (sender busy / frame large): cut any
            # open coalescing window so the sender flushes immediately
            self.sendq.put(_FLUSH)

    def flush(self) -> None:
        """Explicit flush: cut any open coalescing window — queued small
        frames stop waiting for company and go to the wire now."""
        self.sendq.put(_FLUSH)

    def _write_batch(self, batch: list) -> None:
        """Write ``[(parts, nbytes), ...]`` to the wire (caller holds the
        send lock).  Each frame tries the same-host shm lane first; the
        TCP leftovers go as ONE vectored sendmsg — ``coalesced_frames``
        counts frames that shared it with at least one other."""
        led, klass = self.ep.ledger, self.ep.ledger_class
        shm = self._shm
        tcp_parts: list = []
        tcp_frames = 0
        tcp_bytes = 0
        for parts, nbytes in batch:
            # control frames (shm negotiation itself) are TCP-only —
            # the lane never carries its own handshake
            if (
                shm is not None
                and shm.tx_ready
                and parts[0][0] != (TAG_RPC_CTL >> 24)
                and shm.try_send(parts, nbytes)
            ):
                led.add(klass, lane="shm", bytes_sent=nbytes, frames_sent=1)
                continue
            tcp_parts.extend(parts)
            tcp_frames += 1
            tcp_bytes += nbytes
        if tcp_frames:
            _send_parts(self.sock, tcp_parts)
            led.add(
                klass, lane="tcp",
                bytes_sent=tcp_bytes, frames_sent=tcp_frames,
                coalesced_frames=tcp_frames if tcp_frames > 1 else 0,
            )

    def _send_loop(self) -> None:
        stop = False
        while not stop:
            job = self.sendq.get()
            if job is None:
                return
            if job is _FLUSH:
                continue
            batch = [job]
            # gather company: drain whatever is already queued, and —
            # with a flush window configured and only small frames in
            # hand — wait up to flush_us for more (bounded added latency,
            # one sendmsg instead of N)
            deadline = None
            if self._flush_s > 0.0 and job[1] <= _COALESCE_MAX_FRAME:
                deadline = time.perf_counter() + self._flush_s
            while len(batch) < _COALESCE_MAX_FRAMES:
                try:
                    nxt = self.sendq.get_nowait()
                except queue.Empty:
                    if deadline is None:
                        break
                    left = deadline - time.perf_counter()
                    if left <= 0.0:
                        break
                    try:
                        nxt = self.sendq.get(timeout=left)
                    except queue.Empty:
                        break
                if nxt is None:
                    stop = True
                    break
                if nxt is _FLUSH:
                    break
                batch.append(nxt)
                if nxt[1] > _COALESCE_MAX_FRAME:
                    deadline = None  # a big frame closes the wait window
            if self.err is not None:
                continue
            try:
                with self._send_lock:
                    self._write_batch(batch)
            except (OSError, ValueError) as e:
                self._fail(FabricPeerLost(
                    f"rpc send to {self.peer or 'peer'} failed ({e})"), e)

    def _recv_hdr(self) -> bytearray:
        """Read the 16-byte frame header, spin-then-park (r23): busy-poll
        non-blocking recv attempts for the spin window (each attempt
        releases the GIL at the syscall), then park in blocking recv —
        the serve shm ring's post-activity burst discipline applied to
        the TCP reader.  On ping-pong traffic the next frame lands
        inside the window, so steady state never pays a kernel thread
        wakeup."""
        buf = self._hdr_buf
        view = memoryview(buf)
        need = _HDR.size
        got = 0
        if self._spin_s > 0.0:
            end = time.perf_counter() + self._spin_s
            while True:
                try:
                    r = self.sock.recv_into(view, need, socket.MSG_DONTWAIT)
                except (BlockingIOError, InterruptedError):
                    if time.perf_counter() >= end:
                        break
                    continue
                if r == 0:
                    raise FabricPeerLost("fabric peer closed the connection")
                got = r
                break
        while got < need:
            r = self.sock.recv_into(view[got:], need - got)
            if r == 0:
                raise FabricPeerLost("fabric peer closed the connection")
            got += r
        return buf

    def _recv_loop(self) -> None:
        while True:
            try:
                hdr = self._recv_hdr()
                tag, n_blobs, total = _HDR.unpack(hdr)
                kind = tag & _RPC_KIND_MASK
                if (
                    n_blobs != 1
                    or total > self.ep.max_body_bytes
                    or kind not in (TAG_RPC_REQ, TAG_RPC_RES, TAG_RPC_CTL)
                ):
                    raise FabricError(
                        f"rpc frame from {self.peer or 'peer'} malformed "
                        f"(tag {tag:#x}, {n_blobs} blobs, {total} bytes) — "
                        "dropping the connection"
                    )
                if len(self._arena) < total:
                    RECV_ALLOCS.bump()
                    self._arena = bytearray(max(total, 2 * len(self._arena)))
                payload = _recv_exact(self.sock, total, self._arena)
            except BaseException as e:
                if not isinstance(e, FabricError):
                    e = FabricPeerLost(
                        f"rpc connection to {self.peer or 'peer'} lost ({e})")
                self._fail(e, e.__cause__)
                return
            self.ep.ledger.add(
                self.ep.ledger_class, lane="tcp",
                bytes_recv=_HDR.size + total, frames_recv=1,
            )
            try:
                self._dispatch_frame(tag, payload, "tcp")
            except BaseException as e:
                # an undecodable frame is a broken peer (the pre-r21
                # reader dropped the connection on garbage; same here)
                if not isinstance(e, FabricError):
                    e = FabricError(
                        f"rpc frame from {self.peer or 'peer'} undecodable: "
                        f"{type(e).__name__}: {e}")
                self._fail(e, e.__cause__)
                return

    def _dispatch_frame(self, tag: int, payload, lane: str) -> None:
        """Demux one inbound frame (either lane) on the reading thread."""
        kind = tag & _RPC_KIND_MASK
        rid = tag & _RPC_ID_MASK
        if kind == TAG_RPC_RES:
            with self._lock:
                cb = self._pending.pop(rid, None)
            if cb is not None:
                cb(payload, lane)
        elif kind == TAG_RPC_REQ:
            self.ep._handle_request(self, rid, payload)
        else:  # TAG_RPC_CTL: shm-lane negotiation (TCP only)
            self._handle_ctl(payload)

    # -- shm lane negotiation (r23) -------------------------------------------
    #
    # TCP carries the control frames: the dialer creates the segment and
    # OFFERs (name, geometry, its doorbell path); the acceptor attaches —
    # which succeeds exactly when the hosts share the segment namespace —
    # and ACKs with its own doorbell path, or NAKs (lane disabled /
    # attach failed / cross-host).  Frames ride TCP until the ack lands;
    # oversized frames and full-ring moments ride TCP forever after.

    def _offer_shm(self) -> None:
        try:
            lane = _RpcShmLane.create(
                slots=self.ep.shm_slots, slot_bytes=self.ep.shm_slot_bytes)
        except Exception:
            return  # no usable shm on this host — stay on TCP
        with self._lock:
            if self.err is not None:
                installed = False
            else:
                self._shm = lane
                installed = True
        if not installed:
            lane.close()
            return
        lane.start_reader(self)
        body = json.dumps({
            "op": "offer", "name": lane.name, "slots": lane.slots,
            "slot_bytes": lane.slot_bytes, "bell": lane.my_bell_path,
        }).encode()
        self._enqueue(TAG_RPC_CTL, body, urgent=True)

    def _handle_ctl(self, payload) -> None:
        try:
            msg = json.loads(bytes(payload))
        except ValueError as e:
            raise FabricError(
                f"rpc control frame from {self.peer or 'peer'} undecodable"
            ) from e
        op = msg.get("op")
        if op == "offer":
            if not self.ep.shm_lane or self._shm is not None:
                self._enqueue(TAG_RPC_CTL, b'{"op":"nak"}', urgent=True)
                return
            try:
                lane = _RpcShmLane.attach(
                    msg["name"], int(msg["slots"]), int(msg["slot_bytes"]),
                    peer_bell=msg.get("bell"))
            except Exception:
                self._enqueue(TAG_RPC_CTL, b'{"op":"nak"}', urgent=True)
                return
            with self._lock:
                # recheck under the link state lock: _fail may have won
                # the race and torn the link down mid-attach
                if self.err is not None or self._shm is not None:
                    installed = False
                else:
                    self._shm = lane
                    installed = True
            if not installed:
                lane.close()
                self._enqueue(TAG_RPC_CTL, b'{"op":"nak"}', urgent=True)
                return
            lane.start_reader(self)
            lane.tx_ready = True
            self._enqueue(TAG_RPC_CTL, json.dumps(
                {"op": "ack", "bell": lane.my_bell_path}).encode(),
                urgent=True)
        elif op == "ack":
            lane = self._shm
            if lane is not None:
                if msg.get("bell"):
                    lane.peer_bell = msg["bell"]
                lane.tx_ready = True
        elif op == "nak":
            with self._lock:
                # atomic swap vs. _fail: exactly one path closes the lane
                lane, self._shm = self._shm, None
            if lane is not None:
                lane.close()
        # unknown ops are ignored: forward-compatible control plane

    def _fail(self, err: BaseException, cause=None) -> None:
        if cause is not None and err.__cause__ is None:
            err.__cause__ = cause
        with self._lock:
            if self.err is None:
                self.err = err
            pending = list(self._pending.values())
            self._pending.clear()
            lane, self._shm = self._shm, None
        self.ep._unregister(self)
        # shutdown BEFORE close: a reader blocked in recv holds the
        # kernel file reference, so a bare close() would neither wake it
        # nor send FIN — the peer would never learn the link died
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if lane is not None:
            lane.close()
        # sticky-failure contract: EVERY pending waiter — loop-bridged or
        # inline/sync — observes the same typed error, exactly once (the
        # pending table pop above makes a late response frame a no-op)
        for cb in pending:
            try:
                cb(err, "tcp")
            except Exception:  # pragma: no cover - reply sinks must not throw
                pass

    def close(self, err: Optional[BaseException] = None) -> None:
        self.sendq.put(None)
        self._fail(err or FabricError("rpc link closed"))
        self._sender.join(timeout=2.0)
        if threading.current_thread() is not self._reader:
            self._reader.join(timeout=2.0)


class RpcEndpoint:
    """One node's endpoint on the RPC plane: a listener (accept thread)
    plus a dial-once outbound link registry — the connection handling,
    framing, retry surface and peer registry that ``TCPChannel`` used to
    implement on its own asyncio loop, now on the fabric core's
    persistent links.  ``handler(link, rid, payload)`` runs on reader
    threads for inbound requests; answer via ``link.respond(rid, body)``
    from any thread.

    r23 latency tiers: ``spin_us`` is the reader spin-then-park window
    (0 disables; default from ``RINGPOP_TPU_RPC_SPIN_US``); ``flush_us``
    > 0 coalesces small frames — they wait up to the window for company
    and flush as one ``sendmsg`` (``urgent`` sends and ``link.flush()``
    cut the window); ``shm_lane=True`` negotiates a same-host shm frame
    ring per dialed loopback link (``RINGPOP_TPU_RPC_SHM=1`` flips the
    default), TCP staying the negotiation and fallback path."""

    def __init__(
        self,
        handler=None,
        *,
        ledger: Optional[TransportLedger] = None,
        ledger_class: str = "rpc",
        max_body_bytes: int = MAX_RPC_BODY_BYTES,
        spin_us: Optional[float] = None,
        flush_us: float = 0.0,
        shm_lane: Optional[bool] = None,
        shm_slots: int = 32,
        shm_slot_bytes: int = 16384,
    ):
        self.handler = handler
        self.ledger = ledger if ledger is not None else TransportLedger()
        self.ledger_class = ledger_class
        self.max_body_bytes = max_body_bytes
        self.spin_us = (
            _default_spin_us() if spin_us is None else float(spin_us))
        self.flush_us = float(flush_us)
        if shm_lane is None:
            shm_lane = os.environ.get("RINGPOP_TPU_RPC_SHM", "") in (
                "1", "true", "yes")
        self.shm_lane = bool(shm_lane)
        self.shm_slots = int(shm_slots)
        self.shm_slot_bytes = int(shm_slot_bytes)
        self.hostport = ""
        self._srv: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._links: dict[str, RpcLink] = {}  # outbound, by hostport
        self._accepted: set[RpcLink] = set()
        self._lock = threading.Lock()
        self._closed = False

    # -- server side ----------------------------------------------------------

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> str:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(128)
        self._srv = srv
        addr = srv.getsockname()
        self.hostport = f"{addr[0]}:{addr[1]}"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"rpc-accept-{self.hostport}")
        self._accept_thread.start()
        return self.hostport

    def _accept_loop(self) -> None:
        while True:
            try:
                s, _ = self._srv.accept()
            except OSError:
                return  # listener closed
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            link = RpcLink(self, s)
            with self._lock:
                closed = self._closed
                if not closed:
                    self._accepted.add(link)
            if closed:
                link.close()  # outside the lock: close unregisters
                return

    def _handle_request(self, link: RpcLink, rid: int, payload) -> None:
        if self.handler is None:
            raise FabricError("rpc request received but no handler installed")
        self.handler(link, rid, payload)

    # -- client side ----------------------------------------------------------

    def get(self, peer: str) -> Optional[RpcLink]:
        """The cached live link to ``peer``, or None (never dials)."""
        with self._lock:
            link = self._links.get(peer)
            return link if link is not None and link.err is None else None

    def connect(self, peer: str) -> RpcLink:
        """Dial-once outbound link (blocking; run off the event loop).
        A dead cached link is replaced; refusal raises FabricPeerLost."""
        with self._lock:
            if self._closed:
                raise FabricError("rpc endpoint is closed")
            link = self._links.get(peer)
            if link is not None and link.err is None:
                return link
        host, port = peer.rsplit(":", 1)
        try:
            s = socket.create_connection((host, int(port)))
        except OSError as e:
            raise FabricPeerLost(f"connect {peer}: {e}") from e
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        link = RpcLink(self, s, peer)
        with self._lock:
            cur = self._links.get(peer)
            won = cur is None or cur.err is not None
            if won:
                self._links[peer] = link
        if won:
            # same-host shm lane (r23): offer on loopback dials only —
            # attach succeeding at the acceptor IS the same-host proof,
            # but a loopback gate keeps cross-host dials from paying a
            # wasted segment + round trip
            if self.shm_lane and host in ("127.0.0.1", "::1", "localhost"):
                link._offer_shm()
            return link
        # lost a dial race; keep the established one.  close() OUTSIDE
        # the lock — it unregisters, which takes the lock again
        link.close()
        return cur

    # -- lifecycle ------------------------------------------------------------

    def _unregister(self, link: RpcLink) -> None:
        with self._lock:
            if self._links.get(link.peer) is link:
                del self._links[link.peer]
            self._accepted.discard(link)

    def wire_stats(self) -> dict:
        """This endpoint's class row of the merged ledger — the channel
        keeps its legacy ``{bytes_sent, frames_sent}`` keys from this."""
        st = self.ledger.stats()
        return st["classes"].get(
            self.ledger_class, {f: 0 for f in TransportLedger.FIELDS}
        )

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            links = list(self._links.values()) + list(self._accepted)
            self._links.clear()
            self._accepted.clear()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        err = FabricPeerLost("connection closed")
        for link in links:
            link.close(err)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)


# -- cyclic-window arithmetic (shared by both endpoints of every leg) ---------


def window_pieces(start: int, length: int, n: int) -> list[tuple[int, int]]:
    """The cyclic row window ``[start, start+length) mod n`` as ordered
    contiguous global pieces (at most two).  ``start`` is taken mod ``n``
    (negative and >= n shifts are legal); a zero-length window is the
    empty list; ``length`` beyond ``n`` is a contract violation (the
    window would cover rows twice)."""
    if not 0 <= length <= n:
        raise ValueError(f"window length {length} outside [0, n={n}]")
    if length == 0:
        return []
    start %= n
    if start + length <= n:
        return [(start, length)]
    return [(start, n - start), (0, start + length - n)]


def intersect(a_lo: int, a_len: int, b_lo: int, b_len: int) -> Optional[tuple[int, int]]:
    lo = max(a_lo, b_lo)
    hi = min(a_lo + a_len, b_lo + b_len)
    return (lo, hi - lo) if hi > lo else None


def plan_window(
    want_start: int, block: int, n: int, nprocs: int
) -> list[tuple[int, int, int, int]]:
    """Assembly plan for the cyclic window ``[want_start, want_start+block)``
    over equal process blocks: ordered ``(owner_rank, global_lo, length,
    window_offset)`` entries, window offsets ascending.  Derived
    identically on every rank — the sender runs it for the RECEIVER's
    window to learn what to send.  ``n`` must divide evenly over
    ``nprocs``: silently planning over truncated ``n // nprocs`` blocks
    would assign the ring's tail rows to no owner (the same divisibility
    ``partition.process_block`` imposes, surfaced HERE because this
    function is also reachable from schedule tooling that never builds a
    partition)."""
    if nprocs < 1:
        raise ValueError(f"nprocs must be >= 1, got {nprocs}")
    if n % nprocs:
        raise ValueError(
            f"n={n} does not divide over {nprocs} processes — equal-block "
            "window plans would drop the tail rows (pad n or change the "
            "process count)"
        )
    b = n // nprocs
    out = []
    off = 0
    for glo, glen in window_pieces(want_start, block, n):
        # owners overlapping [glo, glo+glen)
        first, last = glo // b, (glo + glen - 1) // b
        for r in range(first, last + 1):
            piece = intersect(glo, glen, r * b, b)
            assert piece is not None
            out.append((r, piece[0], piece[1], off + piece[0] - glo))
        off += glen
    return out


@functools.lru_cache(maxsize=8192)
def plan_window_swing(
    rel_start: int, n: int, nprocs: int
) -> tuple[dict[int, tuple], ...]:
    """Distance-halving relay schedule for the per-rank block windows
    ``[rank*b + rel_start, ...+b) mod n`` (Swing-style, PAPERS arxiv
    2401.09356, realized as hypercube dimension-fixing on the full-mesh
    fabric): ``log2(P)`` rounds, each rank talking to exactly ONE partner
    at distance ``2^j``, relay ranks forwarding coalesced pieces — vs the
    cyclic :func:`plan_window` execution's direct sends to partners at
    arbitrary ring distance.  On a physical ring/torus DCN that bounds
    the worst-case leg count at O(log P) power-of-two hops instead of the
    O(P)-step walk a distant window piece implies; on this TCP mesh the
    hop count is priced honestly as relay bytes (the wire accounting
    counts every forwarded copy).

    Returns one manifest per round: ``rounds[j]`` maps ``holder_rank`` to
    its ordered entries ``(dest, owner, global_lo, length, window_off)``;
    every listed entry moves ``holder -> holder ^ (1 << j)`` in round
    ``j`` (bit j of ``owner ^ dest`` set).  Derived identically on every
    rank from :func:`plan_window`, so the assembled windows are
    byte-identical to the cyclic plan's by construction.  Pieces with
    ``owner == dest`` never enter the wire schedule."""
    if nprocs < 2 or nprocs & (nprocs - 1):
        raise ValueError(
            f"swing schedule requires a power-of-two process count >= 2, "
            f"got {nprocs}"
        )
    if nprocs > (1 << 15):
        raise ValueError(
            "swing round tags ride the low nibble-and-a-bit of the leg "
            f"tag byte — {nprocs} processes would overflow it"
        )
    block = n // nprocs  # plan_window validates divisibility
    nrounds = nprocs.bit_length() - 1
    rounds: list[dict[int, list]] = [{} for _ in range(nrounds)]
    for d in range(nprocs):
        start_d = (rel_start + d * block) % n
        for owner, glo, glen, woff in plan_window(start_d, block, n, nprocs):
            if owner == d:
                continue
            diff = owner ^ d
            h = owner
            for j in range(nrounds):
                if (diff >> j) & 1:
                    rounds[j].setdefault(h, []).append((d, owner, glo, glen, woff))
                    h ^= 1 << j
    return tuple(
        {h: tuple(sorted(v, key=lambda e: (e[0], e[4]))) for h, v in r.items()}
        for r in rounds
    )
