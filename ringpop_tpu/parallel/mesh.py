"""Device-mesh sharding for the sim plane.

The reference scales by running more processes connected over TChannel
(§2.8 of SURVEY.md); the sim plane scales by sharding the cluster-state
arrays over a ``jax.sharding.Mesh`` and letting GSPMD insert the
collectives:

* ``DeltaState`` planes shard as ``("node", "rumor")`` — a 2D mesh:
  node-axis data parallelism × rumor-axis model parallelism.  NOTE the
  rumor axis counts different units per leaf: ``pcount [N, K]`` shards K
  SLOTS, while the bit-packed ``learned``/``ride_ok`` ``uint32[N, K/32]``
  shard WORDS — so K must supply at least 32 slots per rumor shard
  (k >= 32 * rumor_axis_size), the constraint behind the k=64 minima in
  the tests and ``dryrun_multichip``.
* the per-tick cross-shard traffic is the ping scatter/gather
  (``segment_max`` by target + row gather), which XLA lowers to
  all-to-all/all-gather over ICI — the message-exchange analog of the
  reference's peer-to-peer RPC fabric.

This is annotate-and-let-XLA-partition (the scaling-book recipe), not
hand-written collectives: the same jitted ``step`` runs single-chip or on a
v5e-8 unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.sim.delta import DeltaParams, DeltaState, step


def make_mesh(n_devices: Optional[int] = None, shape: Optional[tuple[int, int]] = None) -> Mesh:
    """2D ("node", "rumor") mesh over the first ``n_devices`` devices.
    Default shape puts most parallelism on the node axis.

    If the default backend exposes fewer than ``n_devices`` devices (e.g. a
    single real TPU chip), falls back to the CPU backend, which honors
    ``--xla_force_host_platform_device_count`` — so sharding dry-runs work on
    any host."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devices = cpu
    if len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    devices = devices[:n_devices]
    if shape is None:
        rumor = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
        shape = (n_devices // rumor, rumor)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=("node", "rumor"))


def delta_shardings(mesh: Mesh) -> DeltaState:
    """NamedShardings for each DeltaState leaf — derived from the ONE
    canonical per-leaf rule table (``parallel.partition.PARTITION_RULES``);
    this wrapper only fixes the pytree type."""
    from ringpop_tpu.parallel.partition import named_shardings

    skeleton = DeltaState(learned=0, pcount=0, ride_ok=0, tick=0, key=0)
    return named_shardings(skeleton, mesh)


def shard_delta_state(state: DeltaState, mesh: Mesh) -> DeltaState:
    sh = delta_shardings(mesh)
    return jax.tree.map(jax.device_put, state, sh)


def with_exchange_mesh(params, mesh: Mesh, h: Optional[int] = None,
                       pipelined: Optional[bool] = None):
    """Return ``params`` with ``exchange_mesh`` bound to ``mesh`` (works for
    DeltaParams and LifecycleParams alike) — the shift exchange then lowers
    its roll legs as shard-local crossing-block ppermutes
    (``parallel/shift``) instead of GSPMD's plane all-gathers.
    Bit-identical values; a no-op when the caller already bound a mesh, or
    when the mesh has no >1-way node axis to exchange over.

    ``h`` overrides the sub-block factor (``exchange_h``, H+1 sends per
    rolled leaf per leg); ``pipelined`` selects the r11 fused leg loop vs
    the sequential r8 legs (``exchange_pipelined``) — both bit- and
    census-identical across settings, see parallel/shift.py.  Explicit
    overrides are applied even when the caller already bound a mesh
    (only the mesh itself is never rebound), so an A/B built from
    already-meshed params cannot silently compare a program against
    itself."""
    extra = {}
    if h is not None:
        extra["exchange_h"] = h
    if pipelined is not None:
        extra["exchange_pipelined"] = pipelined
    if params.exchange_mesh is not None:
        return dataclasses.replace(params, **extra) if extra else params
    if mesh.shape.get("node", 1) <= 1:
        return params
    return dataclasses.replace(params, exchange_mesh=mesh, **extra)


def sharded_delta_step(params: DeltaParams, mesh: Mesh):
    """Jitted step with explicit in/out shardings over the mesh (and the
    shift exchange's roll legs lowered shard-local — ``with_exchange_mesh``;
    the partitioned program stays bit-equal to the unsharded one)."""
    from ringpop_tpu.sim.packbits import check_rumor_shardable

    # packed planes shard words, unpacked planes shard slots — k must be a
    # multiple of 32 * rumor_shards (shared rule; raises with the real
    # constraint instead of an opaque GSPMD divisibility error inside jit)
    check_rumor_shardable(params.k, mesh.shape.get("rumor", 1))
    params = with_exchange_mesh(params, mesh)
    sh = delta_shardings(mesh)
    return jax.jit(
        functools.partial(step, params),
        in_shardings=(sh,),
        out_shardings=sh,
    )
