"""Multi-host (DCN) mesh construction for the sim plane.

The reference scales across machines by pointing more TChannel processes at
each other (SURVEY §2.8); the sim plane scales across TPU hosts with
``jax.distributed`` + one global mesh spanning every process's local chips.
Nothing in the engines branches on host count — the same jitted ``step``
from ``sim/delta.py`` / ``sim/lifecycle.py`` runs on the mesh built here
unchanged; only mesh construction differs from the single-host path in
``parallel/mesh.py``.

Axis layout (the one decision that matters — PERF.md "Multi-host (DCN)
design"): the **node axis spans hosts**, because its per-tick collectives
are the cyclic ``jnp.roll`` exchanges — nearest-neighbor permutes that
cross the host boundary (DCN) only at slice edges, once per tick — while
the **rumor axis stays inside a host** where its row-gathers/all-to-alls
ride ICI.  ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` packs
devices so exactly that holds: the outer (DCN) factor multiplies the node
axis, the inner (ICI) factors fill rumor first.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


def distributed_initialized() -> bool:
    """Is the jax.distributed runtime up?  ``jax.distributed.is_initialized``
    where the build has it (>= 0.5); on older builds (this container's
    0.4.37) fall back to probing the internal global-state client — the
    exact condition ``initialize`` itself checks before refusing a second
    call, so the idempotence contract is identical either way."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Idempotently initialize the JAX distributed runtime.

    Args default from the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) so a launcher can export
    them and every rank calls ``init_distributed()`` bare.  Returns True
    when the distributed client is (now) up, False when running
    single-process with no coordinator configured — single-process callers
    can then fall back to :func:`ringpop_tpu.parallel.mesh.make_mesh`.
    """
    if distributed_initialized():  # already up
        return True
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None
    if not coordinator_address:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_multihost_mesh(rumor_shards: Optional[int] = None) -> Mesh:
    """Global 2D ("node", "rumor") mesh over every device in the job.

    The DCN granule is the TPU slice when the runtime reports more than one
    (real multi-slice jobs — ICI spans hosts *within* a slice, so that is
    the true fast-interconnect domain), else the process (e.g. the
    multi-process CPU fabric used to validate this path without a pod).
    The rumor axis never leaves a granule: it is carved entirely out of the
    per-granule (ICI) device block, so its all-to-alls stay on fast
    interconnect and only the node axis pays DCN latency.  ``rumor_shards``
    defaults to 2 when a granule holds an even number of chips (matching
    :func:`ringpop_tpu.parallel.mesh.make_mesh`'s default), else 1.
    """
    devices = jax.devices()
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    slice_is_granule = n_slices > 1
    n_granules = n_slices if slice_is_granule else jax.process_count()
    per_granule = len(devices) // n_granules
    if rumor_shards is None:
        rumor_shards = 2 if per_granule % 2 == 0 and per_granule > 1 else 1
    if per_granule % rumor_shards:
        raise ValueError(
            f"rumor_shards={rumor_shards} must divide per-granule device count "
            f"{per_granule} (the rumor axis must not cross DCN)"
        )
    if n_granules == 1:
        dev_array = np.asarray(devices).reshape(len(devices) // rumor_shards, rumor_shards)
        return Mesh(dev_array, axis_names=("node", "rumor"))
    from jax.experimental import mesh_utils

    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(per_granule // rumor_shards, rumor_shards),  # ICI block per granule
        dcn_mesh_shape=(n_granules, 1),  # granules multiply the node axis only
        devices=devices,
        process_is_granule=not slice_is_granule,
    )
    return Mesh(dev_array, axis_names=("node", "rumor"))
