"""Canonical per-leaf partition rules for every sim-plane pytree.

The multi-host scale-out (ROADMAP "16M on real meshes") needs one answer,
written down once, to "where does this leaf live on a mesh?".  Before this
module each caller placed state ad hoc (``mesh.delta_shardings``,
``lifecycle.state_shardings``, ``montecarlo.fleet_state_shardings`` — three
hand-maintained tables that agreed only by review).  This is the one
canonical table, in the match-partition-rules style of the pjit
shard/gather-fn helpers (SNIPPETS.md [2][3]): an ordered list of
``(leaf-name regex, PartitionSpec)`` rules applied to the tree-path name of
every leaf.  The legacy per-engine helpers now DERIVE from this table (and
a test pins the derivation), so a layout change edits exactly one list.

Layout (PERF.md "Multi-host (DCN) design"): the **node axis** shards nodes
(its per-tick collectives are nearest-neighbor exchange permutes — DCN
crosses only at slice edges), the **rumor axis** shards rumor slots/words
(its gathers ride ICI inside a host), per-node vectors are node-sharded,
the rumor table is rumor-sharded, and everything else — scalars, PRNG
keys, the tiny ``reach[G, G]`` matrix, the placement vectors — replicates.

Placement/gather (the multi-host half):

* :func:`shard_put` builds each GLOBAL array from every process's LOCAL
  block via ``jax.make_array_from_single_device_arrays`` — no host ever
  materializes a cross-process plane, which is what lets a 16M-node state
  (1.3 GB at k=64) spread over hosts that could not hold it alone.
* :func:`host_gather` is the inverse: the locally-addressable rows of each
  leaf, as one contiguous host block per process.
* :func:`process_block` is the node-axis ownership rule — contiguous
  equal blocks in process order, the same split GSPMD produces for the
  meshes built by ``make_multihost_mesh`` (pinned by test against
  ``Sharding.devices_indices_map``).

Digest partials (the certification half): :func:`leaf_partial_sums` /
:func:`combine_leaf_partials` split ``telemetry.tree_digest`` into
per-process partial sums over each process's rows AT GLOBAL flat indices.
Because the digest's inner per-leaf accumulation is a wrapping uint32 SUM,
partials over disjoint row blocks add to exactly the single-host value —
so "sharded == unsharded" certifies across OS processes by exchanging one
uint32 per leaf instead of gathering planes.
"""

from __future__ import annotations

import math
import re
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# -- the table ----------------------------------------------------------------

# Ordered (regex, spec) rules matched against "/"-joined tree-path names
# (first match wins; a leaf no rule matches REPLICATES — scalars and
# whatever new small leaf lands next).  Names cover, today: DeltaState,
# LifecycleState, TelemetryState, DeltaFaults, chaos.FaultPlan, and any
# dict/NamedTuple nesting of them.
PARTITION_RULES: list[tuple[str, P]] = [
    # big per-(node, rumor) planes — packed planes shard WORDS, unpacked
    # planes shard SLOTS (packbits.check_rumor_shardable is the k rule)
    (r"(^|/)(learned|pcount|ride_ok|piggybacked|expired)$", P("node", "rumor")),
    # topology tier ids: int32[TIER_LEVELS, N] — the node axis is LAST
    # (sim/topology.py), so the rule shards axis 1 and replicates the
    # tiny fixed level axis
    (r"(^|/)(tier_ids)$", P(None, "node")),
    # per-node vectors (engine state, telemetry masks, fault legs); the
    # per-tier suspicion counters are [N, N_TIERS] — P("node") shards
    # their node axis and replicates the 4-wide tier axis
    (
        r"(^|/)(base_status|base_inc|base_present|base_pending|base_deadline"
        r"|self_inc|pings|ping_reqs|probes_failed|incarnation_bumps"
        r"|base_timer_fires|up|base_up|group|drop_node|crash_tick"
        r"|restart_tick|flap_period|flap_phase|flap_down"
        r"|suspects_by_tier|false_suspects_by_tier)$",
        P("node"),
    ),
    # rumor-table vectors
    (r"(^|/)(r_subject|r_inc|r_status|r_deadline|timer_fires)$", P("rumor")),
    # everything else replicates: tick/key scalars, decl_* placement
    # vectors ([M] = alloc budget, replicated post-merge), heal_attempts,
    # drop_rate, part_from/part_until, the tiny reach[G, G] matrix, the
    # [4] tier_drop table and the suspect_ticks scalar
]


def _path_name(path) -> str:
    parts = []
    for k in path:
        name = getattr(k, "name", None)
        if name is None:
            name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "idx", None)
        if name is None:
            name = getattr(k, "key_idx", None)  # FlattenedIndexKey
        parts.append(str(name))
    return "/".join(parts)


def spec_for(name: str) -> P:
    """The canonical PartitionSpec for a leaf path name (first rule wins;
    no match = replicated)."""
    for pattern, spec in PARTITION_RULES:
        if re.search(pattern, name):
            return spec
    return P()


def partition_spec(tree, batch_axes: int = 0, batch_axis: Optional[str] = None):
    """Pytree of PartitionSpec, one per leaf, from the canonical table.

    ``batch_axes`` prepends that many axes to EVERY leaf spec — the
    Monte-Carlo fleet's ``[B, ...]`` replica batch (scalar leaves like
    ``tick``/``key`` are batched to [B]/[B, 2] too, so they get the
    prefix as well — the ``montecarlo.fleet_state_shardings``
    convention).  By default the prefix replicates (None axes: scenarios
    are independent, and a small fleet costs nothing to replicate);
    ``batch_axis`` names a mesh axis the FIRST prepended axis shards
    over instead — the r19 block-sharded fleet, where B·R ≫ 10⁴
    replica-scenarios split their batch dimension across
    devices/processes and per-host RSS actually shards.
    """

    def one(path, leaf):
        spec = spec_for(_path_name(path))
        if batch_axes:
            prefix = [batch_axis] + [None] * (batch_axes - 1)
            spec = P(*prefix, *spec)
        return spec

    return jax.tree_util.tree_map_with_path(one, tree)


def named_shardings(tree, mesh: Mesh, batch_axes: int = 0,
                    batch_axis: Optional[str] = None):
    """Pytree of NamedSharding over ``mesh`` from :func:`partition_spec`.
    ``tree`` may hold arrays OR ShapeDtypeStructs — only structure and
    leaf names are read."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        partition_spec(tree, batch_axes=batch_axes, batch_axis=batch_axis),
    )


# -- process-block ownership --------------------------------------------------


def process_block(n: int, rank: int, nprocs: int) -> tuple[int, int]:
    """Node rows [lo, hi) owned by ``rank`` of ``nprocs``: contiguous equal
    blocks in process order — the split the hybrid meshes built by
    ``make_multihost_mesh`` produce for a node-sharded leaf (processes
    multiply the OUTER node-axis factor, so each process's devices cover a
    contiguous row range; pinned against ``devices_indices_map`` by test).
    ``n`` must divide evenly (the same rigidity GSPMD imposes)."""
    if n % nprocs:
        raise ValueError(
            f"n={n} must divide over {nprocs} processes (pad n or change the "
            f"process count; GSPMD imposes the same divisibility on the mesh path)"
        )
    block = n // nprocs
    if not 0 <= rank < nprocs:
        raise ValueError(f"rank {rank} outside [0, {nprocs})")
    return rank * block, (rank + 1) * block


# -- placement: local blocks -> global sharded arrays -------------------------


def shard_put(local_tree, mesh: Mesh, global_n: int, batch_axes: int = 0):
    """Build GLOBAL sharded arrays from this process's LOCAL node-blocks.

    ``local_tree`` holds, per leaf, ONLY the rows this process owns
    (node-sharded leaves: the ``process_block`` slice; replicated /
    rumor-sharded leaves: the full (small) array).  Each leaf is placed
    via ``jax.make_array_from_single_device_arrays`` — every process
    device_puts exactly its own shards, so no host ever materializes a
    global plane.  ``global_n`` is the global node count (the local
    block's node axis is ``global_n / process_count``).

    Works single-process too (the virtual-mesh tests), where "local" is
    simply "all".
    """
    specs = partition_spec(local_tree, batch_axes=batch_axes)
    nprocs = jax.process_count()
    lo, _hi = process_block(global_n, jax.process_index(), nprocs) if nprocs > 1 else (0, global_n)

    def place(leaf, spec):
        arr = np.asarray(leaf)
        node_axis = _node_axis(spec)
        sharding = NamedSharding(mesh, spec)
        if node_axis is None:
            # replicated or rumor-only sharded: every process holds the
            # full (small) array; put each local device's shard
            gshape = arr.shape
            row_base = 0
        else:
            gshape = arr.shape[:node_axis] + (global_n,) + arr.shape[node_axis + 1 :]
            row_base = lo
        dmap = sharding.devices_indices_map(gshape)
        pieces = []
        for d in jax.local_devices():
            idx = list(dmap[d])
            if node_axis is not None:
                s = idx[node_axis]
                start = (0 if s.start is None else s.start) - row_base
                stop = (gshape[node_axis] if s.stop is None else s.stop) - row_base
                if start < 0 or stop > arr.shape[node_axis]:
                    raise ValueError(
                        "mesh places non-local rows on a local device — the "
                        "mesh's node axis does not follow process_block order "
                        "(build it with make_multihost_mesh)"
                    )
                idx[node_axis] = slice(start, stop)
            pieces.append(jax.device_put(arr[tuple(idx)], d))
        return jax.make_array_from_single_device_arrays(gshape, sharding, pieces)

    return jax.tree.map(place, local_tree, specs)


def _node_axis(spec: P) -> Optional[int]:
    for i, ax in enumerate(spec):
        if ax == "node" or (isinstance(ax, tuple) and "node" in ax):
            return i
    return None


def host_gather(tree, batch_axes: int = 0):
    """The inverse of :func:`shard_put`: per leaf, one contiguous host
    array of the LOCALLY-ADDRESSABLE rows (node-sharded leaves: this
    process's block; others: the full array).  At one process this is the
    whole global array — the SNIPPETS [2][3] gather-fn analog.  Never
    touches another process's shards."""
    specs = partition_spec(tree, batch_axes=batch_axes)

    def gather(leaf, spec):
        if not isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        node_axis = _node_axis(spec)
        shards = list(leaf.addressable_shards)
        if node_axis is None:
            return np.asarray(shards[0].data) if shards else np.asarray(leaf)
        # order shards by their global row start; de-dup replicas (the
        # rumor axis may replicate a row block across local devices)
        by_start = {}
        for sh in shards:
            s = sh.index[node_axis]
            start = 0 if s.start is None else s.start
            cols = tuple(
                (0 if c.start is None else c.start)
                for i, c in enumerate(sh.index)
                if i != node_axis
            )
            by_start.setdefault(start, {})[cols] = np.asarray(sh.data)
        rows = []
        for start in sorted(by_start):
            pieces = by_start[start]
            if len(pieces) == 1:
                rows.append(next(iter(pieces.values())))
            else:
                # multiple column blocks (rumor-sharded): stitch along the
                # non-node axes in column order
                ordered = [pieces[c] for c in sorted(pieces)]
                rows.append(np.concatenate(ordered, axis=-1))
        return np.concatenate(rows, axis=node_axis) if len(rows) > 1 else rows[0]

    return jax.tree.map(gather, tree, specs)


# -- fleet placement: batch-axis shards --------------------------------------


def fleet_shard_put(local_tree, mesh: Mesh, global_b: int):
    """Build GLOBAL batch-sharded arrays from this process's LOCAL batch
    slice — the leading-axis analog of :func:`shard_put` for the r19
    scenario fleet's checkpoint carry.

    Every leaf of ``local_tree`` is ``[B_local, ...]`` — the
    ``process_block(global_b, rank, nprocs)`` slice of a ``[global_b,
    ...]`` fleet leaf (states, telemetry counters, per-replica
    first-detection ticks).  ``mesh`` must carry a ``"batch"`` axis whose
    device order follows process order (``make_fleet_mesh`` /
    ``montecarlo.fleet_save_mesh``); each process device_puts exactly its
    own shards via ``jax.make_array_from_single_device_arrays``, so no
    host ever materializes the global fleet — which is what lets each
    rank of a B=4096 × n=4096 sweep checkpoint only its slice.  Works
    single-process too (the virtual-mesh tests), where "local" is "all".
    """
    nprocs = jax.process_count()
    lo, _hi = (
        process_block(global_b, jax.process_index(), nprocs)
        if nprocs > 1
        else (0, global_b)
    )

    def place(leaf):
        arr = np.asarray(leaf)
        gshape = (global_b,) + arr.shape[1:]
        sharding = NamedSharding(mesh, P("batch", *([None] * (arr.ndim - 1))))
        dmap = sharding.devices_indices_map(gshape)
        pieces = []
        for d in jax.local_devices():
            idx = list(dmap[d])
            s = idx[0]
            start = (0 if s.start is None else s.start) - lo
            stop = (global_b if s.stop is None else s.stop) - lo
            if start < 0 or stop > arr.shape[0]:
                raise ValueError(
                    "mesh places non-local fleet rows on a local device — "
                    "the mesh's batch axis does not follow process_block "
                    "order (build it with montecarlo.fleet_save_mesh)"
                )
            idx[0] = slice(start, stop)
            pieces.append(jax.device_put(arr[tuple(idx)], d))
        return jax.make_array_from_single_device_arrays(gshape, sharding, pieces)

    return jax.tree.map(place, local_tree)


def fleet_host_gather(tree):
    """The inverse of :func:`fleet_shard_put`: per leaf, one contiguous
    host array of the LOCALLY-addressable batch rows (this process's
    ``process_block`` slice of the fleet).  Assumes only the leading
    batch axis is sharded — the fleet checkpoint layout; never touches
    another process's shards."""

    def gather(leaf):
        if not isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        by_start = {}
        for sh in leaf.addressable_shards:
            s = sh.index[0] if sh.index else slice(None)
            start = 0 if s.start is None else s.start
            by_start[start] = np.asarray(sh.data)
        rows = [by_start[s] for s in sorted(by_start)]
        return np.concatenate(rows, axis=0) if len(rows) > 1 else rows[0]

    return jax.tree.map(gather, tree)


# -- digest partials ----------------------------------------------------------


def leaf_partial_sums(tree, lo: int = 0, include_replicated: bool = True):
    """Per-leaf partial digest sums (uint32[L]) of a LOCAL block whose
    node-sharded rows sit at global offset ``lo``.

    ``telemetry.tree_digest`` is, per leaf, a wrapping-uint32 sum of
    ``mix32(value ^ mix32(global_flat_index))`` — so partials over
    disjoint row blocks ADD EXACTLY.  Node-sharded leaves contribute
    their rows at global indices; replicated/rumor leaves contribute only
    where ``include_replicated`` (rank 0), so summing every rank's vector
    and applying the outer mix (:func:`combine_leaf_partials`) reproduces
    the single-host ``tree_digest`` bit-for-bit.  The [L] layout follows
    ``jax.tree.leaves`` order — identical on every rank by construction
    (same pytree structure).
    """
    from ringpop_tpu.sim.telemetry import leaf_digest_sum

    specs = jax.tree.leaves(
        partition_spec(tree), is_leaf=lambda x: isinstance(x, P)
    )
    leaves = jax.tree.leaves(tree)
    out = []
    for leaf, spec in zip(leaves, specs):
        sharded = _node_axis(spec) == 0
        if not sharded and not include_replicated:
            out.append(jnp.uint32(0))
            continue
        row_elems = int(math.prod(np.shape(leaf)[1:])) if np.ndim(leaf) else 0
        offset = np.uint32((np.uint64(lo) * np.uint64(row_elems)) & np.uint64(0xFFFFFFFF)) if sharded else np.uint32(0)
        out.append(leaf_digest_sum(leaf, offset=offset))
    return jnp.stack(out)


def combine_leaf_partials(partials: Sequence[np.ndarray]) -> int:
    """Fold per-rank partial vectors (each uint32[L]) into the global
    ``tree_digest`` value: per-leaf wrapping sum across ranks, then the
    digest's outer per-leaf mix and accumulate.  Pure host numpy — the
    combine runs after a fabric allgather of L words per rank."""
    from ringpop_tpu.sim.packbits import mix32 as _mix32_dev  # noqa: F401 (doc pointer)

    with np.errstate(over="ignore"):  # wrapping uint32 sums BY DESIGN
        acc = np.uint32(0)
        total = np.zeros_like(np.asarray(partials[0], np.uint32))
        for p in partials:
            total = (total + np.asarray(p, np.uint32)).astype(np.uint32)
        for li, leaf_sum in enumerate(total):
            acc = (
                acc
                + _np_mix32(
                    np.uint32(leaf_sum) ^ np.uint32((li * 0x9E37_79B9) & 0xFFFFFFFF)
                )
            ).astype(np.uint32)
    return int(acc)


def _np_mix32(x: np.uint32) -> np.uint32:
    """Host-numpy murmur3 fmix32 — the same constants as packbits.mix32
    (digest combines run host-side after the fabric allgather)."""
    with np.errstate(over="ignore"):
        x = np.uint32(x)
        x ^= x >> np.uint32(16)
        x = np.uint32(x * np.uint32(0x85EB_CA6B))
        x ^= x >> np.uint32(13)
        x = np.uint32(x * np.uint32(0xC2B2_AE35))
        x ^= x >> np.uint32(16)
    return x
