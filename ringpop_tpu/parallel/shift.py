"""Shard-local exchange legs for the ``exchange="shift"`` topology.

The shift exchange's two legs are cyclic rolls of the packed rumor plane
by a per-tick traced amount ``s``: ``out[i] = x[(i - s) mod n]``.  Under
GSPMD the partitioner cannot route a traced shift — data must physically
move between chips by an amount it cannot see at compile time — so it
falls back to ALL-GATHERING the operand and slicing locally: one
plane-sized gather per leg, the dominant class of the r6 collective
budget (PERF.md "Multi-chip collective cost model").

This module is the manual lowering the r6 analysis deferred.  Split each
shard's ``nb``-row block into ``H`` equal sub-blocks and write
``s = hq·(nb/H) + rh``.  Then every destination shard's output window
covers exactly ``H+1`` consecutive sub-blocks of the input ring — only
ONE sub-block per shard straddles the roll's crossing boundary — so:

* ``hq`` is traced and a ``lax.ppermute`` perm must be static, so a
  ``lax.switch`` over the ``H·S`` possible values of ``hq`` picks the
  static perm set; exactly ONE branch executes per tick;
* inside the branch, ``H+1`` ppermutes deliver the window's sub-blocks
  (sends whose ring offset is 0 are local and skipped) and one local
  ``dynamic_slice`` at ``nb/H - rh`` stitches the output.

Per-chip bytes per leg drop from a full plane (the partitioner's
all-gather) to ``(H+1)/H`` local blocks — at the default ``H=2``, 1.5
local blocks, ~5× less than an 8-way all-gather — and the cost is flat
in ``s``.  ``H+1`` sends per leg is the floor for this decomposition:
the window spans ``H+1`` sub-blocks on up to two source shards, and a
ppermute has one destination per source.  Raising ``H`` shaves padding
(→ 1 local block as ``H → ∞``) but multiplies switch branches and
per-send latency; ``H=2`` already clears the r8 byte budget.

Bit-identity: the region is pure data movement (permute + concat +
slice), so the result equals ``jnp.roll(x, s, axis=0)`` — and therefore
the engines' materialized-index-gather formulation — exactly;
``tests/test_mesh_budget.py`` pins it against the gather path over every
shift class and the paired sharded trajectory runs certify it end to
end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.35 exposes shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from jax.sharding import Mesh, PartitionSpec as P


def shard_roll(leaves: tuple, shift, mesh: Mesh, axis: str, specs: tuple) -> tuple:
    """``jnp.roll(x, shift, axis=0)`` for every array in ``leaves``, as the
    crossing-block ppermute exchange described in the module docstring.

    ``leaves``: arrays whose axis 0 is the full node axis (one shared n,
    ``n % S == 0`` — the state-sharding divisibility rule).  ``shift``: a
    traced int32 scalar in ``[0, n)``.  ``specs``: one ``PartitionSpec``
    per leaf describing its sharding over ``mesh`` (axis 0 must be
    ``axis``); they become the region's in/out specs, so the call neither
    reshards its inputs nor leaves resharding work behind.

    Requires ``mesh.shape[axis] > 1`` (with one node shard there is
    nothing to exchange — callers keep the local gather path).
    """
    s_shards = mesh.shape[axis]
    if s_shards <= 1:
        raise ValueError("shard_roll needs >1 node shard; use the gather path")
    n = leaves[0].shape[0]
    if n % s_shards:
        raise ValueError(f"n={n} not divisible by {s_shards} node shards")
    nb = n // s_shards
    h = 2 if nb % 2 == 0 else 1  # sub-blocks per shard (module docstring)
    sub = nb // h

    def body(shift, *locs):
        hq = shift // sub
        rh = shift - hq * sub

        def branch(hqi: int):
            # window part p (of H+1) for destination d is global sub-block
            # H·d - m with m = hqi + 1 - p: it lives on the shard m/H
            # (ceil) ring-steps back, at local sub-index (-m) mod H
            plan = []
            for p in range(h + 1):
                m = hqi + 1 - p
                ring = -(-m // h) % s_shards  # ceil(m/H) mod S
                plan.append((ring, (-m) % h))

            def run(rh, *xs):
                outs = []
                for x in xs:
                    subs = x.reshape((h, sub) + x.shape[1:])
                    parts = []
                    for ring, si in plan:
                        piece = subs[si]
                        if ring:  # ring offset 0 = already local, no send
                            perm = [(j, (j + ring) % s_shards) for j in range(s_shards)]
                            piece = jax.lax.ppermute(piece, axis, perm)
                        parts.append(piece)
                    cat = jnp.concatenate(parts, axis=0)
                    outs.append(jax.lax.dynamic_slice_in_dim(cat, sub - rh, nb, axis=0))
                return tuple(outs)

            return run

        return jax.lax.switch(hq, [branch(i) for i in range(h * s_shards)], rh, *locs)

    with jax.named_scope("shard-roll"):
        kw = {"mesh": mesh, "in_specs": (P(),) + tuple(specs), "out_specs": tuple(specs)}
        try:
            fn = _shard_map(body, check_vma=False, **kw)
        except TypeError:  # pragma: no cover - older jax spells it check_rep
            fn = _shard_map(body, check_rep=False, **kw)
        return fn(jnp.asarray(shift, jnp.int32), *leaves)
