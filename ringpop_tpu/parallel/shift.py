"""Shard-local exchange legs for the ``exchange="shift"`` topology.

The shift exchange's two legs are cyclic rolls of the packed rumor plane
by a per-tick traced amount ``s``: ``out[i] = x[(i - s) mod n]``.  Under
GSPMD the partitioner cannot route a traced shift — data must physically
move between chips by an amount it cannot see at compile time — so it
falls back to ALL-GATHERING the operand and slicing locally: one
plane-sized gather per leg, the dominant class of the r6 collective
budget (PERF.md "Multi-chip collective cost model").

This module is the manual lowering the r6 analysis deferred.  Split each
shard's ``nb``-row block into ``H`` equal sub-blocks and write
``s = hq·(nb/H) + rh``.  Then every destination shard's output window
covers exactly ``H+1`` consecutive sub-blocks of the input ring — only
ONE sub-block per shard straddles the roll's crossing boundary — so:

* ``hq`` is traced and a ``lax.ppermute`` perm must be static, so a
  ``lax.switch`` over the ``H·S`` possible values of ``hq`` picks the
  static perm set; exactly ONE branch executes per tick;
* inside the branch, ``H+1`` ppermutes deliver the window's sub-blocks
  (sends whose ring offset is 0 are local and skipped) and one local
  ``dynamic_slice`` at ``nb/H - rh`` stitches the output.

Per-chip bytes per leg drop from a full plane (the partitioner's
all-gather) to ``(H+1)/H`` local blocks — at the default ``H=2``, 1.5
local blocks, ~5× less than an 8-way all-gather — and the cost is flat
in ``s``.  ``H+1`` sends per leg is the floor for this decomposition:
the window spans ``H+1`` sub-blocks on up to two source shards, and a
ppermute has one destination per source.  Raising ``H`` shaves padding
(→ 1 local block as ``H → ∞``) but multiplies switch branches and
per-send latency; ``H=2`` already clears the r8 byte budget.  Since r11
``H`` is a caller parameter (``exchange_h`` on the engine params), with
the historical fallback to 1 when it does not divide the shard block.

Bit-identity: the region is pure data movement (permute + concat +
slice), so the result equals ``jnp.roll(x, s, axis=0)`` — and therefore
the engines' materialized-index-gather formulation — exactly;
``tests/test_mesh_budget.py`` pins it against the gather path over every
shift class and the paired sharded trajectory runs certify it end to
end.  Shifts outside ``[0, n)`` (negative included) follow the mod-n
contract of ``jnp.roll``: the traced shift is reduced mod n on entry,
pinned by ``tests/test_shift_pipeline.py``.

Pipelining (r11, :func:`shard_roll_pipelined`): the engines' exchange is
TWO rolls per tick — the request leg carries the sent plane forward by
``s``, then a merge (OR into the learned plane + ride-gate mask) builds
the response plane, which rolls back by ``n - s``.  As two sequential
``shard_roll`` calls, every response-leg ppermute waits on the *full*
request-leg stitch.  But the response plane's sub-block ``d`` needs only
the two request-leg pieces that stitch into ``d`` — so the fused region
runs a leg loop with a double-buffered carry: leg 1's ``H+1`` sends are
issued up front, each leg-2 send is issued as soon as the two pieces of
its window arrive (while the other ``H-1`` stitches and the full-plane
merge still compute), and the final stitches consume both buffers at the
end.  The data-dependency graph this emits is what lets XLA's
async-collective scheduler overlap the crossing sends with the merge —
``scripts/profile_mesh.py --overlap`` verifies the compiled schedule.
Collective count and bytes are IDENTICAL to the sequential legs (same
``H+1`` sends per rolled leaf per leg, same piece shapes; one switch
over ``2·H·S`` branches instead of two over ``H·S`` — the leg-2 quotient
is a static function of (leg-1 quotient, remainder==0) because the two
shifts sum to n).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.35 exposes shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from jax.sharding import Mesh, PartitionSpec as P

DEFAULT_H = 2


def _layout(leaves: tuple, mesh: Mesh, axis: str, h: int):
    """Shared validation + (n, nb, h_eff, sub) resolution.  ``h`` falls
    back to 1 when it does not divide the shard block (the historical
    odd-block behavior, now for any caller-chosen factor)."""
    s_shards = mesh.shape[axis]
    if s_shards <= 1:
        raise ValueError("shard_roll needs >1 node shard; use the gather path")
    n = leaves[0].shape[0]
    if n % s_shards:
        raise ValueError(f"n={n} not divisible by {s_shards} node shards")
    if h < 1:
        raise ValueError(f"sub-block factor h={h} must be >= 1")
    nb = n // s_shards
    h_eff = h if nb % h == 0 else 1
    return n, nb, h_eff, nb // h_eff


def _window_plan(hqi: int, h: int, s_shards: int) -> list:
    """Static send plan for one quotient class: window part p (of H+1)
    for destination d is global sub-block H·d - m with m = hqi + 1 - p:
    it lives on the shard m/H (ceil) ring-steps back, at local sub-index
    (-m) mod H."""
    plan = []
    for p in range(h + 1):
        m = hqi + 1 - p
        ring = -(-m // h) % s_shards  # ceil(m/H) mod S
        plan.append((ring, (-m) % h))
    return plan


def _issue(plan: list, subs, axis: str, s_shards: int) -> list:
    """Issue one leg's window: per plan entry, the source sub-block of
    every leaf, ppermuted when it crosses shards (ring offset 0 = already
    local, no send).  Returns ``recv[p][leaf]`` — the in-flight buffer
    the stitch (and, pipelined, the next leg) consumes."""
    recv = []
    for ring, si in plan:
        pieces = []
        for sx in subs:
            piece = sx[si]
            if ring:
                perm = [(j, (j + ring) % s_shards) for j in range(s_shards)]
                piece = jax.lax.ppermute(piece, axis, perm)
            pieces.append(piece)
        recv.append(pieces)
    return recv


def _stitch_sub(recv: list, leaf: int, d: int, rh, sub: int):
    """Destination sub-block ``d`` of one rolled leaf: window pieces d and
    d+1 at offset ``sub - rh`` (rh == 0 ⇒ piece d+1 whole) — the per-sub-
    block form of the sequential concat+slice, value-identical."""
    two = jnp.concatenate([recv[d][leaf], recv[d + 1][leaf]], axis=0)
    return jax.lax.dynamic_slice_in_dim(two, sub - rh, sub, axis=0)


def shard_roll(
    leaves: tuple, shift, mesh: Mesh, axis: str, specs: tuple, h: int = DEFAULT_H
) -> tuple:
    """``jnp.roll(x, shift, axis=0)`` for every array in ``leaves``, as the
    crossing-block ppermute exchange described in the module docstring.

    ``leaves``: arrays whose axis 0 is the full node axis (one shared n,
    ``n % S == 0`` — the state-sharding divisibility rule).  ``shift``: a
    traced int32 scalar, reduced mod n on entry (the ``jnp.roll``
    contract — shifts >= n and negative shifts are legal).  ``specs``:
    one ``PartitionSpec`` per leaf describing its sharding over ``mesh``
    (axis 0 must be ``axis``); they become the region's in/out specs, so
    the call neither reshards its inputs nor leaves resharding work
    behind.  ``h``: sub-blocks per shard (the H of the decomposition;
    falls back to 1 when it does not divide the shard block).

    Requires ``mesh.shape[axis] > 1`` (with one node shard there is
    nothing to exchange — callers keep the local gather path).
    """
    s_shards = mesh.shape[axis]
    n, nb, h, sub = _layout(leaves, mesh, axis, h)

    def body(shift, *locs):
        shift = jnp.mod(shift, n)
        hq = shift // sub
        rh = shift - hq * sub

        def branch(hqi: int):
            plan = _window_plan(hqi, h, s_shards)

            def run(rh, *xs):
                subs = [x.reshape((h, sub) + x.shape[1:]) for x in xs]
                recv = _issue(plan, subs, axis, s_shards)
                outs = []
                for li in range(len(xs)):
                    outs.append(
                        jnp.concatenate(
                            [_stitch_sub(recv, li, d, rh, sub) for d in range(h)],
                            axis=0,
                        )
                    )
                return tuple(outs)

            return run

        return jax.lax.switch(hq, [branch(i) for i in range(h * s_shards)], rh, *locs)

    with jax.named_scope("shard-roll"):
        kw = {"mesh": mesh, "in_specs": (P(),) + tuple(specs), "out_specs": tuple(specs)}
        try:
            fn = _shard_map(body, check_vma=False, **kw)
        except TypeError:  # pragma: no cover - older jax spells it check_rep
            fn = _shard_map(body, check_rep=False, **kw)
        return fn(jnp.asarray(shift, jnp.int32), *leaves)


def shard_roll_pipelined(
    leg1: tuple,
    shift,
    mesh: Mesh,
    axis: str,
    specs1: tuple,
    carry: tuple,
    carry_specs: tuple,
    leg2_of,
    spec2,
    h: int = DEFAULT_H,
) -> tuple:
    """Both exchange legs of one tick in ONE shard_map region, pipelined.

    Leg 1 rolls every leaf of ``leg1`` forward by ``shift`` (mod n); the
    response plane — ``leg2_of(*leg1_rolled_sub_blocks, *carry_sub_blocks)``,
    which must be ELEMENTWISE along axis 0 (each output row a function of
    the same rows of its inputs; this is what lets piece extraction
    commute with it) — rolls back by ``n - shift``.  Returns
    ``(*leg1_rolled, leg2_rolled)``, bit-identical to::

        outs = shard_roll(leg1, shift, ...)
        plane = leg2_of(*outs, *carry)
        (back,) = shard_roll((plane,), n - shift, ...)

    but with the leg loop double-buffered: leg 1's H+1 sends are all
    issued first; each leg-2 send is issued as soon as the TWO leg-1
    pieces its window sub-block stitches from have arrived — before the
    other H-1 sub-blocks' stitches (leg 1's merge) consume their windows.
    The emitted dependency graph leaves XLA's scheduler free to overlap
    the leg-2 crossing sends with the merge compute (``profile_mesh
    --overlap`` checks the compiled schedule does); collective count and
    bytes are identical to the sequential pair by construction.

    One static switch covers both legs: with ``s = hq1·sub + rh1``, the
    back-roll ``n - s`` has quotient ``(H·S - hq1 - (0 if rh1 == 0 else
    1)) mod H·S`` and remainder ``(sub - rh1) mod sub`` — so the branch
    index is ``2·hq1 + (rh1 == 0)`` and each branch bakes both legs'
    static send plans.
    """
    s_shards = mesh.shape[axis]
    n, nb, h, sub = _layout(leg1, mesh, axis, h)
    hs = h * s_shards
    n1 = len(leg1)

    def body(shift, *locs):
        shift = jnp.mod(shift, n)
        hq1 = shift // sub
        rh1 = shift - hq1 * sub
        back = jnp.mod(n - shift, n)
        rh2 = back - (back // sub) * sub

        def branch(hq1i: int, zero_r: bool):
            plan1 = _window_plan(hq1i, h, s_shards)
            hq2i = (hs - hq1i - (0 if zero_r else 1)) % hs
            plan2 = _window_plan(hq2i, h, s_shards)

            def run(rh1, rh2, *xs):
                xs1, xc = xs[:n1], xs[n1:]
                subs1 = [x.reshape((h, sub) + x.shape[1:]) for x in xs1]
                subsc = [x.reshape((h, sub) + x.shape[1:]) for x in xc]
                # leg 1: issue every crossing send up front — the first
                # buffer of the double-buffered leg loop
                recv1 = _issue(plan1, subs1, axis, s_shards)
                # leg 2: per send, stitch ONLY the window sub-block it
                # needs (two leg-1 pieces), build the response sub-block
                # elementwise, and issue — the remaining leg-1 stitches
                # and the full-plane merge compute while it flies
                resp_subs: dict = {}

                def resp_sub(d: int):
                    if d not in resp_subs:
                        ins = [_stitch_sub(recv1, li, d, rh1, sub) for li in range(n1)]
                        resp_subs[d] = leg2_of(*ins, *(c[d] for c in subsc))
                    return resp_subs[d]

                recv2 = []
                for ring, si in plan2:
                    piece = resp_sub(si)
                    if ring:
                        perm = [(j, (j + ring) % s_shards) for j in range(s_shards)]
                        piece = jax.lax.ppermute(piece, axis, perm)
                    recv2.append([piece])
                # final stitches consume both buffers
                outs = []
                for li in range(n1):
                    outs.append(
                        jnp.concatenate(
                            [_stitch_sub(recv1, li, d, rh1, sub) for d in range(h)],
                            axis=0,
                        )
                    )
                outs.append(
                    jnp.concatenate(
                        [_stitch_sub(recv2, 0, d, rh2, sub) for d in range(h)],
                        axis=0,
                    )
                )
                return tuple(outs)

            return run

        idx = hq1 * 2 + (rh1 == 0).astype(jnp.int32)
        branches = [branch(i // 2, bool(i % 2)) for i in range(2 * hs)]
        return jax.lax.switch(idx, branches, rh1, rh2, *locs)

    with jax.named_scope("shard-roll"):
        kw = {
            "mesh": mesh,
            "in_specs": (P(),) + tuple(specs1) + tuple(carry_specs),
            "out_specs": tuple(specs1) + (spec2,),
        }
        try:
            fn = _shard_map(body, check_vma=False, **kw)
        except TypeError:  # pragma: no cover - older jax spells it check_rep
            fn = _shard_map(body, check_rep=False, **kw)
        return fn(jnp.asarray(shift, jnp.int32), *leg1, *carry)
