"""Quorum replication over LookupN preference lists
(parity: reference ``replica/replicator.go``).

``read``/``write`` fan a request out to the N owners of a key and succeed
when R/W responses arrive; fanout is Parallel, SerialSequential or
SerialBalanced (``replicator.go:40-52``).  N/R/W default to 3/1/3.

The serve plane's hash-batch analog is
``ringpop_tpu.forward.batch.QuorumReader`` (r17): same
group-by-destination rule as :meth:`Replicator._group_replicas`, but
over uint32 hash batches with ONE coalesced RPC per owner per wave and
the majority bar ⌈(R+1)/2⌉.  Semantic changes to grouping or ack policy
should be mirrored between the two planes."""

from __future__ import annotations

import asyncio
import enum
import random
from dataclasses import dataclass, field
from typing import Any, Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu import util
from ringpop_tpu.forward import Forwarder
from ringpop_tpu.forward.forwarder import Options as ForwardOptions


class FanoutMode(enum.IntEnum):
    PARALLEL = 0
    SERIAL_SEQUENTIAL = 1
    SERIAL_BALANCED = 2


@dataclass
class Options:
    """(parity: ``replicator.go:78-82``; zero selects defaults 3/1/3)"""

    n_value: int = 0
    r_value: int = 0
    w_value: int = 0
    fanout_mode: FanoutMode = FanoutMode.PARALLEL

    def merged_with(self, defaults: "Options") -> "Options":
        return Options(
            n_value=util.select_int(self.n_value, defaults.n_value),
            r_value=util.select_int(self.r_value, defaults.r_value),
            w_value=util.select_int(self.w_value, defaults.w_value),
            fanout_mode=self.fanout_mode
            if self.fanout_mode in tuple(FanoutMode)
            else FanoutMode.PARALLEL,
        )


DEFAULT_OPTIONS = Options(n_value=3, r_value=1, w_value=3, fanout_mode=FanoutMode.PARALLEL)


@dataclass
class Response:
    """(parity: ``replicator.go:71-76``)"""

    destination: str = ""
    keys: list[str] = field(default_factory=list)
    body: Any = None


class NotEnoughResponsesError(Exception):
    def __init__(self, wanted: int, got: int):
        super().__init__(f"wanted {wanted} responses, got {got}")
        self.wanted = wanted
        self.got = got


class Replicator:
    def __init__(self, sender, channel, options: Optional[Options] = None, rng=None):
        self.sender = sender
        self.channel = channel
        self.forwarder = Forwarder(sender, channel)
        self.defaults = (options or Options()).merged_with(DEFAULT_OPTIONS)
        self.rng = rng or random.Random()
        self.logger = logging_mod.logger("replicator")

    async def read(
        self,
        keys: list[str],
        body: dict,
        operation: str,
        fopts: Optional[ForwardOptions] = None,
        opts: Optional[Options] = None,
    ) -> list[Response]:
        opts = (opts or Options()).merged_with(self.defaults)
        return await self._read_write(keys, body, operation, fopts, opts, opts.r_value)

    async def write(
        self,
        keys: list[str],
        body: dict,
        operation: str,
        fopts: Optional[ForwardOptions] = None,
        opts: Optional[Options] = None,
    ) -> list[Response]:
        opts = (opts or Options()).merged_with(self.defaults)
        return await self._read_write(keys, body, operation, fopts, opts, opts.w_value)

    def _group_replicas(
        self, keys: list[str], n: int
    ) -> tuple[list[str], dict[str, list[str]]]:
        """Group keys by replica destination
        (parity: ``replicator.go:170-191`` groupReplicas)."""
        keys_by_dest: dict[str, list[str]] = {}
        dests: list[str] = []
        batch = getattr(self.sender, "lookup_n_batch", None)
        if batch is not None and len(keys) > 1:
            rows = batch(keys, n)  # one native ring walk for all keys
        else:
            rows = [self.sender.lookup_n(key, n) for key in keys]
        for key, row in zip(keys, rows):
            for dest in row:
                if dest not in keys_by_dest:
                    dests.append(dest)
                keys_by_dest.setdefault(dest, []).append(key)
        return dests, keys_by_dest

    async def _read_write(
        self, keys, body, operation, fopts, opts, required: int
    ) -> list[Response]:
        """(parity: ``replicator.go:193-256`` readWrite)"""
        dests, keys_by_dest = self._group_replicas(keys, opts.n_value)
        if len(dests) < required:
            raise NotEnoughResponsesError(required, len(dests))

        fopts = fopts or ForwardOptions()

        async def call(dest: str) -> Response:
            res = await self.forwarder.forward_request(
                body, dest, self.channel.app or "replica", operation, keys_by_dest[dest], fopts
            )
            return Response(destination=dest, keys=keys_by_dest[dest], body=res)

        if opts.fanout_mode == FanoutMode.PARALLEL:
            results = await asyncio.gather(*(call(d) for d in dests), return_exceptions=True)
            responses = [r for r in results if isinstance(r, Response)]
        else:
            order = list(dests)
            if opts.fanout_mode == FanoutMode.SERIAL_BALANCED:
                self.rng.shuffle(order)
            responses = []
            for dest in order:
                try:
                    responses.append(await call(dest))
                except Exception:
                    continue
                if len(responses) >= required:
                    break

        if len(responses) < required:
            raise NotEnoughResponsesError(required, len(responses))
        return responses
