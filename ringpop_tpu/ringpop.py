"""Ringpop facade — the public API (parity: reference ``ringpop.go``).

Wires the SWIM node, the hash ring and the forwarder; keeps the lifecycle
state machine (created→initialized→ready→destroyed, ``ringpop.go:101-119``);
translates membership changes into ring add/removes
(``ringpop.go:550-563``); maps every event to stats under
``ringpop.<host_port>.<metric>`` (``ringpop.go:385-548``); and exposes
``lookup``/``handle_or_forward``/``forward`` for keyed request routing.
"""

from __future__ import annotations

import enum
import time as _time
from typing import Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu import events as facade_ev
from ringpop_tpu.errors import InvalidStateError, NotBootstrappedError
from ringpop_tpu.events import EventEmitter
from ringpop_tpu.forward import Forwarder, Options as ForwardOptions, has_forwarded_header
from ringpop_tpu.forward import events as fwd_ev
from ringpop_tpu.hashring import HashRing
from ringpop_tpu.options import NoopStats, Options, default_identity_resolver
from ringpop_tpu.swim import events as swim_ev
from ringpop_tpu.swim.member import ALIVE, FAULTY, LEAVE, SUSPECT, TOMBSTONE, state_name
from ringpop_tpu.swim.node import BootstrapOptions, Node, NodeOptions
from ringpop_tpu.swim import stats as swim_stats


class State(enum.Enum):
    CREATED = 0
    INITIALIZED = 1
    READY = 2
    DESTROYED = 3


class Interface:
    """The facade API surface (parity: ``ringpop.go:48-63`` Interface)."""

    def destroy(self) -> None: ...

    def app(self) -> str: ...

    def who_am_i(self) -> str: ...

    def uptime(self) -> float: ...

    def register_listener(self, l) -> None: ...

    async def bootstrap(self, opts) -> list[str]: ...

    def checksum(self) -> int: ...

    def lookup(self, key: str) -> str: ...

    def lookup_n(self, key: str, n: int) -> list[str]: ...

    def get_reachable_members(self) -> list[str]: ...

    def count_reachable_members(self) -> int: ...


class Ringpop(Interface):
    def __init__(self, app: str, channel, options: Optional[Options] = None):
        if channel is None:
            raise ValueError("channel is required (options.go:113 Channel)")
        self._app = app
        self.channel = channel
        self.options = options or Options()
        self.logger = logging_mod.logger("ringpop")
        self.stats = self.options.stats_reporter or NoopStats()
        self.emitter = EventEmitter()
        self._state = State.CREATED
        self._start_time: Optional[float] = None
        self._stat_key_cache: dict[str, str] = {}
        self._stat_hostport: str = ""
        self._stat_timers: list = []

        self.node: Optional[Node] = None
        self.ring: Optional[HashRing] = None
        self.forwarder: Optional[Forwarder] = None
        self.whoami: Optional[str] = None

    # -- lifecycle (parity: ringpop.go:101-119, 153-186) --------------------

    @property
    def state(self) -> State:
        return self._state

    def _init(self) -> None:
        if self.options.identity:
            address = self.options.identity
        elif self.options.identity_resolver is not None:
            address = self.options.identity_resolver()
        else:
            address = default_identity_resolver(self.channel)
        self.whoami = address
        self._stat_hostport = address.replace(":", "_").replace(".", "_")

        node_opts = NodeOptions(
            state_timeouts=self.options.resolved_state_timeouts(),
            clock=self.options.clock,
            seed=self.options.seed,
        )
        self.node = Node(self._app, address, self.channel, node_opts)
        self.ring = HashRing(
            hashfunc=self.options.hashfunc, replica_points=self.options.replica_points
        )
        self.forwarder = Forwarder(self, self.channel)

        # the facade listens to everything and is the glue between layers
        # (ringpop.go:170-180)
        self.node.register_listener(self)
        self.ring.register_listener(self)
        self.forwarder.register_listener(self)

        self._register_admin_handlers()
        self._start_timers()
        self._state = State.INITIALIZED

    def _start_timers(self) -> None:
        """Periodic membership/ring checksum gauges
        (parity: ``ringpop.go:190-221`` startTimers)."""
        clock = self.node.clock

        def emit_membership_checksum():
            self.stat_gauge("membership.checksum-periodic", self.node.memberlist.checksum())
            self._stat_timers.append(
                clock.after(self.options.membership_checksum_stat_period, emit_membership_checksum)
            )

        def emit_ring_checksum():
            self.stat_gauge("ring.checksum-periodic", self.ring.checksum())
            self._stat_timers.append(
                clock.after(self.options.ring_checksum_stat_period, emit_ring_checksum)
            )

        if self.options.membership_checksum_stat_period > 0:
            self._stat_timers.append(
                clock.after(self.options.membership_checksum_stat_period, emit_membership_checksum)
            )
        if self.options.ring_checksum_stat_period > 0:
            self._stat_timers.append(
                clock.after(self.options.ring_checksum_stat_period, emit_ring_checksum)
            )

    async def bootstrap(self, opts: Optional[BootstrapOptions] = None, **kw) -> list[str]:
        """(parity: ``ringpop.go:348-377`` Bootstrap)"""
        if self._state == State.DESTROYED:
            raise InvalidStateError("destroyed ringpop cannot bootstrap")
        if self._state == State.CREATED:
            self._init()
        if opts is None:
            opts = BootstrapOptions(**kw)
        joined = await self.node.bootstrap(opts)
        self._state = State.READY
        self._start_time = _time.time()
        self.emitter.emit(facade_ev.Ready())
        return joined

    def ready(self) -> bool:
        return self._state == State.READY

    def destroy(self) -> None:
        if self.node is not None:
            self.node.destroy()
        for t in self._stat_timers:
            t.stop()
        self._state = State.DESTROYED
        self.emitter.emit(facade_ev.Destroyed())

    # -- identity / basics --------------------------------------------------

    def app(self) -> str:
        return self._app

    def who_am_i(self) -> str:
        if self.whoami is None:
            raise NotBootstrappedError()
        return self.whoami

    def uptime(self) -> float:
        if not self.ready() or self._start_time is None:
            raise NotBootstrappedError()
        return _time.time() - self._start_time

    def checksum(self) -> int:
        if not self.ready():
            raise NotBootstrappedError()
        return self.ring.checksum()

    def register_listener(self, listener) -> None:
        self.emitter.register_listener(listener)

    def get_reachable_members(self) -> list[str]:
        if not self.ready():
            raise NotBootstrappedError()
        return self.node.get_reachable_members()

    def count_reachable_members(self) -> int:
        if not self.ready():
            raise NotBootstrappedError()
        return self.node.count_reachable_members()

    # -- lookup (parity: ringpop.go:582-625) --------------------------------

    def lookup(self, key: str) -> str:
        if not self.ready():
            raise NotBootstrappedError()
        t0 = _time.perf_counter()
        dest = self.ring.lookup(key)
        duration = _time.perf_counter() - t0
        self.stat_timing("lookup", duration)
        self.emitter.emit(facade_ev.LookupEvent(key, duration))
        if dest is None:
            raise NotBootstrappedError()
        return dest

    def lookup_n(self, key: str, n: int) -> list[str]:
        if not self.ready():
            raise NotBootstrappedError()
        t0 = _time.perf_counter()
        dests = self.ring.lookup_n(key, n)
        duration = _time.perf_counter() - t0
        self.stat_timing("lookupn", duration)
        self.emitter.emit(facade_ev.LookupNEvent(key, n, duration))
        return dests

    def lookup_n_batch(self, keys: list[str], n: int) -> list[list[str]]:
        """Preference lists for many keys in one native ring walk — the
        batched path the replicator's multi-key fan-out uses."""
        if not self.ready():
            raise NotBootstrappedError()
        t0 = _time.perf_counter()
        rows = self.ring.lookup_n_batch(keys, n)
        duration = _time.perf_counter() - t0
        # distinct stat: this sample covers the whole batch — mixing it into
        # the per-key "lookupn" timer would corrupt that distribution
        self.stat_timing("lookupn-batch", duration)
        self.emitter.emit(facade_ev.LookupNBatchEvent(len(keys), n, duration))
        return rows

    # -- keyed routing (parity: ringpop.go:687-723) -------------------------

    async def handle_or_forward(
        self,
        key: str,
        body: dict,
        service: str,
        endpoint: str,
        options: Optional[ForwardOptions] = None,
        headers: Optional[dict] = None,
    ) -> tuple[bool, Optional[dict]]:
        """Returns (True, None) when the local node owns ``key`` — the caller
        handles the request — else forwards and returns (False, response)
        (parity: ``ringpop.go:687-713`` HandleOrForward)."""
        if not self.ready():
            raise NotBootstrappedError()
        if has_forwarded_header(headers):
            return True, None  # loop guard: already forwarded once
        dest = self.lookup(key)
        if dest == self.who_am_i():
            return True, None
        res = await self.forward(dest, [key], body, service, endpoint, options)
        return False, res

    async def forward(
        self,
        dest: str,
        keys: list[str],
        body: dict,
        service: str,
        endpoint: str,
        options: Optional[ForwardOptions] = None,
    ) -> dict:
        """(parity: ``ringpop.go:715-723`` Forward)"""
        if self.forwarder is None:
            raise NotBootstrappedError()
        return await self.forwarder.forward_request(body, dest, service, endpoint, keys, options)

    # -- stats plumbing (parity: ringpop.go:175-177, 665-675) ---------------

    def get_stat_key(self, key: str) -> str:
        cached = self._stat_key_cache.get(key)
        if cached is None:
            cached = f"ringpop.{self._stat_hostport}.{key}"
            self._stat_key_cache[key] = cached
        return cached

    def stat_incr(self, key: str, value: int = 1) -> None:
        self.stats.incr(self.get_stat_key(key), value)

    def stat_gauge(self, key: str, value: float) -> None:
        self.stats.gauge(self.get_stat_key(key), value)

    def stat_timing(self, key: str, seconds: float) -> None:
        self.stats.timing(self.get_stat_key(key), seconds)

    # -- event -> stats + ring sync (parity: ringpop.go:385-563) ------------

    def handle_event(self, event) -> None:
        # dict dispatch on the exact event type (the events are flat
        # dataclasses, never subclassed) — the reference's 60-stat switch
        # (ringpop.go:385-548) as one table lookup instead of ~40 isinstance
        # probes per event; this runs 3-4x per forwarded request
        fn = _EVENT_STATS.get(type(event))
        if fn is not None:
            fn(self, event)

        # relay everything to facade listeners (async dispatch in the
        # reference, ringpop.go:297-301; synchronous relay here)
        self.emitter.emit(event)

    def _on_changes_applied(self, e) -> None:
        self.stat_incr("changes.apply", 0)  # applied count below
        self.stat_gauge("num-members", e.num_members)
        self.stat_incr("membership-set.alive", 0)
        for change in e.changes:
            self.stat_incr(f"membership-update.{state_name(change.status)}")
        self.stat_gauge("checksum", e.new_checksum)
        self.stat_incr("membership.checksum-computed")
        self._handle_changes(e.changes)

    def _on_join_complete(self, e) -> None:
        self.stat_incr("join.complete")
        self.stat_timing("join", e.duration)
        self.stat_incr("join.succeeded")

    def _on_checksum_computed(self, e) -> None:
        self.stat_timing("compute-checksum", e.duration)
        self.stat_gauge("checksum", e.checksum)

    def _on_ring_changed(self, e) -> None:
        self.stat_incr("ring.changed")
        for _ in e.servers_added:
            self.stat_incr("ring.server-added")
        for _ in e.servers_removed:
            self.stat_incr("ring.server-removed")

    def _handle_changes(self, changes) -> None:
        """Membership → ring sync (parity: ``ringpop.go:550-563``)."""
        to_add, to_remove = [], []
        for change in changes:
            if change.status in (ALIVE, SUSPECT):
                to_add.append(change.address)
            elif change.status in (FAULTY, LEAVE, TOMBSTONE):
                to_remove.append(change.address)
        if to_add or to_remove:
            self.ring.add_remove_servers(to_add, to_remove)

    # -- Forwarder Sender protocol ------------------------------------------

    # who_am_i and lookup double as the forward.Sender interface
    # (forward/forwarder.go:39-45)

    # -- admin endpoints (parity: handlers.go:33-67, stats_handler.go) ------

    def _register_admin_handlers(self) -> None:
        async def health(body, headers):
            return {"ok": True}

        async def admin_stats(body, headers):
            return self._collect_stats()

        async def admin_lookup(body, headers):
            key = (body or {}).get("key", "")
            return {"dest": self.ring.lookup(key)}

        self.channel.register("ringpop", "/health", health)
        self.channel.register("ringpop", "/admin/stats", admin_stats)
        self.channel.register("ringpop", "/admin/lookup", admin_lookup)

    def _collect_stats(self) -> dict:
        """(parity: ``stats_handler.go:32-63`` handleStats)"""
        import resource

        usage = resource.getrusage(resource.RUSAGE_SELF)
        return {
            "hooks": None,
            "membership": swim_stats.member_stats(self.node),
            "process": {
                "maxrss_kb": usage.ru_maxrss,
                "utime_s": usage.ru_utime,
                "stime_s": usage.ru_stime,
            },
            "protocol": swim_stats.protocol_stats(self.node),
            "ring": {
                "servers": self.ring.servers(),
                "checksum": self.ring.checksum(),
            },
            "state": self._state.name.lower(),
            "uptime": self.uptime() if self.ready() else 0,
        }


def new(app: str, channel, options: Optional[Options] = None, **kw) -> Ringpop:
    """(parity: ``ringpop.go:122`` New)"""
    if options is None and kw:
        options = Options(**kw)
    return Ringpop(app, channel, options)


# event type -> stats action for Ringpop.handle_event (parity with the
# reference's per-event switch, ringpop.go:385-548)
_EVENT_STATS = {
    swim_ev.MemberlistChangesReceivedEvent: lambda rp, e: rp.stat_incr("changes.apply", len(e.changes)),
    swim_ev.MemberlistChangesAppliedEvent: Ringpop._on_changes_applied,
    swim_ev.FullSyncEvent: lambda rp, e: rp.stat_incr("full-sync"),
    swim_ev.StartReverseFullSyncEvent: lambda rp, e: rp.stat_incr("full-sync.reverse"),
    swim_ev.OmitReverseFullSyncEvent: lambda rp, e: rp.stat_incr("full-sync.reverse.omitted"),
    swim_ev.MaxPAdjustedEvent: lambda rp, e: rp.stat_gauge("max-piggyback", e.new_pcount),
    swim_ev.JoinReceiveEvent: lambda rp, e: rp.stat_incr("join.recv"),
    swim_ev.JoinCompleteEvent: Ringpop._on_join_complete,
    swim_ev.JoinFailedEvent: lambda rp, e: rp.stat_incr("join.failed"),
    swim_ev.JoinTriesUpdateEvent: lambda rp, e: rp.stat_gauge("join.retries", e.retries),
    swim_ev.PingSendEvent: lambda rp, e: rp.stat_incr("ping.send"),
    swim_ev.PingSendCompleteEvent: lambda rp, e: rp.stat_timing("ping", e.duration),
    swim_ev.PingReceiveEvent: lambda rp, e: rp.stat_incr("ping.recv"),
    swim_ev.PingRequestsSendEvent: lambda rp, e: rp.stat_incr("ping-req.send", len(e.peers)),
    swim_ev.PingRequestsSendCompleteEvent: lambda rp, e: rp.stat_timing("ping-req", e.duration),
    swim_ev.PingRequestSendErrorEvent: lambda rp, e: rp.stat_incr("ping-req.err"),
    swim_ev.PingRequestReceiveEvent: lambda rp, e: rp.stat_incr("ping-req.recv"),
    swim_ev.PingRequestPingEvent: lambda rp, e: rp.stat_timing("ping-req.ping", e.duration),
    swim_ev.ProtocolDelayComputeEvent: lambda rp, e: rp.stat_timing("protocol.delay", e.duration),
    swim_ev.ProtocolFrequencyEvent: lambda rp, e: rp.stat_timing("protocol.frequency", e.duration),
    swim_ev.ChecksumComputeEvent: Ringpop._on_checksum_computed,
    swim_ev.ChangesCalculatedEvent: lambda rp, e: rp.stat_gauge("changes.disseminate", len(e.changes)),
    swim_ev.ChangeFilteredEvent: lambda rp, e: rp.stat_incr("filtered-change"),
    swim_ev.RefuteUpdateEvent: lambda rp, e: rp.stat_incr("refuted-update"),
    swim_ev.RequestBeforeReadyEvent: lambda rp, e: rp.stat_incr(
        "not-ready.ping" if "ping" in e.endpoint else "not-ready.ping-req"
    ),
    swim_ev.DiscoHealEvent: lambda rp, e: rp.stat_incr("heal.triggered"),
    swim_ev.AttemptHealEvent: lambda rp, e: rp.stat_incr("heal.attempt"),
    facade_ev.RingChecksumEvent: lambda rp, e: rp.stat_incr("ring.checksum-computed"),
    facade_ev.RingChangedEvent: Ringpop._on_ring_changed,
    fwd_ev.RequestForwardedEvent: lambda rp, e: rp.stat_incr("requestProxy.egress"),
    fwd_ev.InflightRequestsChangedEvent: lambda rp, e: rp.stat_gauge("requestProxy.inflight", e.inflight),
    fwd_ev.InflightRequestsMiscountEvent: lambda rp, e: rp.stat_incr(f"requestProxy.miscount.{e.operation}"),
    fwd_ev.SuccessEvent: lambda rp, e: rp.stat_incr("requestProxy.send.success"),
    fwd_ev.FailedEvent: lambda rp, e: rp.stat_incr("requestProxy.send.error"),
    fwd_ev.MaxRetriesEvent: lambda rp, e: rp.stat_incr("requestProxy.retry.failed"),
    fwd_ev.RetryAttemptEvent: lambda rp, e: rp.stat_incr("requestProxy.retry.attempted"),
    fwd_ev.RetryAbortEvent: lambda rp, e: rp.stat_incr("requestProxy.retry.aborted"),
    fwd_ev.RetrySuccessEvent: lambda rp, e: rp.stat_incr("requestProxy.retry.succeeded"),
    fwd_ev.RerouteEvent: lambda rp, e: rp.stat_incr("requestProxy.retry.reroute.remote"),
}
