"""Key→client routing cache (parity: reference ``router/router.go``).

``get_client(key)`` looks the key up on the ring and returns either the local
service implementation or a cached remote client for the owner; cached
clients are evicted when the owner becomes Faulty/Leave
(``router/router.go:70-84``)."""

from __future__ import annotations

import threading
from typing import Any, Protocol

from ringpop_tpu.swim import events as swim_ev
from ringpop_tpu.swim.member import FAULTY, LEAVE


class ClientFactory(Protocol):
    """(parity: ``router/router.go:47-54``)"""

    def get_local_client(self) -> Any: ...

    def make_remote_client(self, hostport: str) -> Any: ...


class Router:
    def __init__(self, ringpop, factory: ClientFactory, lookup_source=None):
        self.ringpop = ringpop
        self.factory = factory
        # optional batched owner resolver ``keys -> list[hostport]`` —
        # e.g. a serve-tier frontend resolving through the shared
        # device-resident ring (``serve.client.ServeClient.lookup`` /
        # an ``ShmClient`` wrapper); the scalar path stays ringpop.lookup
        self.lookup_source = lookup_source
        self._cache: dict[str, Any] = {}
        self._lock = threading.RLock()
        ringpop.register_listener(self)

    def handle_event(self, event) -> None:
        """Evict cached clients for members that became unusable
        (parity: ``router/router.go:70-84``)."""
        if isinstance(event, swim_ev.MemberlistChangesAppliedEvent):
            for change in event.changes:
                if change.status in (FAULTY, LEAVE):
                    self.remove_client(change.address)

    def _client_for(self, dest: str, me: str) -> tuple[Any, bool]:
        """Cache-or-create the client for ``dest`` — caller holds _lock."""
        client = self._cache.get(dest)
        if client is None:
            if dest == me:
                client = self.factory.get_local_client()
            else:
                client = self.factory.make_remote_client(dest)
            self._cache[dest] = client
        return client, dest == me

    def get_client(self, key: str) -> tuple[Any, bool]:
        """(client, is_local) for the owner of ``key``
        (parity: ``router/router.go:88-133`` GetClient)."""
        dest = self.ringpop.lookup(key)
        me = self.ringpop.who_am_i()
        with self._lock:
            return self._client_for(dest, me)

    def get_client_batch(self, keys: list[str]) -> list[tuple[Any, bool]]:
        """Batched GetClient: resolve every key's owner in ONE lookup —
        through the injected ``lookup_source`` when configured (the
        serve tier's shared device ring), else the host ring's
        vectorized ``lookup_batch`` — then serve clients from the same
        cache ``get_client`` uses.  The batch shape is what lets a
        frontend amortize the shared-ring round trip across its whole
        request wave instead of paying one lookup per key."""
        if not keys:
            return []
        if self.lookup_source is not None:
            dests = list(self.lookup_source(keys))
        else:
            batch = getattr(self.ringpop, "lookup_batch", None)
            if batch is not None:
                dests = batch(keys)
            else:
                dests = [self.ringpop.lookup(k) for k in keys]
        me = self.ringpop.who_am_i()
        with self._lock:
            return [self._client_for(dest, me) for dest in dests]

    def remove_client(self, hostport: str) -> None:
        with self._lock:
            self._cache.pop(hostport, None)
