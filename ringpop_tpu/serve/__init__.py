"""Serve the ring: a shared device-resident lookup tier.

The host plane answers keyed lookups at ~15-24k req/s per process (the
bisect walk, PERF.md "Host-plane performance target") while the device op
(``ops/ring_ops.py``) sustains tens of millions of lookups/s — a ~1000×
gap.  This package closes it for serving: many frontend processes submit
key-hash batches to ONE device-resident ring over the ``net/channel.py``
framing, a micro-batching collector coalesces pending requests across
frontends into single padded-ring dispatches (flush at B keys or T µs),
and live membership changes swap new ring generations in under a
generation counter read back from the device with every answer — so every
routing decision is certified against the exact membership generation
that produced it.

Pieces:

* :mod:`~ringpop_tpu.serve.state` — ``DeviceRing`` (capacity-padded
  tokens/owners + count + generation, all device-resident),
  ``ring_commit`` (the donating generation swap), ``RingStore`` (the
  host-side feed: incremental `hashring` updates → padded arrays →
  commit; subscribes to live ``RingChangedEvent`` streams).
* :mod:`~ringpop_tpu.serve.service` — ``RingService``: the asyncio
  micro-batching collector + telemetry (batch-size/queue-wait/dispatch
  histograms through the r7 stats plumbing, JSONL journal with
  generation records).
* :mod:`~ringpop_tpu.serve.client` — ``ServeClient`` (frontend half) and
  ``HostBisectFrontend`` (the per-process baseline the A/B prices).
* :mod:`~ringpop_tpu.serve.placement` — the DGRO-style token-placement
  pass (PAPERS.md: diameter/spread-guided), opt-in behind the default
  random replica placement.
* :mod:`~ringpop_tpu.serve.bench` — the multi-process paired A/B driver
  simbench's ``serve_ring`` scenario and ``make serve-smoke`` share.
* :mod:`~ringpop_tpu.serve.mesh` — the r17 multi-host serve mesh: P
  serve ranks each own a contiguous ring block (the r14
  ``process_block`` rule) and cross-forward mis-routed keys over the
  DCN fabric, answering LookupN preference lists through the fused
  dispatch — every (owner, successors, generation) tuple digest-equal
  to the single-process oracle at any P.
"""

_EXPORTS = {
    "DeviceRing": "ringpop_tpu.serve.state",
    "RingStore": "ringpop_tpu.serve.state",
    "ring_commit": "ringpop_tpu.serve.state",
    "serve_lookup": "ringpop_tpu.serve.state",
    "serve_lookup_fused": "ringpop_tpu.serve.state",
    "serve_lookup_n": "ringpop_tpu.serve.state",
    "serve_lookup_n_fused": "ringpop_tpu.serve.state",
    "RingService": "ringpop_tpu.serve.service",
    "ServeMesh": "ringpop_tpu.serve.mesh",
    "run_serve_mesh": "ringpop_tpu.serve.mesh",
    "ServeClient": "ringpop_tpu.serve.client",
    "HostBisectFrontend": "ringpop_tpu.serve.client",
    "ShmServer": "ringpop_tpu.serve.shm",
    "ShmClient": "ringpop_tpu.serve.shm",
    "dgro_place": "ringpop_tpu.serve.placement",
    "key_movement": "ringpop_tpu.serve.placement",
    "run_ab": "ringpop_tpu.serve.bench",
}


def __getattr__(name):
    # lazy like the facade package: frontend processes import
    # serve.client without paying the device tier's jax import
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod), name)


__all__ = list(_EXPORTS)
