"""Paired A/B driver: N frontend processes vs the shared device ring.

The measurement the serve tier exists for: F real OS processes drive
keyed lookups (A) through the shared device-resident ring service —
over the shared-memory request ring (``serve/shm.py``, the same-host
fast lane) or over TCP (``net/channel.py`` framing) — and (B) through
their own in-process host bisect walk, the exact lookup the host plane
does today.  Phases are INTERLEAVED rep by rep (serve, bisect, serve,
...) behind a cross-process barrier, the same pairing methodology as
``forward_ab``, so container-load drift hits both sides of each pair
equally.  Every (worker, rep) computes a fingerprint32 digest over its
owner-id stream + the membership generation that answered it; A/B
digests must match pairwise — owner decisions bit-identical per key and
per membership generation is the certificate, not an assumption.

Workers are ``spawn`` processes (no inherited JAX/asyncio state); the
service runs on a dedicated thread in the parent with its own event loop.
Top-level imports here stay jax-free so spawned children never
initialize a backend.
"""

from __future__ import annotations

import asyncio
import multiprocessing
import threading
import time
from typing import Optional


def _digest_owners(digest: int, owners, gen: int) -> int:
    """Chain a fingerprint32 over one batch's owner ids + generation."""
    from ringpop_tpu.hashing import fingerprint32

    import numpy as np

    payload = (
        digest.to_bytes(4, "little")
        + np.asarray(owners, np.int32).tobytes()
        + int(gen).to_bytes(4, "little")
    )
    return fingerprint32(payload)


def _batch_hashes(seed: int, wid: int, rep: int, bi: int, batch: int):
    import numpy as np

    rng = np.random.default_rng(seed + wid * 1_000_003 + rep * 1009 + bi)
    return rng.integers(0, 2**32, size=batch, dtype=np.uint32)


def _measure_reps(
    wid: int, lookup, bisect_fe, gen: int, batch: int, batches_per_rep: int,
    reps: int, seed: int, barrier,
) -> list[dict]:
    """The shared inner loop: interleaved serve/bisect phases behind the
    barrier; per-(rep, mode) wall, key count and owner digest."""
    out = []
    for rep in range(reps):
        for mode in ("serve", "bisect"):
            barrier.wait()
            t0 = time.perf_counter()
            digest, keys = 0, 0
            gens = set()
            for bi in range(batches_per_rep):
                hashes = _batch_hashes(seed, wid, rep, bi, batch)
                if mode == "serve":
                    owners, g = lookup(hashes)
                else:
                    owners, g = bisect_fe.lookup_hashes(hashes), gen
                gens.add(g)
                digest = _digest_owners(digest, owners, g)
                keys += len(hashes)
            wall = time.perf_counter() - t0
            barrier.wait()
            out.append(
                dict(wid=wid, rep=rep, mode=mode, keys=keys,
                     wall=round(wall, 6), digest=digest, gens=sorted(gens))
            )
    return out


def _worker(
    wid: int,
    transport: str,
    address,
    servers: list[str],
    replica_points: int,
    gen: int,
    batch: int,
    batches_per_rep: int,
    reps: int,
    seed: int,
    codec: str,
    barrier,
    outq,
) -> None:
    """One frontend process (shm: synchronous slot client; tcp: asyncio
    channel client — both drive the same measurement loop)."""
    from ringpop_tpu.serve.client import HostBisectFrontend

    bisect_fe = HostBisectFrontend(servers, replica_points)

    if transport == "shm":
        from ringpop_tpu.serve.shm import ShmClient

        shm_name, sock_path, slots, key_cap, max_n = address
        client = ShmClient(
            shm_name, sock_path, wid, slots=slots, key_cap=key_cap, max_n=max_n
        )
        client.lookup_hashes(_batch_hashes(seed, wid, 0, 0, 8))  # warm path
        out = _measure_reps(
            wid, client.lookup_hashes, bisect_fe, gen, batch,
            batches_per_rep, reps, seed, barrier,
        )
        client.close()
        outq.put(out)
        return

    from ringpop_tpu.net import TCPChannel
    from ringpop_tpu.serve.client import ServeClient

    async def run():
        chan = TCPChannel(app="serve-fe", codec=codec)
        client = ServeClient(chan, address)
        # connection warm-up outside any timed phase
        await client.lookup_hashes(_batch_hashes(seed, wid, 0, 0, 8)[:1])

        def lookup(hashes):
            return loop.run_until_complete(client.lookup_hashes(hashes))

        loop = asyncio.get_event_loop()
        return lookup

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    lookup = loop.run_until_complete(run())
    out = _measure_reps(
        wid, lookup, bisect_fe, gen, batch, batches_per_rep, reps, seed, barrier
    )
    outq.put(out)


def _latency_worker(
    transport: str, address, servers, replica_points: int, n_req: int,
    codec: str, outq,
) -> None:
    """Single-frontend degenerate case: B=1 sequential round trips, every
    answer checked against the local bisect oracle ("routes correctly" is
    part of the certificate, not an assumption)."""
    from ringpop_tpu.serve.client import HostBisectFrontend

    oracle = HostBisectFrontend(servers, replica_points)

    if transport == "shm":
        from ringpop_tpu.serve.shm import ShmClient

        shm_name, sock_path, slots, key_cap, max_n = address
        client = ShmClient(
            shm_name, sock_path, 0, slots=slots, key_cap=key_cap, max_n=max_n
        )

        lat, ok = _time_latency(client.lookup_hashes, oracle, n_req)
        client.close()
        outq.put((lat, ok))
        return

    from ringpop_tpu.net import TCPChannel
    from ringpop_tpu.serve.client import ServeClient

    async def run():
        chan = TCPChannel(app="serve-lat", codec=codec)
        client = ServeClient(chan, address)

        def lookup(hashes):
            return loop.run_until_complete(client.lookup_hashes(hashes))

        return lookup

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    lookup = loop.run_until_complete(run())
    outq.put(_time_latency(lookup, oracle, n_req))


def _time_latency(lookup, oracle, n_req: int) -> tuple[list[float], bool]:
    import numpy as np

    hashes = np.arange(1, dtype=np.uint32)
    for _ in range(16):  # warm the path
        lookup(hashes)
    lat, ok = [], True
    for i in range(n_req):
        hashes[0] = np.uint32(i * 2654435761 % (2**32))
        t0 = time.perf_counter()
        owners, _g = lookup(hashes)
        lat.append(time.perf_counter() - t0)
        ok = ok and int(owners[0]) == int(oracle.lookup_hashes(hashes)[0])
    return sorted(lat), ok


class ServiceThread:
    """The shared ring service on its own thread + event loop, listening
    on TCP and (optionally) the shared-memory request ring."""

    def __init__(self, store, *, codec: str = "json", max_batch: int = 8192,
                 flush_us: float = 0.0, inline_resolve_max: int = 4096,
                 journal=None, stats=None, journal_every: int = 64,
                 shm_slots: int = 0, shm_key_cap: int = 1 << 16,
                 shm_max_n: int = 4, ledger=None):
        from ringpop_tpu.serve.service import RingService

        self.store = store
        # ledger: a shared TransportLedger — the TCP channel accounts as
        # class "rpc" and the shm ring as class "shm" in ONE place
        self.ledger = ledger
        self.service = RingService(
            store, max_batch=max_batch, flush_us=flush_us,
            inline_resolve_max=inline_resolve_max, journal=journal,
            stats=stats, journal_every=journal_every,
        )
        self._codec = codec
        self._shm_slots = shm_slots
        self._shm_key_cap = shm_key_cap
        self._shm_max_n = shm_max_n
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self.hostport: Optional[str] = None
        self.shm_server = None
        self.channel = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        from ringpop_tpu.net import TCPChannel

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        chan = TCPChannel(app="serve", codec=self._codec, ledger=self.ledger)
        self.channel = chan

        async def boot():
            await chan.listen("127.0.0.1", 0)
            self.service.attach(chan)
            self.hostport = chan.hostport
            if self._shm_slots:
                from ringpop_tpu.serve.shm import ShmServer

                self.shm_server = ShmServer(
                    self.service, slots=self._shm_slots,
                    key_cap=self._shm_key_cap, max_n=self._shm_max_n,
                    ledger=self.ledger,
                )
                self.shm_server.attach(loop)
            self._started.set()

        loop.run_until_complete(boot())
        try:
            loop.run_forever()
        finally:
            if self.shm_server is not None:
                self.shm_server.close()
            loop.run_until_complete(chan.close())
            loop.close()

    def start(self) -> str:
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serve service thread failed to start")
        return self.hostport

    def shm_address(self):
        name, sock = self.shm_server.address
        return (name, sock, self._shm_slots, self._shm_key_cap, self._shm_max_n)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)


def run_ab(
    *,
    n_servers: int = 64,
    replica_points: int = 100,
    frontends: int = 4,
    batch: int = 4096,
    batches_per_rep: int = 8,
    reps: int = 3,
    warm_reps: int = 1,
    seed: int = 0,
    transport: str = "shm",
    codec: str = "json",
    flush_us: Optional[float] = None,
    max_batch: int = 65536,
    inline_resolve_max: int = 65536,
    latency_reqs: int = 200,
    journal=None,
    stats=None,
    placement: str = "random",
) -> dict:
    """The full paired A/B: returns the simbench-ready record payload."""
    import numpy as np

    from ringpop_tpu.serve.state import RingStore, serve_lookup

    if transport not in ("shm", "tcp"):
        raise ValueError(f"unknown transport {transport!r}")
    if placement != "random":
        # the bisect baseline and the post-update oracle answer from the
        # REFERENCE placement (HostBisectFrontend builds a default ring),
        # so a DGRO-placed device ring would fail the bit-identity
        # certificate by construction — DGRO quality is scored by the
        # placement report (simbench serve_ring), not by this A/B
        raise ValueError(
            "run_ab certifies against the reference placement; "
            f"placement={placement!r} would mis-certify a correct system"
        )
    if flush_us is None:
        # shm coalesces structurally (one slot scan picks up every posted
        # frontend), so it flushes on the next loop iteration; tcp needs
        # the latency trigger to collect requests still in flight
        flush_us = 0.0 if transport == "shm" else 200.0
    servers = [f"10.8.{i // 256}.{i % 256}:3000" for i in range(n_servers)]
    store = RingStore(
        servers, replica_points=replica_points, placement=placement
    )
    thread = ServiceThread(
        store, codec=codec, max_batch=max_batch, flush_us=flush_us,
        inline_resolve_max=inline_resolve_max, journal=journal, stats=stats,
        shm_slots=max(frontends, 1) if transport == "shm" else 0,
        shm_key_cap=max(1 << 16, batch),
    )
    hostport = thread.start()
    address = thread.shm_address() if transport == "shm" else hostport
    total_reps = warm_reps + reps
    try:
        # -- pre-warm the bounded pow-of-2 dispatch shape set -----------------
        # (the collector pads every coalesced flush to the next power of
        # two; compiling those shapes inside a measured rep would charge
        # XLA compile time to the serving tier).  Warm the FUSED program —
        # that is what the collector's n=1 flushes dispatch; serve_lookup
        # is a different jitted program with its own cache.
        import jax

        from ringpop_tpu.serve.state import serve_lookup_fused

        ring, gen0, _ = store.snapshot()
        size = 1
        while size <= min(frontends * batch * 2, max_batch):
            serve_lookup_fused(ring, jax.numpy.zeros(size, jax.numpy.uint32))
            size *= 2

        # -- direct-dispatch latency baseline (in-process, B=1) --------------
        one = np.zeros(1, np.uint32)
        direct = []
        for i in range(max(latency_reqs, 32)):
            one[0] = np.uint32(i * 2654435761 % (2**32))
            t0 = time.perf_counter()
            owners, _g = serve_lookup(ring, jax.numpy.asarray(one))
            np.asarray(owners)
            if i >= 16:  # first calls include compile
                direct.append(time.perf_counter() - t0)
        direct.sort()

        # -- the paired multi-process A/B ------------------------------------
        ctx = multiprocessing.get_context("spawn")
        barrier = ctx.Barrier(frontends + 1)
        outq = ctx.Queue()
        procs = [
            ctx.Process(
                target=_worker,
                args=(w, transport, address, servers, replica_points, gen0,
                      batch, batches_per_rep, total_reps, seed, codec,
                      barrier, outq),
            )
            for w in range(frontends)
        ]
        for p in procs:
            p.start()
        phase_walls: list[tuple[int, str, float]] = []
        for rep in range(total_reps):
            for mode in ("serve", "bisect"):
                barrier.wait()  # release the workers
                t0 = time.perf_counter()
                barrier.wait()  # all workers finished the phase
                phase_walls.append((rep, mode, time.perf_counter() - t0))
        results = [r for _ in procs for r in outq.get()]
        for p in procs:
            p.join(timeout=60)

        # -- reduce -----------------------------------------------------------
        keys_per_phase = frontends * batch * batches_per_rep
        agg = {}
        for rep, mode, wall in phase_walls:
            agg[(rep, mode)] = keys_per_phase / wall
        serve_qps = [agg[(r, "serve")] for r in range(warm_reps, total_reps)]
        bisect_qps = [agg[(r, "bisect")] for r in range(warm_reps, total_reps)]
        ratios = sorted(s / b for s, b in zip(serve_qps, bisect_qps))
        # the certificate: every (worker, rep) digest pair must match, and
        # every serve answer must have come from the pinned generation
        by_key = {}
        for r in results:
            by_key[(r["wid"], r["rep"], r["mode"])] = r
        digest_equal = all(
            by_key[(w, r, "serve")]["digest"] == by_key[(w, r, "bisect")]["digest"]
            for w in range(frontends)
            for r in range(total_reps)
        )
        gens = sorted(
            {g for r in results if r["mode"] == "serve" for g in r["gens"]}
        )

        # -- single-frontend degenerate case (B=1 through the transport) ----
        lat_q = ctx.Queue()
        lp = ctx.Process(
            target=_latency_worker,
            args=(transport, address, servers, replica_points, latency_reqs,
                  codec, lat_q),
        )
        lp.start()
        lat, lat_correct = lat_q.get(timeout=120)
        lp.join(timeout=60)

        # -- live-update certification: owners re-certify per generation ----
        upd = store.update(add=["10.9.0.1:3000"])
        probe = _batch_hashes(seed, 99, 0, 0, 256)
        ring2, gen2, _ = store.snapshot()
        dev_owned, dev_gen = serve_lookup(ring2, jax.numpy.asarray(probe))
        dev_owned = np.asarray(dev_owned)
        from ringpop_tpu.serve.client import HostBisectFrontend

        oracle2 = HostBisectFrontend(
            store.servers_at(gen2), replica_points
        ).lookup_hashes(probe)
        update_certified = bool(
            int(np.asarray(dev_gen)[0]) == gen2
            and gen2 == gen0 + 1
            and np.array_equal(dev_owned, oracle2)
        )

        t = thread.service.telemetry
        direct_p50 = direct[len(direct) // 2]
        lat_p50 = lat[len(lat) // 2]
        return {
            "transport": transport,
            "frontends": frontends,
            "n_servers": n_servers,
            "replica_points": replica_points,
            "batch": batch,
            "keys_per_rep_per_side": keys_per_phase,
            "codec": codec,
            "flush_us": flush_us,
            "max_batch": max_batch,
            "serve_qps_reps": sorted(round(q) for q in serve_qps),
            "bisect_qps_reps": sorted(round(q) for q in bisect_qps),
            "serve_qps_median": round(sorted(serve_qps)[len(serve_qps) // 2]),
            "bisect_qps_median": round(sorted(bisect_qps)[len(bisect_qps) // 2]),
            "ratio_reps": [round(r, 3) for r in ratios],
            "speedup_median": round(ratios[len(ratios) // 2], 3),
            "digest_equal": digest_equal,
            "generations_seen": gens,
            "generation_pinned": gens == [gen0],
            "update_certified": update_certified,
            "update_record": {
                k: upd[k] for k in ("gen", "n_servers", "count", "reallocated")
            } if upd else None,
            "latency_b1": {
                "direct_dispatch_p50_us": round(direct_p50 * 1e6, 1),
                "serve_p50_us": round(lat_p50 * 1e6, 1),
                "serve_p90_us": round(lat[int(len(lat) * 0.9)] * 1e6, 1),
                "ratio_p50": round(lat_p50 / direct_p50, 2),
                "owners_match_oracle": lat_correct,
            },
            "telemetry": {
                "flushes": t.flushes_total,
                "requests": t.requests_total,
                "keys": t.keys_total,
                "keys_per_flush_mean": round(
                    t.keys_total / max(t.flushes_total, 1), 1
                ),
            },
        }
    finally:
        thread.stop()
