"""Frontend halves of the serve tier.

``ServeClient`` is what a frontend process holds: it submits key-hash
batches to the shared ring service over any channel and resolves owner
ids to addresses through a cached per-generation server list (fetched
once per generation from ``/ring``).

``HostBisectFrontend`` is the per-process BASELINE the paired A/B prices:
the exact bisect walk the host plane does today (plain-int token list,
first token >= hash with wraparound — ``hashring._lookup_n_hash``'s n=1
fast path), rebuilt locally from the same server list, so its owner
decisions are bit-comparable to the device tier's per key and per
membership generation.
"""

from __future__ import annotations

import bisect
from typing import Optional

import numpy as np

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.net.channel import decode_array, encode_array


class ServeClient:
    """Frontend handle on a remote ``RingService``."""

    def __init__(self, channel, peer: str, *, timeout: float = 30.0):
        self.channel = channel
        self.peer = peer
        self.timeout = timeout
        self.codec = getattr(channel, "codec", "json")
        self._servers: dict[int, list[str]] = {}

    async def lookup_hashes(self, hashes: np.ndarray, n: int = 1):
        """(owners int32[B] or int32[B, n], generation) for a uint32 hash
        batch — one request, micro-batched server-side."""
        res = await self.channel.call(
            self.peer,
            "serve",
            "/lookup",
            {"h": encode_array(hashes, self.codec, "<u4"), "n": n},
            timeout=self.timeout,
        )
        owners = decode_array(res["o"], "<i4")
        if n > 1:
            owners = owners.reshape(-1, n)
        return owners, int(res["gen"])

    async def servers_at(self, gen: int) -> list[str]:
        """Server list of a generation (cached; one ``/ring`` fetch per
        new generation)."""
        if gen not in self._servers:
            res = await self.channel.call(
                self.peer, "serve", "/ring", {"gen": gen}, timeout=self.timeout
            )
            self._servers[int(res["gen"])] = res["servers"]
        return self._servers[gen]

    async def lookup(self, hashes: np.ndarray) -> list[Optional[str]]:
        """Resolved owner addresses for a hash batch (the convenience
        wrapper; the A/B drives :meth:`lookup_hashes` directly)."""
        owners, gen = await self.lookup_hashes(hashes)
        servers = await self.servers_at(gen)
        return [servers[o] if o >= 0 else None for o in owners]


class HostBisectFrontend:
    """The per-process baseline: local bisect walk over the same ring."""

    def __init__(self, servers: list[str], replica_points: int = 100):
        self.ring = HashRing(replica_points=replica_points)
        if servers:
            self.ring.add_remove_servers(list(servers), [])
        self._tokens = self.ring._tokens_list
        self._owners = self.ring._owners_list

    def lookup_hashes(self, hashes: np.ndarray) -> np.ndarray:
        """int32[B] owner ids — the scalar bisect walk per key, the host
        plane's data-path lookup as it exists today."""
        toks, owners = self._tokens, self._owners
        t = len(toks)
        out = np.empty(hashes.shape[0], np.int32)
        if t == 0:
            out.fill(-1)
            return out
        bl = bisect.bisect_left
        for i, h in enumerate(hashes.tolist()):
            idx = bl(toks, h)
            if idx == t:
                idx = 0
            out[i] = owners[idx]
        return out
