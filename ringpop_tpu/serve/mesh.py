"""Multi-host serve mesh: P serve processes, each owning a ring block.

The r13 serve tier scales FRONTENDS against one device-resident ring;
this module scales the serve tier itself: P serve processes each own a
contiguous block of the ring's token index space (the r14 partition
table's ``process_block`` rule — ``forward.batch.rank_of_hashes`` is the
key→rank map) and cross-forward mis-routed keys over the host-bridged
DCN fabric (``parallel/fabric.py``), so the whole mesh answers LookupN
preference lists at aggregate fan-in while every individual answer still
rides ONE fused device dispatch on the block owner.

Round structure (deterministic on every rank, the fabric contract):

1. each rank draws this round's key batch for the VIRTUAL STREAMS it
   hosts (streams are the workload unit: ``V`` streams exist at any P,
   stream ``s`` lives on rank ``s % P`` — so P∈{1,2,4} process the
   IDENTICAL total workload and the per-stream digests must agree);
2. request leg — keys are split by owning rank; every peer gets ONE
   coalesced request message per round (possibly empty — the schedule
   never depends on data), shipped via ``exchange_async`` so the local
   fused dispatch runs UNDER the inbound drain; message count per round
   is 2·(P-1) per rank regardless of key count — the O(owners), never
   O(keys), forwarding contract, priced in the returned records;
3. answer leg — the block owner answers local + forwarded keys through
   ``serve_lookup_n_fused`` (owners + generation, one transfer) and
   returns each peer's answers in one response message (the fused
   [B·n+1] vector verbatim — the generation travels with the owners);
4. every stream chains a fingerprint32 digest over (key hash, owner
   tuple, generation) in stream order.  At the end the per-stream
   digests allgather and combine in stream order — P-invariant by
   construction, so the P>1 mesh digest must equal the single-process
   oracle's bit-for-bit.  That equality is the certificate the simbench
   ``serve_fanin`` scenario and ``make serve-fanin-smoke`` assert.

Wire accounting comes straight off ``Fabric.wire_stats()`` (the r15
codec is available to every forwarded batch; random key hashes are
incompressible so the measured-raw fallback is the honest common case,
and the split wire/raw counters prove nothing is hidden).

Span tracing (r20): pass ``tracer=`` an ``obs.trace.Tracer`` and every
round's cross-forwarded batch emits ``mesh_request`` (sender side) and
``mesh_answer`` (owner side) spans for its sampled keys.  NO header
crosses the fabric — both sides derive the SAME trace and span ids from
the batch content + the deterministic (round, sender, owner) salt, so
the answer span's ``parent`` is computed, not propagated, and the
journal join works exactly as it does on the channel path.  Answer spans
carry ``gen``, joinable against the serve tier's ``ring_update``
records.  Host-plane only: digests are bit-identical tracer-on vs off
(pinned by ``tests/test_serve_mesh.py`` and the trace smoke).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ringpop_tpu.forward.batch import rank_of_hashes
from ringpop_tpu.hashing import fingerprint32
from ringpop_tpu.parallel.fabric import Fabric, LocalKV
from ringpop_tpu.parallel.partition import process_block

# fabric tags: round in the high bits, leg in the low byte (the
# delta_multihost convention); the digest allgather keeps its own space
_TAG_REQ = 0x10
_TAG_RESP = 0x20
_TAG_DIGEST = 0x7FFF0000


def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 1).bit_length() if x > 2 else max(int(x), 1)


def _stream_hashes(seed: int, stream: int, rnd: int, batch: int) -> np.ndarray:
    rng = np.random.default_rng(seed + stream * 1_000_003 + rnd * 1009)
    return rng.integers(0, 2**32, size=batch, dtype=np.uint32)


def _digest_chain(digest: int, hashes, owners, gen: int) -> int:
    payload = (
        digest.to_bytes(4, "little")
        + np.ascontiguousarray(hashes, np.uint32).tobytes()
        + np.ascontiguousarray(owners, np.int32).tobytes()
        + int(gen).to_bytes(4, "little", signed=False)
    )
    return fingerprint32(payload)


class ServeMesh:
    """One rank's endpoint of the serve mesh (thread- or process-hosted;
    the fabric's KV decides — LocalKV threads in tests/simbench, the
    jax.distributed client on a real multi-host job)."""

    def __init__(
        self,
        rank: int,
        nprocs: int,
        servers: list[str],
        *,
        replica_points: int = 100,
        n: int = 3,
        streams: int = 4,
        seed: int = 0,
        kv=None,
        namespace: str = "serve-mesh",
        codec: bool = True,
        timeout_ms: int = 60_000,
        gen: int = 0,
        tracer=None,
        ledger=None,
    ):
        if streams % nprocs:
            raise ValueError(
                f"streams={streams} must divide over {nprocs} ranks so every "
                "P processes the identical workload"
            )
        from ringpop_tpu.ops.ring_ops import build_ring_tokens
        from ringpop_tpu.serve.state import device_ring

        self.rank, self.nprocs = rank, nprocs
        self.n = n
        self.seed = seed
        self.streams = streams
        self.my_streams = [s for s in range(streams) if s % nprocs == rank]
        self.n_servers = len(servers)
        toks, owns = build_ring_tokens(servers, replica_points)
        self.tokens = np.asarray(toks, np.uint32)
        self.owners = np.asarray(owns, np.int32)
        self.gen = gen
        count = int(self.tokens.shape[0])
        # the block this rank owns — the r14 equal-block rule over the
        # token index space (refuses non-divisible counts the same way)
        self.block = process_block(count, rank, nprocs)
        self.ring = device_ring(self.tokens, self.owners, _next_pow2(2 * count),
                                gen=gen)
        # r21: mesh exchange bytes account into the merged TransportLedger
        # under class "exchange"; pass a shared ledger for one cross-plane
        # byte view (wire_stats() keeps its legacy per-fabric shape)
        self.fabric = Fabric(
            rank, nprocs, kv if kv is not None else LocalKV(),
            namespace=namespace, codec=codec, timeout_ms=timeout_ms,
            ledger=ledger,
        )
        self.ledger = self.fabric.ledger
        self.tracer = tracer
        self.keys_local = 0
        self.keys_forwarded_out = 0
        self.keys_answered_for_peers = 0
        self.messages_sent = 0
        self._digests = {s: 0 for s in self.my_streams}

    # -- the fused local answer ----------------------------------------------

    def _answer(self, hashes: np.ndarray) -> np.ndarray:
        """int32[B, n] owner tuples for ``hashes`` through the fused
        device dispatch (pow-2 padded so the compiled-shape set is
        bounded, exactly like the r13 collector)."""
        import jax.numpy as jnp

        from ringpop_tpu.serve.state import serve_lookup_n_fused

        b = int(hashes.shape[0])
        if b == 0:
            return np.empty((0, self.n), np.int32)
        p2 = _next_pow2(b)
        padded = np.zeros(p2, np.uint32)
        padded[:b] = hashes
        fused = np.asarray(
            serve_lookup_n_fused(
                self.ring, self.n_servers, jnp.asarray(padded), self.n
            )
        )
        if int(fused[-1]) != self.gen:
            # a hard raise, not an assert: this guards the digest
            # certificate itself (a ring/gen divergence here would embed
            # the same wrong generation in BOTH twin runs and pass the
            # equality check), so it must survive python -O
            raise RuntimeError(
                f"rank {self.rank}: device ring answered generation "
                f"{int(fused[-1])} but this rank is at {self.gen}"
            )
        return fused[: b * self.n].reshape(b, self.n)

    # -- one mesh round --------------------------------------------------------

    def round(self, rnd: int, keys_per_stream: int) -> None:
        """Draw, route, cross-forward, answer and digest one round."""
        peers = [p for p in range(self.nprocs) if p != self.rank]
        stream_hashes = {
            s: _stream_hashes(self.seed, s, rnd, keys_per_stream)
            for s in self.my_streams
        }
        # split every stream's keys by owning rank; remember positions so
        # answers reassemble in stream order
        sends: dict[int, list[np.ndarray]] = {p: [np.empty(0, np.uint32)] for p in peers}
        pending: dict[int, list[tuple[int, np.ndarray]]] = {p: [] for p in peers}
        local_parts: list[tuple[int, np.ndarray, np.ndarray]] = []
        for s, hashes in stream_hashes.items():
            ranks = rank_of_hashes(self.tokens, hashes, self.nprocs)
            mine = ranks == self.rank
            if mine.any():
                local_parts.append((s, np.flatnonzero(mine), hashes[mine]))
            for p in peers:
                ix = np.flatnonzero(ranks == p)
                if ix.size:
                    pending[p].append((s, ix))
        for p in peers:
            if pending[p]:
                sends[p] = [
                    np.concatenate(
                        [stream_hashes[s][ix] for s, ix in pending[p]]
                    ).astype(np.uint32)
                ]
        # mesh_request spans for sampled keys in each outbound batch —
        # begun BEFORE the exchange so the span times the full
        # frontend → owner → answer round trip; ids are pure functions
        # of (content, rnd, sender, dest), so the owner derives them
        # without any header crossing the fabric
        req_spans: dict[int, object] = {}
        if self.tracer is not None:
            from ringpop_tpu.obs.trace import salt_of

            for p in peers:
                batch = sends[p][0]
                if batch.shape[0]:
                    sp = self.tracer.begin(
                        "mesh_request", batch,
                        salt=salt_of("mesh", rnd, self.rank, p),
                        rank=self.rank, dest=p, rnd=rnd,
                    )
                    if sp is not None:
                        req_spans[p] = sp
        tag_req = (rnd << 8) | _TAG_REQ
        h_req = self.fabric.exchange_async(tag_req, sends, peers)
        self.messages_sent += len(peers)
        self.keys_forwarded_out += sum(int(a[0].shape[0]) for a in sends.values())

        # the local fused dispatch runs while the request leg drains
        answers: dict[int, np.ndarray] = {
            s: np.full((keys_per_stream, self.n), -1, np.int32)
            for s in self.my_streams
        }
        gens: dict[int, np.ndarray] = {
            s: np.full(keys_per_stream, self.gen, np.int32)
            for s in self.my_streams
        }
        for s, ix, hashes in local_parts:
            rows = self._answer(hashes)
            answers[s][ix] = rows
            self.keys_local += int(hashes.shape[0])

        got = h_req.wait(join_sends=False)
        # answer every peer's forwarded batch — ONE fused dispatch per
        # peer, the response is the fused vector verbatim (gen rides it)
        resp: dict[int, list[np.ndarray]] = {}
        for p in peers:
            req = got[p][0]
            b = int(req.shape[0])
            self.keys_answered_for_peers += b
            if b == 0:
                resp[p] = [np.empty(0, np.int32)]
                continue
            answer_span = None
            if self.tracer is not None:
                from ringpop_tpu.obs.trace import (
                    salt_of,
                    span_id_of,
                    trace_id_of,
                )

                keys = self.tracer.sampled_keys(np.asarray(req, np.uint32))
                if keys.size:
                    # the parent is the SENDER's mesh_request span id,
                    # derived (not propagated): same trace, the sender's
                    # (rnd, src=p, dest=me) salt
                    trace = trace_id_of(int(keys[0]))
                    answer_span = self.tracer.begin(
                        "mesh_answer", np.asarray(req, np.uint32),
                        parent=span_id_of(
                            trace, "mesh_request",
                            salt_of("mesh", rnd, p, self.rank),
                        ),
                        salt=salt_of("mesha", rnd, self.rank, p),
                        rank=self.rank, src=p, rnd=rnd,
                    )
            rows = self._answer(np.asarray(req, np.uint32))
            if answer_span is not None:
                answer_span.finish(gen=self.gen, answered=b)
            resp[p] = [
                np.concatenate(
                    [rows.reshape(-1), np.asarray([self.gen], np.int32)]
                )
            ]
        tag_resp = (rnd << 8) | _TAG_RESP
        h_resp = self.fabric.exchange_async(tag_resp, resp, peers)
        self.messages_sent += len(peers)
        got_resp = h_resp.wait(join_sends=False)
        for p in peers:
            vec = got_resp[p][0]
            if vec.shape[0] == 0:
                if pending[p]:
                    raise RuntimeError(
                        f"rank {self.rank}: peer {p} answered 0 keys for a "
                        f"non-empty forwarded batch"
                    )
                continue
            peer_gen = int(vec[-1])
            rows = np.asarray(vec[:-1], np.int32).reshape(-1, self.n)
            sp = req_spans.get(p)
            if sp is not None:
                sp.finish(gen=peer_gen, answered=rows.shape[0])
            off = 0
            for s, ix in pending[p]:
                answers[s][ix] = rows[off : off + ix.size]
                gens[s][ix] = peer_gen
                off += ix.size
        # chain the per-stream digests: (hashes, owner tuples, gen) in
        # stream order — the P-invariant certificate payload
        for s in self.my_streams:
            g = int(gens[s][0]) if keys_per_stream else self.gen
            if keys_per_stream and not (gens[s] == g).all():
                raise RuntimeError(
                    f"rank {self.rank}: stream {s} answered from mixed "
                    f"generations {sorted(set(gens[s].tolist()))}"
                )
            self._digests[s] = _digest_chain(
                self._digests[s], stream_hashes[s], answers[s], g
            )

    # -- the run + certificate -------------------------------------------------

    def run(self, rounds: int, keys_per_stream: int) -> dict:
        t0 = time.perf_counter()
        for rnd in range(rounds):
            self.round(rnd, keys_per_stream)
        wall = time.perf_counter() - t0
        # every stream's digest, allgathered and combined in stream order
        mine = np.asarray(
            [[s, self._digests[s]] for s in self.my_streams], np.uint32
        ).reshape(len(self.my_streams), 2)
        gathered = self.fabric.allgather(_TAG_DIGEST, mine)
        by_stream = {}
        for block in gathered:
            for s, d in np.asarray(block, np.uint32).reshape(-1, 2):
                by_stream[int(s)] = int(d)
        combined = fingerprint32(
            b"".join(
                by_stream[s].to_bytes(4, "little") for s in range(self.streams)
            )
        )
        keys_total = len(self.my_streams) * rounds * keys_per_stream
        return {
            "rank": self.rank,
            "nprocs": self.nprocs,
            "rounds": rounds,
            "streams": self.my_streams,
            "digest": combined,
            "stream_digests": by_stream,
            "wall_s": round(wall, 4),
            "keys_total": keys_total,
            "keys_per_s": round(keys_total / max(wall, 1e-9)),
            "keys_local": self.keys_local,
            "keys_forwarded_out": self.keys_forwarded_out,
            "keys_answered_for_peers": self.keys_answered_for_peers,
            "messages_sent": self.messages_sent,
            # O(owners) pricing: the naive plane ships one message per
            # forwarded KEY; the mesh ships 2(P-1) per round per rank
            "messages_naive": 2 * self.keys_forwarded_out,
            "wire": self.fabric.wire_stats(),
        }

    def close(self) -> None:
        self.fabric.close()


def run_serve_mesh(
    nprocs: int,
    *,
    n_servers: int = 16,
    replica_points: int = 100,
    n: int = 3,
    streams: int = 4,
    rounds: int = 4,
    keys_per_stream: int = 2048,
    seed: int = 0,
    codec: bool = True,
    namespace: Optional[str] = None,
    trace_sink=None,
    trace_sample: int = 64,
) -> list[dict]:
    """Drive a P-rank serve mesh on LocalKV threads (the same fabric code
    paths real OS processes run — r14's threaded-twin discipline) and
    return the per-rank records.  The caller asserts the certificate:
    every rank's combined digest equal, and equal to the P=1 oracle's."""
    import threading

    if streams % nprocs:
        raise ValueError(
            f"streams={streams} must divide over {nprocs} ranks so every "
            "P processes the identical workload"
        )
    servers = [f"10.21.{i // 256}.{i % 256}:3000" for i in range(n_servers)]
    kv = LocalKV()
    ns = namespace or f"serve-mesh-{nprocs}-{seed}"
    out: list[Optional[dict]] = [None] * nprocs
    errs: list[Optional[BaseException]] = [None] * nprocs

    def worker(rank: int) -> None:
        mesh = None
        try:
            tracer = None
            if trace_sink is not None:
                from ringpop_tpu.obs.trace import Tracer

                # one Tracer per rank (rank stamped on every span); the
                # sink must be thread-safe — JsonlSink locks, lists
                # under the test harness are append-only per CPython
                tracer = Tracer(trace_sink, sample=trace_sample, rank=rank)
            mesh = ServeMesh(
                rank, nprocs, servers, replica_points=replica_points, n=n,
                streams=streams, seed=seed, kv=kv, namespace=ns, codec=codec,
                tracer=tracer,
            )
            out[rank] = mesh.run(rounds, keys_per_stream)
        except BaseException as e:  # noqa: BLE001 - surfaced to the driver
            errs[rank] = e
        finally:
            if mesh is not None:
                mesh.close()

    threads = [
        threading.Thread(target=worker, args=(r,), name=f"serve-mesh-{r}")
        for r in range(nprocs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for r, e in enumerate(errs):
        if e is not None:
            raise RuntimeError(f"serve-mesh rank {r} failed") from e
    if any(rec is None for rec in out):
        raise RuntimeError("serve-mesh worker hung")
    return out  # type: ignore[return-value]
