"""DGRO-style token placement: diameter/spread-guided, churn-scored.

DGRO (PAPERS.md, arxiv 2410.11142) optimizes ring memberships by
diameter-guided search over candidate orderings; the analog for a
consistent-hash ring is TOKEN PLACEMENT: the reference's random replica
points (``farm32(addr + i)``) leave both large uncovered arcs (the ring
"diameter" — the largest token gap, which one owner's load scales with)
and load imbalance.  This pass scores a small family of candidate
placements as ONE batched device computation and picks the best — guided
by diameter and spread, gated by the same key-movement-under-churn metric
the ``ring1m`` churn-rebalance harness measures, so a candidate can never
win by sacrificing the consistent-hashing property the ring exists for.

Candidate family: per-(server, replica) re-mixes ``mix32(base ^ salt_c)``
of the default farm tokens.  Candidate 0 is the UNMODIFIED default
placement, and each candidate's tokens depend only on (server address,
replica index, salt) — membership churn never moves a surviving server's
tokens under any fixed candidate, so the scoring differences are pure
placement quality.  Scores per candidate (all computed on device, vmapped
over the candidate axis):

* ``movement`` — fraction of probe keys whose owner changes when a churn
  cohort is removed (the ring1m rebalance metric).  Minimal movement
  equals the cohort's load share, so this doubles as load-under-churn.
* ``excess`` — moved keys whose OLD owner survived the churn: nonzero
  means the placement broke consistent hashing (asserted zero in tests).
* ``imbalance`` — max/mean owner load over the probe set.
* ``diameter`` — largest uncovered arc (max token gap incl. wraparound),
  as a fraction of the hash space.

Selection: among candidates whose ``movement`` does not exceed candidate
0's (the acceptance gate: never worse than random replica placement at
equal token count), minimize ``imbalance`` then ``diameter``.  Opt-in:
``RingStore(placement="dgro")``; the default serving path never runs it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashing import ring_tokens
from ringpop_tpu.sim.packbits import mix32

_SALT_STRIDE = np.uint32(0x9E37_79B9)


def _candidate_tokens(base: jax.Array, salt: jax.Array) -> jax.Array:
    """uint32[T] tokens of one candidate: salt 0 = the default placement,
    else a full-avalanche re-mix keyed on (base token, salt) only."""
    return jnp.where(salt == 0, base, mix32(base ^ salt))


@functools.partial(jax.jit, static_argnames=())
def _score_candidates(base, owners, salts, probes, cohort):
    """Per-candidate (movement, excess, imbalance, diameter) — one
    batched program over the candidate axis.

    base: uint32[T] default tokens (owner-major, replica-minor order);
    owners: int32[T]; salts: uint32[C]; probes: uint32[P];
    cohort: bool[S] — servers removed by the churn probe.
    """
    t = base.shape[0]
    n_servers = cohort.shape[0]
    space = jnp.float32(2.0**32)

    def one(salt):
        toks = _candidate_tokens(base, salt)
        # stable argsort == the host composite (token, owner) order:
        # the flat layout is owner-ascending, so ties keep owner order
        order = jnp.argsort(toks, stable=True)
        st, so = toks[order], owners[order]

        def lookup(sorted_toks, sorted_owners, live_t):
            idx = jnp.searchsorted(sorted_toks, probes, side="left")
            idx = jnp.where(idx >= live_t, 0, idx)
            return sorted_owners[idx]

        before = lookup(st, so, t)  # [P]
        # churn: push the cohort's tokens past the live region and re-sort
        dead = cohort[so]
        toks_after = jnp.where(dead, jnp.uint32(0xFFFF_FFFF), st)
        order2 = jnp.argsort(toks_after, stable=True)
        st2, so2 = toks_after[order2], so[order2]
        live_t = t - dead.sum()
        after = lookup(st2, so2, live_t)

        moved = before != after
        movement = moved.mean(dtype=jnp.float32)
        excess = (moved & ~cohort[before]).mean(dtype=jnp.float32)
        loads = jnp.zeros(n_servers, jnp.float32).at[before].add(1.0)
        imbalance = loads.max() * n_servers / jnp.float32(probes.shape[0])
        if t > 1:
            gaps = st[1:] - st[:-1]
            wrap = st[0] + (jnp.uint32(0xFFFF_FFFF) - st[-1]) + jnp.uint32(1)
            diameter = jnp.maximum(gaps.max(), wrap).astype(jnp.float32) / space
        else:  # a single token owns the whole ring
            diameter = jnp.float32(1.0)
        return movement, excess, imbalance, diameter

    return jax.vmap(one)(salts)


@functools.partial(jax.jit, static_argnames=())
def _materialize(base, owners, salt):
    """The chosen candidate's (sorted tokens, sorted owners)."""
    toks = _candidate_tokens(base, salt)
    order = jnp.argsort(toks, stable=True)
    return toks[order], owners[order]


def dgro_place(
    servers: list[str],
    replica_points: int,
    *,
    candidates: int = 8,
    probes: int = 1 << 15,
    churn_frac: float = 0.01,
    seed: int = 0,
    fixed_salt: int | None = None,
):
    """(tokens uint32[T], owners int32[T], report) — the DGRO pass.

    ``fixed_salt`` replays a previously chosen candidate without
    re-scoring — the sticky mode ``RingStore`` uses after its first
    placement so membership churn never flips candidates mid-flight
    (a flip would move every token, exactly what the movement gate
    exists to prevent).
    """
    s = len(servers)
    base = jnp.asarray(
        ring_tokens(servers, replica_points).reshape(-1).astype(np.uint32)
    )
    owners = jnp.asarray(
        np.repeat(np.arange(s, dtype=np.int32), replica_points)
    )
    if fixed_salt is not None:
        st, so = _materialize(base, owners, jnp.uint32(fixed_salt))
        return (
            np.asarray(st),
            np.asarray(so),
            {"salt": int(fixed_salt), "rescored": False},
        )
    rng = np.random.default_rng(seed)
    salt_arr = (np.arange(candidates, dtype=np.uint64) * _SALT_STRIDE).astype(
        np.uint32
    )
    probe_arr = rng.integers(0, 2**32, size=probes, dtype=np.uint32)
    m = max(1, int(round(churn_frac * s))) if s > 1 else 0
    cohort = np.zeros(s, bool)
    if m:
        cohort[rng.choice(s, size=m, replace=False)] = True
    movement, excess, imbalance, diameter = (
        np.asarray(a)
        for a in _score_candidates(
            base, owners, jnp.asarray(salt_arr), jnp.asarray(probe_arr),
            jnp.asarray(cohort),
        )
    )
    # the gate: never worse than random (candidate 0) on churn movement;
    # then diameter/spread-guided among the eligible
    eligible = movement <= movement[0] + 1e-9
    score = np.where(eligible, imbalance + diameter, np.inf)
    chosen = int(np.argmin(score))
    st, so = _materialize(base, owners, jnp.uint32(salt_arr[chosen]))
    report = {
        "chosen": chosen,
        "salt": int(salt_arr[chosen]),
        "rescored": True,
        "candidates": candidates,
        "probes": probes,
        "churn_cohort": int(m),
        "movement": [round(float(v), 6) for v in movement],
        "excess_movement": [round(float(v), 6) for v in excess],
        "imbalance": [round(float(v), 4) for v in imbalance],
        "diameter": [round(float(v), 6) for v in diameter],
        "movement_random": round(float(movement[0]), 6),
        "movement_chosen": round(float(movement[chosen]), 6),
        "imbalance_random": round(float(imbalance[0]), 4),
        "imbalance_chosen": round(float(imbalance[chosen]), 4),
    }
    return np.asarray(st), np.asarray(so), report


def key_movement(
    tokens_a, owners_a, servers_a: list[str],
    tokens_b, owners_b, servers_b: list[str],
    hashes,
) -> dict:
    """Key movement between two ring snapshots over a probe hash batch —
    the ring1m churn-rebalance metric, shared with the DGRO scorer.

    Owner ids are matched ACROSS snapshots through the server lists (ids
    renumber on membership change), so ``moved`` counts real ownership
    transfers.  ``excess_moved`` is the consistent-hashing violation
    count: keys that moved between two servers present in BOTH snapshots
    (always 0 for identity-keyed token placement)."""
    from ringpop_tpu.ops.ring_ops import ring_lookup

    oa = np.asarray(ring_lookup(jnp.asarray(tokens_a), jnp.asarray(owners_a), hashes))
    ob = np.asarray(ring_lookup(jnp.asarray(tokens_b), jnp.asarray(owners_b), hashes))
    index_a = {srv: i for i, srv in enumerate(servers_a)}
    # b-id -> a-id (or -1 for servers new in b)
    b_to_a = np.array([index_a.get(srv, -1) for srv in servers_b], np.int64)
    survivors_a = np.zeros(len(servers_a), bool)
    survivors_a[b_to_a[b_to_a >= 0]] = True
    ob_in_a = b_to_a[ob]
    moved = ob_in_a != oa
    excess = moved & survivors_a[oa] & (ob_in_a >= 0)
    return {
        "probes": int(oa.shape[0]),
        "moved_frac": round(float(moved.mean()), 6),
        "excess_moved": int(excess.sum()),
        "removed_load_frac": round(float((~survivors_a[oa]).mean()), 6),
    }
