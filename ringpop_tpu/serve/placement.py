"""DGRO-style token placement: diameter/spread-guided, churn-scored.

DGRO (PAPERS.md, arxiv 2410.11142) optimizes ring memberships by
diameter-guided search over candidate orderings; the analog for a
consistent-hash ring is TOKEN PLACEMENT: the reference's random replica
points (``farm32(addr + i)``) leave both large uncovered arcs (the ring
"diameter" — the largest token gap, which one owner's load scales with)
and load imbalance.  This pass scores a small family of candidate
placements as ONE batched device computation and picks the best — guided
by diameter and spread, gated by the same key-movement-under-churn metric
the ``ring1m`` churn-rebalance harness measures, so a candidate can never
win by sacrificing the consistent-hashing property the ring exists for.

Candidate family (r17 widened): per-(server, replica) re-mixes
``mix32(base ^ salt_c)`` of the default farm tokens, PLUS
diameter-guided LOCAL MOVES (the DGRO-paper analog of local search
steps): for each move count ``m`` in ``local_moves``, the tokens
adjacent to the ``m`` SMALLEST arcs relocate to the midpoints of the
``m`` LARGEST arcs — shrinking the ring diameter by spending tokens
whose removal costs least.  A move is recorded as a sticky
(server address, replica index) → token OVERRIDE chosen once at scoring
time and replayed VERBATIM on later membership changes, so — exactly
like the salt family — a surviving server's tokens never move under a
fixed candidate and churn movement stays pure placement quality (a dead
server's overrides vanish with its tokens; consistent hashing is
preserved by construction, asserted by the ``excess`` score).
Candidate 0 is the UNMODIFIED default placement.  Scores per candidate
(all computed on device, vmapped over the candidate axis):

* ``movement`` — fraction of probe keys whose owner changes when a churn
  cohort is removed (the ring1m rebalance metric).  Minimal movement
  equals the cohort's load share, so this doubles as load-under-churn.
* ``excess`` — moved keys whose OLD owner survived the churn: nonzero
  means the placement broke consistent hashing (asserted zero in tests).
* ``imbalance`` — max/mean owner load over the probe set.
* ``diameter`` — largest uncovered arc (max token gap incl. wraparound),
  as a fraction of the hash space.

Selection: among candidates whose ``movement`` does not exceed candidate
0's (the acceptance gate: never worse than random replica placement at
equal token count), minimize ``imbalance`` then ``diameter``.  Opt-in:
``RingStore(placement="dgro")``; the default serving path never runs it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashing import ring_tokens
from ringpop_tpu.sim.packbits import mix32

_SALT_STRIDE = np.uint32(0x9E37_79B9)


def _candidate_tokens(base: jax.Array, salt: jax.Array) -> jax.Array:
    """uint32[T] tokens of one candidate: salt 0 = the default placement,
    else a full-avalanche re-mix keyed on (base token, salt) only."""
    return jnp.where(salt == 0, base, mix32(base ^ salt))


@functools.partial(jax.jit, static_argnames=())
def _score_candidates(cand_tokens, owners, probes, cohort):
    """Per-candidate (movement, excess, imbalance, diameter) — one
    batched program over the candidate axis.

    cand_tokens: uint32[C, T] candidate token values in the flat
    owner-major, replica-minor layout (salt re-mixes and local-move
    overrides alike — the scorer is family-agnostic);
    owners: int32[T]; probes: uint32[P];
    cohort: bool[S] — servers removed by the churn probe.
    """
    t = cand_tokens.shape[1]
    n_servers = cohort.shape[0]
    space = jnp.float32(2.0**32)

    def one(toks):
        # stable argsort == the host composite (token, owner) order:
        # the flat layout is owner-ascending, so ties keep owner order
        order = jnp.argsort(toks, stable=True)
        st, so = toks[order], owners[order]

        def lookup(sorted_toks, sorted_owners, live_t):
            idx = jnp.searchsorted(sorted_toks, probes, side="left")
            idx = jnp.where(idx >= live_t, 0, idx)
            return sorted_owners[idx]

        before = lookup(st, so, t)  # [P]
        # churn: push the cohort's tokens past the live region and re-sort
        dead = cohort[so]
        toks_after = jnp.where(dead, jnp.uint32(0xFFFF_FFFF), st)
        order2 = jnp.argsort(toks_after, stable=True)
        st2, so2 = toks_after[order2], so[order2]
        live_t = t - dead.sum()
        after = lookup(st2, so2, live_t)

        moved = before != after
        movement = moved.mean(dtype=jnp.float32)
        excess = (moved & ~cohort[before]).mean(dtype=jnp.float32)
        loads = jnp.zeros(n_servers, jnp.float32).at[before].add(1.0)
        imbalance = loads.max() * n_servers / jnp.float32(probes.shape[0])
        if t > 1:
            gaps = st[1:] - st[:-1]
            wrap = st[0] + (jnp.uint32(0xFFFF_FFFF) - st[-1]) + jnp.uint32(1)
            diameter = jnp.maximum(gaps.max(), wrap).astype(jnp.float32) / space
        else:  # a single token owns the whole ring
            diameter = jnp.float32(1.0)
        return movement, excess, imbalance, diameter

    return jax.vmap(one)(cand_tokens)


def _flat_tokens(base, salt: int) -> np.ndarray:
    """Host copy of one salt candidate's tokens in the flat layout."""
    return np.asarray(_candidate_tokens(base, jnp.uint32(salt)))


def _apply_moves(flat: np.ndarray, owners: np.ndarray, m: int):
    """Diameter-guided local moves on one candidate's flat tokens: the
    tokens bounding the ``m`` smallest arcs move to the midpoints of the
    ``m`` largest arcs.  Returns (new flat tokens, overrides) where
    overrides maps FLAT index -> new token value — the caller re-keys by
    (server, replica) identity for sticky replay."""
    from ringpop_tpu.ops.ring_ops import ring_composite_order

    t = flat.shape[0]
    m = int(min(m, max(t // 2 - 1, 0)))
    if m == 0:
        return flat, {}
    order = ring_composite_order(flat, owners)
    st = flat[order]
    gaps = np.empty(t, np.uint64)  # gap i: arc ABOVE sorted token i
    gaps[:-1] = st[1:].astype(np.uint64) - st[:-1].astype(np.uint64)
    gaps[-1] = (np.uint64(1 << 32) + st[0].astype(np.uint64)
                - st[-1].astype(np.uint64))
    big = np.argsort(gaps, kind="stable")[-m:]          # arcs to fill
    small = np.argsort(gaps, kind="stable")[: 2 * m]    # donor pool
    # donors: the token CLOSING each small arc (its own arc is tiny, so
    # relocating it moves the least key mass) — skipping any donor that
    # bounds a chosen large arc
    banned = set(big.tolist()) | {int((b + 1) % t) for b in big}
    donors = [int((s + 1) % t) for s in small if int((s + 1) % t) not in banned]
    donors = donors[:m]
    out = flat.copy()
    overrides = {}
    for d_sorted, g in zip(donors, sorted(big.tolist(), key=lambda i: -int(gaps[i]))):
        mid = (st[g].astype(np.uint64) + gaps[g] // np.uint64(2)) & np.uint64(
            0xFFFFFFFF
        )
        fi = int(order[d_sorted])  # back to the flat (server, replica) slot
        out[fi] = np.uint32(mid)
        overrides[fi] = int(mid)
    return out, overrides


def _materialize_flat(flat: np.ndarray, owners: np.ndarray):
    """(sorted tokens, sorted owners) of one candidate's flat tokens —
    the host composite (token, owner) collision order
    (``ring_ops.ring_composite_order``, the one shared rule)."""
    from ringpop_tpu.ops.ring_ops import ring_composite_order

    order = ring_composite_order(flat, owners)
    return flat[order].astype(np.uint32), owners[order].astype(np.int32)


def _apply_overrides(
    flat: np.ndarray, servers: list[str], replica_points: int, moves: dict
) -> np.ndarray:
    """Replay sticky ``(server, replica) -> token`` overrides onto the
    flat token layout of the CURRENT server set — overrides of departed
    servers vanish with their tokens, surviving ones keep their exact
    values (zero replay movement by construction)."""
    if not moves:
        return flat
    index = {srv: i for i, srv in enumerate(servers)}
    out = flat.copy()
    for (srv, rep), tok in moves.items():
        i = index.get(srv)
        if i is not None and 0 <= rep < replica_points:
            out[i * replica_points + rep] = np.uint32(tok)
    return out


def dgro_place(
    servers: list[str],
    replica_points: int,
    *,
    candidates: int = 8,
    local_moves: tuple = (1, 2, 4, 8),
    probes: int = 1 << 15,
    churn_frac: float = 0.01,
    seed: int = 0,
    fixed_salt: int | None = None,
    fixed_moves: dict | None = None,
):
    """(tokens uint32[T], owners int32[T], report) — the DGRO pass.

    ``fixed_salt``/``fixed_moves`` replay a previously chosen candidate
    without re-scoring — the sticky mode ``RingStore`` uses after its
    first placement so membership churn never flips candidates mid-flight
    (a flip would move every token, exactly what the movement gate
    exists to prevent).  ``local_moves`` widens the family with
    diameter-guided local token moves on top of the default placement
    (``()`` restores the salt-only r13 family).
    """
    s = len(servers)
    base = jnp.asarray(
        ring_tokens(servers, replica_points).reshape(-1).astype(np.uint32)
    )
    owners_np = np.repeat(np.arange(s, dtype=np.int32), replica_points)
    owners = jnp.asarray(owners_np)
    if fixed_salt is not None:
        flat = _flat_tokens(base, fixed_salt)
        flat = _apply_overrides(flat, servers, replica_points, fixed_moves or {})
        st, so = _materialize_flat(flat, owners_np)
        return (
            st,
            so,
            {
                "salt": int(fixed_salt),
                "moves": dict(fixed_moves or {}),
                "rescored": False,
            },
        )
    rng = np.random.default_rng(seed)
    salt_arr = (np.arange(candidates, dtype=np.uint64) * _SALT_STRIDE).astype(
        np.uint32
    )
    # the family: salt re-mixes (candidate 0 = the reference placement),
    # then diameter-guided local-move variants of the DEFAULT placement
    family: list[dict] = [{"salt": int(v), "moves": {}} for v in salt_arr]
    flats = [_flat_tokens(base, int(v)) for v in salt_arr]
    base_flat = flats[0]
    for mcount in local_moves:
        moved, ov = _apply_moves(base_flat, owners_np, int(mcount))
        if not ov:
            continue
        flats.append(moved)
        family.append(
            {
                "salt": 0,
                "moves": {
                    (servers[fi // replica_points], fi % replica_points): tok
                    for fi, tok in ov.items()
                },
                "local_moves": int(mcount),
            }
        )
    probe_arr = rng.integers(0, 2**32, size=probes, dtype=np.uint32)
    m = max(1, int(round(churn_frac * s))) if s > 1 else 0
    cohort = np.zeros(s, bool)
    if m:
        cohort[rng.choice(s, size=m, replace=False)] = True
    movement, excess, imbalance, diameter = (
        np.asarray(a)
        for a in _score_candidates(
            jnp.asarray(np.stack(flats)), owners, jnp.asarray(probe_arr),
            jnp.asarray(cohort),
        )
    )
    # the gate: never worse than random (candidate 0) on churn movement;
    # then diameter/spread-guided among the eligible
    eligible = movement <= movement[0] + 1e-9
    score = np.where(eligible, imbalance + diameter, np.inf)
    chosen = int(np.argmin(score))
    st, so = _materialize_flat(flats[chosen], owners_np)
    report = {
        "chosen": chosen,
        "salt": family[chosen]["salt"],
        "moves": family[chosen]["moves"],
        "local_moves": family[chosen].get("local_moves", 0),
        "family": len(family),
        "rescored": True,
        "candidates": candidates,
        "move_candidates": len(family) - candidates,
        "probes": probes,
        "churn_cohort": int(m),
        "movement": [round(float(v), 6) for v in movement],
        "excess_movement": [round(float(v), 6) for v in excess],
        "imbalance": [round(float(v), 4) for v in imbalance],
        "diameter": [round(float(v), 6) for v in diameter],
        "movement_random": round(float(movement[0]), 6),
        "movement_chosen": round(float(movement[chosen]), 6),
        "imbalance_random": round(float(imbalance[0]), 4),
        "imbalance_chosen": round(float(imbalance[chosen]), 4),
        "diameter_random": round(float(diameter[0]), 6),
        "diameter_chosen": round(float(diameter[chosen]), 6),
    }
    return st, so, report


def key_movement(
    tokens_a, owners_a, servers_a: list[str],
    tokens_b, owners_b, servers_b: list[str],
    hashes,
) -> dict:
    """Key movement between two ring snapshots over a probe hash batch —
    the ring1m churn-rebalance metric, shared with the DGRO scorer.

    Owner ids are matched ACROSS snapshots through the server lists (ids
    renumber on membership change), so ``moved`` counts real ownership
    transfers.  ``excess_moved`` is the consistent-hashing violation
    count: keys that moved between two servers present in BOTH snapshots
    (always 0 for identity-keyed token placement)."""
    from ringpop_tpu.ops.ring_ops import ring_lookup

    oa = np.asarray(ring_lookup(jnp.asarray(tokens_a), jnp.asarray(owners_a), hashes))
    ob = np.asarray(ring_lookup(jnp.asarray(tokens_b), jnp.asarray(owners_b), hashes))
    index_a = {srv: i for i, srv in enumerate(servers_a)}
    # b-id -> a-id (or -1 for servers new in b)
    b_to_a = np.array([index_a.get(srv, -1) for srv in servers_b], np.int64)
    survivors_a = np.zeros(len(servers_a), bool)
    survivors_a[b_to_a[b_to_a >= 0]] = True
    ob_in_a = b_to_a[ob]
    moved = ob_in_a != oa
    excess = moved & survivors_a[oa] & (ob_in_a >= 0)
    return {
        "probes": int(oa.shape[0]),
        "moved_frac": round(float(moved.mean()), 6),
        "excess_moved": int(excess.sum()),
        "removed_load_frac": round(float((~survivors_a[oa]).mean()), 6),
    }
