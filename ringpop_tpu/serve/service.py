"""RingService: the micro-batching device-ring lookup service.

Frontends call ``("serve", "/lookup")`` with a key-hash batch
(``net.channel.encode_array`` payload); the collector appends it to the
pending queue and flushes — ONE padded-ring dispatch for everything
pending — when either trigger fires:

* **size**: pending keys reach ``max_batch``;
* **latency**: ``flush_us`` microseconds elapsed since the first pending
  request (``flush_us=0`` degrades gracefully: the flush runs on the next
  event-loop iteration, still coalescing everything that arrived in the
  same iteration — the B=1 single-frontend case pays one loop hop over a
  direct dispatch, which is what keeps its latency within 2× of the raw
  ``ring_lookup`` call).

Coalesced hashes are padded to the next power of two before dispatch so
the compiled-program set is bounded (log₂ shapes, not one per batch
size); the device wait runs in an executor so the event loop keeps
reading frames while XLA computes — flushes pipeline.

Telemetry rides the r7 plumbing: batch-size / queue-wait / dispatch-time
histograms + counters, emitted as ``ringpop.serve.*`` through any
``StatsReporter``, aggregated into one ``kind: "serve"`` JSONL record per
``journal_every`` flushes, with one ``kind: "ring_update"`` record per
committed generation (schema: OBSERVABILITY.md).  Every response carries
the generation the DEVICE answered with (``serve_lookup`` reads it from
the same state in the same dispatch), so owner decisions are certifiable
per membership generation.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.net.channel import decode_array, encode_array
from ringpop_tpu.ops.ring_ops import host_lookup_n
from ringpop_tpu.serve.state import (
    RingStore,
    serve_lookup_fused,
    serve_lookup_n_fused,
)
from ringpop_tpu.util.metrics import Histogram

_logger = logging_mod.logger("serve")

SERVE_STAT_PREFIX = "ringpop.serve"


class _PendingReq:
    __slots__ = ("hashes", "n", "sink", "t_enqueue")

    def __init__(self, hashes: np.ndarray, n: int, sink, t_enqueue: float):
        self.hashes = hashes
        self.n = n
        # an asyncio.Future (TCP path) or a plain callable(rows, gen)
        # (shared-memory path — delivered synchronously, no loop hop)
        self.sink = sink
        self.t_enqueue = t_enqueue


def _next_pow2(x: int) -> int:
    return 1 << max(x - 1, 1).bit_length() if x > 2 else max(x, 1)


def _is_deleted_buffer(e: Exception) -> bool:
    """True for jax's retired-donated-buffer error — the only dispatch
    failure the collector retries (it means the ring generation moved
    twice while this dispatch was in flight)."""
    return "deleted" in str(e).lower()


def _fail_sinks(reqs, exc: Exception) -> None:
    """Deliver a dispatch failure to every request: futures get the
    exception, callback sinks get ``(None, -1)`` (the shm server answers
    STATUS_ERR) — a sink is NEVER stranded pending."""
    for r in reqs:
        sink = r.sink
        if isinstance(sink, asyncio.Future):
            if not sink.done():
                sink.set_exception(exc)
        else:
            try:
                sink(None, -1)
            except Exception:  # pragma: no cover - responder must not throw
                pass


class ServeTelemetry:
    """Per-flush counters/histograms + the aggregated journal record."""

    def __init__(self, journal=None, stats=None, journal_every: int = 64):
        self.journal = journal
        self.stats = stats
        self.journal_every = journal_every
        self.reset_window()
        self.flushes_total = 0
        self.keys_total = 0
        self.requests_total = 0

    def reset_window(self):
        self.batch_hist = Histogram(sample_size=64)
        self.wait_hist = Histogram(sample_size=64)
        self.dispatch_hist = Histogram(sample_size=64)
        self.w_flushes = 0
        self.w_keys = 0
        self.w_requests = 0

    def flush_event(
        self, *, keys: int, requests: int, waits_us: list[float],
        dispatch_us: float, gen: int,
    ) -> None:
        self.flushes_total += 1
        self.keys_total += keys
        self.requests_total += requests
        self.w_flushes += 1
        self.w_keys += keys
        self.w_requests += requests
        self.batch_hist.update(keys)
        for w in waits_us:
            self.wait_hist.update(w)
        self.dispatch_hist.update(dispatch_us)
        if self.stats is not None:
            self.stats.incr(f"{SERVE_STAT_PREFIX}.keys", keys)
            self.stats.incr(f"{SERVE_STAT_PREFIX}.requests", requests)
            self.stats.incr(f"{SERVE_STAT_PREFIX}.flushes", 1)
            self.stats.timing(f"{SERVE_STAT_PREFIX}.dispatch", dispatch_us / 1e6)
            self.stats.gauge(f"{SERVE_STAT_PREFIX}.generation", gen)
        if self.journal is not None and self.w_flushes >= self.journal_every:
            self.journal_window(gen)

    def _hist_row(self, h: Histogram) -> dict:
        return {
            "mean": round(h.mean(), 2),
            "p50": round(h.percentile(0.5), 2),
            "p90": round(h.percentile(0.9), 2),
            "max": round(h.max(), 2),
        }

    def journal_window(self, gen: int) -> None:
        if self.journal is None or self.w_flushes == 0:
            self.reset_window()
            return
        self.journal._write(
            {
                "kind": "serve",
                "gen": gen,
                "flushes": self.w_flushes,
                "requests": self.w_requests,
                "keys": self.w_keys,
                "keys_per_flush": self._hist_row(self.batch_hist),
                "queue_wait_us": self._hist_row(self.wait_hist),
                "dispatch_us": self._hist_row(self.dispatch_hist),
            }
        )
        self.reset_window()


class RingService:
    """The shared-ring lookup service; attach to any Base/TCP/Local channel."""

    def __init__(
        self,
        store: RingStore,
        *,
        max_batch: int = 8192,
        flush_us: float = 200.0,
        inline_resolve_max: int = 4096,
        journal=None,
        stats=None,
        journal_every: int = 64,
    ):
        self.store = store
        self.max_batch = max_batch
        self.flush_us = flush_us
        # flushes at or under this many keys resolve INLINE (block the loop
        # on the device result) instead of hopping through the executor —
        # the executor pipelines big dispatches, but its two thread
        # hand-offs dominate a microsecond-scale lookup and would sink the
        # B=1 latency bar; 0 forces the executor always
        self.inline_resolve_max = inline_resolve_max
        self.telemetry = ServeTelemetry(
            journal=journal, stats=stats, journal_every=journal_every
        )
        self._pending: list[_PendingReq] = []
        self._pending_keys = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._codec = "json"
        # generation updates journal through the store's hook — CHAINED
        # after any caller-installed callback, never replacing it
        prev_hook = store.on_update

        def _chained(record: dict) -> None:
            self._on_ring_update(record)
            if prev_hook is not None:
                prev_hook(record)

        store.on_update = _chained

    # -- wiring ---------------------------------------------------------------

    def attach(self, channel) -> None:
        """Register the serve endpoints on a listening channel.  Response
        arrays ride the channel's codec (raw bytes under msgpack, base64
        under JSON — ``net.channel.encode_array``)."""
        self._codec = getattr(channel, "codec", "json")
        channel.register("serve", "/lookup", self._handle_lookup)
        channel.register("serve", "/ring", self._handle_ring)
        channel.register("serve", "/stats", self._handle_stats)

    def _on_ring_update(self, record: dict) -> None:
        if self.telemetry.journal is not None:
            self.telemetry.journal._write(record)
        if self.telemetry.stats is not None:
            self.telemetry.stats.gauge(
                f"{SERVE_STAT_PREFIX}.ring.servers", record["n_servers"]
            )
            self.telemetry.stats.incr(f"{SERVE_STAT_PREFIX}.ring.changed", 1)

    # -- request path ---------------------------------------------------------

    def submit(self, hashes, n: int = 1, loop=None) -> asyncio.Future:
        """Enqueue one key-hash batch into the collector; the returned
        future resolves to ``(owners, generation)``.  This is the ONE
        entry point both transports share — the TCP ``/lookup`` endpoint
        and the shared-memory server feed the same pending queue, so
        cross-transport requests coalesce into the same dispatches.

        ``hashes`` may be a READ-ONLY VIEW of a transport buffer (r21
        registered-buffer zero-copy): the collector never mutates it and
        consumes it in the flush's single staging gather — the caller
        must keep the buffer stable until its sink is delivered (the shm
        server holds the slot unpublished exactly that long)."""
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        loop = loop or asyncio.get_event_loop()
        self._loop = loop
        fut: asyncio.Future = loop.create_future()
        self._pending.append(_PendingReq(hashes, n, fut, time.perf_counter()))
        self._pending_keys += len(hashes)
        if self._pending_keys >= self.max_batch:
            self._schedule_flush(immediate=True)
        elif self._flush_handle is None:
            if self.flush_us <= 0:
                self._flush_handle = loop.call_soon(self._flush)
            else:
                self._flush_handle = loop.call_later(self.flush_us / 1e6, self._flush)
        return fut

    def submit_nowait(self, hashes, n: int, callback, loop=None) -> None:
        """Enqueue with a synchronous delivery callback and NO flush
        scheduling — the shared-memory server enqueues every pending slot
        it scanned, then calls :meth:`flush_now` once, so an entire scan
        coalesces into one dispatch (plus whatever TCP requests were
        already pending) with zero event-loop hand-offs on the response
        path.  ``callback(rows, gen)`` may run on the executor thread for
        over-``inline_resolve_max`` flushes."""
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if loop is not None:
            self._loop = loop
        self._pending.append(_PendingReq(hashes, n, callback, time.perf_counter()))
        self._pending_keys += len(hashes)
        if self._pending_keys >= self.max_batch:
            self._schedule_flush(immediate=True)

    def flush_now(self) -> None:
        """Dispatch everything pending immediately (cancels any armed
        latency trigger)."""
        self._schedule_flush(immediate=True)

    def dispatch_direct(self, hashes, n: int, callback) -> None:
        """The degenerate-case fast lane: ONE small (≤64-key) request,
        nothing else pending — answered from the HOST MIRROR of the
        committed generation (``RingStore.snapshot_host``), bit-identical
        to the device ring by the property-suite pin, without paying a
        device round trip a single key cannot amortize (a jit dispatch
        alone costs ~100 µs on this container; the batch path exists
        precisely to spread that over thousands of keys).  n>1 point
        requests answer from the SAME mirror through the exact
        ``host_lookup_n`` walk (the LookupNUniqueAt parity oracle), so the
        fast lane returns the same (owner, successors) tuple the fused
        device dispatch would.  Telemetered as a flush of one request, so
        the B=1 stream shows up in the same batch-size/queue-wait
        histograms."""
        t0 = time.perf_counter()
        toks, owns, gen, n_servers = self.store.snapshot_host()
        if n == 1:
            if toks.shape[0] == 0:
                rows = np.full(len(hashes), -1, np.int32)
            else:
                idx = np.searchsorted(toks, np.asarray(hashes, np.uint32), side="left")
                rows = owns[np.where(idx == toks.shape[0], 0, idx)]
        else:
            rows = host_lookup_n(toks, owns, hashes, n, n_servers)
        callback(rows, gen)
        self.telemetry.flush_event(
            keys=len(hashes), requests=1, waits_us=[0.0],
            dispatch_us=(time.perf_counter() - t0) * 1e6, gen=gen,
        )

    async def _handle_lookup(self, body: dict, headers: dict) -> dict:
        hashes = decode_array(body["h"], "<u4")
        n = int(body.get("n", 1))
        owners, gen = await self.submit(hashes, n=n)
        return {
            "o": encode_array(owners, self._codec, "<i4"),
            "gen": gen,
            "n": n,
        }

    async def _handle_ring(self, body: dict, headers: dict) -> dict:
        gen = body.get("gen")
        with self.store._lock:
            cur = self.store.gen
        servers = (
            self.store.servers_at(int(gen)) if gen is not None
            else self.store.servers_at(cur)
        )
        if servers is None:
            raise ValueError(f"generation {gen} aged out (current {cur})")
        return {
            "gen": int(gen) if gen is not None else cur,
            "current_gen": cur,
            "servers": servers,
            "checksum": self.store.ring.checksum(),
        }

    async def _handle_stats(self, body: dict, headers: dict) -> dict:
        t = self.telemetry
        return {
            "flushes": t.flushes_total,
            "requests": t.requests_total,
            "keys": t.keys_total,
            "keys_per_flush_mean": round(
                t.keys_total / max(t.flushes_total, 1), 2
            ),
            "gen": self.store.gen,
        }

    # -- the collector --------------------------------------------------------

    def _requeue(self, reqs) -> None:
        """Put requests whose dispatch raced a double ring-commit back on
        the pending queue and flush against the fresh generation."""
        self._pending.extend(reqs)
        self._pending_keys += sum(len(r.hashes) for r in reqs)
        self._schedule_flush(immediate=True)

    def _schedule_flush(self, immediate: bool = False) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        if immediate:
            self._flush()

    def _flush(self) -> None:
        self._flush_handle = None
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        self._pending_keys = 0
        loop = self._loop or asyncio.get_event_loop()
        t_flush = time.perf_counter()
        waits_us = [(t_flush - r.t_enqueue) * 1e6 for r in batch]
        # group by n: n=1 rides the single serve_lookup program; each n > 1
        # group is its own exact preference-list dispatch
        groups: dict[int, list[_PendingReq]] = {}
        for r in batch:
            groups.setdefault(r.n, []).append(r)
        gen = self.store.gen  # fallback if every group's dispatch fails
        for n, reqs in groups.items():
            # r21 zero-copy: requests may hand in read-only views of
            # transport buffers (shm ring slots).  Gather them ONCE,
            # directly into the padded staging buffer the device upload
            # reads — the old concatenate-then-pad pair cost two copies
            # of every payload byte; the slot-copy in the shm scan was a
            # third.  The single gather below is the dispatch's own input
            # materialization, after which the transport buffers are free
            # to be republished.
            total = sum(int(r.hashes.shape[0]) for r in reqs)
            p2 = _next_pow2(total)
            if len(reqs) == 1 and p2 == total:
                padded = np.asarray(reqs[0].hashes, np.uint32)
            else:
                padded = np.zeros(p2, np.uint32)
                off = 0
                for r in reqs:
                    b = int(r.hashes.shape[0])
                    padded[off:off + b] = r.hashes
                    off += b
            dev_hashes = jnp.asarray(padded)
            try:
                # journal the generation the dispatch ACTUALLY answered
                # with — the retry path may refetch a newer snapshot
                gen = self._dispatch_group(loop, reqs, dev_hashes, total, n)
            except Exception as e:  # deliver, never strand a sink
                _logger.error(f"serve flush dispatch failed: {e!r}")
                _fail_sinks(reqs, e)
        dispatch_us = (time.perf_counter() - t_flush) * 1e6
        self.telemetry.flush_event(
            keys=sum(len(r.hashes) for r in batch),
            requests=len(batch),
            waits_us=waits_us,
            dispatch_us=dispatch_us,
            gen=gen,
        )

    def _dispatch_group(self, loop, reqs, dev_hashes, total: int, n: int) -> int:
        """One group's dispatch, retried on a retired ring: the store's
        ping-pong donation keeps a snapshot valid across ONE concurrent
        commit, so hitting a deleted buffer means TWO membership changes
        landed mid-dispatch — refetch the newest generation and redo
        (the answer then rightly carries the newer generation).  Returns
        the generation of the snapshot that answered."""
        for attempt in range(5):
            ring, _gen, n_servers = self.store.snapshot()
            try:
                # fused transfer either way: owners + generation in one
                # device array (generation in the tail slot), split
                # host-side after a single sync — n=1 rides the plain
                # fused program, n>1 the fused preference-list windows
                if n == 1:
                    owners_dev = serve_lookup_fused(ring, dev_hashes)
                else:
                    owners_dev = serve_lookup_n_fused(ring, n_servers, dev_hashes, n)
                if total <= self.inline_resolve_max:
                    # small flush: the device answer is microseconds away
                    # and two executor hand-offs would dominate it
                    self._resolve(reqs, owners_dev, total, n, inline=True)
                else:
                    task = loop.run_in_executor(
                        None, self._resolve, reqs, owners_dev, total, n
                    )
                    task.add_done_callback(self._log_resolve_error)
                return _gen
            except RuntimeError as e:
                if not _is_deleted_buffer(e) or attempt == 4:
                    raise
        return _gen  # pragma: no cover - loop always returns or raises

    @staticmethod
    def _log_resolve_error(task) -> None:
        exc = task.exception()
        if exc is not None:  # pragma: no cover - resolve() sets futures
            _logger.error(f"serve flush resolve failed: {exc!r}")

    def _resolve(
        self, reqs, owners_dev, total: int, n: int, inline: bool = False
    ) -> None:
        """Block on the device result and scatter rows back to request
        futures — on the loop thread directly (``inline``) or from the
        executor (thread-safe via call_soon_threadsafe).  ``owners_dev``
        is the fused vector with the generation in its tail slot: [B+1]
        for n=1, [B*n+1] flattened rows for preference lists."""
        try:
            host = np.asarray(owners_dev)
            gen = int(host[-1])
            if n == 1:
                owners = host[:total]
            else:
                owners = host[: total * n].reshape(total, n)
        except RuntimeError as e:
            if inline or not _is_deleted_buffer(e):
                raise
            # executor path hit a retired ring mid-transfer (two commits
            # landed since dispatch): requeue on the loop — the next
            # flush answers from the fresh generation
            self._loop.call_soon_threadsafe(self._requeue, reqs)
            return
        loop = self._loop
        off = 0
        for r in reqs:
            b = len(r.hashes)
            rows = owners[off : off + b]
            off += b
            if not isinstance(r.sink, asyncio.Future):
                # callback sink: deliver synchronously (slot-exclusive,
                # safe from any thread)
                r.sink(rows, gen)
                continue

            def _set(fut=r.sink, rows=rows):
                if not fut.done():
                    fut.set_result((rows, gen))

            if inline:
                _set()
            else:
                loop.call_soon_threadsafe(_set)
