"""Shared-memory request ring: the serve tier's same-host fast transport.

The TCP path (``net/channel.py`` framing) is general but prices every
batch at one frame encode/decode plus a socket round trip — fine across
hosts, throwaway overhead for frontends that share the machine with the
device ring.  This module gives those frontends a zero-copy-in,
zero-serialization lane: one shared-memory segment holds S fixed-size
request slots (one per frontend); a client writes its key hashes
directly into its slot, bumps a sequence word, and pokes a 1-byte UNIX
datagram at the server's wakeup socket; the server's event loop scans
all slots on wake and feeds every pending request into the SAME
micro-batching collector the TCP endpoints use — so cross-frontend
coalescing is structural (one scan picks up every frontend that posted
during the last dispatch), not timer-dependent.

Slot protocol (all words uint32, x86-TSO-ordered numpy stores):

* client: write ``count``/``n`` + hashes, THEN ``req_seq += 1``, then
  wake the server (datagram).  Spin on ``resp_seq == req_seq``.
* server: slot pending iff ``req_seq != resp_seq`` and not in flight;
  write owners + ``gen``/``status``, THEN ``resp_seq = req_seq``.

The sequence words make the payload hand-off safe without locks: each
side only reads the other's payload after observing the matching seq,
and each writes its payload strictly before publishing its seq.
"""

from __future__ import annotations

import os
import socket
import tempfile
import time
from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.errors import FabricPeerLost, FabricTimeout
from ringpop_tpu.parallel.fabric import TransportLedger

_logger = logging_mod.logger("serve.shm")

# per-slot header words (uint32)
_REQ_SEQ = 0  # client bumps after writing a request
_RESP_SEQ = 1  # server sets == req_seq after writing the response
_COUNT = 2  # keys in the request
_N = 3  # owners requested per key
_GEN = 4  # response: membership generation that answered
_STATUS = 5  # response: 0 ok, 1 error (count/n out of bounds)
_HEADER_WORDS = 8

STATUS_OK = 0
STATUS_ERR = 1


def _slot_words(key_cap: int, max_n: int) -> int:
    return _HEADER_WORDS + key_cap + key_cap * max_n


class ShmRing:
    """The raw segment: S slots of (header, hashes u32[key_cap],
    owners i32[key_cap * max_n]) — attached by name from any process."""

    def __init__(
        self,
        *,
        slots: int = 16,
        key_cap: int = 1 << 16,
        max_n: int = 4,
        name: Optional[str] = None,
        create: bool = False,
    ):
        self.slots = slots
        self.key_cap = key_cap
        self.max_n = max_n
        nbytes = slots * _slot_words(key_cap, max_n) * 4
        if create:
            self.shm = shared_memory.SharedMemory(create=True, size=nbytes)
        else:
            assert name is not None
            self.shm = shared_memory.SharedMemory(name=name)
        self.name = self.shm.name
        words = np.frombuffer(self.shm.buf, dtype=np.uint32)
        per = _slot_words(key_cap, max_n)
        self._headers = []
        self._hashes = []
        self._owners = []
        for s in range(slots):
            base = s * per
            self._headers.append(words[base : base + _HEADER_WORDS])
            self._hashes.append(words[base + _HEADER_WORDS : base + _HEADER_WORDS + key_cap])
            self._owners.append(
                words[base + _HEADER_WORDS + key_cap : base + per].view(np.int32)
            )
        if create:
            words[:] = 0

    def close(self, unlink: bool = False) -> None:
        # drop the numpy views before closing the mmap (BufferError otherwise)
        self._headers = self._hashes = self._owners = None
        try:
            self.shm.close()
        except BufferError:
            # r21 zero-copy: a dispatch may still hold a slot view (or a
            # CPU jax array aliasing one) at teardown.  Collect the
            # dropped references and retry; if a live view remains, defer
            # the unmap to process exit — unlink below must still happen
            # so the segment name is reclaimed either way.
            import gc

            gc.collect()
            try:
                self.shm.close()
            except BufferError:
                _logger.debug(
                    "shm segment close deferred: exported slot views alive"
                )
        if unlink:
            try:
                self.shm.unlink()
            except FileNotFoundError:  # pragma: no cover - double unlink
                pass


class ShmServer:
    """Server half: owns the segment + the wakeup socket; hands pending
    requests to a ``RingService`` collector and writes responses back."""

    def __init__(self, service, *, slots: int = 16, key_cap: int = 1 << 16,
                 max_n: int = 4, burst_us: float = 500.0,
                 ledger: Optional[TransportLedger] = None):
        self.service = service
        # merged transport accounting (r21), class "shm": request payload
        # bytes read out of the ring, response bytes written back, and
        # ``copy_bytes`` — payload bytes COPIED out of a slot before the
        # dispatch's own staging gather.  The zero-copy contract is that
        # this stays 0: slots are handed to the collector as read-only
        # views and not republished until the dispatch consumed them.
        self.ledger = ledger if ledger is not None else TransportLedger()
        # after SMALL-batch activity (count <= 64: the latency-sensitive
        # point-lookup class) the server keeps rescanning the slots for
        # ``burst_us`` before falling back to the wakeup socket — one epoll
        # hop per BURST of traffic instead of per request, which is what
        # keeps the B=1 sequential stream near direct-dispatch latency.
        # Large batches never arm it: their epoll wake is amortized over
        # thousands of keys, and a polling loop would burn a core the
        # dispatches themselves need (this container has two).
        self.burst_us = burst_us
        self._burst_deadline = 0.0
        self._burst_live = False
        self._small_seen = False
        self.ring = ShmRing(slots=slots, key_cap=key_cap, max_n=max_n, create=True)
        self.sock_path = os.path.join(
            tempfile.gettempdir(), f"rp-serve-{os.getpid()}-{self.ring.name}.sock"
        )
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._sock.bind(self.sock_path)
        self._sock.setblocking(False)
        self._inflight: set[int] = set()
        self._loop = None

    @property
    def address(self) -> tuple[str, str]:
        """(shm segment name, wakeup socket path) — what a client needs."""
        return self.ring.name, self.sock_path

    def attach(self, loop) -> None:
        self._loop = loop
        loop.add_reader(self._sock.fileno(), self._on_wake)

    def _on_wake(self) -> None:
        # drain every queued wake datagram, then scan ALL slots once —
        # the structural coalescing: requests posted by different
        # frontends during the previous dispatch are picked up together
        while True:
            try:
                self._sock.recv(64)
            except BlockingIOError:
                break
        if self.scan() and self._small_seen:
            self._extend_burst()

    def _extend_burst(self) -> None:
        self._burst_deadline = time.perf_counter() + self.burst_us / 1e6
        if not self._burst_live and self._loop is not None and self.burst_us > 0:
            self._burst_live = True
            self._loop.call_soon(self._burst)

    def _burst(self) -> None:
        """Post-activity polling window: rescan via ``call_soon`` (the loop
        still services fds and timers between scans) until ``burst_us``
        passes with no new work, then return to pure epoll waiting."""
        if self.ring._headers is None:  # closed mid-burst
            self._burst_live = False
            return
        if self.scan() and self._small_seen:
            self._burst_deadline = time.perf_counter() + self.burst_us / 1e6
        if time.perf_counter() < self._burst_deadline:
            self._loop.call_soon(self._burst)
        else:
            self._burst_live = False

    def scan(self) -> int:
        """Enqueue every pending slot into the collector, then flush ONCE —
        the whole scan (plus any pending TCP requests) coalesces into a
        single dispatch.  Responses are delivered through synchronous
        callbacks (no event-loop hand-off).  Returns how many slots were
        picked up."""
        ring = self.ring
        found = 0
        self._small_seen = False
        picked: list[tuple[int, int, int, int]] = []  # (slot, req, count, n)
        for s in range(ring.slots):
            if s in self._inflight:
                continue
            hdr = ring._headers[s]
            req = int(hdr[_REQ_SEQ])
            if req == int(hdr[_RESP_SEQ]):
                continue
            count = int(hdr[_COUNT])
            n = int(hdr[_N])
            if not (0 < count <= ring.key_cap and 0 < n <= ring.max_n):
                hdr[_STATUS] = STATUS_ERR
                hdr[_RESP_SEQ] = np.uint32(req)
                continue
            found += 1
            if count <= 64:
                self._small_seen = True
            picked.append((s, req, count, n))
        if not picked:
            return 0
        svc = self.service
        try:
            if len(picked) == 1 and picked[0][2] <= 64 and not svc._pending:
                # degenerate single point-lookup, nothing else pending:
                # skip the collector's grouping/padding machinery entirely
                # — this is the B=1 latency path
                s, req, count, n = picked[0]
                self._inflight.add(s)
                self.ledger.add("shm", lane="shm", bytes_recv=count * 4, frames_recv=1)
                svc.dispatch_direct(
                    self._slot_view(s, count), n, self._responder(s, req)
                )
                return found
            for s, req, count, n in picked:
                self._inflight.add(s)
                self.ledger.add("shm", lane="shm", bytes_recv=count * 4, frames_recv=1)
                # r21 zero-copy: hand the collector a READ-ONLY VIEW of
                # the slot — no copy out of the segment.  Lifetime is
                # explicit: the slot stays in ``_inflight`` (and
                # ``resp_seq`` unpublished, so the client keeps its hands
                # off the buffer) until the responder runs, which is
                # strictly after the dispatch's staging gather consumed
                # the view.  ``flush_now`` below dispatches synchronously
                # within this scan.
                svc.submit_nowait(
                    self._slot_view(s, count), n=n, loop=self._loop,
                    callback=self._responder(s, req),
                )
            svc.flush_now()
        except Exception as e:
            # answer STATUS_ERR for every picked slot the collector did
            # not already respond to — an exception must never strand a
            # slot in _inflight (the frontend would time out forever) nor
            # kill the burst/wake callback chain
            _logger.error(f"shm scan dispatch failed: {e!r}")
            for s, req, _count, _n in picked:
                if s in self._inflight:
                    self._responder(s, req)(None, -1)
        return found

    def _slot_view(self, slot: int, count: int) -> np.ndarray:
        """A read-only numpy view of a slot's pending hashes — the
        registered-buffer hand-off.  Zero bytes are copied; the returned
        view aliases the shared segment and is valid until the slot's
        responder publishes ``resp_seq``."""
        view = self.ring._hashes[slot][:count].view()
        view.flags.writeable = False
        return view

    def _responder(self, slot: int, req: int):
        def respond(rows, gen) -> None:
            ring = self.ring
            hdr = ring._headers[slot]
            if rows is None:  # dispatch failed: the client raises
                hdr[_STATUS] = STATUS_ERR
            else:
                flat = np.asarray(rows, np.int32).reshape(-1)
                ring._owners[slot][: flat.shape[0]] = flat
                hdr[_GEN] = np.uint32(gen)
                hdr[_STATUS] = STATUS_OK
                self.ledger.add("shm", lane="shm", bytes_sent=int(flat.shape[0]) * 4,
                                frames_sent=1)
            self._inflight.discard(slot)
            hdr[_RESP_SEQ] = np.uint32(req)
            # retry-while-held: if the client gave up waiting and posted a
            # NEW request into this slot while the old one was in flight,
            # ``req_seq`` has moved past what we just answered — the wake
            # datagram for it was already drained, so without a rescan the
            # retry would strand until the next unrelated wake.  (This
            # responder may run on the executor thread; scan() is
            # loop-only, hence the threadsafe hop.)
            if int(hdr[_REQ_SEQ]) != req and self._loop is not None:
                try:
                    self._loop.call_soon_threadsafe(self.scan)
                except RuntimeError:  # pragma: no cover - loop shut down
                    pass

        return respond

    def close(self) -> None:
        if self._loop is not None:
            self._loop.remove_reader(self._sock.fileno())
        self._sock.close()
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:  # pragma: no cover
            pass
        self.ring.close(unlink=True)


class ShmClient:
    """Frontend half: blocking lookups through one owned slot.

    ``lookup_hashes`` is synchronous by design — the frontend's unit of
    work is one posted batch.  The wait is batch-size aware: tiny batches
    (latency-sensitive point lookups) spin hot for up to ``spin_us`` —
    the server's post-activity burst answers them in that window — while
    large batches spin only briefly and then SLEEP in short steps,
    yielding their core to the service doing the actual work (on a
    2-core container a spinning client would starve the very dispatch it
    is waiting on)."""

    def __init__(self, shm_name: str, sock_path: str, slot: int, *,
                 slots: int = 16, key_cap: int = 1 << 16, max_n: int = 4,
                 spin_us: float = 1000.0, timeout: float = 30.0):
        self.ring = ShmRing(slots=slots, key_cap=key_cap, max_n=max_n, name=shm_name)
        self.slot = slot
        self.spin_us = spin_us
        self.timeout = timeout
        self._hdr = self.ring._headers[slot]
        self._hashes = self.ring._hashes[slot]
        self._owners = self.ring._owners[slot]
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._sock.connect(sock_path)

    def lookup_hashes(self, hashes: np.ndarray, n: int = 1):
        """(owners int32[B] or int32[B, n], generation) — blocking."""
        count = int(hashes.shape[0])
        if not (0 < count <= self.ring.key_cap):
            raise ValueError(f"batch of {count} exceeds slot capacity {self.ring.key_cap}")
        if not (0 < n <= self.ring.max_n):
            raise ValueError(f"n={n} outside 1..{self.ring.max_n}")
        hdr = self._hdr
        self._hashes[:count] = np.asarray(hashes, np.uint32)
        hdr[_COUNT] = np.uint32(count)
        hdr[_N] = np.uint32(n)
        # mask before the uint32 construction: at seq 0xFFFFFFFF the +1
        # would overflow (newer numpy raises OverflowError instead of
        # wrapping) — the protocol is modular, wrap-around is legitimate
        req = np.uint32((int(hdr[_REQ_SEQ]) + 1) & 0xFFFFFFFF)
        hdr[_REQ_SEQ] = req
        try:
            self._sock.send(b"\x01")
        except OSError as e:
            # the wakeup socket refusing the datagram means the server
            # process died (its unix socket is gone) — the shm flavor of
            # a dead fabric peer
            raise FabricPeerLost(
                f"shm serve server unreachable at its wakeup socket ({e})"
            ) from e
        t0 = time.perf_counter()
        deadline = t0 + self.timeout
        spin_until = t0 + (self.spin_us if count <= 64 else 50.0) / 1e6
        while hdr[_RESP_SEQ] != req:
            now = time.perf_counter()
            if now > deadline:
                # the unified (r17) transport error family: a silent shm
                # server is the same failure class as a silent fabric or
                # channel peer — FabricTimeout everywhere
                raise FabricTimeout(
                    f"shm lookup timed out after {self.timeout}s — server "
                    "wedged or gone (slot never answered)"
                )
            if now > spin_until:
                time.sleep(1e-4)
        if int(hdr[_STATUS]) != STATUS_OK:
            raise RuntimeError("shm lookup rejected by server")
        owners = self._owners[: count * n].copy()
        gen = int(hdr[_GEN])
        if n > 1:
            return owners.reshape(count, n), gen
        return owners, gen

    def close(self) -> None:
        self._sock.close()
        self._hdr = self._hashes = self._owners = None
        self.ring.close()
