"""Device-resident ring state with generation-certified swaps.

``DeviceRing`` keeps the serving ring's sorted token/owner arrays at a
fixed CAPACITY on the device (``ops/ring_ops.py`` padded variants), with
the live count and a generation counter as device scalars.  Updates are
value swaps at constant shape — the serving program compiles once per
(capacity, batch-size) and never retraces on membership churn — and
``ring_commit`` donates buffers ping-pong style (commit N reuses
generation N-2's HBM; jaxlint RPJ204 pins every leaf aliased), so churn
never allocates and a snapshot held by an in-flight dispatch stays
valid across one concurrent commit.

``serve_lookup`` returns the generation ALONGSIDE the owners, read from
the same device state inside the same dispatch — the answer and the
membership generation it was computed against are atomically paired,
which is what lets the serving tier certify routing decisions per
generation (the ``serve_ring`` A/B's owner-decision digests are keyed by
it).

``RingStore`` is the host-side feed: it owns a ``hashring.HashRing``
(incremental token add/remove), pads, commits, and journals one
``ring_update`` record per generation.  ``listen_to`` subscribes it to
any ``RingChangedEvent`` emitter (a live SWIM node's ring, or a sim
snapshot replayed in bench mode).
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.events import RingChangedEvent
from ringpop_tpu.hashring import HashRing
from ringpop_tpu.ops.ring_ops import (
    _lookup_n_window_padded,
    pad_ring_arrays,
    ring_lookup_n_padded,
    ring_lookup_padded,
)


class DeviceRing(NamedTuple):
    """The device-resident serving ring (capacity-padded)."""

    tokens: jax.Array  # uint32[C], PAD_TOKEN past count
    owners: jax.Array  # int32[C], -1 past count
    count: jax.Array  # int32[1] live tokens
    gen: jax.Array  # uint32[1] membership generation


def device_ring(tokens, owners, capacity: int, gen: int = 0) -> DeviceRing:
    """Host arrays -> a fresh DeviceRing at ``capacity``."""
    pt, po, count = pad_ring_arrays(tokens, owners, capacity)
    return DeviceRing(
        tokens=jnp.asarray(pt),
        owners=jnp.asarray(po),
        count=jnp.asarray([count], jnp.int32),
        gen=jnp.asarray([gen], jnp.uint32),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def ring_commit(
    ring: DeviceRing, tokens: jax.Array, owners: jax.Array, count: jax.Array,
    gen: jax.Array,
) -> DeviceRing:
    """Swap a new generation into the DONATED old ring — every leaf is a
    full-length in-place update of the old buffer (dynamic_update_slice at
    offset 0).  ``RingStore`` ping-pongs two buffer sets through this:
    commit N donates generation N-2's buffers, so a reader holding the
    previous snapshot stays valid across one concurrent commit (peak HBM
    is two rings, never three — and never a fresh allocation per churn
    event)."""
    upd = jax.lax.dynamic_update_slice
    z = (jnp.int32(0),)
    return DeviceRing(
        tokens=upd(ring.tokens, tokens, z),
        owners=upd(ring.owners, owners, z),
        count=upd(ring.count, count, z),
        gen=upd(ring.gen, gen, z),
    )


@jax.jit
def serve_lookup(ring: DeviceRing, key_hashes: jax.Array):
    """Single-owner lookup + the generation it was answered against, in one
    dispatch (int32[B] owners, uint32[1] gen)."""
    return (
        ring_lookup_padded(ring.tokens, ring.owners, ring.count[0], key_hashes),
        ring.gen,
    )


@jax.jit
def serve_lookup_fused(ring: DeviceRing, key_hashes: jax.Array) -> jax.Array:
    """:func:`serve_lookup` with the generation FUSED into the owner vector
    (int32[B+1], generation in the last slot) — one device array, one
    host transfer.  The collector's n=1 flushes ride this: the second
    ``np.asarray`` sync for the generation scalar is measurable against a
    microsecond-scale lookup."""
    owners = ring_lookup_padded(ring.tokens, ring.owners, ring.count[0], key_hashes)
    return jnp.concatenate([owners, ring.gen.astype(jnp.int32)])


def serve_lookup_n(ring: DeviceRing, num_servers, key_hashes: jax.Array, n: int):
    """N-owner preference-list lookup against the padded ring (exact —
    the window-doubling rescue of ``ring_lookup_n_padded``)."""
    return (
        ring_lookup_n_padded(
            ring.tokens, ring.owners, ring.count[0],
            jnp.asarray(num_servers, jnp.int32), key_hashes, n,
        ),
        ring.gen,
    )


@functools.partial(jax.jit, static_argnames=("n", "w"))
def _serve_lookup_n_window_fused(
    ring: DeviceRing, num_servers: jax.Array, key_hashes: jax.Array, n: int, w: int
):
    """One fused window pass of the LookupN serve dispatch: the padded
    windowed scan (``ops/ring_ops._lookup_n_window_padded``) with the
    generation CONCATENATED into the flattened owner matrix — one device
    array, one host transfer, the exact analog of ``serve_lookup_fused``
    for preference lists.  Returns ``(int32[B*n + 1] fused, bool
    satisfied)``; the caller's host loop doubles ``w`` until satisfied
    (same rescue contract as ``ring_lookup_n_padded``)."""
    out, found = _lookup_n_window_padded(
        ring.tokens, ring.owners, ring.count[0], key_hashes, n, w
    )
    fused = jnp.concatenate([out.reshape(-1), ring.gen.astype(jnp.int32)])
    return fused, (found >= jnp.minimum(n, num_servers)).all()


def serve_lookup_n_fused(
    ring: DeviceRing, num_servers, key_hashes: jax.Array, n: int
) -> jax.Array:
    """:func:`serve_lookup_n` with the generation FUSED into the owner
    vector: int32[B*n + 1], rows flattened row-major, generation in the
    last slot — the collector's n>1 flushes ride this so owner tuples and
    the membership generation arrive in ONE transfer after a single sync
    (the r13 fused-dispatch design extended to LookupN).  EXACT: the same
    window-doubling rescue as ``ring_lookup_n_padded`` (each window size a
    cached jit specialization, the doubling decided on the host), pinned
    against the host ``LookupNUniqueAt`` walk by the property suite."""
    c = int(ring.tokens.shape[0])
    b = int(key_hashes.shape[0])
    if c == 0 or n <= 0:
        return jnp.concatenate(
            [jnp.full(b * max(n, 0), -1, jnp.int32), ring.gen.astype(jnp.int32)]
        )
    num = jnp.asarray(num_servers, jnp.int32)
    w = min(max(4 * n, 16), c)
    while True:
        fused, ok = _serve_lookup_n_window_fused(ring, num, key_hashes, n, w)
        # w >= capacity >= count covers the whole live ring: exact
        if w >= c or bool(ok):
            return fused
        w = min(2 * w, c)


class RingStore:
    """Host-side owner of the DeviceRing: membership in, generations out.

    Capacity doubles (one retrace) when the server set outgrows it;
    every committed generation's server list is retained in a short
    ring buffer so responses tagged with a recent generation can still be
    resolved to addresses by frontends.
    """

    def __init__(
        self,
        servers: Optional[list[str]] = None,
        *,
        replica_points: int = 100,
        capacity: Optional[int] = None,
        keep_generations: int = 8,
        placement: str = "random",
        placement_kw: Optional[dict] = None,
        on_update: Optional[Callable[[dict], None]] = None,
    ):
        if placement not in ("random", "dgro"):
            raise ValueError(f"unknown placement {placement!r}")
        self._lock = threading.Lock()
        self.ring = HashRing(replica_points=replica_points)
        self.placement = placement
        self.placement_kw = dict(placement_kw or {})
        self.keep_generations = keep_generations
        self.on_update = on_update
        self._gens: dict[int, list[str]] = {}
        self.gen = 0
        if servers:
            self.ring.add_remove_servers(list(servers), [])
        count = self.ring._tokens.shape[0]
        cap = capacity if capacity is not None else max(2 * count, 1024)
        tokens, owners = self._placed_arrays()
        self.device = device_ring(tokens, owners, cap, gen=self.gen)
        # host mirror of the COMMITTED (placed) arrays: the degenerate
        # point-lookup fast lane answers from these under the same lock
        # and generation — bit-identical to the device ring by the
        # property-suite pin, without a device round trip for one key
        self.host_tokens = np.asarray(tokens, np.uint32)
        self.host_owners = np.asarray(owners, np.int32)
        self.capacity = cap
        # the generation before last, whose buffers the NEXT value-swap
        # commit donates (ping-pong): a snapshot of the current ring is
        # guaranteed valid across one concurrent commit; the dispatch
        # paths retry on the (double-commit-mid-dispatch) tail
        self._retired: Optional[DeviceRing] = None
        self._gens[self.gen] = self.ring.servers()

    # -- placement -----------------------------------------------------------

    def _placed_arrays(self):
        """(tokens uint32, owners int32) for the current server set under
        the configured placement.  ``random`` is the ring's own (reference
        hashring.go) placement; ``dgro`` re-places tokens through the
        diameter/spread-guided pass (serve/placement.py) — opt-in, and
        STICKY: the candidate is scored once, then replayed by salt on
        every later membership change (a candidate flip would move every
        token — the movement the pass exists to bound)."""
        toks, owners, servers = self.ring.token_arrays()
        if self.placement == "dgro" and servers:
            from ringpop_tpu.serve.placement import dgro_place

            kw = dict(self.placement_kw)
            salt = getattr(self, "_dgro_salt", None)
            if salt is not None:
                kw["fixed_salt"] = salt
                # sticky local-move overrides replay verbatim alongside
                # the salt: surviving (server, replica) tokens keep their
                # exact values, departed servers' overrides lapse
                kw["fixed_moves"] = getattr(self, "_dgro_moves", {})
            toks32, owners32, report = dgro_place(
                servers, self.ring.replica_points, **kw
            )
            if salt is None:
                self.placement_report = report
            self._dgro_salt = report["salt"]
            self._dgro_moves = report.get("moves", {})
            return toks32, owners32
        return toks.astype(np.uint32), owners.astype(np.int32)

    # -- mutation ------------------------------------------------------------

    def update(self, add=None, remove=None) -> Optional[dict]:
        """Apply one membership change and commit the next generation.
        Returns the ``ring_update`` journal record (None on no-op)."""
        with self._lock:
            if not self.ring.add_remove_servers(list(add or []), list(remove or [])):
                return None
            return self._commit(added=list(add or []), removed=list(remove or []))

    def drain(self, servers) -> Optional[dict]:
        """Route a degrading server's ring block away BEFORE its peers
        declare it faulty: remove it from the ring and commit the next
        generation, stamped ``"drain": True`` so journal readers (and
        the game-day judge) distinguish a controller-initiated drain
        from an organic membership loss.  Returns the commit record
        (None when none of the servers are in the ring)."""
        with self._lock:
            removed = list(servers)
            if not self.ring.add_remove_servers([], removed):
                return None
            return self._commit(added=[], removed=removed, drain=True)

    def rescore_placement(self) -> Optional[dict]:
        """Drop the sticky DGRO candidate and re-score from scratch at
        the CURRENT membership, committing the result.  The scorer is
        deliberately sticky (a candidate flip moves every token); this
        is the telemetry-triggered exception — observed skew says the
        replayed candidate has degraded enough to pay the movement.
        Only meaningful under ``placement="dgro"`` (None otherwise);
        the record carries ``"rescored": True`` plus the fresh scorer
        report's movement/imbalance/diameter summary."""
        if self.placement != "dgro":
            return None
        with self._lock:
            self._dgro_salt = None
            self._dgro_moves = {}
            return self._commit(added=[], removed=[], rescored=True)

    def _commit(
        self,
        added: list[str],
        removed: list[str],
        drain: bool = False,
        rescored: bool = False,
    ) -> dict:
        tokens, owners = self._placed_arrays()
        self.host_tokens = np.asarray(tokens, np.uint32)
        self.host_owners = np.asarray(owners, np.int32)
        count = int(tokens.shape[0])
        if count > self.capacity:
            # outgrown: reallocate at double capacity (one retrace of the
            # serving programs at the new C — rare, logged in the record).
            # Both resident buffer sets have the old capacity, so the
            # ping-pong restarts: nothing to donate into.
            self.capacity = max(2 * count, 2 * self.capacity)
            self.gen += 1
            self.device = device_ring(tokens, owners, self.capacity, gen=self.gen)
            self._retired = None
            reallocated = True
        else:
            pt, po, count = pad_ring_arrays(tokens, owners, self.capacity)
            self.gen += 1
            if self._retired is not None:
                new = ring_commit(
                    self._retired,
                    jnp.asarray(pt),
                    jnp.asarray(po),
                    jnp.asarray([count], jnp.int32),
                    jnp.asarray([self.gen], jnp.uint32),
                )
            else:
                new = device_ring(tokens, owners, self.capacity, gen=self.gen)
            self._retired = self.device
            self.device = new
            reallocated = False
        self._gens[self.gen] = self.ring.servers()
        for g in list(self._gens):
            if g <= self.gen - self.keep_generations:
                del self._gens[g]
        record = {
            "kind": "ring_update",
            "gen": self.gen,
            "checksum": self.ring.checksum(),
            "n_servers": self.ring.server_count(),
            "count": count,
            "capacity": self.capacity,
            "reallocated": reallocated,
            "added": added,
            "removed": removed,
        }
        # controller-initiated commits carry their provenance; ORGANIC
        # commits keep the exact r13 record shape (no new keys), so
        # existing journal readers and digests are untouched
        if drain:
            record["drain"] = True
        if rescored:
            record["rescored"] = True
            report = getattr(self, "placement_report", None) or {}
            record["placement"] = {
                k: report[k]
                for k in (
                    "chosen", "salt", "movement_chosen", "movement_random",
                    "imbalance_chosen", "imbalance_random",
                    "diameter_chosen", "diameter_random",
                )
                if k in report
            }
        if self.on_update is not None:
            self.on_update(record)
        return record

    # -- live feed -----------------------------------------------------------

    def listen_to(self, emitter_owner) -> None:
        """Subscribe to a ``RingChangedEvent`` source (a ``HashRing`` or
        anything exposing ``register_listener``) — the live SWIM membership
        feed.  Each event becomes one committed generation."""
        store = self

        class _L:
            def handle_event(self, event):
                if isinstance(event, RingChangedEvent):
                    store.update(event.servers_added, event.servers_removed)

        emitter_owner.register_listener(_L())

    # -- queries -------------------------------------------------------------

    def snapshot(self) -> tuple[DeviceRing, int, int]:
        """(device ring, generation, n_servers) — one consistent view."""
        with self._lock:
            return self.device, self.gen, self.ring.server_count()

    def snapshot_host(self) -> tuple[np.ndarray, np.ndarray, int, int]:
        """(host tokens, host owners, generation, n_servers) — the
        committed generation's placed arrays, for the point-lookup fast
        lane (n=1 searchsorted and the n>1 ``host_lookup_n`` walk)."""
        with self._lock:
            return self.host_tokens, self.host_owners, self.gen, self.ring.server_count()

    def servers_at(self, gen: int) -> Optional[list[str]]:
        """Server list of a recent generation (None if aged out)."""
        with self._lock:
            return self._gens.get(gen)
